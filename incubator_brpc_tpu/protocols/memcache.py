"""Memcache binary protocol — pipelined client.

Analog of reference policy/memcache_binary_protocol.cpp +
memcache.{h,cpp} (client-only there too). Binary framing: 24-byte
header (magic 0x80 request / 0x81 response, opcode, key/extras/body
lengths, status, opaque, cas) + extras + key + value.

Usage (mirrors memcache.h Get/Set/PopGet):

    req = MemcacheRequest()
    req.set("k", b"v", flags=0, exptime=0)
    req.get("k")
    resp = MemcacheResponse()
    channel.call_method(memcache_method_spec(), ctrl, req, resp)
    ok, value, flags, cas = resp.pop_get()

Each op answers exactly one response, in order, so a request of N ops
rides Socket.pipelined_info with count=N like redis.
"""

from __future__ import annotations

import struct
from typing import List, Optional, Tuple

from incubator_brpc_tpu import errors
from incubator_brpc_tpu.protocols import ParseResult, Protocol, register_protocol
from incubator_brpc_tpu.runtime.call_id import default_pool as _id_pool
from incubator_brpc_tpu.utils.iobuf import IOBuf

MAGIC_REQUEST = 0x80
MAGIC_RESPONSE = 0x81

# opcodes (protocol_binary.h names)
OP_GET = 0x00
OP_SET = 0x01
OP_ADD = 0x02
OP_REPLACE = 0x03
OP_DELETE = 0x04
OP_INCREMENT = 0x05
OP_DECREMENT = 0x06
OP_FLUSH = 0x08
OP_NOOP = 0x0A
OP_VERSION = 0x0B
OP_APPEND = 0x0E
OP_PREPEND = 0x0F
OP_TOUCH = 0x1C

# status codes
STATUS_OK = 0x0000
STATUS_KEY_NOT_FOUND = 0x0001
STATUS_KEY_EXISTS = 0x0002
STATUS_ITEM_NOT_STORED = 0x0005

_HEADER = struct.Struct(">BBHBBHIIQ")  # magic op keylen extras dtype status bodylen opaque cas


def pack_header(
    magic: int, opcode: int, key_len: int, extras_len: int, body_len: int,
    status: int = 0, opaque: int = 0, cas: int = 0,
) -> bytes:
    return _HEADER.pack(
        magic, opcode, key_len, extras_len, 0, status, body_len, opaque, cas
    )


class MemcacheOpResponse:
    __slots__ = ("opcode", "status", "key", "extras", "value", "cas")

    def __init__(self, opcode, status, key, extras, value, cas):
        self.opcode = opcode
        self.status = status
        self.key = key
        self.extras = extras
        self.value = value
        self.cas = cas

    @property
    def ok(self) -> bool:
        return self.status == STATUS_OK


class MemcacheRequest:
    def __init__(self):
        self._buf = bytearray()
        self._count = 0

    @property
    def op_count(self) -> int:
        return self._count

    def _add(self, opcode: int, key: bytes = b"", extras: bytes = b"",
             value: bytes = b"", cas: int = 0):
        self._buf += pack_header(
            MAGIC_REQUEST, opcode, len(key), len(extras),
            len(extras) + len(key) + len(value), cas=cas,
        )
        self._buf += extras + key + value
        self._count += 1

    @staticmethod
    def _b(v) -> bytes:
        return v.encode() if isinstance(v, str) else bytes(v)

    # ---- ops (memcache.h surface) ------------------------------------------
    def get(self, key):
        self._add(OP_GET, self._b(key))

    def set(self, key, value, flags: int = 0, exptime: int = 0, cas: int = 0):
        extras = struct.pack(">II", flags, exptime)
        self._add(OP_SET, self._b(key), extras, self._b(value), cas)

    def add(self, key, value, flags: int = 0, exptime: int = 0):
        self._add(OP_ADD, self._b(key), struct.pack(">II", flags, exptime),
                  self._b(value))

    def replace(self, key, value, flags: int = 0, exptime: int = 0, cas: int = 0):
        self._add(OP_REPLACE, self._b(key), struct.pack(">II", flags, exptime),
                  self._b(value), cas)

    def append(self, key, value):
        self._add(OP_APPEND, self._b(key), b"", self._b(value))

    def prepend(self, key, value):
        self._add(OP_PREPEND, self._b(key), b"", self._b(value))

    def delete(self, key):
        self._add(OP_DELETE, self._b(key))

    def incr(self, key, delta: int = 1, initial: int = 0, exptime: int = 0xFFFFFFFF):
        extras = struct.pack(">QQI", delta, initial, exptime)
        self._add(OP_INCREMENT, self._b(key), extras)

    def decr(self, key, delta: int = 1, initial: int = 0, exptime: int = 0xFFFFFFFF):
        extras = struct.pack(">QQI", delta, initial, exptime)
        self._add(OP_DECREMENT, self._b(key), extras)

    def touch(self, key, exptime: int):
        self._add(OP_TOUCH, self._b(key), struct.pack(">I", exptime))

    def flush_all(self, delay: int = 0):
        self._add(OP_FLUSH, b"", struct.pack(">I", delay))

    def version(self):
        self._add(OP_VERSION)

    def SerializeToString(self) -> bytes:
        return bytes(self._buf)


class MemcacheResponse:
    def __init__(self):
        self._ops: List[MemcacheOpResponse] = []
        self._pop_index = 0

    def _set_ops(self, ops: List[MemcacheOpResponse]):
        self._ops = list(ops)
        self._pop_index = 0

    @property
    def op_count(self) -> int:
        return len(self._ops)

    def op(self, i: int) -> MemcacheOpResponse:
        return self._ops[i]

    def _pop(self) -> Optional[MemcacheOpResponse]:
        if self._pop_index >= len(self._ops):
            return None
        op = self._ops[self._pop_index]
        self._pop_index += 1
        return op

    # ---- pop helpers (PopGet/PopStore/PopCounter analogs) -------------------
    def pop_get(self) -> Tuple[bool, bytes, int, int]:
        """→ (ok, value, flags, cas)."""
        op = self._pop()
        if op is None or not op.ok:
            return False, b"", 0, 0
        flags = struct.unpack(">I", op.extras[:4])[0] if len(op.extras) >= 4 else 0
        return True, op.value, flags, op.cas

    def pop_store(self) -> Tuple[bool, int]:
        """→ (ok, cas) for set/add/replace/append/prepend/delete/touch."""
        op = self._pop()
        if op is None:
            return False, 0
        return op.ok, op.cas

    def pop_counter(self) -> Tuple[bool, int]:
        """→ (ok, new_value) for incr/decr."""
        op = self._pop()
        if op is None or not op.ok or len(op.value) < 8:
            return False, 0
        return True, struct.unpack(">Q", op.value[:8])[0]

    def pop_version(self) -> Tuple[bool, str]:
        op = self._pop()
        if op is None or not op.ok:
            return False, ""
        return True, op.value.decode("latin1")

    def ParseFromString(self, data: bytes):
        pass


class _MemcacheMethodSpec:
    service_name = "memcache"
    method_name = "ops"
    full_name = "memcache.ops"
    request_class = MemcacheRequest
    response_class = MemcacheResponse


def memcache_method_spec() -> _MemcacheMethodSpec:
    return _MemcacheMethodSpec()


# ---- protocol callbacks (client only, like the reference) -------------------
def parse(buf: IOBuf, sock, read_eof: bool) -> ParseResult:
    head = buf.fetch(1)
    if not head:
        return ParseResult.not_enough()
    magic = head[0]
    if sock.is_server_side or magic != MAGIC_RESPONSE:
        return ParseResult.try_others()
    header = buf.fetch(24)
    if header is None:
        return ParseResult.not_enough()
    (magic, opcode, key_len, extras_len, _dt, status, body_len, _opq, cas) = (
        _HEADER.unpack(header)
    )
    if len(buf) < 24 + body_len:
        return ParseResult.not_enough()
    buf.pop_front(24)
    body = buf.cut_bytes(body_len)
    extras = body[:extras_len]
    key = body[extras_len : extras_len + key_len]
    value = body[extras_len + key_len :]
    return ParseResult.ok(
        MemcacheOpResponse(opcode, status, key, extras, value, cas)
    )


def serialize_request(request: MemcacheRequest, controller) -> IOBuf:
    if request.op_count == 0:
        raise ValueError("MemcacheRequest has no ops")
    controller._memcache_count = request.op_count
    return IOBuf(request.SerializeToString())


def pack_request(request_buf: IOBuf, wire_cid: int, method_spec, controller) -> IOBuf:
    count = getattr(controller, "_memcache_count", 1)
    packet = IOBuf()
    channel = controller._channel
    auth = channel.options.auth if channel is not None else None
    if auth is not None:
        # couchbase-style SASL: the authenticator's credential IS a
        # complete memcache SASL_AUTH packet (CouchbaseAuthenticator,
        # reference policy/couchbase_authenticator.cpp); it must be the
        # FIRST packet on the connection, so it rides the same
        # conn_preamble mechanism as redis AUTH — Socket.write decides
        # the one writer that prepends it.  cid 0 discards the server's
        # SASL response.
        cred = auth.generate_credential()
        controller._conn_preamble = (
            IOBuf(cred.encode("latin1")), [(0, 1)],
        )
    packet.append(request_buf)
    # FIFO entry registers inside the write, atomic with queue order
    controller._pipelined_entries = [(wire_cid, count)]
    return packet


def process_response(op: MemcacheOpResponse, sock) -> None:
    from incubator_brpc_tpu.protocols import accumulate_pipelined

    done = accumulate_pipelined(sock, op)
    if done is None:
        return
    cid, ops = done
    if not cid:
        return
    pool = _id_pool()
    ctrl = pool.lock(cid)
    if ctrl is None:
        return
    if ctrl._response is not None:
        ctrl._response._set_ops(ops)
    ctrl._finalize_locked(cid)


PROTOCOL = Protocol(
    name="memcache",
    parse=parse,
    serialize_request=serialize_request,
    pack_request=pack_request,
    process_response=process_response,
    support_server=False,  # client-only, like the reference
    support_pipelined=True,
    process_ordered=True,
)


def register():
    register_protocol(PROTOCOL)
