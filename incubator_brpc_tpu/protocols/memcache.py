"""Memcache binary protocol — pipelined client + server.

Analog of reference policy/memcache_binary_protocol.cpp +
memcache.{h,cpp} (client-only there). Binary framing: 24-byte header
(magic 0x80 request / 0x81 response, opcode, key/extras/body lengths,
status, opaque, cas) + extras + key + value.

Usage (mirrors memcache.h Get/Set/PopGet):

    req = MemcacheRequest()
    req.set("k", b"v", flags=0, exptime=0)
    req.get("k")
    resp = MemcacheResponse()
    channel.call_method(memcache_method_spec(), ctrl, req, resp)
    ok, value, flags, cas = resp.pop_get()

Each op answers exactly one response, in order, so a request of N ops
rides Socket.pipelined_info with count=N like redis.

Server side (TPU extension past the reference): set
``ServerOptions.memcache_service`` to a ``MemcacheService`` and any
binary-protocol memcached client can talk to the port.  The length-
prefixed framing makes the device-value path simpler than redis: a
value region that is exactly one whole-array DeviceRef ships HBM→HBM
over ICI without materializing (GET replies and SET ingests both)."""

from __future__ import annotations

import struct
import threading
from typing import List, Optional, Tuple

from incubator_brpc_tpu import errors
from incubator_brpc_tpu.protocols import ParseResult, Protocol, register_protocol
from incubator_brpc_tpu.runtime.call_id import default_pool as _id_pool
from incubator_brpc_tpu.utils.iobuf import DeviceRef, IOBuf
from incubator_brpc_tpu.utils.logging import log_error

MAGIC_REQUEST = 0x80
MAGIC_RESPONSE = 0x81

# opcodes (protocol_binary.h names)
OP_GET = 0x00
OP_SET = 0x01
OP_ADD = 0x02
OP_REPLACE = 0x03
OP_DELETE = 0x04
OP_INCREMENT = 0x05
OP_DECREMENT = 0x06
OP_FLUSH = 0x08
OP_NOOP = 0x0A
OP_VERSION = 0x0B
OP_APPEND = 0x0E
OP_PREPEND = 0x0F
OP_TOUCH = 0x1C

# status codes
STATUS_OK = 0x0000
STATUS_KEY_NOT_FOUND = 0x0001
STATUS_KEY_EXISTS = 0x0002
STATUS_ITEM_NOT_STORED = 0x0005

_HEADER = struct.Struct(">BBHBBHIIQ")  # magic op keylen extras dtype status bodylen opaque cas


def pack_header(
    magic: int, opcode: int, key_len: int, extras_len: int, body_len: int,
    status: int = 0, opaque: int = 0, cas: int = 0,
) -> bytes:
    return _HEADER.pack(
        magic, opcode, key_len, extras_len, 0, status, body_len, opaque, cas
    )


class MemcacheOpResponse:
    __slots__ = ("opcode", "status", "key", "extras", "value", "cas")

    def __init__(self, opcode, status, key, extras, value, cas):
        self.opcode = opcode
        self.status = status
        self.key = key
        self.extras = extras
        self.value = value
        self.cas = cas

    @property
    def ok(self) -> bool:
        return self.status == STATUS_OK

    def device_array(self):
        """The HBM-resident jax.Array of a device-path value, or None
        for host values."""
        if isinstance(self.value, DeviceRef):
            return self.value.whole_array()
        return None

    def bytes_value(self) -> bytes:
        """The value as host bytes; device values MATERIALIZE (one
        manifested pull through iobuf.host-view)."""
        if isinstance(self.value, DeviceRef):
            return bytes(self.value.view())
        return self.value


def _is_device_value(v) -> bool:
    """An HBM-resident value operand (jax.Array / DeviceRef), not host
    bytes — rides the wire as a DeviceRef segment."""
    if isinstance(v, DeviceRef):
        return True
    return (
        hasattr(v, "nbytes")
        and hasattr(v, "dtype")
        and not isinstance(v, (bytes, bytearray, memoryview))
    )


class MemcacheRequest:
    def __init__(self):
        # host-byte chunks interleaved with device arrays (a SET value
        # may be an HBM-resident jax.Array — the cache ingest path)
        self._chunks: List = []
        self._count = 0
        self._has_device = False

    @property
    def op_count(self) -> int:
        return self._count

    def _add(self, opcode: int, key: bytes = b"", extras: bytes = b"",
             value=b"", cas: int = 0):
        if _is_device_value(value):
            vlen = int(value.nbytes)
            self._chunks.append(
                pack_header(
                    MAGIC_REQUEST, opcode, len(key), len(extras),
                    len(extras) + len(key) + vlen, cas=cas,
                )
                + extras + key
            )
            self._chunks.append(value)
            self._has_device = True
        else:
            self._chunks.append(
                pack_header(
                    MAGIC_REQUEST, opcode, len(key), len(extras),
                    len(extras) + len(key) + len(value), cas=cas,
                )
                + extras + key + value
            )
        self._count += 1

    @staticmethod
    def _b(v):
        if _is_device_value(v):
            return v
        return v.encode() if isinstance(v, str) else bytes(v)

    # ---- ops (memcache.h surface) ------------------------------------------
    def get(self, key):
        self._add(OP_GET, self._b(key))

    def set(self, key, value, flags: int = 0, exptime: int = 0, cas: int = 0):
        extras = struct.pack(">II", flags, exptime)
        self._add(OP_SET, self._b(key), extras, self._b(value), cas)

    def add(self, key, value, flags: int = 0, exptime: int = 0):
        self._add(OP_ADD, self._b(key), struct.pack(">II", flags, exptime),
                  self._b(value))

    def replace(self, key, value, flags: int = 0, exptime: int = 0, cas: int = 0):
        self._add(OP_REPLACE, self._b(key), struct.pack(">II", flags, exptime),
                  self._b(value), cas)

    def append(self, key, value):
        self._add(OP_APPEND, self._b(key), b"", self._b(value))

    def prepend(self, key, value):
        self._add(OP_PREPEND, self._b(key), b"", self._b(value))

    def delete(self, key):
        self._add(OP_DELETE, self._b(key))

    def incr(self, key, delta: int = 1, initial: int = 0, exptime: int = 0xFFFFFFFF):
        extras = struct.pack(">QQI", delta, initial, exptime)
        self._add(OP_INCREMENT, self._b(key), extras)

    def decr(self, key, delta: int = 1, initial: int = 0, exptime: int = 0xFFFFFFFF):
        extras = struct.pack(">QQI", delta, initial, exptime)
        self._add(OP_DECREMENT, self._b(key), extras)

    def touch(self, key, exptime: int):
        self._add(OP_TOUCH, self._b(key), struct.pack(">I", exptime))

    def flush_all(self, delay: int = 0):
        self._add(OP_FLUSH, b"", struct.pack(">I", delay))

    def version(self):
        self._add(OP_VERSION)

    def SerializeToString(self) -> bytes:
        if self._has_device:
            raise ValueError("device-payload request needs serialize_iobuf()")
        return b"".join(self._chunks)

    def serialize_iobuf(self) -> IOBuf:
        out = IOBuf()
        for c in self._chunks:
            if isinstance(c, bytes):
                out.append(c)
            else:
                out.append_device(c)
        return out


class MemcacheResponse:
    def __init__(self):
        self._ops: List[MemcacheOpResponse] = []
        self._pop_index = 0

    def _set_ops(self, ops: List[MemcacheOpResponse]):
        self._ops = list(ops)
        self._pop_index = 0

    @property
    def op_count(self) -> int:
        return len(self._ops)

    def op(self, i: int) -> MemcacheOpResponse:
        return self._ops[i]

    def _pop(self) -> Optional[MemcacheOpResponse]:
        if self._pop_index >= len(self._ops):
            return None
        op = self._ops[self._pop_index]
        self._pop_index += 1
        return op

    # ---- pop helpers (PopGet/PopStore/PopCounter analogs) -------------------
    def pop_get(self) -> Tuple[bool, bytes, int, int]:
        """→ (ok, value, flags, cas)."""
        op = self._pop()
        if op is None or not op.ok:
            return False, b"", 0, 0
        flags = struct.unpack(">I", op.extras[:4])[0] if len(op.extras) >= 4 else 0
        return True, op.value, flags, op.cas

    def pop_store(self) -> Tuple[bool, int]:
        """→ (ok, cas) for set/add/replace/append/prepend/delete/touch."""
        op = self._pop()
        if op is None:
            return False, 0
        return op.ok, op.cas

    def pop_counter(self) -> Tuple[bool, int]:
        """→ (ok, new_value) for incr/decr."""
        op = self._pop()
        if op is None or not op.ok or len(op.value) < 8:
            return False, 0
        return True, struct.unpack(">Q", op.value[:8])[0]

    def pop_version(self) -> Tuple[bool, str]:
        op = self._pop()
        if op is None or not op.ok:
            return False, ""
        return True, op.value.decode("latin1")

    def ParseFromString(self, data: bytes):
        pass


class _MemcacheMethodSpec:
    service_name = "memcache"
    method_name = "ops"
    full_name = "memcache.ops"
    request_class = MemcacheRequest
    response_class = MemcacheResponse


def memcache_method_spec() -> _MemcacheMethodSpec:
    return _MemcacheMethodSpec()


# ---- protocol callbacks -----------------------------------------------------
class _MemcacheReq:
    """One parsed server-side request op."""

    __slots__ = ("opcode", "key", "extras", "value", "cas", "opaque")

    def __init__(self, opcode, key, extras, value, cas, opaque):
        self.opcode = opcode
        self.key = key
        self.extras = extras
        self.value = value  # bytes | DeviceRef (device-resident SET)
        self.cas = cas
        self.opaque = opaque


def _fetch_header(buf: IOBuf) -> Optional[bytes]:
    """The 24-byte header without materializing device segments (the
    header is always host bytes at the front; ``fetch`` would copy_to
    across a device ref if the header straddled segments)."""
    parts = []
    need = 24
    for ref in buf.iter_refs():
        if need <= 0:
            break
        if isinstance(ref, DeviceRef):
            raise ValueError("memcache header inside a device segment")
        v = ref.view()
        take = min(len(v), need)
        parts.append(bytes(v[:take]))
        need -= take
    if need > 0:
        return None
    return b"".join(parts)


def _cut_value(buf: IOBuf, value_len: int):
    """Consume the value region: exactly one whole-array DeviceRef at
    the front stays device-resident; anything else takes the byte path
    (materializing device windows through iobuf.host-view)."""
    if value_len == 0:
        return b""
    first = next(iter(buf.iter_refs()), None)
    if (
        isinstance(first, DeviceRef)
        and first.length == value_len
        and first.whole_array() is not None
    ):
        out = IOBuf()
        buf.cutn(out, value_len)
        return out.device_segments()[0]
    return buf.cut_bytes(value_len)


def parse(buf: IOBuf, sock, read_eof: bool) -> ParseResult:
    if buf.has_device_payload():
        first = next(iter(buf.iter_refs()), None)
        if isinstance(first, DeviceRef):
            return ParseResult.bad()  # a frame never starts mid-payload
        head = bytes(first.view()[:1])
    else:
        head = buf.fetch(1)
    if not head:
        return ParseResult.not_enough()
    magic = head[0]
    if sock.is_server_side:
        if magic != MAGIC_REQUEST:
            return ParseResult.try_others()
        # only servers that actually speak memcache claim 0x80 frames —
        # other binary protocols must keep their shot at the bytes
        service = getattr(
            getattr(getattr(sock, "server", None), "options", None),
            "memcache_service",
            None,
        )
        if service is None:
            return ParseResult.try_others()
    elif magic != MAGIC_RESPONSE:
        return ParseResult.try_others()
    try:
        header = _fetch_header(buf)
    except ValueError:
        return ParseResult.bad()
    if header is None:
        return ParseResult.not_enough()
    (magic, opcode, key_len, extras_len, _dt, status, body_len, opaque, cas) = (
        _HEADER.unpack(header)
    )
    if body_len < extras_len + key_len:
        return ParseResult.bad()
    if len(buf) < 24 + body_len:
        return ParseResult.not_enough()
    buf.pop_front(24)
    ek = buf.cut_bytes(extras_len + key_len)
    extras = ek[:extras_len]
    key = ek[extras_len:]
    value = _cut_value(buf, body_len - extras_len - key_len)
    if sock.is_server_side:
        return ParseResult.ok(
            _MemcacheReq(opcode, key, extras, value, cas, opaque)
        )
    return ParseResult.ok(
        MemcacheOpResponse(opcode, status, key, extras, value, cas)
    )


def serialize_request(request: MemcacheRequest, controller) -> IOBuf:
    if request.op_count == 0:
        raise ValueError("MemcacheRequest has no ops")
    controller._memcache_count = request.op_count
    return request.serialize_iobuf()


def pack_request(request_buf: IOBuf, wire_cid: int, method_spec, controller) -> IOBuf:
    count = getattr(controller, "_memcache_count", 1)
    packet = IOBuf()
    channel = controller._channel
    auth = channel.options.auth if channel is not None else None
    if auth is not None:
        # couchbase-style SASL: the authenticator's credential IS a
        # complete memcache SASL_AUTH packet (CouchbaseAuthenticator,
        # reference policy/couchbase_authenticator.cpp); it must be the
        # FIRST packet on the connection, so it rides the same
        # conn_preamble mechanism as redis AUTH — Socket.write decides
        # the one writer that prepends it.  cid 0 discards the server's
        # SASL response.
        cred = auth.generate_credential()
        controller._conn_preamble = (
            IOBuf(cred.encode("latin1")), [(0, 1)],
        )
    packet.append(request_buf)
    # FIFO entry registers inside the write, atomic with queue order
    controller._pipelined_entries = [(wire_cid, count)]
    return packet


def process_response(op: MemcacheOpResponse, sock) -> None:
    from incubator_brpc_tpu.protocols import accumulate_pipelined

    done = accumulate_pipelined(sock, op)
    if done is None:
        return
    cid, ops = done
    if not cid:
        return
    pool = _id_pool()
    ctrl = pool.lock(cid)
    if ctrl is None:
        return
    if ctrl._response is not None:
        ctrl._response._set_ops(ops)
    ctrl._finalize_locked(cid)


# ---- server side (TPU extension past the client-only reference) -------------
class MemcacheService:
    """In-memory binary-memcached server: set
    ``ServerOptions.memcache_service = MemcacheService()`` and the port
    answers get/set/add/replace/delete/incr/decr/append/prepend/touch/
    flush/version/noop.  Subclasses override ``handle_op`` for custom
    stores (the HBM cache tier overrides it to serve DeviceRef values);
    the default keeps host bytes in a dict with flags + cas."""

    VERSION = b"1.6.0-tpu"

    def __init__(self):
        self._d = {}  # key -> [value bytes, flags, cas]
        self._cas = 0
        self._lock = threading.Lock()

    @staticmethod
    def _host(value) -> bytes:
        if isinstance(value, DeviceRef):
            return bytes(value.view())
        if _is_device_value(value):
            return bytes(DeviceRef(value).view())
        return bytes(value)

    def handle_op(self, op: _MemcacheReq, sock) -> Tuple[int, bytes, object, int]:
        """→ (status, extras, value, cas).  ``value`` may be bytes or a
        device array (whole jax.Array) for the HBM-resident path."""
        code = op.opcode
        if code == OP_GET:
            with self._lock:
                ent = self._d.get(op.key)
            if ent is None:
                return STATUS_KEY_NOT_FOUND, b"", b"Not found", 0
            return STATUS_OK, struct.pack(">I", ent[1]), ent[0], ent[2]
        if code in (OP_SET, OP_ADD, OP_REPLACE):
            flags = struct.unpack(">I", op.extras[:4])[0] if len(op.extras) >= 4 else 0
            value = self._host(op.value)
            with self._lock:
                exists = op.key in self._d
                if code == OP_ADD and exists:
                    return STATUS_KEY_EXISTS, b"", b"", 0
                if code == OP_REPLACE and not exists:
                    return STATUS_KEY_NOT_FOUND, b"", b"", 0
                if op.cas and exists and self._d[op.key][2] != op.cas:
                    return STATUS_KEY_EXISTS, b"", b"", 0
                self._cas += 1
                self._d[op.key] = [value, flags, self._cas]
                return STATUS_OK, b"", b"", self._cas
        if code == OP_DELETE:
            with self._lock:
                ok = self._d.pop(op.key, None) is not None
            return (STATUS_OK if ok else STATUS_KEY_NOT_FOUND), b"", b"", 0
        if code in (OP_APPEND, OP_PREPEND):
            value = self._host(op.value)
            with self._lock:
                ent = self._d.get(op.key)
                if ent is None:
                    return STATUS_ITEM_NOT_STORED, b"", b"", 0
                ent[0] = ent[0] + value if code == OP_APPEND else value + ent[0]
                self._cas += 1
                ent[2] = self._cas
                return STATUS_OK, b"", b"", self._cas
        if code in (OP_INCREMENT, OP_DECREMENT):
            if len(op.extras) < 20:
                return STATUS_ITEM_NOT_STORED, b"", b"", 0
            delta, initial, _exp = struct.unpack(">QQI", op.extras[:20])
            with self._lock:
                ent = self._d.get(op.key)
                if ent is None:
                    cur = initial
                else:
                    try:
                        cur = int(ent[0])
                    except ValueError:
                        return STATUS_ITEM_NOT_STORED, b"", b"", 0
                    cur = cur + delta if code == OP_INCREMENT else max(0, cur - delta)
                self._cas += 1
                self._d[op.key] = [str(cur).encode(), 0, self._cas]
                return STATUS_OK, b"", struct.pack(">Q", cur), self._cas
        if code == OP_TOUCH:
            with self._lock:
                ok = op.key in self._d
            return (STATUS_OK if ok else STATUS_KEY_NOT_FOUND), b"", b"", 0
        if code == OP_FLUSH:
            with self._lock:
                self._d.clear()
            return STATUS_OK, b"", b"", 0
        if code == OP_NOOP:
            return STATUS_OK, b"", b"", 0
        if code == OP_VERSION:
            return STATUS_OK, b"", self.VERSION, 0
        return 0x0081, b"", b"Unknown command", 0  # UNKNOWN_COMMAND


def pack_response_into(
    out: IOBuf, opcode: int, status: int, extras: bytes, value, cas: int,
    opaque: int = 0,
) -> None:
    """Pack one response frame; an HBM-resident value ships as a
    DeviceRef segment (memcache's length-prefixed framing needs no
    trailer, so the device array IS the value region)."""
    if _is_device_value(value):
        arr = value.whole_array() if isinstance(value, DeviceRef) else value
        if arr is None:  # windowed ref: materialize once, manifested
            value = bytes(value.view())
        else:
            out.append(pack_header(
                MAGIC_RESPONSE, opcode, 0, len(extras),
                len(extras) + int(arr.nbytes), status=status,
                opaque=opaque, cas=cas,
            ))
            if extras:
                out.append(extras)
            out.append_device(arr)
            return
    out.append(pack_header(
        MAGIC_RESPONSE, opcode, 0, len(extras), len(extras) + len(value),
        status=status, opaque=opaque, cas=cas,
    ))
    if extras:
        out.append(extras)
    if value:
        out.append(value)


def process_request(op: _MemcacheReq, sock) -> None:
    service = getattr(
        getattr(getattr(sock, "server", None), "options", None),
        "memcache_service",
        None,
    )
    if service is None:
        status, extras, value, cas = 0x0081, b"", b"Unknown command", 0
    else:
        # same unified admission gate as every other protocol; a shed
        # answers the binary-protocol Busy status (0x0085)
        verdict = sock.server.admission.admit(
            f"memcache.{op.opcode:#04x}", None
        )
        if not verdict.admitted:
            status, extras, value, cas = 0x0085, b"", b"Busy", 0
        else:
            ticket = verdict.ticket
            try:
                status, extras, value, cas = service.handle_op(op, sock)
            except Exception as e:  # noqa: BLE001 — handler bug answers, not kills
                log_error("memcache handler op=%#x raised: %r", op.opcode, e)
                status, extras, value, cas = 0x0084, b"", b"Internal error", 0
            finally:
                if ticket is not None:
                    ticket.release()
    out = IOBuf()
    pack_response_into(out, op.opcode, status, extras, value, cas, op.opaque)
    sock.write(out, ignore_eovercrowded=True)


PROTOCOL = Protocol(
    name="memcache",
    parse=parse,
    serialize_request=serialize_request,
    pack_request=pack_request,
    process_request=process_request,
    process_response=process_response,
    support_server=True,  # TPU extension: memcache_service on the port
    support_pipelined=True,
    process_ordered=True,
)


def register():
    register_protocol(PROTOCOL)
