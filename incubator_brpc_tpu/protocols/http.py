"""HTTP/1.x protocol — restful RPC + builtin service pages.

Analog of reference policy/http_rpc_protocol.cpp (1,603 LoC) + the
http_parser/HttpHeader/URI stack (SURVEY.md §2.4 "HTTP stack"):
- Server side: pb services are exposed automatically as
  ``POST /ServiceName/MethodName`` with JSON bodies (json2pb), and
  builtin observability pages (/status /vars /flags ...) are served on
  the same port — the same-port-speaks-all-protocols inversion.
- Client side: channels with protocol="http" issue requests and match
  responses by arrival order on the connection (HTTP/1.1 has no
  correlation id; in-order matching is what the reference does for
  single connections).
"""

from __future__ import annotations

import struct
from typing import Dict, Optional, Tuple
from urllib.parse import parse_qs, unquote, urlsplit

from incubator_brpc_tpu import errors
from incubator_brpc_tpu.protocols import ParseResult, Protocol, register_protocol
from incubator_brpc_tpu.runtime.call_id import default_pool as _id_pool
from incubator_brpc_tpu.serialization.json2pb import json_to_proto, proto_to_json
from incubator_brpc_tpu.utils.iobuf import IOBuf
from incubator_brpc_tpu.utils.logging import log_error

_METHODS = (b"GET ", b"POST", b"PUT ", b"DELE", b"HEAD", b"PATC", b"OPTI")
_MAX_HEADER = 64 << 10
# budget for a pb handler to run its done callback before the request
# is answered 503 (tests shrink this to exercise the timeout path)
HANDLER_TIMEOUT_S = 30.0

HTTP_STATUS = {
    200: "OK",
    400: "Bad Request",
    403: "Forbidden",
    404: "Not Found",
    500: "Internal Server Error",
    501: "Not Implemented",
    503: "Service Unavailable",
}


class HttpMessage:
    """Parsed request or response (HttpHeader + body analog)."""

    __slots__ = (
        "is_request",
        "method",
        "path",
        "query",
        "status",
        "headers",
        "body",
        "version",
        "progressive_stream",  # _ProgressiveBody for chunked responses
        "received_us",  # rpcz phase stamps (transport cut loop)
        "parse_done_us",
        "enqueued_us",
    )

    def __init__(self):
        self.is_request = True
        self.method = "GET"
        self.path = "/"
        self.query: Dict[str, str] = {}
        self.status = 200
        self.headers: Dict[str, str] = {}
        self.body = IOBuf()
        self.version = "HTTP/1.1"
        self.progressive_stream = None
        self.received_us = 0
        self.parse_done_us = 0
        self.enqueued_us = 0

    def header(self, name: str, default=None):
        return self.headers.get(name.lower(), default)


class _ChunkedCtx:
    """Per-socket state for an in-progress chunked body (RFC 7230 §4.1).
    Lives on the socket between parse() calls. Client responses stream
    (the headers message was already dispatched, chunks flow to the
    _ProgressiveBody); server requests accumulate into msg.body."""

    __slots__ = ("msg", "stream")

    def __init__(self, msg, stream=None):
        self.msg = msg
        self.stream = stream  # _ProgressiveBody | None


def parse(buf: IOBuf, sock, read_eof: bool) -> ParseResult:
    ctx = getattr(sock, "_http_chunk_ctx", None)
    if ctx is not None:
        r = _parse_chunks(buf, sock, ctx)
        if read_eof and getattr(sock, "_http_chunk_ctx", None) is not None:
            # connection died mid-body: unblock any progressive reader
            # (they get the end marker; the half body is all there is)
            sock._http_chunk_ctx = None
            if ctx.stream is not None:
                ctx.stream.finish()
            return ParseResult.bad()
        return r
    head = buf.fetch(min(len(buf), 8))
    if head is None or len(head) < 4:
        return ParseResult.not_enough() if _maybe_http(head or b"") else ParseResult.try_others()
    if not _maybe_http(head):
        return ParseResult.try_others()
    # find end of headers
    raw = buf.copy_to(min(len(buf), _MAX_HEADER))
    idx = raw.find(b"\r\n\r\n")
    if idx < 0:
        if len(raw) >= _MAX_HEADER:
            return ParseResult.bad()
        return ParseResult.not_enough()
    header_block = raw[:idx].decode("latin1")
    lines = header_block.split("\r\n")
    msg = HttpMessage()
    first = lines[0].split(" ", 2)
    if first[0].startswith("HTTP/"):
        msg.is_request = False
        msg.version = first[0]
        try:
            msg.status = int(first[1])
        except (IndexError, ValueError):
            return ParseResult.bad()
    else:
        if len(first) < 3:
            return ParseResult.bad()
        msg.method = first[0].upper()
        msg.version = first[2]
        parts = urlsplit(first[1])
        msg.path = unquote(parts.path) or "/"
        msg.query = {k: v[0] for k, v in parse_qs(parts.query).items()}
    for line in lines[1:]:
        k, _, v = line.partition(":")
        msg.headers[k.strip().lower()] = v.strip()
    if "chunked" in (msg.headers.get("transfer-encoding", "") or "").lower():
        buf.pop_front(idx + 4)
        if not msg.is_request and not sock.is_server_side:
            # client response: dispatch the HEADERS message through the
            # normal (ordered) path NOW — process_response binds it to
            # the right controller in FIFO order; the cut loop re-enters
            # parse() and the chunks stream into msg.progressive_stream
            stream = _ProgressiveBody()
            msg.progressive_stream = stream
            sock._http_chunk_ctx = _ChunkedCtx(msg, stream)
            return ParseResult.ok(msg)
        sock._http_chunk_ctx = _ChunkedCtx(msg, None)
        return _parse_chunks(buf, sock, sock._http_chunk_ctx)
    body_len = int(msg.headers.get("content-length", "0") or 0)
    total = idx + 4 + body_len
    if len(buf) < total:
        return ParseResult.not_enough()
    buf.pop_front(idx + 4)
    buf.cutn(msg.body, body_len)
    return ParseResult.ok(msg)


def _parse_chunks(buf: IOBuf, sock, ctx: _ChunkedCtx) -> ParseResult:
    """Consume as many complete chunks as available.

    Accumulate mode (server-side chunked REQUEST): returns ok(msg) with
    the full de-chunked body after the terminal chunk.
    Stream mode (client-side chunked RESPONSE): the headers message was
    already dispatched; chunks feed the stream, the terminal chunk
    finish()es it, and parsing falls through to whatever pipelined
    message follows in the buffer."""
    while True:
        raw = buf.copy_to(min(len(buf), 32))
        nl = raw.find(b"\r\n")
        if nl < 0:
            if len(raw) >= 32:
                return _chunk_fail(sock, ctx)
            return ParseResult.not_enough()
        size_token = raw[:nl].split(b";", 1)[0].strip()
        try:
            size = int(size_token, 16)
        except ValueError:
            return _chunk_fail(sock, ctx)
        if size == 0:
            # terminal chunk: "0\r\n" + optional trailers + "\r\n"
            tail = buf.copy_to(min(len(buf), _MAX_HEADER))
            end = tail.find(b"\r\n\r\n")
            if end < 0:
                if len(tail) >= _MAX_HEADER:
                    return _chunk_fail(sock, ctx)
                return ParseResult.not_enough()  # trailers in flight
            buf.pop_front(end + 4)
            sock._http_chunk_ctx = None
            if ctx.stream is not None:
                ctx.stream.finish()
                # stream mode already emitted its message at the
                # headers: hand the remaining bytes (the next pipelined
                # message, if complete) straight back to the parser
                if len(buf):
                    return parse(buf, sock, False)
                return ParseResult.not_enough()
            return ParseResult.ok(ctx.msg)
        if len(buf) < nl + 2 + size + 2:
            return ParseResult.not_enough()
        buf.pop_front(nl + 2)
        chunk = buf.cut_bytes(size)
        buf.pop_front(2)  # trailing CRLF
        if ctx.stream is not None:
            ctx.stream.feed(chunk)
        else:
            ctx.msg.body.append(chunk)
            if len(ctx.msg.body) > get_max_body():
                return _chunk_fail(sock, ctx)


def _chunk_fail(sock, ctx: _ChunkedCtx) -> ParseResult:
    """Malformed chunk framing: kill the connection, and unblock any
    progressive reader with the end marker so it never hangs."""
    sock._http_chunk_ctx = None
    if ctx.stream is not None:
        ctx.stream.finish()
    return ParseResult.bad()


def get_max_body() -> int:
    from incubator_brpc_tpu.utils.flags import get_flag

    return get_flag("max_body_size", 2 << 30)


class _ProgressiveBody:
    """Client-side progressive body (reference ProgressiveReader,
    progressive_attachment.h): chunks buffer until a reader attaches
    via Controller.read_progressive_attachment(fn); fn(bytes) per part,
    fn(None) at end-of-body."""

    def __init__(self):
        import threading as _threading

        self._lock = _threading.Lock()
        self._pending = []
        self._reader = None
        self._finished = False

    def feed(self, chunk: bytes):
        with self._lock:
            reader = self._reader
            if reader is None:
                self._pending.append(chunk)
                return
        _safe_read(reader, chunk)

    def finish(self):
        with self._lock:
            reader = self._reader
            self._finished = True
        if reader is not None:
            _safe_read(reader, None)

    def attach(self, reader):
        with self._lock:
            self._reader = reader
            pending, self._pending = self._pending, []
            finished = self._finished
        for chunk in pending:
            _safe_read(reader, chunk)
        if finished:
            _safe_read(reader, None)


def _safe_read(reader, part):
    try:
        reader(part)
    except Exception as e:  # noqa: BLE001 — a raising reader must not
        log_error("progressive reader raised: %r", e)  # kill the parse loop


def _maybe_http(head: bytes) -> bool:
    up = head[:4].upper()
    return up.startswith(b"HTTP") or any(up.startswith(m[: len(up)]) for m in _METHODS)


def build_response(
    status: int, body, content_type: str = "text/plain", headers: Optional[Dict] = None
) -> IOBuf:
    if isinstance(body, str):
        body = body.encode()
    body_buf = body if isinstance(body, IOBuf) else IOBuf(body)
    out = IOBuf()
    hdrs = {
        "Content-Type": content_type,
        "Content-Length": str(len(body_buf)),
        "Connection": "keep-alive",
    }
    if headers:
        hdrs.update(headers)
    head = f"HTTP/1.1 {status} {HTTP_STATUS.get(status, '')}\r\n"
    head += "".join(f"{k}: {v}\r\n" for k, v in hdrs.items())
    out.append(head + "\r\n")
    out.append(body_buf)
    return out


def build_request(
    method: str,
    path: str,
    body=b"",
    content_type="application/json",
    host="",
    headers: Optional[Dict] = None,
) -> IOBuf:
    body_buf = body if isinstance(body, IOBuf) else IOBuf(body)
    out = IOBuf()
    head = f"{method} {path} HTTP/1.1\r\n"
    head += f"Host: {host or 'tpubrpc'}\r\nContent-Type: {content_type}\r\n"
    head += f"Content-Length: {len(body_buf)}\r\nConnection: keep-alive\r\n"
    if headers:
        head += "".join(f"{k}: {v}\r\n" for k, v in headers.items())
    out.append(head + "\r\n")
    out.append(body_buf)
    return out


class ProgressiveAttachment:
    """Server-side chunked response body (reference
    progressive_attachment.{h,cpp}): the handler writes parts as they
    are produced; writes before the response headers go out are
    buffered; close() sends the terminal chunk. Thread-safe — the
    producer usually outlives the request handler."""

    def __init__(self, content_type: str = "application/octet-stream"):
        import threading as _threading

        self._lock = _threading.Lock()
        self._sock = None
        self._pending = []
        self._closed = False
        # what the chunked response's Content-Type header announces —
        # "text/event-stream" turns the stream into SSE (the generate
        # service's browser-shaped path, docs/streaming.md)
        self.content_type = content_type

    def write(self, data) -> int:
        if isinstance(data, str):
            data = data.encode()
        if isinstance(data, IOBuf):
            data = data.to_bytes()
        with self._lock:
            if self._closed:
                return errors.ECLOSE
            sock = self._sock
            if sock is None:
                self._pending.append(data)
                return 0
            # per-write hold, taken under the same lock close() uses:
            # a close() that wins the lock makes this write see _closed;
            # one that loses cannot recycle the slot under our feet
            # (its lifetime-guard release defers until we release)
            if not sock._inuse_acquire():
                return errors.ECLOSE
        try:
            return self._write_chunk(sock, data)
        finally:
            sock._inuse_release()

    @staticmethod
    def _write_chunk(sock, data: bytes) -> int:
        if not data:
            return 0
        out = IOBuf()
        out.append(f"{len(data):x}\r\n".encode())
        out.append(data)
        out.append(b"\r\n")
        return sock.write(out, ignore_eovercrowded=True)

    def backlog_bytes(self) -> int:
        """Unsent bytes queued on the bound connection — producers that
        must not grow without bound against a stalled client (the SSE
        generate path) poll this and stop/evict past their budget.
        0 while unbound (writes are buffering) or after close."""
        with self._lock:
            sock = self._sock
        if sock is None:
            return 0
        return sock._unwritten

    def close(self) -> int:
        with self._lock:
            if self._closed:
                return 0
            self._closed = True
            sock = self._sock
            self._sock = None
        if sock is not None:
            rc = sock.write(IOBuf(b"0\r\n\r\n"), ignore_eovercrowded=True)
            # the response advertised Connection: close — the stream
            # owned the connection, nothing else may ride it.  Graceful:
            # buffered chunks + the terminator above may still sit in
            # the KeepWrite queue under backpressure; an immediate
            # set_failed would drop them (truncated chunked body)
            sock.close_after_flush(errors.ECLOSE, "progressive response complete")
            sock._inuse_release()  # guard taken at _bind
            return rc
        return 0

    def _bind(self, sock):
        """Called once the chunked response headers are written.

        Takes the socket's in-use guard for the attachment's lifetime
        (released at close()): the producer thread writes long after
        the request handler returned, and without the hold the socket's
        pool slot could be recycled and REBORN under a different
        connection — a late write would then ride (and a late failure
        close the fd of) an unrelated socket.  This is the reference's
        SocketUniquePtr refcount held by ProgressiveAttachment
        (progressive_attachment.h: _httpsock member)."""
        if not sock._inuse_acquire():
            # socket already dying: the stream can never be written
            self._abort()
            return
        # Drain the buffered parts BEFORE publishing _sock: once _sock
        # is visible, concurrent write()s go straight to the wire, and
        # publishing first would let a fresh part overtake (or a
        # close() truncate) the buffered ones.  Loop: writes landing
        # during a drain pass re-buffer and drain next pass.
        while True:
            with self._lock:
                pending, self._pending = self._pending, []
                if not pending:
                    self._sock = sock
                    closed = self._closed
                    break
            for data in pending:
                self._write_chunk(sock, data)
        if closed:
            with self._lock:
                self._sock = None
            sock.write(IOBuf(b"0\r\n\r\n"), ignore_eovercrowded=True)
            # graceful for the same reason as close() above
            sock.close_after_flush(errors.ECLOSE, "progressive response complete")
            sock._inuse_release()

    def _abort(self):
        """Handler failed/timed out before the response went out: the
        stream will never bind — writes must stop buffering and report
        the death instead of accumulating forever."""
        with self._lock:
            self._closed = True
            self._pending.clear()

    def __del__(self):
        # backstop for abandoned attachments (producer died without
        # close()): the reference's SocketUniquePtr releases in its
        # destructor; without this the bound socket's pool slot would
        # stay pinned forever
        try:
            self.close()
        except Exception:  # noqa: BLE001 — never raise from GC
            pass


# ---- server side -----------------------------------------------------------
def process_request(msg: HttpMessage, sock) -> None:
    server = sock.server
    if server is None:
        return
    if getattr(sock, "_http_exclusive_stream", False):
        # a progressive response owns this connection (its headers said
        # Connection: close); a request that raced in anyway must not
        # interleave a second response with the chunk stream
        return
    pa_holder = [None]
    try:
        status, body, ctype = _route(server, msg, sock, pa_holder)
    except Exception as e:  # noqa: BLE001
        log_error("http handler raised: %r", e)
        status, body, ctype = 500, f"internal error: {e}", "text/plain"
    pa = pa_holder[0]
    if pa is not None and status == 200:
        # progressive response: headers announce chunked + close (the
        # stream owns the connection from here), body follows as the
        # handler's producer writes into the attachment
        sock._http_exclusive_stream = True
        head = (
            f"HTTP/1.1 200 OK\r\nContent-Type: {ctype}\r\n"
            "Transfer-Encoding: chunked\r\nConnection: close\r\n\r\n"
        )
        sock.write(IOBuf(head.encode()), ignore_eovercrowded=True)
        pa._bind(sock)
        return
    want_close = (msg.header("connection", "") or "").lower() == "close"
    hdrs = {"Connection": "close"} if want_close else None
    sock.write(
        build_response(status, body, ctype, headers=hdrs), ignore_eovercrowded=True
    )
    if want_close:
        # graceful: the response queued above may still be in the
        # KeepWrite path after a partial write — close only once it
        # fully reaches the kernel (set_failed here truncated it)
        sock.close_after_flush(errors.ECLOSE, "connection: close requested")


def _route(server, msg: HttpMessage, sock, pa_holder=None) -> Tuple[int, object, str]:
    path = msg.path.rstrip("/") or "/"
    # 1. builtin services (exact or prefix match)
    handler = server.find_builtin_handler(path)
    if handler is not None:
        if not server.builtin_allowed():
            # internal_port is set: observability pages are reachable
            # only through it (server.cpp:1042-1080)
            return (
                403,
                "builtin services are served on the internal port only",
                "text/plain",
            )
        return handler(server, msg)
    # 2. restful pb service: /Service/Method
    parts = [p for p in path.split("/") if p]
    if len(parts) == 2:
        method = server.find_method(parts[0], parts[1])
        if method is None:
            return 404, f"no such method {parts[0]}.{parts[1]}", "text/plain"
        return _call_pb_method(server, method, msg, sock, pa_holder)
    return 404, f"no handler for {msg.path}", "text/plain"


def _trace_header_ids(msg: HttpMessage) -> Tuple[int, int]:
    """(trace_id, span_id) propagated via x-trace-id / x-span-id hex
    request headers — the HTTP carriage of what tpu_std rides in its
    RpcMeta, so HTTP and tpu_std calls join the same trace. Parsed
    independently: a mangled span id must not discard a valid trace
    id (the join would be lost)."""
    from incubator_brpc_tpu.observability.span import parse_trace_id

    try:
        tid = parse_trace_id(msg.header("x-trace-id", "0") or "0")
    except ValueError:
        tid = 0
    try:
        sid = parse_trace_id(msg.header("x-span-id", "0") or "0")
    except ValueError:
        sid = 0
    return tid, sid


def _call_pb_method(server, method, msg: HttpMessage, sock, pa_holder=None):
    from incubator_brpc_tpu.client.controller import Controller
    from incubator_brpc_tpu.observability.span import Span

    request = method.request_class()
    if len(msg.body):
        ok, err = json_to_proto(msg.body, request)
        if not ok:
            return 400, f"bad json request: {err}", "text/plain"
    elif msg.query:
        # query params map onto top-level string/int fields
        for k, v in msg.query.items():
            if request.DESCRIPTOR.fields_by_name.get(k) is not None:
                field = request.DESCRIPTOR.fields_by_name[k]
                try:
                    setattr(request, k, int(v) if field.cpp_type in (1, 2, 3, 4) else v)
                except (TypeError, ValueError):
                    pass
    ctrl = Controller()
    ctrl.server = server
    ctrl._server_socket = sock
    ctrl.remote_side = sock.remote
    tid, psid = _trace_header_ids(msg)
    span = Span.create_server(method.service_name, method.method_name, tid, psid)
    if span is not None:
        span.remote_side = str(sock.remote or "")
        span.request_size = len(msg.body)
        span.adopt_message_stamps(msg)
        ctrl._span = span
    response = method.response_class()
    status = server.method_status(method.full_name)
    # unified admission decision point (server/admission.py): tenant
    # identity rides the x-tpu-tenant header on HTTP
    tenant = msg.header("x-tpu-tenant", "") or ""
    verdict = server.admission.admit(method.full_name, status, tenant)
    if not verdict.admitted:
        if span is not None:
            span.end(verdict.code)
        return 503, f"[{verdict.code}] {verdict.reason}", "text/plain"
    if verdict.tier is not None:
        ctrl._admission_tier = verdict.tier
        ctrl._admission_ticket = verdict.ticket
    import threading
    import time as _time

    def _finish(code: int, body=b""):
        # HTTP responses are written by process_request after this
        # returns: response_write is the closest stampable point, and
        # the span closes here with the serialized body size
        ticket = ctrl.__dict__.pop("_admission_ticket", None)
        if ticket is not None:
            ticket.release()
        if span is not None:
            span.response_size = len(body)
            span.stamp("response_write_us")
            span.end(code)

    start = _time.monotonic_ns()
    ev = threading.Event()
    # server span scoped as task-local parent: nested calls the
    # handler makes join this trace (restored before the response)
    from incubator_brpc_tpu.observability.span import swap_current_span

    prev_parent = swap_current_span(span) if span is not None else None
    try:
        exc = server.run_user_method(method, ctrl, request, response, ev.set)
        finished = False if exc is not None else ev.wait(HANDLER_TIMEOUT_S)
    finally:
        if span is not None:
            swap_current_span(prev_parent)
    if span is not None:
        span.stamp("callback_done_us")
    latency_us = (_time.monotonic_ns() - start) // 1000
    if status is not None:
        # a timed-out handler is an error in the method stats even
        # though ctrl (still owned by the running handler) isn't failed
        status.on_response(latency_us, error=(not finished) or ctrl.failed())
    if finished:
        # per-tier observed latency (server/admission.py): feeds the
        # latency-fed auto limiter; no-op unless a tier was stamped
        from incubator_brpc_tpu.server import admission as _admission

        _admission.note_controller_latency(ctrl, latency_us)
    pa = ctrl._progressive_attachment
    if exc is not None:
        if pa is not None:
            pa._abort()
        _finish(errors.EINTERNAL)
        return 500, f"internal error: {exc}", "text/plain"
    if not finished:
        # handler never ran done within the budget: a half-built 200
        # would hand the client partial state as success (and it may
        # still be USING its session-local object — leak, don't pool)
        if pa is not None:
            pa._abort()  # never binding: stop the producer's buffering
        _finish(errors.ERPCTIMEDOUT)
        return 503, "handler timed out", "text/plain"
    ctrl._release_session_local()  # handler done: pool the user data
    if ctrl.failed():
        if pa is not None:
            pa._abort()
        _finish(ctrl.error_code)
        return 500, f"[{ctrl.error_code}] {ctrl.error_text()}", "text/plain"
    if pa is not None and pa_holder is not None:
        pa_holder[0] = pa
        _finish(0)
        return 200, b"", pa.content_type
    body = proto_to_json(response, pretty=True)
    _finish(0, body)
    return 200, body, "application/json"


# ---- client side -----------------------------------------------------------
def serialize_request(request, controller) -> IOBuf:
    if request is None:
        return IOBuf()
    return IOBuf(proto_to_json(request).encode())


def pack_request(request_buf: IOBuf, wire_cid: int, method_spec, controller) -> IOBuf:
    path = f"/{method_spec.service_name}/{method_spec.method_name}"
    body = IOBuf()
    body.append(request_buf)
    extra = None
    if controller._span is not None:
        # trace propagation over HTTP (x-trace-id/x-span-id): the
        # header form of tpu_std's RpcMeta trace fields, in the one
        # canonical printable form (span.format_trace_id)
        from incubator_brpc_tpu.observability.span import format_trace_id

        extra = {
            "x-trace-id": format_trace_id(controller._span.trace_id),
            "x-span-id": format_trace_id(controller._span.span_id),
        }
    tenant = controller.__dict__.get("tenant")
    if tenant:
        # tenant identity for server-side admission — the header form
        # of RpcRequestMeta.tenant (docs/overload.md); CR/LF would
        # smuggle headers into the wire
        if "\r" in tenant or "\n" in tenant:
            raise ValueError("tenant contains CR/LF")
        extra = dict(extra or {})
        extra["x-tpu-tenant"] = tenant
    channel = controller._channel
    auth = channel.options.auth if channel is not None else None
    if auth is not None:
        # raising fails the RPC at pack time (no silent anonymous send);
        # CR/LF in a credential would smuggle headers into the wire
        cred = auth.generate_credential()
        if cred:
            if "\r" in cred or "\n" in cred:
                raise ValueError("credential contains CR/LF")
            extra = dict(extra or {})
            extra["Authorization"] = cred
    packet = build_request("POST", path, body, headers=extra)
    # HTTP/1.1 matches responses by order: the FIFO entry registers
    # inside the write, atomically with the packet's queue position
    controller._pipelined_entries = [(wire_cid, 1)]
    return packet


def process_response(msg: HttpMessage, sock) -> None:
    with sock._write_lock:
        cid, _ = sock.pipelined_info.popleft() if sock.pipelined_info else (0, 0)
    if not cid:
        return
    pool = _id_pool()
    ctrl = pool.lock(cid)
    if ctrl is None:
        return
    if ctrl._span is not None:
        ctrl._span.adopt_message_stamps(msg)
    stream = msg.progressive_stream
    if stream is not None:
        # chunked response: the body follows this headers message
        if getattr(ctrl, "_read_progressively", False):
            # the RPC completes at the headers; the caller reads the
            # body via read_progressive_attachment (controller.h
            # response_will_be_read_progressively)
            ctrl._progressive_body = stream
            if msg.status != 200:
                ctrl.set_failed(errors.EHTTP, f"http status {msg.status}")
            ctrl._finalize_locked(cid)
            return
        # plain caller: buffer the chunks, finish the RPC at end-of-body
        status = msg.status
        parts = []

        def accumulate(part, cid=cid, status=status):
            if part is not None:
                parts.append(part)
                return
            c2 = pool.lock(cid)
            if c2 is None:  # timed out / canceled while streaming
                return
            body = b"".join(parts)
            if status != 200:
                c2.set_failed(errors.EHTTP, f"http status {status}: {body[:200]!r}")
            else:
                try:
                    if c2._response is not None and body:
                        ok, err = json_to_proto(IOBuf(body), c2._response)
                        if not ok:
                            c2.set_failed(
                                errors.ERESPONSE, f"bad json response: {err}"
                            )
                except Exception as e:  # noqa: BLE001
                    c2.set_failed(errors.ERESPONSE, repr(e))
            c2._finalize_locked(cid)

        pool.unlock(cid)  # reattached at end-of-body by `accumulate`
        stream.attach(accumulate)
        return
    if msg.status != 200:
        ctrl.set_failed(errors.EHTTP, f"http status {msg.status}: {msg.body.copy_to(200)!r}")
        ctrl._finalize_locked(cid)
        return
    try:
        if ctrl._response is not None and len(msg.body):
            ok, err = json_to_proto(msg.body, ctrl._response)
            if not ok:
                ctrl.set_failed(errors.ERESPONSE, f"bad json response: {err}")
    except Exception as e:  # noqa: BLE001
        ctrl.set_failed(errors.ERESPONSE, repr(e))
    ctrl._finalize_locked(cid)


def verify(msg: HttpMessage, sock) -> bool:
    """First-message auth (server authenticator): the Authorization
    header must verify. Requests on an unauthenticated connection are
    rejected by closing it (same as the reference's Verify path)."""
    server = sock.server
    auth = getattr(getattr(server, "options", None), "auth", None)
    if auth is None:
        return True
    if not msg.is_request:
        return True  # client side never verifies
    from incubator_brpc_tpu.protocols import _call_verify_credential

    rc, _ = _call_verify_credential(auth, msg.header("authorization", "") or "", sock)
    return rc == 0


PROTOCOL = Protocol(
    name="http",
    parse=parse,
    serialize_request=serialize_request,
    pack_request=pack_request,
    process_request=process_request,
    process_response=process_response,
    verify=verify,
    support_pipelined=True,
    # HTTP/1.1 has no correlation id: the client matches responses FIFO,
    # so one connection's requests must be processed (and answered) in
    # arrival order (round-1 advisor misroute fix)
    process_ordered=True,
)


def register():
    register_protocol(PROTOCOL)
