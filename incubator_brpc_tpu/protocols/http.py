"""HTTP/1.x protocol — restful RPC + builtin service pages.

Analog of reference policy/http_rpc_protocol.cpp (1,603 LoC) + the
http_parser/HttpHeader/URI stack (SURVEY.md §2.4 "HTTP stack"):
- Server side: pb services are exposed automatically as
  ``POST /ServiceName/MethodName`` with JSON bodies (json2pb), and
  builtin observability pages (/status /vars /flags ...) are served on
  the same port — the same-port-speaks-all-protocols inversion.
- Client side: channels with protocol="http" issue requests and match
  responses by arrival order on the connection (HTTP/1.1 has no
  correlation id; in-order matching is what the reference does for
  single connections).
"""

from __future__ import annotations

import struct
from typing import Dict, Optional, Tuple
from urllib.parse import parse_qs, unquote, urlsplit

from incubator_brpc_tpu import errors
from incubator_brpc_tpu.protocols import ParseResult, Protocol, register_protocol
from incubator_brpc_tpu.runtime.call_id import default_pool as _id_pool
from incubator_brpc_tpu.serialization.json2pb import json_to_proto, proto_to_json
from incubator_brpc_tpu.utils.iobuf import IOBuf
from incubator_brpc_tpu.utils.logging import log_error

_METHODS = (b"GET ", b"POST", b"PUT ", b"DELE", b"HEAD", b"PATC", b"OPTI")
_MAX_HEADER = 64 << 10
# budget for a pb handler to run its done callback before the request
# is answered 503 (tests shrink this to exercise the timeout path)
HANDLER_TIMEOUT_S = 30.0

HTTP_STATUS = {
    200: "OK",
    400: "Bad Request",
    403: "Forbidden",
    404: "Not Found",
    500: "Internal Server Error",
    501: "Not Implemented",
    503: "Service Unavailable",
}


class HttpMessage:
    """Parsed request or response (HttpHeader + body analog)."""

    __slots__ = (
        "is_request",
        "method",
        "path",
        "query",
        "status",
        "headers",
        "body",
        "version",
    )

    def __init__(self):
        self.is_request = True
        self.method = "GET"
        self.path = "/"
        self.query: Dict[str, str] = {}
        self.status = 200
        self.headers: Dict[str, str] = {}
        self.body = IOBuf()
        self.version = "HTTP/1.1"

    def header(self, name: str, default=None):
        return self.headers.get(name.lower(), default)


def parse(buf: IOBuf, sock, read_eof: bool) -> ParseResult:
    head = buf.fetch(min(len(buf), 8))
    if head is None or len(head) < 4:
        return ParseResult.not_enough() if _maybe_http(head or b"") else ParseResult.try_others()
    if not _maybe_http(head):
        return ParseResult.try_others()
    # find end of headers
    raw = buf.copy_to(min(len(buf), _MAX_HEADER))
    idx = raw.find(b"\r\n\r\n")
    if idx < 0:
        if len(raw) >= _MAX_HEADER:
            return ParseResult.bad()
        return ParseResult.not_enough()
    header_block = raw[:idx].decode("latin1")
    lines = header_block.split("\r\n")
    msg = HttpMessage()
    first = lines[0].split(" ", 2)
    if first[0].startswith("HTTP/"):
        msg.is_request = False
        msg.version = first[0]
        try:
            msg.status = int(first[1])
        except (IndexError, ValueError):
            return ParseResult.bad()
    else:
        if len(first) < 3:
            return ParseResult.bad()
        msg.method = first[0].upper()
        msg.version = first[2]
        parts = urlsplit(first[1])
        msg.path = unquote(parts.path) or "/"
        msg.query = {k: v[0] for k, v in parse_qs(parts.query).items()}
    for line in lines[1:]:
        k, _, v = line.partition(":")
        msg.headers[k.strip().lower()] = v.strip()
    body_len = int(msg.headers.get("content-length", "0") or 0)
    total = idx + 4 + body_len
    if len(buf) < total:
        return ParseResult.not_enough()
    buf.pop_front(idx + 4)
    buf.cutn(msg.body, body_len)
    return ParseResult.ok(msg)


def _maybe_http(head: bytes) -> bool:
    up = head[:4].upper()
    return up.startswith(b"HTTP") or any(up.startswith(m[: len(up)]) for m in _METHODS)


def build_response(
    status: int, body, content_type: str = "text/plain", headers: Optional[Dict] = None
) -> IOBuf:
    if isinstance(body, str):
        body = body.encode()
    body_buf = body if isinstance(body, IOBuf) else IOBuf(body)
    out = IOBuf()
    hdrs = {
        "Content-Type": content_type,
        "Content-Length": str(len(body_buf)),
        "Connection": "keep-alive",
    }
    if headers:
        hdrs.update(headers)
    head = f"HTTP/1.1 {status} {HTTP_STATUS.get(status, '')}\r\n"
    head += "".join(f"{k}: {v}\r\n" for k, v in hdrs.items())
    out.append(head + "\r\n")
    out.append(body_buf)
    return out


def build_request(
    method: str,
    path: str,
    body=b"",
    content_type="application/json",
    host="",
    headers: Optional[Dict] = None,
) -> IOBuf:
    body_buf = body if isinstance(body, IOBuf) else IOBuf(body)
    out = IOBuf()
    head = f"{method} {path} HTTP/1.1\r\n"
    head += f"Host: {host or 'tpubrpc'}\r\nContent-Type: {content_type}\r\n"
    head += f"Content-Length: {len(body_buf)}\r\nConnection: keep-alive\r\n"
    if headers:
        head += "".join(f"{k}: {v}\r\n" for k, v in headers.items())
    out.append(head + "\r\n")
    out.append(body_buf)
    return out


# ---- server side -----------------------------------------------------------
def process_request(msg: HttpMessage, sock) -> None:
    server = sock.server
    if server is None:
        return
    try:
        status, body, ctype = _route(server, msg, sock)
    except Exception as e:  # noqa: BLE001
        log_error("http handler raised: %r", e)
        status, body, ctype = 500, f"internal error: {e}", "text/plain"
    want_close = (msg.header("connection", "") or "").lower() == "close"
    hdrs = {"Connection": "close"} if want_close else None
    sock.write(
        build_response(status, body, ctype, headers=hdrs), ignore_eovercrowded=True
    )
    if want_close:
        sock.set_failed(errors.ECLOSE, "connection: close requested")


def _route(server, msg: HttpMessage, sock) -> Tuple[int, object, str]:
    path = msg.path.rstrip("/") or "/"
    # 1. builtin services (exact or prefix match)
    handler = server.find_builtin_handler(path)
    if handler is not None:
        if not server.builtin_allowed():
            # internal_port is set: observability pages are reachable
            # only through it (server.cpp:1042-1080)
            return (
                403,
                "builtin services are served on the internal port only",
                "text/plain",
            )
        return handler(server, msg)
    # 2. restful pb service: /Service/Method
    parts = [p for p in path.split("/") if p]
    if len(parts) == 2:
        method = server.find_method(parts[0], parts[1])
        if method is None:
            return 404, f"no such method {parts[0]}.{parts[1]}", "text/plain"
        return _call_pb_method(server, method, msg, sock)
    return 404, f"no handler for {msg.path}", "text/plain"


def _call_pb_method(server, method, msg: HttpMessage, sock):
    from incubator_brpc_tpu.client.controller import Controller

    request = method.request_class()
    if len(msg.body):
        ok, err = json_to_proto(msg.body, request)
        if not ok:
            return 400, f"bad json request: {err}", "text/plain"
    elif msg.query:
        # query params map onto top-level string/int fields
        for k, v in msg.query.items():
            if request.DESCRIPTOR.fields_by_name.get(k) is not None:
                field = request.DESCRIPTOR.fields_by_name[k]
                try:
                    setattr(request, k, int(v) if field.cpp_type in (1, 2, 3, 4) else v)
                except (TypeError, ValueError):
                    pass
    ctrl = Controller()
    ctrl.server = server
    ctrl._server_socket = sock
    ctrl.remote_side = sock.remote
    response = method.response_class()
    status = server.method_status(method.full_name)
    if status is not None and not status.on_requested():
        return 503, "concurrency limit reached", "text/plain"
    import threading
    import time as _time

    start = _time.monotonic_ns()
    ev = threading.Event()
    method.fn(ctrl, request, response, ev.set)
    finished = ev.wait(HANDLER_TIMEOUT_S)
    if status is not None:
        # a timed-out handler is an error in the method stats even
        # though ctrl (still owned by the running handler) isn't failed
        status.on_response(
            (_time.monotonic_ns() - start) // 1000,
            error=(not finished) or ctrl.failed(),
        )
    if not finished:
        # handler never ran done within the budget: a half-built 200
        # would hand the client partial state as success
        return 503, "handler timed out", "text/plain"
    if ctrl.failed():
        return 500, f"[{ctrl.error_code}] {ctrl.error_text()}", "text/plain"
    return 200, proto_to_json(response, pretty=True), "application/json"


# ---- client side -----------------------------------------------------------
def serialize_request(request, controller) -> IOBuf:
    if request is None:
        return IOBuf()
    return IOBuf(proto_to_json(request).encode())


def pack_request(request_buf: IOBuf, wire_cid: int, method_spec, controller) -> IOBuf:
    path = f"/{method_spec.service_name}/{method_spec.method_name}"
    body = IOBuf()
    body.append(request_buf)
    extra = None
    channel = controller._channel
    auth = channel.options.auth if channel is not None else None
    if auth is not None:
        # raising fails the RPC at pack time (no silent anonymous send);
        # CR/LF in a credential would smuggle headers into the wire
        cred = auth.generate_credential()
        if cred:
            if "\r" in cred or "\n" in cred:
                raise ValueError("credential contains CR/LF")
            extra = {"Authorization": cred}
    packet = build_request("POST", path, body, headers=extra)
    # HTTP/1.1 matches responses by order: the FIFO entry registers
    # inside the write, atomically with the packet's queue position
    controller._pipelined_entries = [(wire_cid, 1)]
    return packet


def process_response(msg: HttpMessage, sock) -> None:
    with sock._write_lock:
        cid, _ = sock.pipelined_info.popleft() if sock.pipelined_info else (0, 0)
    if not cid:
        return
    pool = _id_pool()
    ctrl = pool.lock(cid)
    if ctrl is None:
        return
    if msg.status != 200:
        ctrl.set_failed(errors.EHTTP, f"http status {msg.status}: {msg.body.copy_to(200)!r}")
        ctrl._finalize_locked(cid)
        return
    try:
        if ctrl._response is not None and len(msg.body):
            ok, err = json_to_proto(msg.body, ctrl._response)
            if not ok:
                ctrl.set_failed(errors.ERESPONSE, f"bad json response: {err}")
    except Exception as e:  # noqa: BLE001
        ctrl.set_failed(errors.ERESPONSE, repr(e))
    ctrl._finalize_locked(cid)


def verify(msg: HttpMessage, sock) -> bool:
    """First-message auth (server authenticator): the Authorization
    header must verify. Requests on an unauthenticated connection are
    rejected by closing it (same as the reference's Verify path)."""
    server = sock.server
    auth = getattr(getattr(server, "options", None), "auth", None)
    if auth is None:
        return True
    if not msg.is_request:
        return True  # client side never verifies
    from incubator_brpc_tpu.protocols import _call_verify_credential

    rc, _ = _call_verify_credential(auth, msg.header("authorization", "") or "", sock)
    return rc == 0


PROTOCOL = Protocol(
    name="http",
    parse=parse,
    serialize_request=serialize_request,
    pack_request=pack_request,
    process_request=process_request,
    process_response=process_response,
    verify=verify,
    support_pipelined=True,
    # HTTP/1.1 has no correlation id: the client matches responses FIFO,
    # so one connection's requests must be processed (and answered) in
    # arrival order (round-1 advisor misroute fix)
    process_ordered=True,
)


def register():
    register_protocol(PROTOCOL)
