"""Compression registry (reference src/brpc/compress.h:43 +
policy/gzip_compress.* / snappy_compress.*).

Handlers operate on IOBuf payloads; registered by type id matching the
reference's CompressType enum (options.proto): 0=none, 1=snappy,
2=gzip, 3=zlib. Snappy is gated on the optional python binding; the
always-available codecs are gzip/zlib via stdlib.
"""

from __future__ import annotations

import gzip as _gzip
import zlib as _zlib
from typing import Callable, Dict, Optional, Tuple

from incubator_brpc_tpu.utils.iobuf import IOBuf

COMPRESS_TYPE_NONE = 0
COMPRESS_TYPE_SNAPPY = 1
COMPRESS_TYPE_GZIP = 2
COMPRESS_TYPE_ZLIB = 3

# name → (type id), for ChannelOptions string configs
_BY_NAME = {
    "none": COMPRESS_TYPE_NONE,
    "snappy": COMPRESS_TYPE_SNAPPY,
    "gzip": COMPRESS_TYPE_GZIP,
    "zlib": COMPRESS_TYPE_ZLIB,
}

_handlers: Dict[int, Tuple[Callable, Callable]] = {}


def register_compress_handler(ctype: int, compress: Callable, decompress: Callable):
    """Analog of RegisterCompressHandler (compress.h:43)."""
    _handlers[ctype] = (compress, decompress)


def compress(buf: IOBuf, ctype: int) -> Optional[IOBuf]:
    if ctype == COMPRESS_TYPE_NONE:
        return buf
    h = _handlers.get(ctype)
    if h is None:
        return None
    return h[0](buf)


def decompress(buf: IOBuf, ctype: int) -> Optional[IOBuf]:
    if ctype == COMPRESS_TYPE_NONE:
        return buf
    h = _handlers.get(ctype)
    if h is None:
        return None
    return h[1](buf)


def compress_type_by_name(name: str) -> int:
    return _BY_NAME.get(name.lower(), COMPRESS_TYPE_NONE)


# ---- built-in handlers -----------------------------------------------------

register_compress_handler(
    COMPRESS_TYPE_GZIP,
    lambda b: IOBuf(_gzip.compress(b.to_bytes())),
    lambda b: IOBuf(_gzip.decompress(b.to_bytes())),
)
register_compress_handler(
    COMPRESS_TYPE_ZLIB,
    lambda b: IOBuf(_zlib.compress(b.to_bytes())),
    lambda b: IOBuf(_zlib.decompress(b.to_bytes())),
)

try:  # optional dependency; reference vendors snappy in butil/third_party
    import snappy as _snappy  # type: ignore

    register_compress_handler(
        COMPRESS_TYPE_SNAPPY,
        lambda b: IOBuf(_snappy.compress(b.to_bytes())),
        lambda b: IOBuf(_snappy.decompress(b.to_bytes())),
    )
except ImportError:
    pass
