"""HTTP/2 + gRPC — framed, multiplexed RPC on one connection.

Analog of reference policy/http2_rpc_protocol.cpp (1,835 LoC client+
server) with gRPC semantics from grpc.{h,cpp} (grpc-timeout parsing,
grpc-status mapping). Framing per RFC 7540: SETTINGS / HEADERS /
CONTINUATION / DATA / RST_STREAM / WINDOW_UPDATE / PING / GOAWAY, with
connection + per-stream flow-control windows. Header blocks ride HPACK
(protocols/hpack.py) — one encoder and one decoder per connection, so
all sends serialize under the connection's send lock.

gRPC mapping: request = HEADERS(:method POST, :path /Service/Method,
content-type application/grpc, grpc-timeout) + DATA(1-byte compress
flag + u32 BE length + payload pb); response = HEADERS(:status 200) +
DATA + trailers HEADERS(grpc-status, grpc-message). One server port
speaks h2 alongside tpu_std/http: the parser claims the connection on
the h2 client preface magic.
"""

from __future__ import annotations

import struct
import threading
from typing import Dict, List, Optional, Tuple

from incubator_brpc_tpu import errors
from incubator_brpc_tpu.protocols import ParseResult, Protocol, register_protocol
from incubator_brpc_tpu.protocols.hpack import HpackDecoder, HpackEncoder
from incubator_brpc_tpu.runtime.call_id import default_pool as _id_pool
from incubator_brpc_tpu.utils.iobuf import IOBuf
from incubator_brpc_tpu.utils.logging import log_error, log_verbose

PREFACE = b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n"

# frame types (RFC 7540 §6)
DATA = 0x0
HEADERS = 0x1
PRIORITY = 0x2
RST_STREAM = 0x3
SETTINGS = 0x4
PUSH_PROMISE = 0x5
PING = 0x6
GOAWAY = 0x7
WINDOW_UPDATE = 0x8
CONTINUATION = 0x9

# flags
FLAG_END_STREAM = 0x1  # DATA/HEADERS
FLAG_ACK = 0x1  # SETTINGS/PING
FLAG_END_HEADERS = 0x4
FLAG_PADDED = 0x8
FLAG_PRIORITY = 0x20

# settings ids
SETTINGS_HEADER_TABLE_SIZE = 0x1
SETTINGS_MAX_CONCURRENT_STREAMS = 0x3
SETTINGS_INITIAL_WINDOW_SIZE = 0x4
SETTINGS_MAX_FRAME_SIZE = 0x5

DEFAULT_WINDOW = 65535
DEFAULT_FRAME_SIZE = 16384
# we advertise (and replenish to) a large receive window: RPC payloads
# are bulk tensors, not browser streams
RECV_WINDOW = 1 << 24
# streams we accept concurrently per connection (advertised + enforced)
MAX_CONCURRENT_STREAMS = 128
# RST_STREAM error codes (RFC 7540 §7)
H2_REFUSED_STREAM = 0x7

# gRPC status codes (subset used for mapping)
GRPC_OK = 0
GRPC_UNKNOWN = 2
GRPC_DEADLINE_EXCEEDED = 4
GRPC_NOT_FOUND = 5
GRPC_RESOURCE_EXHAUSTED = 8
GRPC_OUT_OF_RANGE = 11
GRPC_UNIMPLEMENTED = 12
GRPC_UNAVAILABLE = 14
GRPC_UNAUTHENTICATED = 16


def _grpc_status_of(error_code: int) -> int:
    return {
        0: GRPC_OK,
        errors.ERPCTIMEDOUT: GRPC_DEADLINE_EXCEEDED,
        errors.ENOSERVICE: GRPC_UNIMPLEMENTED,
        errors.ENOMETHOD: GRPC_UNIMPLEMENTED,
        # the drop-vs-retry split (docs/overload.md) must survive the
        # h2 hop: ELIMIT ("request expired while queued — drop") rides
        # OUT_OF_RANGE so it cannot collapse into the retriable
        # RESOURCE_EXHAUSTED that EOVERCROWDED sheds use
        errors.ELIMIT: GRPC_OUT_OF_RANGE,
        errors.EOVERCROWDED: GRPC_RESOURCE_EXHAUSTED,
        errors.ELOGOFF: GRPC_UNAVAILABLE,
        errors.ERPCAUTH: GRPC_UNAUTHENTICATED,
    }.get(error_code, GRPC_UNKNOWN)


def _error_of_grpc(status: int) -> int:
    return {
        GRPC_OK: 0,
        GRPC_DEADLINE_EXCEEDED: errors.ERPCTIMEDOUT,
        GRPC_UNIMPLEMENTED: errors.ENOMETHOD,
        # RESOURCE_EXHAUSTED is what the server sends for ADMISSION
        # sheds: decode as EOVERCROWDED (retry elsewhere —
        # docs/overload.md code mapping), not ELIMIT (drop) — mapping
        # it to the drop code would make grpc overload rejections
        # non-retriable while tpu_std's reissue against another replica
        GRPC_RESOURCE_EXHAUSTED: errors.EOVERCROWDED,
        GRPC_OUT_OF_RANGE: errors.ELIMIT,
        GRPC_UNAVAILABLE: errors.ELOGOFF,
        GRPC_UNAUTHENTICATED: errors.ERPCAUTH,
    }.get(status, errors.ERESPONSE)


def pack_frame(ftype: int, flags: int, stream_id: int, payload: bytes = b"") -> bytes:
    return (
        struct.pack(">I", len(payload))[1:]
        + bytes((ftype, flags))
        + struct.pack(">I", stream_id & 0x7FFFFFFF)
        + payload
    )


class H2Stream:
    __slots__ = (
        "sid", "headers", "trailers", "data", "end_stream", "cid",
        "send_window", "pending_out", "sent_end", "pending_trailers",
    )

    def __init__(self, sid: int, initial_window: int):
        self.sid = sid
        self.headers: Optional[List[Tuple[str, str]]] = None
        self.trailers: Optional[List[Tuple[str, str]]] = None
        self.data = IOBuf()
        self.end_stream = False
        self.cid = 0  # client-side correlation
        self.send_window = initial_window
        self.pending_out = IOBuf()  # DATA bytes waiting for window
        self.sent_end = False
        # trailers to emit AFTER pending_out fully drains: sending them
        # eagerly while DATA is parked on flow control would truncate
        # the response (trailers-before-data) — encoded lazily at drain
        # time so HPACK order equals wire order
        self.pending_trailers: Optional[List[Tuple[str, str]]] = None


class H2Context:
    """Per-connection HTTP/2 state (the reference's H2Context on
    Socket::parsing_context)."""

    def __init__(self, sock, is_server: bool):
        self.sock = sock
        self.is_server = is_server
        self.encoder = HpackEncoder()
        self.decoder = HpackDecoder()
        self.send_lock = threading.RLock()  # orders HPACK encode + write
        self.streams: Dict[int, H2Stream] = {}
        self.next_stream_id = 1 if not is_server else 2
        self.peer_frame_size = DEFAULT_FRAME_SIZE
        self.peer_initial_window = DEFAULT_WINDOW
        self.peer_max_streams = 1 << 30  # until peer's SETTINGS says less
        self.max_concurrent_streams = MAX_CONCURRENT_STREAMS  # we enforce
        self.conn_send_window = DEFAULT_WINDOW
        self.conn_recv_consumed = 0
        self.goaway_received = False
        self.preface_sent = False
        self.settings_sent = False
        # header-block assembly (HEADERS + CONTINUATION*)
        self.assembling_sid = 0
        self.assembling = b""
        self.assembling_flags = 0
        self.goaway_sent = False

    # ---- sending ------------------------------------------------------------
    def ensure_preface(self):
        """Client magic + both sides' initial SETTINGS (first use)."""
        out = b""
        if not self.is_server and not self.preface_sent:
            self.preface_sent = True
            out += PREFACE
        if not self.settings_sent:
            self.settings_sent = True
            out += pack_frame(
                SETTINGS,
                0,
                0,
                struct.pack(">HI", SETTINGS_INITIAL_WINDOW_SIZE, RECV_WINDOW)
                + struct.pack(">HI", SETTINGS_MAX_FRAME_SIZE, DEFAULT_FRAME_SIZE)
                + struct.pack(
                    ">HI", SETTINGS_MAX_CONCURRENT_STREAMS, self.max_concurrent_streams
                ),
            )
            # grow the connection-level receive window
            out += pack_frame(
                WINDOW_UPDATE, 0, 0, struct.pack(">I", RECV_WINDOW - DEFAULT_WINDOW)
            )
        return out

    def send_headers(
        self, sid: int, headers: List[Tuple[str, str]], end_stream: bool
    ) -> bytes:
        block = self.encoder.encode(headers)
        flags = FLAG_END_HEADERS | (FLAG_END_STREAM if end_stream else 0)
        return pack_frame(HEADERS, flags, sid, block)

    def data_frames(self, stream: H2Stream, data: IOBuf, end_stream: bool) -> bytes:
        """Chunk DATA to frame-size and available windows; excess parks
        in stream.pending_out (drained by WINDOW_UPDATE)."""
        stream.pending_out.append(data)
        if end_stream:
            stream.sent_end = True
        return self._drain_stream(stream)

    def _drain_stream(self, stream: H2Stream) -> bytes:
        out = b""
        while not stream.pending_out.empty():
            budget = min(
                self.peer_frame_size, stream.send_window, self.conn_send_window
            )
            if budget <= 0:
                return out
            chunk = IOBuf()
            stream.pending_out.cutn(chunk, budget)
            n = len(chunk)
            stream.send_window -= n
            self.conn_send_window -= n
            last = (
                stream.pending_out.empty()
                and stream.sent_end
                and stream.pending_trailers is None
            )
            out += pack_frame(
                DATA, FLAG_END_STREAM if last else 0, stream.sid, chunk.to_bytes()
            )
        if stream.pending_out.empty() and stream.pending_trailers is not None:
            # all DATA flushed: NOW the trailers may go (encoding here,
            # under send_lock, keeps HPACK order == wire order) and the
            # stream may leave the table (WINDOW_UPDATE no longer needed)
            trailers = stream.pending_trailers
            stream.pending_trailers = None
            out += self.send_headers(stream.sid, trailers, end_stream=True)
            self.streams.pop(stream.sid, None)
        return out

    def drain_all(self) -> bytes:
        out = b""
        for stream in list(self.streams.values()):
            if not stream.pending_out.empty():
                out += self._drain_stream(stream)
        return out

    def write(self, payload: bytes) -> int:
        if not payload:
            return 0
        return self.sock.write(IOBuf(payload), ignore_eovercrowded=True)


_ctx_create_lock = threading.Lock()


def _ctx(sock, is_server: bool) -> H2Context:
    ctx = getattr(sock, "h2_ctx", None)
    if ctx is None:
        with _ctx_create_lock:
            ctx = getattr(sock, "h2_ctx", None)
            if ctx is None:
                ctx = H2Context(sock, is_server)
                sock.h2_ctx = ctx
    return ctx


# ---- parse (both sides) -----------------------------------------------------
class H2Frame:
    __slots__ = ("ftype", "flags", "sid", "payload")

    def __init__(self, ftype, flags, sid, payload):
        self.ftype = ftype
        self.flags = flags
        self.sid = sid
        self.payload = payload


def parse(buf: IOBuf, sock, read_eof: bool) -> ParseResult:
    ctx = getattr(sock, "h2_ctx", None)
    if ctx is None:
        if not sock.is_server_side:
            return ParseResult.try_others()
        # server: claim the connection iff it opens with the h2 preface
        head = buf.fetch(min(len(buf), len(PREFACE)))
        if head is None or not PREFACE.startswith(head):
            return ParseResult.try_others()
        if len(head) < len(PREFACE):
            return ParseResult.not_enough()
        buf.pop_front(len(PREFACE))
        ctx = _ctx(sock, is_server=True)
        with ctx.send_lock:
            ctx.write(ctx.ensure_preface())
    header = buf.fetch(9)
    if header is None:
        return ParseResult.not_enough()
    length = int.from_bytes(header[:3], "big")
    if length > (1 << 24) - 1:
        return ParseResult.bad()
    if len(buf) < 9 + length:
        return ParseResult.not_enough()
    buf.pop_front(9)
    payload = buf.cut_bytes(length)
    ftype, flags = header[3], header[4]
    sid = struct.unpack(">I", header[5:9])[0] & 0x7FFFFFFF
    return ParseResult.ok(H2Frame(ftype, flags, sid, payload))


# ---- frame processing (in place — frames are ordered) ----------------------
def process_frame(frame: H2Frame, sock) -> None:
    ctx = getattr(sock, "h2_ctx", None)
    if ctx is None:
        return
    try:
        _process_frame(ctx, frame, sock)
    except Exception as e:  # noqa: BLE001
        log_error("h2 frame processing failed: %r", e)
        sock.set_failed(errors.EREQUEST, f"h2 error: {e}")


def _process_frame(ctx: H2Context, frame: H2Frame, sock) -> None:
    ftype = frame.ftype
    if ctx.assembling_sid and ftype != CONTINUATION:
        sock.set_failed(errors.EREQUEST, "expected CONTINUATION")
        return
    if ftype == SETTINGS:
        _on_settings(ctx, frame)
    elif ftype in (HEADERS, CONTINUATION):
        _on_headers(ctx, frame, sock)
    elif ftype == DATA:
        _on_data(ctx, frame, sock)
    elif ftype == WINDOW_UPDATE:
        if len(frame.payload) == 4:
            inc = struct.unpack(">I", frame.payload)[0] & 0x7FFFFFFF
            with ctx.send_lock:
                if frame.sid == 0:
                    ctx.conn_send_window += inc
                else:
                    stream = ctx.streams.get(frame.sid)
                    if stream is not None:
                        stream.send_window += inc
                ctx.write(ctx.drain_all())
    elif ftype == RST_STREAM:
        code = struct.unpack(">I", frame.payload)[0] if len(frame.payload) == 4 else 0
        _on_rst(ctx, frame.sid, code)
    elif ftype == PING:
        if not frame.flags & FLAG_ACK:
            with ctx.send_lock:
                ctx.write(pack_frame(PING, FLAG_ACK, 0, frame.payload))
    elif ftype == GOAWAY:
        _on_goaway(ctx, frame, sock)
    elif ftype in (PRIORITY, PUSH_PROMISE):
        pass  # tolerated, unused
    else:
        log_verbose("h2: ignoring unknown frame type %d", ftype)


def _on_settings(ctx: H2Context, frame: H2Frame) -> None:
    if frame.flags & FLAG_ACK:
        return
    payload = frame.payload
    # apply under send_lock: send_window/encoder state is concurrently
    # read-modify-written by _drain_stream on writer threads
    with ctx.send_lock:
        for off in range(0, len(payload) - 5, 6):
            ident, value = struct.unpack_from(">HI", payload, off)
            if ident == SETTINGS_MAX_FRAME_SIZE:
                ctx.peer_frame_size = max(DEFAULT_FRAME_SIZE, min(value, 1 << 24))
            elif ident == SETTINGS_INITIAL_WINDOW_SIZE:
                delta = value - ctx.peer_initial_window
                ctx.peer_initial_window = value
                for stream in ctx.streams.values():
                    stream.send_window += delta
            elif ident == SETTINGS_HEADER_TABLE_SIZE:
                ctx.encoder.set_max_table_size(value)
            elif ident == SETTINGS_MAX_CONCURRENT_STREAMS:
                ctx.peer_max_streams = value
        ctx.write(ctx.ensure_preface() + pack_frame(SETTINGS, FLAG_ACK, 0))


def _strip_padding_priority(frame: H2Frame) -> bytes:
    payload = frame.payload
    if frame.flags & FLAG_PADDED:
        pad = payload[0]
        payload = payload[1 : len(payload) - pad]
    if frame.ftype == HEADERS and frame.flags & FLAG_PRIORITY:
        payload = payload[5:]
    return payload


def _on_headers(ctx: H2Context, frame: H2Frame, sock) -> None:
    if frame.ftype == HEADERS:
        ctx.assembling_sid = frame.sid
        ctx.assembling = _strip_padding_priority(frame)
        ctx.assembling_flags = frame.flags
    else:  # CONTINUATION
        if frame.sid != ctx.assembling_sid:
            sock.set_failed(errors.EREQUEST, "CONTINUATION stream mismatch")
            return
        ctx.assembling += frame.payload
        ctx.assembling_flags |= frame.flags & FLAG_END_HEADERS
    if not ctx.assembling_flags & FLAG_END_HEADERS:
        return
    sid = ctx.assembling_sid
    block, flags = ctx.assembling, ctx.assembling_flags
    ctx.assembling_sid, ctx.assembling = 0, b""
    headers = ctx.decoder.decode(block)
    stream = ctx.streams.get(sid)
    if stream is None:
        if ctx.is_server and len(ctx.streams) >= ctx.max_concurrent_streams:
            # enforce our advertised SETTINGS_MAX_CONCURRENT_STREAMS:
            # refuse (retriable) instead of queueing unbounded work
            with ctx.send_lock:
                ctx.write(
                    pack_frame(
                        RST_STREAM, 0, sid, struct.pack(">I", H2_REFUSED_STREAM)
                    )
                )
            return
        stream = H2Stream(sid, ctx.peer_initial_window)
        ctx.streams[sid] = stream
    if stream.headers is None:
        stream.headers = headers
    else:
        stream.trailers = headers
    if flags & FLAG_END_STREAM:
        stream.end_stream = True
        _on_stream_complete(ctx, stream, sock)


def _on_data(ctx: H2Context, frame: H2Frame, sock) -> None:
    stream = ctx.streams.get(frame.sid)
    payload = _strip_padding_priority(frame)
    n = len(frame.payload)
    if stream is None:
        # DATA racing a local RST/completed stream still consumed
        # connection window: replenish it or the peer's view of the
        # connection send window leaks by n per orphan frame
        if n:
            with ctx.send_lock:
                ctx.write(pack_frame(WINDOW_UPDATE, 0, 0, struct.pack(">I", n)))
        return
    stream.data.append(payload)
    # replenish receive windows eagerly (bulk-RPC profile)
    if n:
        with ctx.send_lock:
            ctx.write(
                pack_frame(WINDOW_UPDATE, 0, 0, struct.pack(">I", n))
                + pack_frame(WINDOW_UPDATE, 0, frame.sid, struct.pack(">I", n))
            )
    if frame.flags & FLAG_END_STREAM:
        stream.end_stream = True
        _on_stream_complete(ctx, stream, sock)


def _on_rst(ctx: H2Context, sid: int, code: int) -> None:
    stream = ctx.streams.pop(sid, None)
    if stream is None:
        return
    if not ctx.is_server and stream.cid:
        _id_pool().error(
            stream.cid, errors.ECLOSE, f"h2 stream reset (code {code})"
        )
    _finish_goaway_drain(ctx)


def _on_goaway(ctx: H2Context, frame: H2Frame, sock) -> None:
    """Graceful GOAWAY (RFC 7540 §6.8): streams the peer promises to
    process (sid <= last_stream_id) keep running; only streams above it
    fail (retriable — they were provably unprocessed). The connection
    drains and dies when the survivors complete."""
    last_sid = (
        struct.unpack(">I", frame.payload[:4])[0] & 0x7FFFFFFF
        if len(frame.payload) >= 4
        else 0
    )
    # flag + sweep under send_lock: issue() checks goaway_received under
    # the same lock, so no new stream can slip between the check and the
    # sweep (it either sees the flag and refuses, or is already in
    # ctx.streams when the sweep runs)
    victims = []
    with ctx.send_lock:
        ctx.goaway_received = True
        sock.draining = True  # SocketMap stops handing this connection out
        if not ctx.is_server:
            for sid in list(ctx.streams):
                if sid > last_sid:
                    stream = ctx.streams.pop(sid, None)
                    if stream is not None and stream.cid:
                        victims.append(stream.cid)
    for cid in victims:
        _id_pool().error(cid, errors.EFAILEDSOCKET, "h2 GOAWAY refused stream")
    _finish_goaway_drain(ctx)


def _finish_goaway_drain(ctx: H2Context) -> None:
    if ctx.goaway_received and not ctx.streams and not ctx.sock.failed:
        ctx.sock.set_failed(errors.ECLOSE, "h2 connection drained after GOAWAY")


def send_goaway(sock) -> None:
    """Server-initiated graceful shutdown notice on an h2 connection."""
    ctx = getattr(sock, "h2_ctx", None)
    if ctx is None or ctx.goaway_sent:
        return
    ctx.goaway_sent = True
    last = max((sid for sid in ctx.streams), default=0)
    with ctx.send_lock:
        ctx.write(pack_frame(GOAWAY, 0, 0, struct.pack(">II", last, 0)))


# ---- gRPC message framing ---------------------------------------------------
def _grpc_wrap(payload: IOBuf) -> IOBuf:
    out = IOBuf(struct.pack(">BI", 0, len(payload)))
    out.append(payload)
    return out


def _grpc_unwrap(data: IOBuf) -> Optional[bytes]:
    if len(data) < 5:
        return b"" if len(data) == 0 else None
    head = data.cut_bytes(5)
    flag, length = struct.unpack(">BI", head)
    if flag & 1:
        return None  # compressed grpc messages unsupported (no codec negotiated)
    body = data.cut_bytes(length)
    return body if len(body) == length else None


def _header(headers: List[Tuple[str, str]], name: str, default: str = "") -> str:
    for n, v in headers:
        if n == name:
            return v
    return default


def _grpc_timeout_value(timeout_ms) -> str:
    return f"{max(1, int(timeout_ms))}m"


def _parse_grpc_timeout(value: str) -> Optional[int]:
    """→ milliseconds (reference grpc.cpp ParseTimeoutFromHeader)."""
    if not value:
        return None
    unit = value[-1]
    try:
        n = int(value[:-1])
    except ValueError:
        return None
    scale = {"H": 3600000, "M": 60000, "S": 1000, "m": 1, "u": 0.001, "n": 1e-6}
    if unit not in scale:
        return None
    return max(1, int(n * scale[unit]))


# ---- client side ------------------------------------------------------------
def serialize_request(request, controller) -> IOBuf:
    return IOBuf(request.SerializeToString())


def issue(sock, request_buf: IOBuf, wire_cid: int, method_spec, controller) -> None:
    """Pack + write one gRPC request atomically on the connection
    (HPACK encode order must equal wire order)."""
    ctx = _ctx(sock, is_server=False)
    path = f"/{method_spec.service_name}/{method_spec.method_name}"
    authority = str(sock.remote or "host")
    headers = [
        (":method", "POST"),
        (":scheme", "http"),
        (":path", path),
        (":authority", authority),
        ("content-type", "application/grpc"),
        ("te", "trailers"),
    ]
    if controller.timeout_ms:
        headers.append(("grpc-timeout", _grpc_timeout_value(controller.timeout_ms)))
    tenant = controller.__dict__.get("tenant")
    if tenant:
        # tenant identity for server-side admission (docs/overload.md)
        headers.append(("x-tpu-tenant", tenant))
    channel = controller._channel
    auth = channel.options.auth if channel is not None else None
    if auth is not None:
        cred = auth.generate_credential()  # raising fails the RPC (issue_rpc)
        if cred:
            if "\r" in cred or "\n" in cred:
                raise ValueError("credential contains CR/LF")
            headers.append(("authorization", cred))
    body = _grpc_wrap(request_buf)
    with ctx.send_lock:
        if ctx.goaway_received:
            _id_pool().error(
                wire_cid, errors.EFAILEDSOCKET, "h2 connection is draining (GOAWAY)"
            )
            return
        if len(ctx.streams) >= ctx.peer_max_streams:
            # peer's SETTINGS_MAX_CONCURRENT_STREAMS reached: backpressure
            _id_pool().error(
                wire_cid, errors.EOVERCROWDED, "h2 peer max_concurrent_streams"
            )
            return
        out = ctx.ensure_preface()
        sid = ctx.next_stream_id
        ctx.next_stream_id += 2
        stream = H2Stream(sid, ctx.peer_initial_window)
        stream.cid = wire_cid
        ctx.streams[sid] = stream
        sock.add_response_waiter(wire_cid)
        out += ctx.send_headers(sid, headers, end_stream=False)
        out += ctx.data_frames(stream, body, end_stream=True)
        rc = ctx.write(out)
    if rc:
        _id_pool().error(wire_cid, rc, "h2 write failed")


def _complete_client_stream(ctx: H2Context, stream: H2Stream, sock) -> None:
    ctx.streams.pop(stream.sid, None)
    cid = stream.cid
    if cid:
        # remove the waiter BEFORE the goaway drain check: the drain's
        # set_failed sweeps waiting_cids, and erroring this cid would
        # discard the response we are holding (retry of a done RPC)
        sock.remove_response_waiter(cid)
    _finish_goaway_drain(ctx)
    if cid:
        _deliver_client_stream(ctx, stream, sock, cid)


def _deliver_client_stream(ctx: H2Context, stream: H2Stream, sock, cid) -> None:
    from incubator_brpc_tpu.transport.event_dispatcher import in_dispatcher

    pool = _id_pool()
    if in_dispatcher():
        # never block the event loop on a contended id (timeout/retry
        # handlers hold it briefly): re-dispatch to a worker — a stall
        # here would freeze every socket on this dispatcher
        ctrl = pool.try_lock(cid)
        if ctrl is type(pool).BUSY:
            from incubator_brpc_tpu.runtime import scheduler

            scheduler.spawn(_deliver_client_stream, ctx, stream, sock, cid)
            return
    else:
        ctrl = pool.lock(cid)
    if ctrl is None:
        return
    headers = stream.headers or []
    trailers = stream.trailers if stream.trailers is not None else headers
    status = _header(headers, ":status", "200")
    grpc_status = _header(trailers, "grpc-status", "")
    grpc_message = _header(trailers, "grpc-message", "")
    if status != "200":
        ctrl.set_failed(errors.EHTTP, f"h2 :status {status}")
        ctrl._finalize_locked(cid)
        return
    if grpc_status not in ("", "0"):
        # a malformed grpc-status fails THIS rpc, not the connection
        try:
            mapped = _error_of_grpc(int(grpc_status))
        except ValueError:
            mapped = errors.ERESPONSE
            grpc_message = grpc_message or f"malformed grpc-status {grpc_status!r}"
        # server-returned retriable codes (an EOVERCROWDED admission
        # shed decoded from RESOURCE_EXHAUSTED) re-enter the same
        # retry arbitration as on tpu_std: the shedding replica joins
        # the exclusion set and the reissue lands elsewhere
        ctrl._error_from_server = True
        if mapped not in (
            errors.ERPCTIMEDOUT, errors.ECANCELED, errors.ERESPONSE
        ) and ctrl._try_retry_locked(
            cid, mapped, grpc_message or f"grpc-status {grpc_status}"
        ):
            return
        ctrl.set_failed(mapped, grpc_message or f"grpc-status {grpc_status}")
        ctrl._finalize_locked(cid)
        return
    body = _grpc_unwrap(stream.data)
    if body is None:
        ctrl.set_failed(errors.ERESPONSE, "bad grpc message framing")
        ctrl._finalize_locked(cid)
        return
    try:
        if ctrl._response is not None:
            ctrl._response.ParseFromString(body)
    except Exception as e:  # noqa: BLE001
        ctrl.set_failed(errors.ERESPONSE, f"parse response failed: {e}")
    ctrl._finalize_locked(cid)


# ---- server side ------------------------------------------------------------
def _on_stream_complete(ctx: H2Context, stream: H2Stream, sock) -> None:
    if ctx.is_server:
        # user code runs OFF the connection's ordered frame loop: one
        # slow handler must not stall the other streams multiplexed on
        # this connection (reference dispatches each stream to a
        # bthread, policy/http2_rpc_protocol.cpp). The in-use hold pins
        # the socket object until the handler's response is written.
        if sock._inuse_acquire():
            from incubator_brpc_tpu.runtime import scheduler

            scheduler.spawn(_run_server_stream, ctx, stream, sock)
    else:
        _complete_client_stream(ctx, stream, sock)


def _run_server_stream(ctx: H2Context, stream: H2Stream, sock) -> None:
    try:
        _process_server_stream(ctx, stream, sock)
    finally:
        sock._inuse_release()


def _respond(ctx: H2Context, sid: int, grpc_status: int, message: str, body: Optional[IOBuf]) -> None:
    with ctx.send_lock:
        stream = ctx.streams.get(sid)
        if stream is None:
            # the peer RST the stream while the handler ran (server
            # streams stay registered until responded): drop the
            # response BEFORE any HPACK encode — encoding mutates the
            # connection's dynamic table, and a discarded block would
            # desynchronize the peer's decoder for good. Resurrecting
            # the entry would also park it forever (no WINDOW_UPDATE
            # comes for a reset stream).
            return
        out = ctx.send_headers(
            sid,
            [(":status", "200"), ("content-type", "application/grpc")],
            end_stream=False,
        )
        # the stream stays registered until its DATA fully drains, so a
        # flow-control-parked body is still reachable by WINDOW_UPDATE;
        # the trailers are parked with it and emitted strictly after the
        # last DATA frame (trailers-before-data truncated big responses)
        if body is not None and grpc_status == GRPC_OK:
            stream.pending_out.append(_grpc_wrap(body))
        stream.sent_end = True
        trailers = [("grpc-status", str(grpc_status))]
        if message:
            trailers.append(("grpc-message", message))
        stream.pending_trailers = trailers
        out += ctx._drain_stream(stream)
        ctx.write(out)


def _process_server_stream(ctx: H2Context, stream: H2Stream, sock) -> None:
    from incubator_brpc_tpu.client.controller import Controller

    headers = stream.headers or []
    path = _header(headers, ":path")
    server = sock.server
    sid = stream.sid
    parts = path.strip("/").split("/")
    if server is None or not server.is_running():
        return _respond(ctx, sid, GRPC_UNAVAILABLE, "server stopped", None)
    if len(parts) != 2:
        return _respond(ctx, sid, GRPC_UNIMPLEMENTED, f"bad path {path!r}", None)
    service_name, method_name = parts
    # h2 has no framing-level first message to verify (the first frame
    # is SETTINGS), so auth rides the request headers per stream —
    # Protocol.auth_in_protocol exempts h2 from the first-message gate.
    # The context stays per-request (attached to the controller below):
    # concurrent streams may carry different identities, so the shared
    # socket must not hold any one of them.
    auth_ctx = None
    auth = getattr(getattr(server, "options", None), "auth", None)
    if auth is not None:
        from incubator_brpc_tpu.protocols import _call_verify_credential

        rc, auth_ctx = _call_verify_credential(
            auth, _header(headers, "authorization", ""), sock, attach_to_sock=False
        )
        if rc != 0:
            return _respond(ctx, sid, GRPC_UNAUTHENTICATED, "authentication failed", None)
    method = server.find_method(service_name, method_name)
    if method is None:
        return _respond(ctx, sid, GRPC_UNIMPLEMENTED, f"unknown {path}", None)
    status = server.method_status(method.full_name)
    # unified admission decision point (server/admission.py): tenant
    # identity rides the x-tpu-tenant request header on h2/grpc
    verdict = server.admission.admit(
        method.full_name, status, _header(headers, "x-tpu-tenant", "") or ""
    )
    if not verdict.admitted:
        return _respond(
            ctx, sid, GRPC_RESOURCE_EXHAUSTED, verdict.reason, None
        )
    ticket = verdict.ticket
    body = _grpc_unwrap(stream.data)
    if body is None:
        if status is not None:
            status.on_response(0, error=True)
        if ticket is not None:
            ticket.release()
        return _respond(ctx, sid, GRPC_UNKNOWN, "bad grpc framing", None)
    request = method.request_class()
    try:
        request.ParseFromString(body)
    except Exception as e:  # noqa: BLE001
        if status is not None:
            status.on_response(0, error=True)
        if ticket is not None:
            ticket.release()
        return _respond(ctx, sid, GRPC_UNKNOWN, f"parse failed: {e}", None)

    ctrl = Controller()
    ctrl.server = server
    ctrl._server_socket = sock
    ctrl._auth_context = auth_ctx
    ctrl.remote_side = sock.remote
    ctrl.service_name = service_name
    ctrl.method_name = method_name
    if verdict.tier is not None:
        # same stamp as tpu_std/http: the batcher's tier-aware queue
        # cap and the per-tier latency feed read it off the controller
        ctrl._admission_tier = verdict.tier
    timeout_ms = _parse_grpc_timeout(_header(headers, "grpc-timeout"))
    if timeout_ms is not None:
        ctrl.timeout_ms = timeout_ms
    response = method.response_class()
    import time as _time

    start_ns = _time.monotonic_ns()
    sent = [False]

    def done():
        if sent[0]:
            return
        sent[0] = True
        ctrl._release_session_local()  # handler done: pool the user data
        if ticket is not None:
            ticket.release()
        latency_us = (_time.monotonic_ns() - start_ns) // 1000
        if status is not None:
            status.on_response(latency_us, error=ctrl.failed())
        # per-tier observed latency (server/admission.py): feeds the
        # latency-fed auto limiter; no-op unless a tier was stamped
        from incubator_brpc_tpu.server import admission as _admission

        _admission.note_controller_latency(ctrl, latency_us)
        if ctrl.failed():
            _respond(ctx, sid, _grpc_status_of(ctrl.error_code), ctrl.error_text(), None)
        else:
            _respond(ctx, sid, GRPC_OK, "", IOBuf(response.SerializeToString()))

    try:
        method.fn(ctrl, request, response, done)  # ← USER CODE
    except Exception as e:  # noqa: BLE001
        log_error("grpc method %s raised: %r", method.full_name, e)
        if not sent[0]:
            ctrl.set_failed(errors.EINTERNAL, f"method raised: {e}")
            done()


PROTOCOL = Protocol(
    name="h2",
    parse=parse,
    serialize_request=serialize_request,
    issue=issue,
    process_request=process_frame,
    process_response=process_frame,
    process_in_place=True,  # frames are stateful and ordered
    auth_in_protocol=True,  # per-stream authorization header check
)

# gRPC is the h2 protocol under its conventional name (reference
# registers h2 once; grpc rides the same wire): parse=None so the
# InputMessenger never double-tries the same wire format.
GRPC_PROTOCOL = Protocol(
    name="grpc",
    parse=None,
    serialize_request=serialize_request,
    issue=issue,
    process_response=process_frame,
    process_in_place=True,
)


def register():
    register_protocol(PROTOCOL)
    register_protocol(GRPC_PROTOCOL)
