"""HPACK — HTTP/2 header compression (RFC 7541).

Analog of reference details/hpack.{h,cpp} (881 LoC): static + dynamic
tables, N-bit-prefix integer coding, string literals with Huffman
coding. Encoder and decoder each own an independent dynamic table, as
the RFC requires (one per direction of one connection).

The two tables below are the RFC 7541 Appendix A/B constants.
"""

from __future__ import annotations

from collections import deque
from typing import List, Optional, Tuple

# RFC 7541 Appendix A: the 61-entry static header table.
STATIC_TABLE = (
    (':authority', ''),
    (':method', 'GET'),
    (':method', 'POST'),
    (':path', '/'),
    (':path', '/index.html'),
    (':scheme', 'http'),
    (':scheme', 'https'),
    (':status', '200'),
    (':status', '204'),
    (':status', '206'),
    (':status', '304'),
    (':status', '400'),
    (':status', '404'),
    (':status', '500'),
    ('accept-charset', ''),
    ('accept-encoding', 'gzip, deflate'),
    ('accept-language', ''),
    ('accept-ranges', ''),
    ('accept', ''),
    ('access-control-allow-origin', ''),
    ('age', ''),
    ('allow', ''),
    ('authorization', ''),
    ('cache-control', ''),
    ('content-disposition', ''),
    ('content-encoding', ''),
    ('content-language', ''),
    ('content-length', ''),
    ('content-location', ''),
    ('content-range', ''),
    ('content-type', ''),
    ('cookie', ''),
    ('date', ''),
    ('etag', ''),
    ('expect', ''),
    ('expires', ''),
    ('from', ''),
    ('host', ''),
    ('if-match', ''),
    ('if-modified-since', ''),
    ('if-none-match', ''),
    ('if-range', ''),
    ('if-unmodified-since', ''),
    ('last-modified', ''),
    ('link', ''),
    ('location', ''),
    ('max-forwards', ''),
    ('proxy-authenticate', ''),
    ('proxy-authorization', ''),
    ('range', ''),
    ('referer', ''),
    ('refresh', ''),
    ('retry-after', ''),
    ('server', ''),
    ('set-cookie', ''),
    ('strict-transport-security', ''),
    ('transfer-encoding', ''),
    ('user-agent', ''),
    ('vary', ''),
    ('via', ''),
    ('www-authenticate', ''),
)

# RFC 7541 Appendix B: canonical Huffman code for each of the 256
# octets plus EOS — (code, bit_length) per symbol.
HUFFMAN_CODES = (
    (0x1ff8, 13), (0x7fffd8, 23), (0xfffffe2, 28), (0xfffffe3, 28),
    (0xfffffe4, 28), (0xfffffe5, 28), (0xfffffe6, 28), (0xfffffe7, 28),
    (0xfffffe8, 28), (0xffffea, 24), (0x3ffffffc, 30), (0xfffffe9, 28),
    (0xfffffea, 28), (0x3ffffffd, 30), (0xfffffeb, 28), (0xfffffec, 28),
    (0xfffffed, 28), (0xfffffee, 28), (0xfffffef, 28), (0xffffff0, 28),
    (0xffffff1, 28), (0xffffff2, 28), (0x3ffffffe, 30), (0xffffff3, 28),
    (0xffffff4, 28), (0xffffff5, 28), (0xffffff6, 28), (0xffffff7, 28),
    (0xffffff8, 28), (0xffffff9, 28), (0xffffffa, 28), (0xffffffb, 28),
    (0x14, 6), (0x3f8, 10), (0x3f9, 10), (0xffa, 12),
    (0x1ff9, 13), (0x15, 6), (0xf8, 8), (0x7fa, 11),
    (0x3fa, 10), (0x3fb, 10), (0xf9, 8), (0x7fb, 11),
    (0xfa, 8), (0x16, 6), (0x17, 6), (0x18, 6),
    (0x0, 5), (0x1, 5), (0x2, 5), (0x19, 6),
    (0x1a, 6), (0x1b, 6), (0x1c, 6), (0x1d, 6),
    (0x1e, 6), (0x1f, 6), (0x5c, 7), (0xfb, 8),
    (0x7ffc, 15), (0x20, 6), (0xffb, 12), (0x3fc, 10),
    (0x1ffa, 13), (0x21, 6), (0x5d, 7), (0x5e, 7),
    (0x5f, 7), (0x60, 7), (0x61, 7), (0x62, 7),
    (0x63, 7), (0x64, 7), (0x65, 7), (0x66, 7),
    (0x67, 7), (0x68, 7), (0x69, 7), (0x6a, 7),
    (0x6b, 7), (0x6c, 7), (0x6d, 7), (0x6e, 7),
    (0x6f, 7), (0x70, 7), (0x71, 7), (0x72, 7),
    (0xfc, 8), (0x73, 7), (0xfd, 8), (0x1ffb, 13),
    (0x7fff0, 19), (0x1ffc, 13), (0x3ffc, 14), (0x22, 6),
    (0x7ffd, 15), (0x3, 5), (0x23, 6), (0x4, 5),
    (0x24, 6), (0x5, 5), (0x25, 6), (0x26, 6),
    (0x27, 6), (0x6, 5), (0x74, 7), (0x75, 7),
    (0x28, 6), (0x29, 6), (0x2a, 6), (0x7, 5),
    (0x2b, 6), (0x76, 7), (0x2c, 6), (0x8, 5),
    (0x9, 5), (0x2d, 6), (0x77, 7), (0x78, 7),
    (0x79, 7), (0x7a, 7), (0x7b, 7), (0x7ffe, 15),
    (0x7fc, 11), (0x3ffd, 14), (0x1ffd, 13), (0xffffffc, 28),
    (0xfffe6, 20), (0x3fffd2, 22), (0xfffe7, 20), (0xfffe8, 20),
    (0x3fffd3, 22), (0x3fffd4, 22), (0x3fffd5, 22), (0x7fffd9, 23),
    (0x3fffd6, 22), (0x7fffda, 23), (0x7fffdb, 23), (0x7fffdc, 23),
    (0x7fffdd, 23), (0x7fffde, 23), (0xffffeb, 24), (0x7fffdf, 23),
    (0xffffec, 24), (0xffffed, 24), (0x3fffd7, 22), (0x7fffe0, 23),
    (0xffffee, 24), (0x7fffe1, 23), (0x7fffe2, 23), (0x7fffe3, 23),
    (0x7fffe4, 23), (0x1fffdc, 21), (0x3fffd8, 22), (0x7fffe5, 23),
    (0x3fffd9, 22), (0x7fffe6, 23), (0x7fffe7, 23), (0xffffef, 24),
    (0x3fffda, 22), (0x1fffdd, 21), (0xfffe9, 20), (0x3fffdb, 22),
    (0x3fffdc, 22), (0x7fffe8, 23), (0x7fffe9, 23), (0x1fffde, 21),
    (0x7fffea, 23), (0x3fffdd, 22), (0x3fffde, 22), (0xfffff0, 24),
    (0x1fffdf, 21), (0x3fffdf, 22), (0x7fffeb, 23), (0x7fffec, 23),
    (0x1fffe0, 21), (0x1fffe1, 21), (0x3fffe0, 22), (0x1fffe2, 21),
    (0x7fffed, 23), (0x3fffe1, 22), (0x7fffee, 23), (0x7fffef, 23),
    (0xfffea, 20), (0x3fffe2, 22), (0x3fffe3, 22), (0x3fffe4, 22),
    (0x7ffff0, 23), (0x3fffe5, 22), (0x3fffe6, 22), (0x7ffff1, 23),
    (0x3ffffe0, 26), (0x3ffffe1, 26), (0xfffeb, 20), (0x7fff1, 19),
    (0x3fffe7, 22), (0x7ffff2, 23), (0x3fffe8, 22), (0x1ffffec, 25),
    (0x3ffffe2, 26), (0x3ffffe3, 26), (0x3ffffe4, 26), (0x7ffffde, 27),
    (0x7ffffdf, 27), (0x3ffffe5, 26), (0xfffff1, 24), (0x1ffffed, 25),
    (0x7fff2, 19), (0x1fffe3, 21), (0x3ffffe6, 26), (0x7ffffe0, 27),
    (0x7ffffe1, 27), (0x3ffffe7, 26), (0x7ffffe2, 27), (0xfffff2, 24),
    (0x1fffe4, 21), (0x1fffe5, 21), (0x3ffffe8, 26), (0x3ffffe9, 26),
    (0xffffffd, 28), (0x7ffffe3, 27), (0x7ffffe4, 27), (0x7ffffe5, 27),
    (0xfffec, 20), (0xfffff3, 24), (0xfffed, 20), (0x1fffe6, 21),
    (0x3fffe9, 22), (0x1fffe7, 21), (0x1fffe8, 21), (0x7ffff3, 23),
    (0x3fffea, 22), (0x3fffeb, 22), (0x1ffffee, 25), (0x1ffffef, 25),
    (0xfffff4, 24), (0xfffff5, 24), (0x3ffffea, 26), (0x7ffff4, 23),
    (0x3ffffeb, 26), (0x7ffffe6, 27), (0x3ffffec, 26), (0x3ffffed, 26),
    (0x7ffffe7, 27), (0x7ffffe8, 27), (0x7ffffe9, 27), (0x7ffffea, 27),
    (0x7ffffeb, 27), (0xffffffe, 28), (0x7ffffec, 27), (0x7ffffed, 27),
    (0x7ffffee, 27), (0x7ffffef, 27), (0x7fffff0, 27), (0x3ffffee, 26),
    (0x3fffffff, 30),
)

_EOS = 256
_STATIC_COUNT = len(STATIC_TABLE)  # 61

# decode map: (bit_length, code) -> symbol. Huffman codes are prefix-
# free, so matching at increasing lengths yields the unique symbol.
_HUFF_DECODE = {
    (ln, code): sym for sym, (code, ln) in enumerate(HUFFMAN_CODES)
}
_HUFF_LENGTHS = sorted({ln for _, ln in HUFFMAN_CODES})

# name -> smallest static index (1-based); (name, value) -> index
_STATIC_BY_PAIR = {}
_STATIC_BY_NAME = {}
for _i, (_n, _v) in enumerate(STATIC_TABLE):
    _STATIC_BY_PAIR.setdefault((_n, _v), _i + 1)
    _STATIC_BY_NAME.setdefault(_n, _i + 1)


class HpackError(ValueError):
    pass


# ---- primitive codings ------------------------------------------------------
def encode_int(value: int, prefix_bits: int, first_byte_flags: int = 0) -> bytes:
    """RFC 7541 §5.1 integer with an N-bit prefix."""
    limit = (1 << prefix_bits) - 1
    if value < limit:
        return bytes((first_byte_flags | value,))
    out = bytearray((first_byte_flags | limit,))
    value -= limit
    while value >= 0x80:
        out.append(0x80 | (value & 0x7F))
        value >>= 7
    out.append(value)
    return bytes(out)


def decode_int(data, pos: int, prefix_bits: int) -> Tuple[int, int]:
    """Returns (value, new_pos)."""
    if pos >= len(data):
        raise HpackError("truncated integer")
    limit = (1 << prefix_bits) - 1
    value = data[pos] & limit
    pos += 1
    if value < limit:
        return value, pos
    shift = 0
    while True:
        if pos >= len(data):
            raise HpackError("truncated varint")
        b = data[pos]
        pos += 1
        value += (b & 0x7F) << shift
        shift += 7
        if not b & 0x80:
            return value, pos
        if shift > 35:
            raise HpackError("integer overflow")


def huffman_encode(data: bytes) -> bytes:
    acc = 0
    nbits = 0
    out = bytearray()
    for b in data:
        code, ln = HUFFMAN_CODES[b]
        acc = (acc << ln) | code
        nbits += ln
        while nbits >= 8:
            nbits -= 8
            out.append((acc >> nbits) & 0xFF)
    if nbits:
        # pad with EOS prefix (all ones)
        out.append(((acc << (8 - nbits)) | ((1 << (8 - nbits)) - 1)) & 0xFF)
    return bytes(out)


def huffman_decode(data: bytes) -> bytes:
    acc = 0
    nbits = 0
    out = bytearray()
    decode = _HUFF_DECODE
    for byte in data:
        acc = (acc << 8) | byte
        nbits += 8
        matched = True
        while matched:
            matched = False
            for ln in _HUFF_LENGTHS:
                if ln > nbits:
                    break
                sym = decode.get((ln, acc >> (nbits - ln)))
                if sym is not None:
                    if sym == _EOS:
                        raise HpackError("EOS in huffman stream")
                    out.append(sym)
                    nbits -= ln
                    acc &= (1 << nbits) - 1
                    matched = True
                    break
    # residue must be an EOS prefix (all ones, < 8 bits)
    if nbits >= 8 or acc != (1 << nbits) - 1:
        raise HpackError("bad huffman padding")
    return bytes(out)


def encode_string(s: str, huffman: bool = True) -> bytes:
    raw = s.encode("utf-8") if isinstance(s, str) else s
    if huffman:
        enc = huffman_encode(raw)
        if len(enc) < len(raw):
            return encode_int(len(enc), 7, 0x80) + enc
    return encode_int(len(raw), 7, 0x00) + raw


def decode_string(data, pos: int) -> Tuple[str, int]:
    if pos >= len(data):
        raise HpackError("truncated string")
    huff = bool(data[pos] & 0x80)
    length, pos = decode_int(data, pos, 7)
    if pos + length > len(data):
        raise HpackError("string exceeds block")
    raw = bytes(data[pos : pos + length])
    pos += length
    if huff:
        raw = huffman_decode(raw)
    return raw.decode("utf-8", errors="replace"), pos


# ---- dynamic table ----------------------------------------------------------
class _DynamicTable:
    """FIFO of (name, value); size accounting per RFC 7541 §4.1
    (entry size = len(name) + len(value) + 32 octets)."""

    def __init__(self, max_size: int = 4096):
        self.entries: deque = deque()  # newest at index 0
        self.size = 0
        self.max_size = max_size
        self.cap_limit = max_size  # protocol ceiling (SETTINGS)

    @staticmethod
    def entry_size(name: str, value: str) -> int:
        return len(name.encode()) + len(value.encode()) + 32

    def add(self, name: str, value: str):
        sz = self.entry_size(name, value)
        while self.entries and self.size + sz > self.max_size:
            en, ev = self.entries.pop()
            self.size -= self.entry_size(en, ev)
        if sz <= self.max_size:
            self.entries.appendleft((name, value))
            self.size += sz
        else:
            self.entries.clear()
            self.size = 0

    def resize(self, new_max: int):
        if new_max > self.cap_limit:
            raise HpackError("table size update beyond limit")
        self.max_size = new_max
        while self.entries and self.size > self.max_size:
            en, ev = self.entries.pop()
            self.size -= self.entry_size(en, ev)

    def get(self, index_from_62: int) -> Tuple[str, str]:
        """index 0 = newest dynamic entry."""
        if index_from_62 >= len(self.entries):
            raise HpackError(f"dynamic index {index_from_62} out of range")
        return self.entries[index_from_62]

    def find(self, name: str, value: str) -> Tuple[Optional[int], Optional[int]]:
        """(pair_index, name_index) as absolute 1-based indices (62+)."""
        pair = name_only = None
        for i, (n, v) in enumerate(self.entries):
            if n == name:
                if v == value and pair is None:
                    pair = _STATIC_COUNT + 1 + i
                if name_only is None:
                    name_only = _STATIC_COUNT + 1 + i
        return pair, name_only


# ---- encoder / decoder ------------------------------------------------------
class HpackEncoder:
    def __init__(self, max_table_size: int = 4096, huffman: bool = True):
        self._table = _DynamicTable(max_table_size)
        self._huffman = huffman
        self._pending_resize: Optional[int] = None

    def set_max_table_size(self, n: int):
        self._table.cap_limit = n
        self._pending_resize = min(n, self._table.max_size)

    def encode(self, headers: List[Tuple[str, str]], sensitive=()) -> bytes:
        out = bytearray()
        if self._pending_resize is not None:
            self._table.resize(self._pending_resize)
            out += encode_int(self._pending_resize, 5, 0x20)
            self._pending_resize = None
        for name, value in headers:
            name = name.lower()
            out += self._encode_one(name, value, name in sensitive)
        return bytes(out)

    def _encode_one(self, name: str, value: str, sensitive: bool) -> bytes:
        if sensitive:
            # never-indexed literal (§6.2.3)
            idx = _STATIC_BY_NAME.get(name) or self._table.find(name, value)[1]
            head = encode_int(idx or 0, 4, 0x10)
            if not idx:
                head += encode_string(name, self._huffman)
            return head + encode_string(value, self._huffman)
        pair = _STATIC_BY_PAIR.get((name, value))
        if pair is None:
            pair, dyn_name = self._table.find(name, value)
        else:
            dyn_name = None
        if pair is not None:
            return encode_int(pair, 7, 0x80)  # indexed (§6.1)
        # literal with incremental indexing (§6.2.1)
        idx = _STATIC_BY_NAME.get(name) or dyn_name or 0
        head = encode_int(idx, 6, 0x40)
        if not idx:
            head += encode_string(name, self._huffman)
        out = head + encode_string(value, self._huffman)
        self._table.add(name, value)
        return out


class HpackDecoder:
    def __init__(self, max_table_size: int = 4096):
        self._table = _DynamicTable(max_table_size)

    def set_max_table_size(self, n: int):
        self._table.cap_limit = n

    def _lookup(self, index: int) -> Tuple[str, str]:
        if index == 0:
            raise HpackError("index 0")
        if index <= _STATIC_COUNT:
            return STATIC_TABLE[index - 1]
        return self._table.get(index - _STATIC_COUNT - 1)

    def decode(self, data) -> List[Tuple[str, str]]:
        headers: List[Tuple[str, str]] = []
        pos = 0
        n = len(data)
        while pos < n:
            b = data[pos]
            if b & 0x80:  # indexed (§6.1)
                idx, pos = decode_int(data, pos, 7)
                headers.append(self._lookup(idx))
            elif b & 0x40:  # literal w/ incremental indexing (§6.2.1)
                idx, pos = decode_int(data, pos, 6)
                name = self._lookup(idx)[0] if idx else None
                if name is None:
                    name, pos = decode_string(data, pos)
                value, pos = decode_string(data, pos)
                self._table.add(name, value)
                headers.append((name, value))
            elif b & 0x20:  # dynamic table size update (§6.3)
                new_max, pos = decode_int(data, pos, 5)
                self._table.resize(new_max)
            else:  # literal without indexing / never-indexed (§6.2.2/3)
                idx, pos = decode_int(data, pos, 4)
                name = self._lookup(idx)[0] if idx else None
                if name is None:
                    name, pos = decode_string(data, pos)
                value, pos = decode_string(data, pos)
                headers.append((name, value))
        return headers
