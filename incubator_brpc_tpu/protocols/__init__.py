"""Pluggable protocol layer (analog of reference src/brpc/protocol.h).

The key inversion preserved from the reference (SURVEY.md §1): the
transport knows nothing about any protocol. Protocols register a table
of callbacks (``struct Protocol``'s 7 function pointers,
protocol.h:77-172) and the InputMessenger tries parsers in order,
caching the matched index per socket, so one server port speaks all
protocols.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Callable, List, Optional


class ParseError(enum.Enum):
    OK = 0
    NOT_ENOUGH_DATA = 1  # keep bytes, wait for more
    TRY_OTHERS = 2  # didn't match magic: try the next protocol
    BAD_FORMAT = 3  # matched but malformed: close the connection


@dataclass
class ParseResult:
    error: ParseError
    message: object = None  # protocol-specific parsed message

    @staticmethod
    def ok(msg) -> "ParseResult":
        return ParseResult(ParseError.OK, msg)

    @staticmethod
    def not_enough() -> "ParseResult":
        return ParseResult(ParseError.NOT_ENOUGH_DATA)

    @staticmethod
    def try_others() -> "ParseResult":
        return ParseResult(ParseError.TRY_OTHERS)

    @staticmethod
    def bad() -> "ParseResult":
        return ParseResult(ParseError.BAD_FORMAT)


@dataclass
class Protocol:
    """The protocol vtable (reference protocol.h:77-172).

    - parse(iobuf, socket, read_eof) -> ParseResult: cut one message.
    - serialize_request(request, controller) -> IOBuf: called ONCE per
      RPC (channel.cpp:517).
    - pack_request(request_buf, cid, method_spec, controller) -> IOBuf:
      called per send, including retries (controller.cpp:1140).
    - process_request(msg_obj, socket): server side, runs in a task.
    - process_response(msg_obj, socket): client side, runs in a task.
    - verify(msg_obj, socket) -> bool: first-message auth on a server
      connection (input_messenger.cpp:282-300).
    - parse_server_address(url) -> bool: whether this protocol supports
      the given scheme for client channels.
    """

    name: str
    parse: Callable = None
    serialize_request: Callable = None
    pack_request: Callable = None
    process_request: Callable = None
    process_response: Callable = None
    verify: Callable = None
    support_client: bool = True
    support_server: bool = True
    # pipelined protocols (redis/memcache) answer in order on one socket
    support_pipelined: bool = False
    # process in the read task instead of a fresh task per message:
    # required by protocols whose messages must keep arrival order
    # (streaming frames route to per-stream execution queues)
    process_in_place: bool = False
    # process messages of one connection sequentially in arrival order,
    # but OFF the read task (per-socket ExecutionQueue). Required by
    # correlation-less protocols (HTTP/1.x) where the client matches
    # responses FIFO: parallel server dispatch would let a fast later
    # handler overtake a slow earlier one and misroute both responses.
    process_ordered: bool = False
    # the protocol authenticates INSIDE its own message flow (h2 checks
    # the authorization header per stream) — exempts it from the
    # first-message verify gate on auth-enforcing servers; a protocol
    # with neither verify nor this flag is rejected there outright
    auth_in_protocol: bool = False
    # stateful-connection protocols (h2: per-connection HPACK tables +
    # stream ids) send through this instead of pack_request+write —
    # issue(sock, request_buf, wire_cid, method_spec, controller) packs
    # and writes atomically under the connection's encode order lock
    issue: Callable = None
    # pack_cancel(wire_cid) -> IOBuf: a cancel frame for an abandoned
    # in-flight request (hedged-request loser cancellation).  Protocols
    # without one simply leave the loser to finish server-side.
    pack_cancel: Callable = None


def accumulate_pipelined(sock, item):
    """Shared FIFO accumulator for pipelined protocols (redis/memcache):
    append one parsed reply for the FIFO-front RPC; when its count is
    reached, pop the entry and return (cid, items) — else None. Runs
    under the socket's write lock (pipelined_info's lock)."""
    with sock._write_lock:
        if not sock.pipelined_info:
            return None  # stray reply (RPC already failed): drop
        cid, count = sock.pipelined_info[0]
        sock._pipelined_acc.append(item)
        if len(sock._pipelined_acc) < count:
            return None
        sock.pipelined_info.popleft()
        items, sock._pipelined_acc = sock._pipelined_acc, []
        return cid, items


def _call_verify_credential(auth, auth_str: str, sock, attach_to_sock: bool = True):
    """Run a server authenticator. Returns (rc, AuthContext). On
    success the context attaches to the connection (reference
    VerifyCredential's out param; handlers read it via
    Controller.auth_context()) — except for per-request verification
    (h2 streams), where the caller attaches it to the request instead
    (attach_to_sock=False). Accepts both verify_credential(auth_str,
    peer) and (auth_str, peer, context) overrides."""
    from incubator_brpc_tpu.client.auth import AuthContext
    from incubator_brpc_tpu.utils.logging import log_error

    ctx = AuthContext()
    try:
        # arity probed once per authenticator, not per request (this is
        # the per-stream hot path on h2 servers)
        nparams = getattr(auth, "_verify_nparams", None)
        if nparams is None:
            import inspect

            try:
                nparams = len(inspect.signature(auth.verify_credential).parameters)
            except (TypeError, ValueError):
                nparams = 2
            try:
                auth._verify_nparams = nparams
            except AttributeError:
                pass  # __slots__ authenticator: re-probe each time
        if nparams >= 3:
            rc = auth.verify_credential(auth_str, sock.remote, ctx)
        else:
            rc = auth.verify_credential(auth_str, sock.remote)
    except Exception as e:  # noqa: BLE001
        log_error("verify_credential raised: %r", e)
        return -1, ctx
    if rc == 0 and attach_to_sock:
        sock.auth_context = ctx
    return rc, ctx


_protocols: List[Protocol] = []


def register_protocol(p: Protocol) -> None:
    """Analog of RegisterProtocol (protocol.h:186); called by
    global_init for every built-in protocol (global.cpp:399-580)."""
    for existing in _protocols:
        if existing.name == p.name:
            return
    _protocols.append(p)


def list_protocols() -> List[Protocol]:
    return list(_protocols)


def find_protocol(name: str) -> Optional[Protocol]:
    for p in _protocols:
        if p.name == name:
            return p
    return None
