"""RTMP — real-time media streaming protocol (client + server).

Analog of reference policy/rtmp_protocol.cpp + rtmp.{h,cpp} (~9k LoC;
SURVEY §2.5): the functional core of RTMP 1.0 —

  * plain handshake (C0/C1/C2 ↔ S0/S1/S2),
  * the chunk stream layer (basic-header formats 0-3, extended
    timestamps, Set Chunk Size both directions),
  * AMF0 (number/bool/string/object/null/ecma-array/strict-array),
  * protocol control + user-control (Stream Begin) messages,
  * NetConnection/NetStream commands: connect, createStream, publish,
    play, deleteStream/closeStream with _result/onStatus replies,
  * audio/video/data message relay from each publisher to the players
    of the same stream name (the media fan-out the reference's
    RtmpService provides).

Server side rides the shared transport: the parse chain recognizes the
0x03 handshake byte, so one port speaks RTMP alongside every other
protocol. User surface mirrors the reference's RtmpService hooks:
subclass RtmpService (on_publish/on_play/on_frame) and register via
ServerOptions.rtmp_service. The client is a standalone RtmpClient
(RTMP is stateful; it does not map onto request/response channels).
"""

from __future__ import annotations

import io
import os
import struct
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from incubator_brpc_tpu import errors
from incubator_brpc_tpu.protocols import ParseResult, Protocol, register_protocol
from incubator_brpc_tpu.utils.iobuf import IOBuf
from incubator_brpc_tpu.utils.logging import log_error, log_verbose

HANDSHAKE_SIZE = 1536
DEFAULT_CHUNK_SIZE = 128

# ---------------------------------------------------------------------------
# complex ("digested") handshake — reference policy/rtmp_protocol.cpp:149-533
# (C1S1Base/DigestBlock/KeyBlock + details/rtmp_utils DH).  Flash-era
# clients send a C1 carrying an HMAC-SHA256 digest and a Diffie-Hellman
# public key; servers must answer with a digested S1 (FMS key) and an
# S2 proving possession of C1's digest, or those clients disconnect.
# The key/digest constants are the public Adobe handshake constants
# every RTMP implementation ships.
# ---------------------------------------------------------------------------

import hashlib as _hashlib
import hmac as _hmaclib

_HS_FMS_KEY = (
    b"Genuine Adobe Flash Media Server 001"
    + bytes.fromhex(
        "f0eec24a8068bee82e00d0d1029e7e576eec5d2d29806fab93b8e636cfeb31ae"
    )
)  # 68 bytes
_HS_FP_KEY = (
    b"Genuine Adobe Flash Player 001"
    + bytes.fromhex(
        "f0eec24a8068bee82e00d0d1029e7e576eec5d2d29806fab93b8e636cfeb31ae"
    )
)  # 62 bytes
_HS_FP_VERSION = 0x80000702
_HS_FMS_VERSION = 0x01000504
# RFC 2409 second Oakley group (1024-bit MODP) — the RTMP handshake DH
_HS_DH_P = int(
    "FFFFFFFFFFFFFFFFC90FDAA22168C234C4C6628B80DC1CD129024E088A67CC74"
    "020BBEA63B139B22514A08798E3404DDEF9519B3CD3A431B302B0A6DF25F1437"
    "4FE1356D6D51C245E485B576625E7EC6F44C42E9A637ED6B0BFF5CB6F406B7ED"
    "EE386BFB5A899FA5AE9F24117C4B1FE649286651ECE65381FFFFFFFFFFFFFFFF",
    16,
)
_HS_DH_G = 2


def _hs_digest_block_offset(blk: bytes) -> int:
    # digest block: offset(4) | random | digest(32) | random
    return (blk[0] + blk[1] + blk[2] + blk[3]) % (764 - 32 - 4)


def _hs_key_block_offset(blk: bytes) -> int:
    # key block: random | key(128) | random | offset(4 AT END)
    return (blk[760] + blk[761] + blk[762] + blk[763]) % (764 - 128 - 4)


def _hs_digest_slice(schema: int) -> int:
    """Byte offset of the digest BLOCK inside C1/S1 for a schema.
    Reference SCHEMA0 = key block first, SCHEMA1 = digest block first
    (rtmp_protocol.cpp C1S1Base::Save)."""
    return 8 + 764 if schema == 0 else 8


def _hs_extract_digest(c1s1: bytes, schema: int):
    """→ (digest_bytes, message_without_digest) for HMAC verification."""
    b0 = _hs_digest_slice(schema)
    blk = c1s1[b0 : b0 + 764]
    off = _hs_digest_block_offset(blk)
    dstart = b0 + 4 + off
    return c1s1[dstart : dstart + 32], c1s1[:dstart] + c1s1[dstart + 32 :]


def _hs_validate_c1(c1: bytes):
    """→ (schema, c1_digest) if C1 carries a valid FP digest, else
    (None, None) — plain-handshake clients land here."""
    for schema in (0, 1):
        digest, joined = _hs_extract_digest(c1, schema)
        calc = _hmaclib.new(_HS_FP_KEY[:30], joined, _hashlib.sha256).digest()
        if _hmaclib.compare_digest(calc, digest):
            return schema, digest
    return None, None


def _hs_client_dh_pub(c1: bytes, schema: int) -> int:
    k0 = 8 if schema == 0 else 8 + 764
    blk = c1[k0 : k0 + 764]
    off = _hs_key_block_offset(blk)
    return int.from_bytes(c1[k0 + off : k0 + off + 128], "big")


def _hs_build_s1s2(c1: bytes, schema: int, c1_digest: bytes):
    """Digested S1 (FMS[:36] digest, DH public key in the key block,
    same schema as C1) + S2 (random || HMAC(HMAC(FMS, c1_digest), random))."""
    body = bytearray(os.urandom(HANDSHAKE_SIZE))
    struct.pack_into(">II", body, 0, int(time.time()) & 0x7FFFFFFF,
                     _HS_FMS_VERSION)
    # key block: server DH public key at its offset
    k0 = 8 if schema == 0 else 8 + 764
    koff = _hs_key_block_offset(bytes(body[k0 : k0 + 764]))
    x = int.from_bytes(os.urandom(64), "big") | 1
    server_pub = pow(_HS_DH_G, x, _HS_DH_P)
    body[k0 + koff : k0 + koff + 128] = server_pub.to_bytes(128, "big")
    # digest block: compute over S1-without-digest with FMS[:36]
    b0 = _hs_digest_slice(schema)
    doff = _hs_digest_block_offset(bytes(body[b0 : b0 + 764]))
    dstart = b0 + 4 + doff
    joined = bytes(body[:dstart]) + bytes(body[dstart + 32 :])
    s1_digest = _hmaclib.new(
        _HS_FMS_KEY[:36], joined, _hashlib.sha256
    ).digest()
    body[dstart : dstart + 32] = s1_digest
    # S2: prove we saw C1's digest (C2S2Base::ComputeDigest)
    rand = os.urandom(HANDSHAKE_SIZE - 32)
    temp_key = _hmaclib.new(_HS_FMS_KEY, c1_digest, _hashlib.sha256).digest()
    s2_digest = _hmaclib.new(temp_key, rand, _hashlib.sha256).digest()
    return bytes(body), rand + s2_digest


def make_digested_c1(schema: int = 1) -> bytes:
    """Client-side digested C1 (FP key) — what a Flash-era client
    sends; used by RtmpClient's complex mode and the handshake tests."""
    body = bytearray(os.urandom(HANDSHAKE_SIZE))
    struct.pack_into(">II", body, 0, int(time.time()) & 0x7FFFFFFF,
                     _HS_FP_VERSION)
    k0 = 8 if schema == 0 else 8 + 764
    koff = _hs_key_block_offset(bytes(body[k0 : k0 + 764]))
    x = int.from_bytes(os.urandom(64), "big") | 1
    body[k0 + koff : k0 + koff + 128] = pow(
        _HS_DH_G, x, _HS_DH_P
    ).to_bytes(128, "big")
    b0 = _hs_digest_slice(schema)
    doff = _hs_digest_block_offset(bytes(body[b0 : b0 + 764]))
    dstart = b0 + 4 + doff
    joined = bytes(body[:dstart]) + bytes(body[dstart + 32 :])
    body[dstart : dstart + 32] = _hmaclib.new(
        _HS_FP_KEY[:30], joined, _hashlib.sha256
    ).digest()
    return bytes(body)
_OUT_CHUNK_SIZE = 4096

# message type ids
MSG_SET_CHUNK_SIZE = 1
MSG_ABORT = 2
MSG_ACK = 3
MSG_USER_CONTROL = 4
MSG_WINDOW_ACK_SIZE = 5
MSG_SET_PEER_BW = 6
MSG_AUDIO = 8
MSG_VIDEO = 9
MSG_DATA_AMF0 = 18
MSG_COMMAND_AMF0 = 20

_MEDIA_TYPES = (MSG_AUDIO, MSG_VIDEO, MSG_DATA_AMF0)


# ---------------------------------------------------------------------------
# AMF0
# ---------------------------------------------------------------------------
def amf0_encode(*values) -> bytes:
    out = bytearray()
    for v in values:
        _amf0_encode_one(out, v)
    return bytes(out)


def _amf0_encode_one(out: bytearray, v):
    if isinstance(v, bool):
        out += b"\x01" + (b"\x01" if v else b"\x00")
    elif isinstance(v, (int, float)):
        out += b"\x00" + struct.pack(">d", float(v))
    elif isinstance(v, str):
        raw = v.encode()
        out += b"\x02" + struct.pack(">H", len(raw)) + raw
    elif v is None:
        out += b"\x05"
    elif isinstance(v, dict):
        out += b"\x03"
        for k, val in v.items():
            raw = k.encode()
            out += struct.pack(">H", len(raw)) + raw
            _amf0_encode_one(out, val)
        out += b"\x00\x00\x09"
    elif isinstance(v, (list, tuple)):
        out += b"\x0a" + struct.pack(">I", len(v))
        for item in v:
            _amf0_encode_one(out, item)
    else:
        raise TypeError(f"amf0: unsupported {type(v)}")


def amf0_decode_all(data: bytes) -> List:
    vals = []
    pos = 0
    while pos < len(data):
        v, pos = _amf0_decode_one(data, pos)
        vals.append(v)
    return vals


def _amf0_decode_one(data: bytes, pos: int):
    marker = data[pos]
    pos += 1
    if marker == 0x00:
        return struct.unpack_from(">d", data, pos)[0], pos + 8
    if marker == 0x01:
        return data[pos] != 0, pos + 1
    if marker == 0x02:
        (n,) = struct.unpack_from(">H", data, pos)
        return data[pos + 2 : pos + 2 + n].decode("utf-8", "replace"), pos + 2 + n
    if marker in (0x03, 0x08):  # object / ecma array (skip count)
        if marker == 0x08:
            pos += 4
        obj = {}
        while True:
            (n,) = struct.unpack_from(">H", data, pos)
            pos += 2
            if n == 0 and data[pos] == 0x09:
                return obj, pos + 1
            key = data[pos : pos + n].decode("utf-8", "replace")
            pos += n
            obj[key], pos = _amf0_decode_one(data, pos)
    if marker == 0x05 or marker == 0x06:  # null / undefined
        return None, pos
    if marker == 0x0A:  # strict array
        (n,) = struct.unpack_from(">I", data, pos)
        pos += 4
        arr = []
        for _ in range(n):
            v, pos = _amf0_decode_one(data, pos)
            arr.append(v)
        return arr, pos
    raise ValueError(f"amf0: unsupported marker 0x{marker:02x}")


# ---------------------------------------------------------------------------
# chunk stream layer
# ---------------------------------------------------------------------------
class RtmpMessage:
    __slots__ = ("type_id", "stream_id", "timestamp", "payload")

    def __init__(self, type_id: int, stream_id: int, timestamp: int, payload: bytes):
        self.type_id = type_id
        self.stream_id = stream_id
        self.timestamp = timestamp
        self.payload = payload


class _CsState:
    """Per-chunk-stream header state (fmt 1-3 inherit prior values)."""

    __slots__ = ("timestamp", "ts_delta", "length", "type_id", "stream_id",
                 "partial", "has_ext")

    def __init__(self):
        self.timestamp = 0
        self.ts_delta = 0
        self.length = 0
        self.type_id = 0
        self.stream_id = 0
        self.partial = bytearray()
        self.has_ext = False  # fmt-3 continuations repeat the ext ts


class RtmpConn:
    """Per-socket RTMP state: handshake stage, chunk reassembly, and
    the negotiated chunk sizes (both directions)."""

    def __init__(self, is_server: bool):
        self.is_server = is_server
        self.stage = "hello"  # hello → ack → live
        self.in_chunk_size = DEFAULT_CHUNK_SIZE
        self.out_chunk_size = _OUT_CHUNK_SIZE
        self.cs: Dict[int, _CsState] = {}
        self.app = ""
        self.next_stream_id = 1
        # server-side roles on this connection
        self.publishing: Dict[int, str] = {}  # msg stream id → name
        self.playing: Dict[int, str] = {}
        self.out_lock = threading.Lock()
        self.sent_out_chunk_size = False


def _clamp_chunk_size(v: int) -> int:
    """RTMP requires 1 <= chunk size (and the wire caps at 0xFFFFFF);
    0 would make the parser consume headers forever without payload."""
    return max(1, min(v & 0x7FFFFFFF, 0xFFFFFF))


def pack_chunks(conn: RtmpConn, msg: RtmpMessage, csid: int = 3) -> bytes:
    """One message → fmt-0 chunk (+ fmt-3 continuations)."""
    out = bytearray()
    ts = msg.timestamp & 0x7FFFFFFF
    ext = ts >= 0xFFFFFF
    hdr_ts = 0xFFFFFF if ext else ts
    out += bytes([(0 << 6) | csid])
    out += struct.pack(">I", hdr_ts)[1:]  # 3 bytes
    out += struct.pack(">I", len(msg.payload))[1:]
    out += bytes([msg.type_id])
    out += struct.pack("<I", msg.stream_id)
    if ext:
        out += struct.pack(">I", ts)
    size = conn.out_chunk_size
    payload = msg.payload
    out += payload[:size]
    pos = size
    while pos < len(payload):
        out += bytes([(3 << 6) | csid])
        if ext:
            out += struct.pack(">I", ts)
        out += payload[pos : pos + size]
        pos += size
    return bytes(out)


def _cut_chunk(conn: RtmpConn, buf: IOBuf) -> Tuple[Optional[RtmpMessage], bool]:
    """Try to consume ONE chunk. → (complete_message|None, progressed)."""
    avail = len(buf)
    if avail < 1:
        return None, False
    first = buf.fetch(1)[0]
    fmt = first >> 6
    csid = first & 0x3F
    base = 1
    if csid == 0:
        if avail < 2:
            return None, False
        csid = 64 + buf.fetch(2)[1]
        base = 2
    elif csid == 1:
        if avail < 3:
            return None, False
        b = buf.fetch(3)
        csid = 64 + b[1] + (b[2] << 8)
        base = 3
    head_len = {0: 11, 1: 7, 2: 3, 3: 0}[fmt]
    need = base + head_len
    head = buf.fetch(need)
    if head is None:
        return None, False
    st = conn.cs.setdefault(csid, _CsState())
    p = base
    ext = False
    if fmt == 0:
        ts = int.from_bytes(head[p : p + 3], "big")
        st.length = int.from_bytes(head[p + 3 : p + 6], "big")
        st.type_id = head[p + 6]
        st.stream_id = struct.unpack_from("<I", head, p + 7)[0]
        ext = ts == 0xFFFFFF
        st.has_ext = ext
        if not ext:
            st.timestamp = ts
            st.ts_delta = 0
    elif fmt == 1:
        delta = int.from_bytes(head[p : p + 3], "big")
        st.length = int.from_bytes(head[p + 3 : p + 6], "big")
        st.type_id = head[p + 6]
        ext = delta == 0xFFFFFF
        st.has_ext = ext
        if not ext:
            st.ts_delta = delta
    elif fmt == 2:
        delta = int.from_bytes(head[p : p + 3], "big")
        ext = delta == 0xFFFFFF
        st.has_ext = ext
        if not ext:
            st.ts_delta = delta
    else:  # fmt 3: repeats the extended timestamp iff the message
        ext = st.has_ext  # opened with one (spec §5.3.1.3)
    if ext:
        ehead = buf.fetch(need + 4)
        if ehead is None:
            return None, False
        tsval = struct.unpack_from(">I", ehead, need)[0]
        if fmt == 0:
            st.timestamp = tsval
            st.ts_delta = 0
        elif fmt in (1, 2):
            st.ts_delta = tsval
        need += 4
    if st.length > 64 << 20:
        raise ValueError(f"rtmp message too large: {st.length}")
    remaining = st.length - len(st.partial)
    take = min(remaining, conn.in_chunk_size)
    total = need + take
    whole = buf.fetch(total)
    if whole is None:
        return None, False
    buf.pop_front(total)
    st.partial += whole[need:]
    if len(st.partial) < st.length:
        return None, True
    # message complete; fmt 1/2 advance the timestamp by their delta
    if fmt != 0:
        st.timestamp += st.ts_delta
    payload = bytes(st.partial)
    st.partial = bytearray()
    return RtmpMessage(st.type_id, st.stream_id, st.timestamp, payload), True


# ---------------------------------------------------------------------------
# parse (shared transport integration)
# ---------------------------------------------------------------------------
def parse(buf: IOBuf, sock, read_eof: bool) -> ParseResult:
    conn: Optional[RtmpConn] = getattr(sock, "_rtmp_conn", None)
    if conn is None:
        if not sock.is_server_side:
            return ParseResult.try_others()  # client uses RtmpClient
        head = buf.fetch(1)
        if head is None or head[0] != 0x03:
            return ParseResult.try_others()
        if len(buf) < 1 + HANDSHAKE_SIZE:
            return ParseResult.not_enough()
        # C0+C1 → reply S0+S1+S2.  A digested C1 (Flash-era "complex"
        # handshake) gets the digested S1/S2 it requires; plain C1s get
        # the simple echo handshake (reference tries digest first and
        # falls back, rtmp_protocol.cpp C1::Load)
        c0c1 = buf.fetch(1 + HANDSHAKE_SIZE)
        buf.pop_front(1 + HANDSHAKE_SIZE)
        c1 = c0c1[1:]
        schema, c1_digest = _hs_validate_c1(c1)
        if schema is not None:
            s1, s2 = _hs_build_s1s2(c1, schema, c1_digest)
            sock.write(IOBuf(b"\x03" + s1 + s2), ignore_eovercrowded=True)
        else:
            s1 = struct.pack(
                ">II", int(time.time()) & 0x7FFFFFFF, 0
            ) + os.urandom(HANDSHAKE_SIZE - 8)
            sock.write(IOBuf(b"\x03" + s1 + c1), ignore_eovercrowded=True)
        conn = RtmpConn(is_server=True)
        conn.stage = "ack"
        sock._rtmp_conn = conn
        return parse(buf, sock, read_eof)
    if conn.stage == "ack":
        if len(buf) < HANDSHAKE_SIZE:
            return ParseResult.not_enough()
        buf.pop_front(HANDSHAKE_SIZE)  # C2 (echo of S1) — accepted as-is
        conn.stage = "live"
    # live: cut chunks until one full message completes
    try:
        while True:
            msg, progressed = _cut_chunk(conn, buf)
            if msg is not None:
                if msg.type_id == MSG_SET_CHUNK_SIZE and len(msg.payload) >= 4:
                    conn.in_chunk_size = _clamp_chunk_size(
                        struct.unpack(">I", msg.payload[:4])[0]
                    )
                    continue
                if msg.type_id == MSG_ABORT and len(msg.payload) >= 4:
                    # drop the aborted chunk stream's partial message
                    # (spec §5.4.2) or its next message inherits it
                    (aborted,) = struct.unpack(">I", msg.payload[:4])
                    st = conn.cs.get(aborted)
                    if st is not None:
                        st.partial = bytearray()
                    continue
                if msg.type_id in (MSG_ACK, MSG_WINDOW_ACK_SIZE, MSG_SET_PEER_BW):
                    continue  # bookkeeping only
                return ParseResult.ok(msg)
            if not progressed:
                return ParseResult.not_enough()
    except (ValueError, IndexError, struct.error) as e:
        log_error("bad rtmp chunk: %r", e)
        return ParseResult.bad()


# ---------------------------------------------------------------------------
# server side
# ---------------------------------------------------------------------------
class RtmpService:
    """User hooks (reference RtmpService/RtmpServerOptions): override to
    gate/observe streams. The built-in relay fans each publisher's
    media out to the stream's players either way."""

    def on_connect(self, app: str) -> bool:
        return True

    def on_publish(self, app: str, stream_name: str) -> bool:
        return True

    def on_play(self, app: str, stream_name: str) -> bool:
        return True

    def on_frame(self, stream_name: str, msg: RtmpMessage) -> None:
        pass


class _StreamHub:
    """name → players; the media fan-out registry (one per server)."""

    def __init__(self):
        self.lock = threading.Lock()
        # name → list of (sock, stream_id on that subscriber's conn)
        self.players: Dict[str, List[Tuple[object, int]]] = {}
        self.meta: Dict[str, List[RtmpMessage]] = {}  # cached sequence headers

    def subscribe(self, name: str, sock, stream_id: int):
        with self.lock:
            self.players.setdefault(name, []).append((sock, stream_id))
            cached = list(self.meta.get(name, ()))
        conn = sock._rtmp_conn
        for m in cached:  # metadata/sequence headers arrive late-joiners
            _send_msg(sock, conn, RtmpMessage(m.type_id, stream_id, m.timestamp, m.payload))

    def unsubscribe_sock(self, sock):
        with self.lock:
            for name in list(self.players):
                self.players[name] = [
                    (s, sid) for (s, sid) in self.players[name] if s is not sock
                ]

    _META_CAP = 16  # cached headers per stream (late-joiner replay)

    def relay(self, name: str, msg: RtmpMessage):
        if msg.type_id == MSG_DATA_AMF0 or _is_sequence_header(msg):
            with self.lock:
                cache = self.meta.setdefault(name, [])
                cache.append(msg)
                # bounded: periodic data messages must not accumulate
                # forever (keep the newest — they supersede)
                if len(cache) > self._META_CAP:
                    del cache[0 : len(cache) - self._META_CAP]
        with self.lock:
            targets = list(self.players.get(name, ()))
        dead = []
        for sock, sid in targets:
            conn = getattr(sock, "_rtmp_conn", None)
            if conn is None or sock.failed:
                dead.append(sock)
                continue
            _send_msg(sock, conn, RtmpMessage(msg.type_id, sid, msg.timestamp, msg.payload))
        for s in dead:
            self.unsubscribe_sock(s)

    def end_stream(self, name: str):
        with self.lock:
            self.meta.pop(name, None)


def _is_sequence_header(msg: RtmpMessage) -> bool:
    """AVC/AAC sequence headers must reach late joiners first."""
    if not msg.payload:
        return False
    if msg.type_id == MSG_VIDEO:
        return (msg.payload[0] & 0x0F) == 7 and len(msg.payload) > 1 and msg.payload[1] == 0
    if msg.type_id == MSG_AUDIO:
        return (msg.payload[0] >> 4) == 10 and len(msg.payload) > 1 and msg.payload[1] == 0
    return False


def _packed_with_preamble(conn: RtmpConn, msg: RtmpMessage, csid: int) -> bytes:
    """Chunk `msg`, prefixing the one-time Set Chunk Size announcement.
    Caller holds conn.out_lock (one helper serves server sockets and
    the client; the wire logic must not fork)."""
    parts = b""
    if not conn.sent_out_chunk_size:
        conn.sent_out_chunk_size = True
        parts += pack_chunks(
            conn,
            RtmpMessage(MSG_SET_CHUNK_SIZE, 0, 0, struct.pack(">I", conn.out_chunk_size)),
            csid=2,
        )
    return parts + pack_chunks(conn, msg, csid)


def _send_msg(sock, conn: RtmpConn, msg: RtmpMessage, csid: int = 3):
    with conn.out_lock:
        sock.write(
            IOBuf(_packed_with_preamble(conn, msg, csid)), ignore_eovercrowded=True
        )


def _hub_of(server) -> _StreamHub:
    hub = getattr(server, "_rtmp_hub", None)
    if hub is None:
        hub = server._rtmp_hub = _StreamHub()
    return hub


def process_request(msg: RtmpMessage, sock) -> None:
    server = sock.server
    conn: RtmpConn = sock._rtmp_conn
    svc = getattr(getattr(server, "options", None), "rtmp_service", None) or RtmpService()
    hub = _hub_of(server)
    if msg.type_id in _MEDIA_TYPES:
        name = conn.publishing.get(msg.stream_id)
        if name:
            try:
                svc.on_frame(name, msg)
            except Exception as e:  # noqa: BLE001
                log_error("rtmp on_frame raised: %r", e)
            hub.relay(name, msg)
        return
    if msg.type_id != MSG_COMMAND_AMF0:
        return
    try:
        vals = amf0_decode_all(msg.payload)
    except (ValueError, IndexError, struct.error):
        log_error("bad amf0 command; closing rtmp conn")
        sock.set_failed(errors.EREQUEST, "bad amf0")
        return
    if not vals or not isinstance(vals[0], str):
        return
    cmd = vals[0]
    txn = vals[1] if len(vals) > 1 else 0
    if cmd == "connect":
        cobj = vals[2] if len(vals) > 2 and isinstance(vals[2], dict) else {}
        conn.app = str(cobj.get("app", ""))
        if not svc.on_connect(conn.app):
            _send_msg(sock, conn, RtmpMessage(
                MSG_COMMAND_AMF0, 0, 0,
                amf0_encode("_error", txn, None, {
                    "level": "error", "code": "NetConnection.Connect.Rejected"})))
            sock.set_failed(errors.ERPCAUTH, "rtmp connect rejected")
            return
        _send_msg(sock, conn, RtmpMessage(
            MSG_WINDOW_ACK_SIZE, 0, 0, struct.pack(">I", 2500000)), csid=2)
        _send_msg(sock, conn, RtmpMessage(
            MSG_SET_PEER_BW, 0, 0, struct.pack(">IB", 2500000, 2)), csid=2)
        _send_msg(sock, conn, RtmpMessage(
            MSG_COMMAND_AMF0, 0, 0,
            amf0_encode("_result", txn,
                        {"fmsVer": "TPB/1.0", "capabilities": 31.0},
                        {"level": "status", "code": "NetConnection.Connect.Success",
                         "description": "Connection succeeded."})))
    elif cmd == "createStream":
        sid = conn.next_stream_id
        conn.next_stream_id += 1
        _send_msg(sock, conn, RtmpMessage(
            MSG_COMMAND_AMF0, 0, 0,
            amf0_encode("_result", txn, None, float(sid))))
    elif cmd == "publish":
        name = vals[3] if len(vals) > 3 and isinstance(vals[3], str) else ""
        if not name or not svc.on_publish(conn.app, name):
            _send_msg(sock, conn, RtmpMessage(
                MSG_COMMAND_AMF0, msg.stream_id, 0,
                amf0_encode("onStatus", 0, None, {
                    "level": "error", "code": "NetStream.Publish.BadName"})))
            return
        conn.publishing[msg.stream_id] = name
        hub.end_stream(name)  # a fresh session must not replay a dead
        # publisher's stale sequence headers to late joiners
        _send_msg(sock, conn, RtmpMessage(
            MSG_COMMAND_AMF0, msg.stream_id, 0,
            amf0_encode("onStatus", 0, None, {
                "level": "status", "code": "NetStream.Publish.Start",
                "description": f"{name} is now published."})))
    elif cmd == "play":
        name = vals[3] if len(vals) > 3 and isinstance(vals[3], str) else ""
        if not name or not svc.on_play(conn.app, name):
            _send_msg(sock, conn, RtmpMessage(
                MSG_COMMAND_AMF0, msg.stream_id, 0,
                amf0_encode("onStatus", 0, None, {
                    "level": "error", "code": "NetStream.Play.StreamNotFound"})))
            return
        conn.playing[msg.stream_id] = name
        # User Control: Stream Begin
        _send_msg(sock, conn, RtmpMessage(
            MSG_USER_CONTROL, 0, 0,
            struct.pack(">HI", 0, msg.stream_id)), csid=2)
        _send_msg(sock, conn, RtmpMessage(
            MSG_COMMAND_AMF0, msg.stream_id, 0,
            amf0_encode("onStatus", 0, None, {
                "level": "status", "code": "NetStream.Play.Start",
                "description": f"Started playing {name}."})))
        hub.subscribe(name, sock, msg.stream_id)
    elif cmd in ("deleteStream", "closeStream"):
        sid = int(vals[3]) if len(vals) > 3 and isinstance(vals[3], (int, float)) else msg.stream_id
        name = conn.publishing.pop(sid, None)
        if name:
            hub.end_stream(name)
        conn.playing.pop(sid, None)
    else:
        log_verbose("rtmp: ignoring command %r", cmd)


PROTOCOL = Protocol(
    name="rtmp",
    parse=parse,
    process_request=process_request,
    support_client=False,
    process_in_place=True,  # chunk state is per-connection and ordered
)


def register():
    register_protocol(PROTOCOL)


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------
class RtmpClient:
    """Blocking RTMP client (reference RtmpClientStream analog):

        cli = RtmpClient("127.0.0.1", port, app="live")
        sid = cli.create_stream()
        cli.publish(sid, "room1")
        cli.write_frame(sid, MSG_VIDEO, ts, payload)

        sub = RtmpClient(..., on_media=fn)      # fn(RtmpMessage)
        sid = sub.create_stream(); sub.play(sid, "room1")
    """

    def __init__(self, host: str, port: int, app: str = "live",
                 on_media: Optional[Callable] = None, timeout_s: float = 8.0,
                 complex_handshake: bool = False):
        import socket as pysock

        self._sock = pysock.create_connection((host, port), timeout=timeout_s)
        self._conn = RtmpConn(is_server=False)
        self._conn.stage = "live"
        self._on_media = on_media
        self._txn = 0
        self._buf = IOBuf()
        self._pending: Dict[float, List] = {}
        self._status: List[dict] = []
        self._cv = threading.Condition()
        self._closed = False
        self._complex_handshake = complex_handshake
        self._handshake()
        self._reader = threading.Thread(target=self._read_loop, daemon=True)
        self._reader.start()
        self._command("connect", {"app": app, "flashVer": "TPB/1.0",
                                  "tcUrl": f"rtmp://{host}:{port}/{app}"})

    # -- wire helpers --
    def _handshake(self):
        if getattr(self, "_complex_handshake", False):
            # digested C1 (FP key) — Flash-era "complex" handshake; the
            # server must answer a digested S1 or we refuse
            schema = 1
            c1 = make_digested_c1(schema)
        else:
            schema = None
            c1 = struct.pack(
                ">II", int(time.time()) & 0x7FFFFFFF, 0
            ) + os.urandom(HANDSHAKE_SIZE - 8)
        self._sock.sendall(b"\x03" + c1)
        need = 1 + 2 * HANDSHAKE_SIZE
        got = b""
        while len(got) < need:
            chunk = self._sock.recv(need - len(got))
            if not chunk:
                raise ConnectionError("rtmp handshake EOF")
            got += chunk
        if got[0] != 0x03:
            raise ConnectionError("bad rtmp version")
        s1 = got[1 : 1 + HANDSHAKE_SIZE]
        if schema is not None:
            dig, joined = _hs_extract_digest(s1, schema)
            calc = _hmaclib.new(
                _HS_FMS_KEY[:36], joined, _hashlib.sha256
            ).digest()
            if not _hmaclib.compare_digest(calc, dig):
                raise ConnectionError(
                    "server S1 digest invalid (complex handshake)"
                )
        self._sock.sendall(s1)  # C2 = echo S1

    def _send(self, msg: RtmpMessage, csid: int = 3):
        with self._conn.out_lock:
            self._sock.sendall(_packed_with_preamble(self._conn, msg, csid))

    def _read_loop(self):
        try:
            while not self._closed:
                data = self._sock.recv(65536)
                if not data:
                    break
                self._buf.append(data)
                while True:
                    msg, progressed = _cut_chunk(self._conn, self._buf)
                    if msg is None:
                        if not progressed:
                            break
                        continue
                    try:
                        self._dispatch(msg)
                    except Exception as e:  # noqa: BLE001 — one malformed
                        # message must not silently kill the reader
                        log_error("rtmp client dispatch failed: %r", e)
        except OSError:
            pass
        except (ValueError, IndexError, struct.error) as e:
            log_error("rtmp client chunk desync: %r", e)
        finally:
            with self._cv:
                self._closed = True
                self._cv.notify_all()

    def _dispatch(self, msg: RtmpMessage):
        if msg.type_id == MSG_SET_CHUNK_SIZE and len(msg.payload) >= 4:
            self._conn.in_chunk_size = _clamp_chunk_size(
                struct.unpack(">I", msg.payload[:4])[0]
            )
            return
        if msg.type_id in _MEDIA_TYPES:
            if self._on_media:
                try:
                    self._on_media(msg)
                except Exception as e:  # noqa: BLE001
                    log_error("rtmp on_media raised: %r", e)
            return
        if msg.type_id != MSG_COMMAND_AMF0:
            return
        try:
            vals = amf0_decode_all(msg.payload)
        except (ValueError, IndexError, struct.error):
            return
        if not vals:
            return
        with self._cv:
            if vals[0] in ("_result", "_error"):
                self._pending[float(vals[1])] = vals
            elif vals[0] == "onStatus":
                self._status.append(vals[3] if len(vals) > 3 else {})
            self._cv.notify_all()

    def _command(self, name: str, *args, stream_id: int = 0, wait: bool = True):
        self._txn += 1
        txn = self._txn
        self._send(RtmpMessage(MSG_COMMAND_AMF0, stream_id, 0,
                               amf0_encode(name, float(txn), *args)))
        if not wait:
            return None
        deadline = time.monotonic() + 8
        with self._cv:
            while float(txn) not in self._pending:
                if self._closed or time.monotonic() > deadline:
                    raise TimeoutError(f"rtmp {name} got no _result")
                self._cv.wait(0.2)
            vals = self._pending.pop(float(txn))
        if vals[0] == "_error":
            raise RuntimeError(f"rtmp {name} rejected: {vals[3:]}" )
        return vals

    def _wait_status(self, code_prefix: str):
        deadline = time.monotonic() + 8
        with self._cv:
            while True:
                for st in self._status:
                    if isinstance(st, dict) and str(st.get("code", "")).startswith(code_prefix):
                        self._status.remove(st)
                        if st.get("level") == "error":
                            raise RuntimeError(f"rtmp status error: {st}")
                        return st
                if self._closed or time.monotonic() > deadline:
                    raise TimeoutError(f"no {code_prefix} status")
                self._cv.wait(0.2)

    # -- public API --
    def create_stream(self) -> int:
        vals = self._command("createStream", None)
        return int(vals[3])

    def publish(self, stream_id: int, name: str):
        self._command("publish", None, name, "live",
                      stream_id=stream_id, wait=False)
        self._wait_status("NetStream.Publish")

    def play(self, stream_id: int, name: str):
        self._command("play", None, name, -2.0,
                      stream_id=stream_id, wait=False)
        self._wait_status("NetStream.Play")

    def write_frame(self, stream_id: int, type_id: int, timestamp: int, payload: bytes):
        self._send(RtmpMessage(type_id, stream_id, timestamp, payload), csid=4)

    def delete_stream(self, stream_id: int):
        self._command("deleteStream", None, float(stream_id), wait=False)

    def close(self):
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass
