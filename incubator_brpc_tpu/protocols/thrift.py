"""Thrift framed-binary protocol — client + server.

Analog of reference policy/thrift_protocol.cpp + thrift_message.h:
TFramedTransport (u32 BE frame length) carrying strict TBinaryProtocol
messages (version 0x8001, message name, seqid, then the args/result
struct). The reference hands raw thrift structs to user code; here
structs round-trip through plain Python values:

    field dict  {field_id: (TType, value)}  — explicit, lossless

The server dispatches by thrift method name to handlers registered on a
ThriftService; seqid is the correlation id, so the client runs over the
standard single multiplexed connection.
"""

from __future__ import annotations

import struct
from typing import Dict, Optional, Tuple

from incubator_brpc_tpu import errors
from incubator_brpc_tpu.protocols import ParseResult, Protocol, register_protocol
from incubator_brpc_tpu.runtime.call_id import default_pool as _id_pool
from incubator_brpc_tpu.runtime.call_id import wire_cid32
from incubator_brpc_tpu.utils.iobuf import IOBuf
from incubator_brpc_tpu.utils.logging import log_error

VERSION_1 = 0x80010000
_VERSION_MASK = 0xFFFF0000

# TMessageType
CALL, REPLY, EXCEPTION, ONEWAY = 1, 2, 3, 4

# TType
T_STOP, T_BOOL, T_BYTE, T_DOUBLE = 0, 2, 3, 4
T_I16, T_I32, T_I64, T_STRING = 6, 8, 10, 11
T_STRUCT, T_MAP, T_SET, T_LIST = 12, 13, 14, 15

_MAX_FRAME = 64 << 20


# ---------------------------------------------------------------------------
# TBinaryProtocol value codec over field dicts {fid: (ttype, value)}
# ---------------------------------------------------------------------------
class _Writer:
    def __init__(self):
        self.parts = []

    def i8(self, v):
        self.parts.append(struct.pack(">b", v))

    def i16(self, v):
        self.parts.append(struct.pack(">h", v))

    def i32(self, v):
        self.parts.append(struct.pack(">i", v))

    def u32(self, v):
        self.parts.append(struct.pack(">I", v & 0xFFFFFFFF))

    def i64(self, v):
        self.parts.append(struct.pack(">q", v))

    def double(self, v):
        self.parts.append(struct.pack(">d", v))

    def string(self, v):
        if isinstance(v, str):
            v = v.encode()
        self.parts.append(struct.pack(">i", len(v)))
        self.parts.append(v)

    def value(self, ttype, v):
        if ttype == T_BOOL:
            self.i8(1 if v else 0)
        elif ttype == T_BYTE:
            self.i8(v)
        elif ttype == T_DOUBLE:
            self.double(v)
        elif ttype == T_I16:
            self.i16(v)
        elif ttype == T_I32:
            self.i32(v)
        elif ttype == T_I64:
            self.i64(v)
        elif ttype == T_STRING:
            self.string(v)
        elif ttype == T_STRUCT:
            self.struct(v)
        elif ttype == T_MAP:
            kt, vt, items = v
            self.i8(kt)
            self.i8(vt)
            self.i32(len(items))
            for k, val in items.items() if isinstance(items, dict) else items:
                self.value(kt, k)
                self.value(vt, val)
        elif ttype in (T_SET, T_LIST):
            et, items = v
            self.i8(et)
            self.i32(len(items))
            for item in items:
                self.value(et, item)
        else:
            raise ValueError(f"unsupported ttype {ttype}")

    def struct(self, fields: Dict[int, Tuple[int, object]]):
        for fid, (ttype, v) in sorted(fields.items()):
            self.i8(ttype)
            self.i16(fid)
            self.value(ttype, v)
        self.i8(T_STOP)

    def bytes(self) -> bytes:
        return b"".join(self.parts)


class _Reader:
    def __init__(self, data: bytes, pos: int = 0):
        self.data = data
        self.pos = pos

    def _take(self, n) -> bytes:
        if self.pos + n > len(self.data):
            raise ValueError("thrift payload truncated")
        out = self.data[self.pos : self.pos + n]
        self.pos += n
        return out

    def i8(self):
        return struct.unpack(">b", self._take(1))[0]

    def i16(self):
        return struct.unpack(">h", self._take(2))[0]

    def i32(self):
        return struct.unpack(">i", self._take(4))[0]

    def i64(self):
        return struct.unpack(">q", self._take(8))[0]

    def double(self):
        return struct.unpack(">d", self._take(8))[0]

    def string(self):
        n = self.i32()
        if n < 0:
            raise ValueError("negative string length")
        return self._take(n)

    def value(self, ttype):
        if ttype == T_BOOL:
            return bool(self.i8())
        if ttype == T_BYTE:
            return self.i8()
        if ttype == T_DOUBLE:
            return self.double()
        if ttype == T_I16:
            return self.i16()
        if ttype == T_I32:
            return self.i32()
        if ttype == T_I64:
            return self.i64()
        if ttype == T_STRING:
            return self.string()
        if ttype == T_STRUCT:
            return self.struct()
        if ttype == T_MAP:
            kt, vt, n = self.i8(), self.i8(), self.i32()
            return (kt, vt, [(self.value(kt), self.value(vt)) for _ in range(n)])
        if ttype in (T_SET, T_LIST):
            et, n = self.i8(), self.i32()
            return (et, [self.value(et) for _ in range(n)])
        raise ValueError(f"unsupported ttype {ttype}")

    def struct(self) -> Dict[int, Tuple[int, object]]:
        fields = {}
        while True:
            ttype = self.i8()
            if ttype == T_STOP:
                return fields
            fid = self.i16()
            fields[fid] = (ttype, self.value(ttype))


class ThriftMessage:
    __slots__ = ("method", "mtype", "seqid", "fields")

    def __init__(self, method: str, mtype: int, seqid: int, fields):
        self.method = method
        self.mtype = mtype
        self.seqid = seqid
        self.fields = fields  # {fid: (ttype, value)}


def pack_message(method: str, mtype: int, seqid: int, fields) -> bytes:
    w = _Writer()
    w.u32(VERSION_1 | mtype)
    w.string(method)
    w.u32(seqid)
    w.struct(fields or {})
    body = w.bytes()
    return struct.pack(">I", len(body)) + body


def exception_fields(message: str, etype: int = 6) -> dict:
    """TApplicationException struct (1: message, 2: type).
    etype 6 = INTERNAL_ERROR, 1 = UNKNOWN_METHOD."""
    return {1: (T_STRING, message), 2: (T_I32, etype)}


# ---- framing ---------------------------------------------------------------
def parse(buf: IOBuf, sock, read_eof: bool) -> ParseResult:
    head = buf.fetch(8)
    if head is None:
        got = buf.fetch(min(len(buf), 8)) or b""
        # an empty/short prefix could still become a thrift frame IF the
        # version bytes we have so far agree
        if len(got) >= 5 and got[4] != 0x80:
            return ParseResult.try_others()
        return ParseResult.not_enough()
    (frame_len,) = struct.unpack_from(">I", head, 0)
    version = struct.unpack_from(">I", head, 4)[0] & _VERSION_MASK
    if version != (VERSION_1 & _VERSION_MASK):
        return ParseResult.try_others()
    if frame_len > _MAX_FRAME or frame_len < 12:
        return ParseResult.bad()
    if len(buf) < 4 + frame_len:
        return ParseResult.not_enough()
    buf.pop_front(4)
    body = buf.cut_bytes(frame_len)
    try:
        r = _Reader(body)
        ver_type = r.i32() & 0xFFFFFFFF
        mtype = ver_type & 0xFF
        method = r.string().decode("utf-8", "replace")
        seqid = r.i32() & 0xFFFFFFFF
        fields = r.struct()
    except ValueError as e:
        log_error("bad thrift frame: %r", e)
        return ParseResult.bad()
    return ParseResult.ok(ThriftMessage(method, mtype, seqid, fields))


# ---- server side -----------------------------------------------------------
class ThriftService:
    """Register with ServerOptions.thrift_service (the reference's
    ServerOptions.thrift_service, thrift_service.h). Handlers:

        svc.add_method("Echo", fn)  with
        fn(controller, fields: dict, done(result_fields | None))
    """

    def __init__(self):
        self._methods = {}

    def add_method(self, name: str, fn):
        self._methods[name] = fn
        return self

    def find(self, name: str):
        return self._methods.get(name)


def process_request(msg: ThriftMessage, sock) -> None:
    from incubator_brpc_tpu.client.controller import Controller

    server = sock.server
    oneway = msg.mtype == ONEWAY  # spec: NOTHING may be written back
    svc = getattr(getattr(server, "options", None), "thrift_service", None)
    if svc is None:
        if not oneway:
            sock.write(
                IOBuf(
                    pack_message(
                        msg.method, EXCEPTION, msg.seqid,
                        exception_fields("no thrift service configured", 1),
                    )
                ),
                ignore_eovercrowded=True,
            )
        return
    fn = svc.find(msg.method)
    if fn is None:
        if not oneway:
            sock.write(
                IOBuf(
                    pack_message(
                        msg.method, EXCEPTION, msg.seqid,
                        exception_fields(f"unknown method {msg.method}", 1),
                    )
                ),
                ignore_eovercrowded=True,
            )
        return
    ctrl = Controller()
    ctrl.server = server
    ctrl._server_socket = sock
    ctrl.remote_side = sock.remote
    sent = [False]

    def done(result_fields=None):
        if sent[0]:
            return
        sent[0] = True
        ctrl._release_session_local()  # handler done: pool the user data
        if oneway:
            return  # oneway calls never get a reply frame
        if ctrl.failed():
            wire = pack_message(
                msg.method, EXCEPTION, msg.seqid,
                exception_fields(ctrl.error_text() or "failed"),
            )
        else:
            # thrift result struct: field 0 = return value
            wire = pack_message(msg.method, REPLY, msg.seqid, result_fields or {})
        sock.write(IOBuf(wire), ignore_eovercrowded=True)

    try:
        fn(ctrl, msg.fields, done)
    except Exception as e:  # noqa: BLE001
        log_error("thrift handler %s raised: %r", msg.method, e)
        if not sent[0]:
            ctrl.set_failed(errors.EINTERNAL, f"handler raised: {e}")
            done()


# ---- client side -----------------------------------------------------------
def serialize_request(request, controller) -> IOBuf:
    """request is the args field dict; packing happens per attempt."""
    out = IOBuf()
    w = _Writer()
    w.struct(request or {})
    out.append(w.bytes())
    return out


def pack_request(request_buf: IOBuf, wire_cid: int, method_spec, controller) -> IOBuf:
    seqid = wire_cid32(wire_cid)
    w = _Writer()
    w.u32(VERSION_1 | CALL)
    w.string(method_spec.method_name)
    w.u32(seqid)
    head = w.bytes()
    body_len = len(head) + len(request_buf)
    out = IOBuf()
    out.append(struct.pack(">I", body_len) + head)
    out.append(request_buf)
    return out


def process_response(msg: ThriftMessage, sock) -> None:
    cid = _full_cid(sock, msg.seqid)
    pool = _id_pool()
    ctrl = pool.lock(cid)
    if ctrl is None:
        return
    if msg.mtype == EXCEPTION:
        emsg = msg.fields.get(1, (T_STRING, b"thrift exception"))[1]
        if isinstance(emsg, bytes):
            emsg = emsg.decode("utf-8", "replace")
        ctrl.set_failed(errors.ERESPONSE, emsg)
    else:
        if ctrl._response is not None and isinstance(ctrl._response, dict):
            ctrl._response.clear()
            ctrl._response.update(msg.fields)
    ctrl._finalize_locked(cid)


def _full_cid(sock, seqid: int) -> int:
    """seqid carries the gen-mixed 32-bit cid form (wire_cid32);
    responses arrive on the socket the request went out on, where the
    full id is registered as a response waiter (socket.waiting_cids)."""
    with sock._write_lock:
        for full in sock.waiting_cids:
            if wire_cid32(full) == seqid:
                return full
    return seqid


class ThriftStub:
    """Client helper: stub.call(cntl, "Echo", {1: (T_STRING, b"hi")})
    → result field dict (field 0 is the thrift return value)."""

    def __init__(self, channel):
        self._channel = channel

    def call(self, controller, method: str, fields=None, done=None) -> dict:
        from incubator_brpc_tpu.server.service import MethodSpec

        spec = MethodSpec("thrift", method, dict, dict)
        response: dict = {}
        self._channel.call_method(spec, controller, fields or {}, response, done)
        return response


PROTOCOL = Protocol(
    name="thrift",
    parse=parse,
    serialize_request=serialize_request,
    pack_request=pack_request,
    process_request=process_request,
    process_response=process_response,
)


def register():
    register_protocol(PROTOCOL)
