"""Redis protocol — RESP client + redis-speaking server, pipelined.

Analog of reference policy/redis_protocol.cpp + redis.{h,cpp} +
redis_command/redis_reply (RESP wire format, RFC-less but precisely
specified): the exemplar correlation-less pipelined protocol. Client
usage mirrors redis.h:43-47:

    req = RedisRequest()
    req.add_command("SET", "k", "v")
    req.add_command("GET", "k")
    resp = RedisResponse()
    channel.call_method(redis_method_spec(), ctrl, req, resp)
    resp.reply(1).value  # b"v"

Server side (reference redis.h RedisService/RedisCommandHandler): set
``ServerOptions.redis_service`` to a ``RedisService`` subclass whose
lower-case methods implement commands; any redis-cli can talk to it.

Pipelining: one RedisRequest = N commands = N in-order replies; the
per-connection FIFO rides Socket.pipelined_info with count=N — the
machinery HTTP uses loosely is exercised exactly here. Responses are
matched strictly in arrival order, so the protocol is process_ordered
on the server and the client accumulates replies per (cid, count).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from incubator_brpc_tpu import errors
from incubator_brpc_tpu.protocols import ParseResult, Protocol, register_protocol
from incubator_brpc_tpu.runtime.call_id import default_pool as _id_pool
from incubator_brpc_tpu.utils.iobuf import DeviceRef, IOBuf
from incubator_brpc_tpu.utils.logging import log_error


def _is_device_value(v) -> bool:
    """A bulk-string payload that lives in HBM: a DeviceRef segment or a
    raw jax.Array (anything with nbytes+dtype that is not host bytes)."""
    if isinstance(v, DeviceRef):
        return True
    return (
        hasattr(v, "nbytes")
        and hasattr(v, "dtype")
        and not isinstance(v, (bytes, bytearray, memoryview))
    )

# reply types (reference redis_reply.h:33-38)
REPLY_STRING = 1  # bulk string
REPLY_ARRAY = 2
REPLY_INTEGER = 3
REPLY_NIL = 4
REPLY_STATUS = 5  # simple string (+OK)
REPLY_ERROR = 6


class RedisReply:
    __slots__ = ("type", "value")

    def __init__(self, rtype: int, value=None):
        self.type = rtype
        self.value = value

    # constructors
    @staticmethod
    def status(s: str) -> "RedisReply":
        return RedisReply(REPLY_STATUS, s)

    @staticmethod
    def error(s: str) -> "RedisReply":
        return RedisReply(REPLY_ERROR, s)

    @staticmethod
    def integer(n: int) -> "RedisReply":
        return RedisReply(REPLY_INTEGER, int(n))

    @staticmethod
    def bulk(b) -> "RedisReply":
        if isinstance(b, str):
            b = b.encode()
        return RedisReply(REPLY_STRING, b)

    @staticmethod
    def nil() -> "RedisReply":
        return RedisReply(REPLY_NIL, None)

    @staticmethod
    def array(items: List["RedisReply"]) -> "RedisReply":
        return RedisReply(REPLY_ARRAY, list(items))

    # predicates (reference redis_reply.h surface)
    def is_device(self) -> bool:
        """True when this bulk's payload is HBM-resident (the zero-copy
        device path: value is a DeviceRef or jax.Array, not host bytes)."""
        return self.type == REPLY_STRING and _is_device_value(self.value)

    def device_array(self):
        """The HBM-resident jax.Array of a device-path bulk reply, or
        None for host replies / windowed refs (which must materialize)."""
        v = self.value
        if isinstance(v, DeviceRef):
            return v.whole_array()
        if _is_device_value(v):
            return v
        return None

    def bytes_value(self) -> Optional[bytes]:
        """The bulk payload as host bytes.  Host replies return their
        value directly; device replies MATERIALIZE (a manifested
        device→host pull through iobuf.host-view) — never call this on
        the hot path of a device consumer."""
        v = self.value
        if isinstance(v, DeviceRef):
            return bytes(v.view())
        if _is_device_value(v):
            return bytes(DeviceRef(v).view())
        return v

    def is_nil(self) -> bool:
        return self.type == REPLY_NIL

    def is_error(self) -> bool:
        return self.type == REPLY_ERROR

    def __eq__(self, other):
        if isinstance(other, RedisReply):
            return self.type == other.type and self.value == other.value
        return NotImplemented

    def __repr__(self):
        names = {1: "str", 2: "arr", 3: "int", 4: "nil", 5: "status", 6: "err"}
        return f"RedisReply<{names.get(self.type)}:{self.value!r}>"


def _coerce_reply(v) -> RedisReply:
    """Server handlers may return plain Python values."""
    if isinstance(v, RedisReply):
        return v
    if v is None:
        return RedisReply.nil()
    if isinstance(v, bool):
        return RedisReply.integer(int(v))
    if isinstance(v, int):
        return RedisReply.integer(v)
    if isinstance(v, (bytes, bytearray)):
        return RedisReply.bulk(bytes(v))
    if isinstance(v, str):
        return RedisReply.bulk(v)
    if isinstance(v, (list, tuple)):
        return RedisReply.array([_coerce_reply(x) for x in v])
    return RedisReply.error(f"ERR unserializable reply type {type(v).__name__}")


# ---- RESP wire format -------------------------------------------------------
def pack_command(*components) -> bytes:
    """One command as a RESP array of bulk strings (what clients send)."""
    out = [b"*%d\r\n" % len(components)]
    for c in components:
        if isinstance(c, str):
            c = c.encode()
        elif isinstance(c, int):
            c = b"%d" % c
        out.append(b"$%d\r\n%s\r\n" % (len(c), c))
    return b"".join(out)


def pack_reply(r: RedisReply) -> bytes:
    t = r.type
    if t == REPLY_STATUS:
        return b"+%s\r\n" % str(r.value).encode()
    if t == REPLY_ERROR:
        return b"-%s\r\n" % str(r.value).encode()
    if t == REPLY_INTEGER:
        return b":%d\r\n" % r.value
    if t == REPLY_NIL:
        return b"$-1\r\n"
    if t == REPLY_STRING:
        v = r.value or b""
        return b"$%d\r\n%s\r\n" % (len(v), v)
    if t == REPLY_ARRAY:
        if r.value is None:
            return b"*-1\r\n"
        return b"*%d\r\n" % len(r.value) + b"".join(pack_reply(x) for x in r.value)
    raise ValueError(f"bad reply type {t}")


def pack_reply_into(r: RedisReply, out: IOBuf) -> None:
    """Pack one reply into ``out``, keeping HBM-resident bulk payloads
    as DeviceRef segments (the ICI transport ships them zero-copy; a
    host transport materializes lazily at the wire).  Host-only replies
    take the plain ``pack_reply`` byte path."""
    if r.type == REPLY_STRING and _is_device_value(r.value):
        arr = r.value.whole_array() if isinstance(r.value, DeviceRef) else r.value
        if arr is None:
            # windowed ref: no zero-copy identity to ship; materialize
            # once through the sanctioned iobuf.host-view choke point
            b = bytes(r.value.view())
            out.append(b"$%d\r\n" % len(b))
            out.append(b)
            out.append(b"\r\n")
            return
        out.append(b"$%d\r\n" % int(arr.nbytes))
        out.append_device(arr)
        out.append(b"\r\n")
        return
    if r.type == REPLY_ARRAY and r.value:
        if any(_carries_device(x) for x in r.value):
            out.append(b"*%d\r\n" % len(r.value))
            for x in r.value:
                pack_reply_into(x, out)
            return
    out.append(pack_reply(r))


def _carries_device(r: RedisReply) -> bool:
    if r.type == REPLY_STRING:
        return _is_device_value(r.value)
    if r.type == REPLY_ARRAY and r.value:
        return any(_carries_device(x) for x in r.value)
    return False


_MAX_NESTING = 32


def parse_reply(
    data: bytes, pos: int = 0, _depth: int = 0
) -> Tuple[Optional[RedisReply], int]:
    """Parse ONE RESP value at pos. Returns (reply, new_pos) or
    (None, pos) when incomplete. Raises ValueError on malformed input
    (including absurd nesting — unbounded recursion would let a peer
    wedge the read task with a RecursionError)."""
    if _depth > _MAX_NESTING:
        raise ValueError("RESP nesting too deep")
    if pos >= len(data):
        return None, pos
    marker = data[pos : pos + 1]
    line_end = data.find(b"\r\n", pos)
    if line_end < 0:
        return None, pos
    line = data[pos + 1 : line_end]
    after = line_end + 2
    if marker == b"+":
        return RedisReply.status(line.decode("utf-8", "replace")), after
    if marker == b"-":
        return RedisReply.error(line.decode("utf-8", "replace")), after
    if marker == b":":
        return RedisReply.integer(int(line)), after
    if marker == b"$":
        n = int(line)
        if n == -1:
            return RedisReply.nil(), after
        if n < 0:
            raise ValueError(f"bad bulk length {n}")
        if len(data) < after + n + 2:
            return None, pos
        if data[after + n : after + n + 2] != b"\r\n":
            raise ValueError("bulk string not CRLF terminated")
        return RedisReply(REPLY_STRING, data[after : after + n]), after + n + 2
    if marker == b"*":
        n = int(line)
        if n == -1:
            return RedisReply(REPLY_ARRAY, None), after
        if n < 0:
            raise ValueError(f"bad array length {n}")
        items = []
        p = after
        for _ in range(n):
            item, p2 = parse_reply(data, p, _depth + 1)
            if item is None:
                return None, pos
            items.append(item)
            p = p2
        return RedisReply.array(items), p
    raise ValueError(f"bad RESP marker {marker!r}")


# ---- device-aware RESP parse ------------------------------------------------
class _FallbackParse(Exception):
    """The buffer's device-segment layout doesn't line up with RESP
    framing (a device ref mid-line, a bulk body only partially device):
    the caller falls back to the materializing byte path — correct, but
    it pulls, so the transfer witness keeps the hot path honest."""


class _SpanCursor:
    """A logical read cursor over an IOBuf's ref sequence that yields
    host bytes and treats DeviceRef segments as opaque spans.  Nothing
    is consumed from the buffer — the caller pops ``consumed`` bytes
    only once a complete reply parsed."""

    __slots__ = ("refs", "i", "off", "consumed")

    def __init__(self, refs):
        self.refs = refs
        self.i = 0
        self.off = 0
        self.consumed = 0

    def _cur(self):
        while self.i < len(self.refs):
            ref = self.refs[self.i]
            if self.off < ref.length:
                return ref
            self.i += 1
            self.off = 0
        return None

    def at_device(self) -> Optional[DeviceRef]:
        ref = self._cur()
        if isinstance(ref, DeviceRef) and self.off == 0:
            return ref
        return None

    def take_device(self) -> DeviceRef:
        ref = self.refs[self.i]
        self.i += 1
        self.off = 0
        self.consumed += ref.length
        return ref

    def read_host(self, n: int) -> Optional[bytes]:
        """Read exactly n host bytes; None = buffer exhausted (need more
        data); raises _FallbackParse when a device segment intrudes."""
        parts = []
        left = n
        while left > 0:
            ref = self._cur()
            if ref is None:
                return None
            if isinstance(ref, DeviceRef):
                raise _FallbackParse
            take = min(ref.length - self.off, left)
            parts.append(bytes(ref.view()[self.off : self.off + take]))
            self.off += take
            self.consumed += take
            left -= take
        return b"".join(parts)

    def read_line(self) -> Optional[bytes]:
        """Read one CRLF-terminated line of host bytes (without the
        CRLF); None = incomplete."""
        out = bytearray()
        while True:
            ref = self._cur()
            if ref is None:
                return None
            if isinstance(ref, DeviceRef):
                raise _FallbackParse
            v = ref.view()
            span = bytes(v[self.off : ref.length])
            idx = span.find(b"\n")
            if idx < 0:
                out += span
                self.consumed += len(span)
                self.i += 1
                self.off = 0
                if len(out) > 1 << 16:
                    raise ValueError("RESP line too long")
                continue
            out += span[: idx + 1]
            self.off += idx + 1
            self.consumed += idx + 1
            if len(out) < 2 or out[-2:] != b"\r\n":
                raise ValueError("RESP line not CRLF terminated")
            return bytes(out[:-2])


def _parse_value_spans(cur: _SpanCursor, _depth: int = 0) -> Optional[RedisReply]:
    """Parse ONE RESP value at the cursor, keeping device segments
    device-resident: a bulk string whose body is exactly one whole-array
    DeviceRef becomes a reply carrying that ref (zero materialization).
    Returns None when incomplete; raises ValueError on malformed input
    and _FallbackParse on layouts needing the byte path."""
    if _depth > _MAX_NESTING:
        raise ValueError("RESP nesting too deep")
    line = cur.read_line()
    if line is None:
        return None
    if not line:
        raise ValueError("empty RESP line")
    marker, body = line[:1], line[1:]
    if marker == b"+":
        return RedisReply.status(body.decode("utf-8", "replace"))
    if marker == b"-":
        return RedisReply.error(body.decode("utf-8", "replace"))
    if marker == b":":
        return RedisReply.integer(int(body))
    if marker == b"$":
        n = int(body)
        if n == -1:
            return RedisReply.nil()
        if n < 0:
            raise ValueError(f"bad bulk length {n}")
        dev = cur.at_device()
        if dev is not None and dev.length == n and dev.whole_array() is not None:
            ref = cur.take_device()
            tail = cur.read_host(2)
            if tail is None:
                return None
            if tail != b"\r\n":
                raise ValueError("bulk string not CRLF terminated")
            return RedisReply(REPLY_STRING, ref)
        if dev is not None:
            raise _FallbackParse  # windowed/partial device body
        data = cur.read_host(n)
        if data is None:
            return None
        tail = cur.read_host(2)
        if tail is None:
            return None
        if tail != b"\r\n":
            raise ValueError("bulk string not CRLF terminated")
        return RedisReply(REPLY_STRING, data)
    if marker == b"*":
        n = int(body)
        if n == -1:
            return RedisReply(REPLY_ARRAY, None)
        if n < 0:
            raise ValueError(f"bad array length {n}")
        items = []
        for _ in range(n):
            item = _parse_value_spans(cur, _depth + 1)
            if item is None:
                return None
            items.append(item)
        return RedisReply.array(items)
    raise ValueError(f"bad RESP marker {marker!r}")


def parse_device_aware(buf: IOBuf) -> Tuple[Optional[RedisReply], int]:
    """Parse ONE RESP value from a buffer that carries DeviceRef
    segments, WITHOUT materializing them (the ``copy_to`` path would
    pull every HBM value to host just to frame the reply).  Returns
    (reply, consumed); (None, 0) = incomplete.  Raises ValueError on
    malformed input, _FallbackParse when the layout needs the byte
    path.  The caller pops ``consumed`` bytes on success — the reply's
    DeviceRef objects keep their arrays alive independently."""
    cur = _SpanCursor(buf.iter_refs())
    value = _parse_value_spans(cur)
    if value is None:
        return None, 0
    return value, cur.consumed


# ---- client-side messages (reference RedisRequest/RedisResponse) -----------
class RedisRequest:
    def __init__(self):
        # chunks: host bytes interleaved with device arrays — a command
        # component may be an HBM-resident jax.Array (the cache SET
        # ingest path); it rides the wire as a DeviceRef bulk segment
        self._chunks: List = []
        self._count = 0
        self._has_device = False

    def add_command(self, *components) -> bool:
        """add_command("SET", "k", "v") — AddCommand analog (one command
        per call; components are sent verbatim, no quoting needed).
        A component may be a device-resident jax.Array: it is framed as
        a bulk string of its nbytes and shipped as a DeviceRef segment
        (zero-copy over ICI; lazily materialized on host transports)."""
        if not components:
            return False
        host = bytearray(b"*%d\r\n" % len(components))
        for c in components:
            if isinstance(c, str):
                c = c.encode()
            elif isinstance(c, int):
                c = b"%d" % c
            if _is_device_value(c):
                host += b"$%d\r\n" % int(c.nbytes)
                self._chunks.append(bytes(host))
                self._chunks.append(c)
                self._has_device = True
                host = bytearray(b"\r\n")
            else:
                host += b"$%d\r\n%s\r\n" % (len(c), c)
        self._chunks.append(bytes(host))
        self._count += 1
        return True

    @property
    def command_count(self) -> int:
        return self._count

    def clear(self):
        self._chunks = []
        self._count = 0
        self._has_device = False

    def SerializeToString(self) -> bytes:  # Message-compatible surface
        if self._has_device:
            raise ValueError("device-payload request needs serialize_iobuf()")
        return b"".join(self._chunks)

    def serialize_iobuf(self) -> IOBuf:
        out = IOBuf()
        for c in self._chunks:
            if isinstance(c, bytes):
                out.append(c)
            else:
                out.append_device(c)
        return out


class RedisResponse:
    def __init__(self):
        self._replies: List[RedisReply] = []

    def reply(self, i: int) -> RedisReply:
        return self._replies[i]

    @property
    def reply_size(self) -> int:
        return len(self._replies)

    def _set_replies(self, replies: List[RedisReply]):
        self._replies = list(replies)

    def ParseFromString(self, data: bytes):  # unused; protocol fills directly
        pass


class _RedisMethodSpec:
    service_name = "redis"
    method_name = "command"
    full_name = "redis.command"
    request_class = RedisRequest
    response_class = RedisResponse


def redis_method_spec() -> _RedisMethodSpec:
    return _RedisMethodSpec()


# ---- protocol callbacks -----------------------------------------------------
class _WireMsg:
    """One parsed wire unit: a reply (client side) or command (server)."""

    __slots__ = ("reply", "command")

    def __init__(self, reply=None, command=None):
        self.reply = reply
        self.command = command


def parse(buf: IOBuf, sock, read_eof: bool) -> ParseResult:
    if buf.has_device_payload():
        # device-resident segments in the frame: the span parser keeps
        # them in HBM (fetch/copy_to below would pull them to host just
        # to frame the reply)
        first = next(iter(buf.iter_refs()), None)
        if isinstance(first, DeviceRef):
            return ParseResult.bad()  # RESP never starts mid-payload
        try:
            value, consumed = parse_device_aware(buf)
        except _FallbackParse:
            value, consumed = None, -1  # materializing path below
        except (ValueError, IndexError, RecursionError):
            return ParseResult.bad()
        if consumed >= 0:
            if value is None:
                return ParseResult.not_enough()
            buf.pop_front(consumed)
            if sock.is_server_side:
                if value.type != REPLY_ARRAY or not value.value:
                    return ParseResult.bad()
                return ParseResult.ok(_WireMsg(command=value))
            return ParseResult.ok(_WireMsg(reply=value))
    head = buf.fetch(1)
    if not head:
        return ParseResult.not_enough()
    if sock.is_server_side:
        if head not in (b"*",):  # clients speak RESP arrays (or inline, unsupported)
            return ParseResult.try_others()
    else:
        if head not in (b"+", b"-", b":", b"$", b"*"):
            return ParseResult.try_others()
    # bound the copy: one reply is usually tiny, and copying the whole
    # buffer per cut makes a large pipelined batch O(N^2). Retry with
    # the full buffer only when a genuinely big reply needs it.
    limit = 1 << 16
    data = buf.copy_to(min(len(buf), limit))
    try:
        value, pos = parse_reply(data, 0)
        if value is None and len(buf) > limit:
            data = buf.copy_to(len(buf))
            value, pos = parse_reply(data, 0)
    except (ValueError, IndexError, RecursionError):
        return ParseResult.bad()
    if value is None:
        return ParseResult.not_enough()
    buf.pop_front(pos)
    if sock.is_server_side:
        if value.type != REPLY_ARRAY or not value.value:
            return ParseResult.bad()
        return ParseResult.ok(_WireMsg(command=value))
    return ParseResult.ok(_WireMsg(reply=value))


def serialize_request(request: RedisRequest, controller) -> IOBuf:
    if request.command_count == 0:
        raise ValueError("RedisRequest has no commands")
    controller._redis_count = request.command_count
    return request.serialize_iobuf()


def pack_request(request_buf: IOBuf, wire_cid: int, method_spec, controller) -> IOBuf:
    count = getattr(controller, "_redis_count", 1)
    packet = IOBuf()
    channel = controller._channel
    auth = channel.options.auth if channel is not None else None
    if auth is not None:
        # The first command on a credentialed connection must be AUTH
        # (the server's verify gate demands it). The credential is
        # computed here (raising fails the RPC), but WHICH writer
        # prepends it is decided inside Socket.write under the write
        # lock — deciding here would let a concurrent packet overtake
        # the AUTH and hit the gate unauthenticated. cid 0 = delivery
        # discards the +OK.
        cred = auth.generate_credential()
        controller._conn_preamble = (IOBuf(pack_command("AUTH", cred)), [(0, 1)])
    packet.append(request_buf)
    # FIFO entries register inside the write, atomic with queue order
    controller._pipelined_entries = [(wire_cid, count)]
    return packet


def process_response(msg: _WireMsg, sock) -> None:
    """Accumulate replies for the FIFO-front RPC; deliver at count."""
    from incubator_brpc_tpu.protocols import accumulate_pipelined

    done = accumulate_pipelined(sock, msg.reply)
    if done is None:
        return
    cid, replies = done
    if not cid:
        return  # cid 0: protocol-internal command (AUTH), discard reply
    pool = _id_pool()
    ctrl = pool.lock(cid)
    if ctrl is None:
        return
    if ctrl._response is not None:
        ctrl._response._set_replies(replies)
    first_err = next((r for r in replies if r.is_error()), None)
    if first_err is not None and len(replies) == 1:
        # single-command convenience: surface the error on the controller
        # (multi-command pipelines inspect per-reply errors themselves).
        # An -OVERCROWDED reply is the server's admission shed riding
        # RESP: map it back to the retry-elsewhere code so LB feedback
        # (on_shed) and the retry policy treat it like any other shed.
        text = str(first_err.value)
        if text.startswith("OVERCROWDED"):
            ctrl.set_failed(errors.EOVERCROWDED, text)
        else:
            ctrl.set_failed(errors.ERESPONSE, text)
    ctrl._finalize_locked(cid)


# ---- server side (reference redis.h RedisService) ---------------------------
class RedisService:
    """Subclass and define lower-case methods named after commands:

        class KV(RedisService):
            def get(self, key): return self._d.get(key)
            def set(self, key, value): self._d[key] = value; return "OK"

    Return values coerce: str→bulk, "OK"-style statuses via
    RedisReply.status, int→integer, None→nil, list→array, RedisReply
    passthrough. Unknown commands answer -ERR unknown command."""

    def handle(self, command: str, args: List[bytes]) -> RedisReply:
        fn = getattr(self, command.lower(), None)
        if fn is None or command.startswith("_") or command.lower() == "handle":
            return RedisReply.error(f"ERR unknown command '{command}'")
        try:
            return _coerce_reply(fn(*args))
        except TypeError as e:
            return RedisReply.error(f"ERR wrong number of arguments: {e}")
        except Exception as e:  # noqa: BLE001
            log_error("redis handler %s raised: %r", command, e)
            return RedisReply.error(f"ERR internal: {e}")

    # defaults everyone expects
    def ping(self, *args):
        if args:
            return RedisReply.bulk(args[0])
        return RedisReply.status("PONG")

    def auth(self, *args):
        # reaching here means the connection's verify gate passed (or no
        # authenticator is configured): acknowledge
        return RedisReply.status("OK")


class KVRedisService(RedisService):
    """In-memory key/value RedisService (the reference redis_server
    example's CommandHandler set, as a service).

    On a native-engine server this flags ``native_kv``: the C++ engine
    answers GET/SET/DEL/EXISTS/INCR/PING from its own sharded map with
    zero Python per command, and only unrecognized commands reach the
    Python methods below.  NOTE the two stores are separate — when the
    engine serves the hot commands, the Python dict here only ever sees
    keys touched by fallback commands.  On the Python transport this
    class is a complete working KV."""

    native_kv = True

    def __init__(self):
        self._d = {}
        self._lock = __import__("threading").Lock()

    def set(self, key, value):
        with self._lock:
            self._d[bytes(key)] = bytes(value)
        return RedisReply.status("OK")

    def get(self, key):
        with self._lock:
            return self._d.get(bytes(key))

    def delete(self, *keys):  # DEL is a python keyword
        with self._lock:
            return sum(1 for k in keys if self._d.pop(bytes(k), None) is not None)

    # RedisService.handle dispatches on the lower-cased command name;
    # map the wire name DEL onto delete()
    def handle(self, command: str, args) -> RedisReply:
        if command.upper() == "DEL":
            return _coerce_reply(self.delete(*args))
        return super().handle(command, args)

    def exists(self, key):
        with self._lock:
            return 1 if bytes(key) in self._d else 0

    def incr(self, key):
        with self._lock:
            k = bytes(key)
            try:
                cur = int(self._d.get(k, b"0"))
            except ValueError:
                return RedisReply.error(
                    "ERR value is not an integer or out of range"
                )
            cur += 1
            self._d[k] = str(cur).encode()
            return cur


def _command_bytes(part) -> Optional[bytes]:
    """A RESP command element must be a bulk string; anything else
    (an integer, a nested array) is a protocol violation, not a crash.
    A device-resident bulk passes its DeviceRef through untouched (the
    cache SET ingest path adopts the array without materializing)."""
    if part.type != REPLY_STRING:
        return None
    if _is_device_value(part.value):
        return part.value
    return part.value or b""


def process_request(msg: _WireMsg, sock) -> None:
    server = sock.server
    service = getattr(getattr(server, "options", None), "redis_service", None)
    parts = msg.command.value
    name = _command_bytes(parts[0])
    ticket = None
    if service is None:
        reply = RedisReply.error("ERR this server speaks no redis")
    elif name is None or not isinstance(name, bytes):
        reply = RedisReply.error("ERR protocol error: command not a bulk string")
    else:
        cmd = name.decode("utf-8", "replace")
        # unified admission decision point (server/admission.py): redis
        # traffic — the cache tier's data plane — sheds like every
        # other protocol.  RESP has no meta error channel, so the
        # retry-elsewhere code rides an -OVERCROWDED error reply that
        # process_response maps back onto EOVERCROWDED (which is what
        # feeds tier-aware LB shed signals client-side).
        verdict = server.admission.admit(f"redis.{cmd.upper()}", None)
        if not verdict.admitted:
            if verdict.code == errors.EOVERCROWDED:
                reply = RedisReply.error(
                    f"OVERCROWDED {verdict.reason or 'admission shed'}"
                )
            else:
                reply = RedisReply.error(
                    f"ERR busy: {verdict.reason or 'admission drop'}"
                )
        else:
            ticket = verdict.ticket
            args = [_command_bytes(p) for p in parts[1:]]
            # connection-aware services (the HBM cache tier) see the
            # socket to decide device-resident vs host-materialized
            # replies
            handler = getattr(service, "handle_conn", None)
            try:
                if handler is not None:
                    reply = handler(cmd, args, sock)
                else:
                    reply = service.handle(cmd, args)
            except BaseException:
                if ticket is not None:
                    ticket.release()
                raise
    out = IOBuf()
    pack_reply_into(reply, out)
    sock.write(out, ignore_eovercrowded=True)
    if ticket is not None:
        ticket.release()


def verify(msg: _WireMsg, sock) -> bool:
    """AUTH-command authentication doesn't fit the first-message
    credential model; a redis-speaking server with a brpc Authenticator
    validates the first command being AUTH <credential>."""
    server = sock.server
    auth = getattr(getattr(server, "options", None), "auth", None)
    if auth is None:
        return True
    parts = msg.command.value if msg.command else None
    if not parts or len(parts) < 2:
        return False
    name = _command_bytes(parts[0])
    cred_b = _command_bytes(parts[1])
    if name is None or cred_b is None or name.upper() != b"AUTH":
        return False
    from incubator_brpc_tpu.protocols import _call_verify_credential

    rc, _ = _call_verify_credential(auth, cred_b.decode("utf-8", "replace"), sock)
    return rc == 0


PROTOCOL = Protocol(
    name="redis",
    parse=parse,
    serialize_request=serialize_request,
    pack_request=pack_request,
    process_request=process_request,
    process_response=process_response,
    verify=verify,
    support_pipelined=True,
    # RESP has no correlation ids: replies must leave in arrival order
    process_ordered=True,
)


def register():
    register_protocol(PROTOCOL)
