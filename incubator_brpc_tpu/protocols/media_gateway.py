"""Media gateway — RTMP ingest fanned out to HLS and FLV consumers.

The integration layer over protocols/flv.py and protocols/ts.py: an
RtmpService that taps every published stream's media into a per-stream
HlsSegmenter (live .ts window + m3u8) and FLV archive, the way
reference users compose FlvWriter (rtmp.h:401) and the TS writer
(ts.{h,cpp}) behind an RTMP/media server.  Plug it into
``ServerOptions.rtmp_service`` and serve the accessors from any HTTP
handler:

    gw = MediaGatewayService()
    srv = Server(ServerOptions(rtmp_service=gw, ...))
    ...
    gw.playlist("room")          # → m3u8 text
    gw.segment("room", seq)      # → .ts bytes
    gw.flv_snapshot("room")      # → progressive-download FLV bytes
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from incubator_brpc_tpu.protocols.flv import FlvWriter
from incubator_brpc_tpu.protocols.rtmp import RtmpMessage, RtmpService
from incubator_brpc_tpu.protocols.ts import HlsSegmenter

_FLV_CAP = 64 << 20  # stop archiving past 64MB (live use: HLS window)
_EVICT_IDLE_S = 10.0  # a stream this quiet counts as gone for eviction


class _StreamState:
    def __init__(self, target_s: float, window: int, flv: bool):
        self.hls = HlsSegmenter(target_duration_s=target_s, window=window)
        self.flv = FlvWriter() if flv else None
        # archive as immutable chunks: snapshots shallow-copy the list
        # under the lock and join OUTSIDE it, so a 64MB poll never
        # stalls live ingest
        self.flv_chunks: List[bytes] = []
        self.flv_size = 0
        self.last_active = time.monotonic()
        self.lock = threading.Lock()


class MediaGatewayService(RtmpService):
    def __init__(
        self,
        target_duration_s: float = 4.0,
        window: int = 5,
        flv_archive: bool = True,
        max_streams: int = 64,
    ):
        self._target = target_duration_s
        self._window = window
        self._flv = flv_archive
        self._max_streams = max_streams
        self._streams: Dict[str, _StreamState] = {}
        self._lock = threading.Lock()

    # ---- RtmpService hooks --------------------------------------------------
    def on_frame(self, stream_name: str, msg: RtmpMessage) -> None:
        st = self._state(stream_name)
        with st.lock:
            st.last_active = time.monotonic()
            st.hls.on_message(msg)
            if st.flv is not None and st.flv_size < _FLV_CAP:
                try:
                    st.flv.write_message(msg)
                except ValueError:
                    pass  # non-media control frames
                else:
                    chunk = st.flv.take()
                    st.flv_chunks.append(chunk)
                    st.flv_size += len(chunk)

    # ---- consumer accessors -------------------------------------------------
    def streams(self):
        with self._lock:
            return sorted(self._streams)

    def playlist(self, stream: str, end: bool = False) -> Optional[str]:
        st = self._get(stream)
        if st is None:
            return None
        with st.lock:
            return st.hls.playlist(end=end)

    def segment(self, stream: str, seq: int) -> Optional[bytes]:
        st = self._get(stream)
        if st is None:
            return None
        with st.lock:
            for s in st.hls.segments:
                if s.seq == seq:
                    return bytes(s.data)
        return None

    def finish(self, stream: str) -> None:
        """Seal the open segment (publisher stopped)."""
        st = self._get(stream)
        if st is not None:
            with st.lock:
                st.hls.finish_segment()

    def flv_snapshot(self, stream: str) -> bytes:
        """Everything archived so far as one FLV byte stream."""
        st = self._get(stream)
        if st is None:
            return b""
        with st.lock:
            chunks = list(st.flv_chunks)
        return b"".join(chunks)  # the big copy runs outside the lock

    def drop(self, stream: str) -> None:
        """Forget a stream's state (publisher gone, archive served)."""
        with self._lock:
            self._streams.pop(stream, None)

    # ---- internals ----------------------------------------------------------
    def _state(self, stream: str) -> _StreamState:
        with self._lock:
            st = self._streams.get(stream)
            if st is None:
                # bounded registry: unique-name churn (or a hostile
                # publisher) must not grow memory forever.  Prefer
                # evicting IDLE streams — evicting a live publisher
                # would drop its cached sequence headers and silently
                # kill its HLS/FLV output until it republishes.  Only
                # when every entry is live does the globally oldest go
                # (bounded memory wins; loudly).
                if len(self._streams) >= self._max_streams:
                    now = time.monotonic()
                    idle = [
                        k
                        for k, v in self._streams.items()
                        if now - v.last_active > _EVICT_IDLE_S
                    ]
                    pool = idle or list(self._streams)
                    victim = min(
                        pool, key=lambda k: self._streams[k].last_active
                    )
                    if not idle:
                        from incubator_brpc_tpu.utils.logging import log_error

                        log_error(
                            "media gateway at max_streams=%d with all "
                            "streams live; evicting %r",
                            self._max_streams, victim,
                        )
                    del self._streams[victim]
                st = self._streams[stream] = _StreamState(
                    self._target, self._window, self._flv
                )
            return st

    def _get(self, stream: str) -> Optional[_StreamState]:
        with self._lock:
            return self._streams.get(stream)
