"""tpu_std — the default protobuf RPC protocol.

Analog of reference baidu_std (policy/baidu_rpc_protocol.cpp, framing
documented in docs/cn/baidu_std.md): fixed 12-byte header
``b"TRPC" + meta_size(u32 BE) + body_size(u32 BE)`` followed by an
RpcMeta protobuf and the body (payload then attachment; attachment
length rides in meta.attachment_size). One framing serves requests and
responses; meta.request/meta.response discriminates.

Supports: correlation ids, compression, attachments, streaming
settings handshake (reference baidu_rpc_protocol.cpp:212-264), and the
TPU extension meta.device_segments describing HBM tensor payloads.
"""

from __future__ import annotations

import struct
import time
from typing import Optional

from incubator_brpc_tpu import errors
from incubator_brpc_tpu.protocols import ParseResult, Protocol, register_protocol
from incubator_brpc_tpu.protocols import compress as compress_mod
from incubator_brpc_tpu.protos import rpc_meta_pb2 as pb
from incubator_brpc_tpu.runtime.call_id import default_pool as _id_pool
from incubator_brpc_tpu.utils.iobuf import IOBuf

MAGIC = b"TRPC"
HEADER_SIZE = 12
_MAX_BODY = 2 << 30


class TpuStdMessage:
    __slots__ = ("meta", "payload", "received_us", "parse_done_us", "enqueued_us")

    def __init__(self, meta, payload: IOBuf):
        self.meta = meta
        self.payload = payload
        # rpcz phase stamps, filled in by the transport cut loop
        self.received_us = 0
        self.parse_done_us = 0
        self.enqueued_us = 0


# ---- parse (both sides) ----------------------------------------------------
def parse(buf: IOBuf, sock, read_eof: bool) -> ParseResult:
    header = buf.fetch(HEADER_SIZE)
    if header is None:
        got = buf.fetch(min(len(buf), 4)) or b""
        if MAGIC.startswith(got[: len(MAGIC)]) or got.startswith(MAGIC):
            return ParseResult.not_enough()
        return ParseResult.try_others()
    if header[:4] != MAGIC:
        return ParseResult.try_others()
    meta_size, body_size = struct.unpack_from(">II", header, 4)
    if meta_size > _MAX_BODY or body_size > _MAX_BODY:
        return ParseResult.bad()
    total = HEADER_SIZE + meta_size + body_size
    if len(buf) < total:
        return ParseResult.not_enough()
    buf.pop_front(HEADER_SIZE)
    meta_bytes = buf.cut_bytes(meta_size)
    payload = IOBuf()
    buf.cutn(payload, body_size)
    meta = pb.RpcMeta()
    try:
        meta.ParseFromString(meta_bytes)
    except Exception:
        return ParseResult.bad()
    # wire-controlled sizes must be validated before any cutn uses them
    if meta.attachment_size < 0 or meta.attachment_size > len(payload):
        return ParseResult.bad()
    if not sock.is_server_side and meta.HasField("response"):
        # A fully-received response means the connection closing is no
        # longer this RPC's problem: deregister the waiter NOW,
        # synchronously in the read task, so an EOF in the same read
        # batch can't error the id before the response task locks it.
        sock.remove_response_waiter(meta.correlation_id)
    return ParseResult.ok(TpuStdMessage(meta, payload))


def _frame(meta: pb.RpcMeta, body: IOBuf) -> IOBuf:
    meta_bytes = meta.SerializeToString()
    out = IOBuf()
    # header+meta in one append (one block write); body ref-shares
    out.append(
        MAGIC + struct.pack(">II", len(meta_bytes), len(body)) + meta_bytes
    )
    out.append(body)
    return out


# ---- client side -----------------------------------------------------------
def serialize_request(request, controller) -> IOBuf:
    """Called once per RPC (channel.cpp:517)."""
    body = IOBuf()
    # bytes = already-serialized request (the pooled fast-path contract,
    # docs/fastpath.md); matches the native path's bytes-mode packing
    raw = request if isinstance(request, bytes) else request.SerializeToString()
    ctype = controller.request_compress_type
    if ctype:
        compressed = compress_mod.compress(IOBuf(raw), ctype)
        if compressed is None:
            raise ValueError(f"unsupported compress type {ctype}")
        body.append(compressed)
    else:
        body.append(raw)
    return body


def pack_request(request_buf: IOBuf, wire_cid: int, method_spec, controller) -> IOBuf:
    """Called per send attempt, retries included (controller.cpp:1140)."""
    meta = pb.RpcMeta()
    meta.request.service_name = method_spec.service_name
    meta.request.method_name = method_spec.method_name
    meta.request.log_id = controller.log_id
    if controller._span is not None:
        meta.request.trace_id = controller._span.trace_id
        meta.request.span_id = controller._span.span_id
    meta.correlation_id = wire_cid
    meta.compress_type = controller.request_compress_type
    tenant = controller.__dict__.get("tenant")
    if tenant:
        # tenant identity for server-side admission (docs/overload.md)
        meta.request.tenant = tenant
    channel = controller._channel
    auth = channel.options.auth if channel is not None else None
    if auth is not None:
        # a raising authenticator FAILS the RPC (issue_rpc catches pack
        # errors) — silently sending unauthenticated would just burn
        # retries against the server's verify gate
        meta.auth_data = auth.generate_credential() or ""
    body = IOBuf()
    body.append(request_buf)  # ref share: serialize-once survives retries
    att = controller.request_attachment
    if len(att):
        meta.attachment_size = len(att)
        body.append(att)
    if controller._request_stream is not None:
        ss = controller._request_stream.fill_settings()
        meta.stream_settings.CopyFrom(ss)
    return _frame(meta, body)


def pack_cancel(wire_cid: int) -> IOBuf:
    """A cancel frame for one in-flight request (hedged-request loser
    cancellation, docs/overload.md): meta only, no body.  The server
    sheds the matching request from batch queues before device work
    and suppresses its response; unknown cids are ignored."""
    meta = pb.RpcMeta()
    meta.correlation_id = wire_cid
    meta.cancel = True
    return _frame(meta, IOBuf())


def process_response(msg: TpuStdMessage, sock) -> None:
    """Client response path (ProcessRpcResponse, baidu_rpc_protocol.cpp:557)."""
    meta = msg.meta
    cid = meta.correlation_id
    pool = _id_pool()
    from incubator_brpc_tpu.transport.event_dispatcher import in_dispatcher

    if in_dispatcher():
        # never block the event loop on a contended id (the timeout /
        # retry handlers hold it briefly): re-dispatch to a worker
        ctrl = pool.try_lock(cid)
        if ctrl is type(pool).BUSY:
            from incubator_brpc_tpu.runtime import scheduler

            scheduler.spawn(process_response, msg, sock)
            return
    else:
        ctrl = pool.lock(cid)
    if ctrl is None:
        return  # stale retry version or finished RPC: dropped
    if ctrl._span is not None:
        # client-side phases: when the response's bytes arrived and
        # when its frame finished parsing
        ctrl._span.adopt_message_stamps(msg)
    if meta.HasField("stream_settings"):
        ctrl._remote_stream_settings = meta.stream_settings
    ctrl._on_response(cid, meta, msg.payload)


# ---- server side -----------------------------------------------------------
def _handle_cancel(sock, cid: int) -> None:
    """A cancel frame (hedge loser / abandoned attempt): flag the
    in-flight request so batch queues shed it before device work and
    its response never hits the wire.  Best-effort — a handler already
    running completes; only the reply is suppressed."""
    reg = getattr(sock, "_srv_inflight", None)
    ctrl = reg.get(cid) if reg is not None else None
    if ctrl is not None:
        ctrl._cancel_requested = True


def process_request(msg: TpuStdMessage, sock) -> None:
    """Server request path (ProcessRpcRequest, baidu_rpc_protocol.cpp:312)."""
    from incubator_brpc_tpu.client.controller import Controller

    meta = msg.meta
    server = sock.server
    req_meta = meta.request
    cid = meta.correlation_id
    if meta.cancel:
        return _handle_cancel(sock, cid)
    ctrl = Controller()
    # wall-clock anchor for RpcResponseMeta.server_time_us: everything
    # from request parse to response serialization counts as "server
    # time"; the client subtracts it from its leg latency to attribute
    # the remainder as wire+queue (observability/cluster.py)
    ctrl._server_recv_ns = time.monotonic_ns()
    ctrl.server = server
    ctrl._server_socket = sock
    ctrl._server_cid = cid
    ctrl._server_meta = meta
    ctrl.remote_side = sock.remote
    ctrl.service_name = req_meta.service_name
    ctrl.method_name = req_meta.method_name
    ctrl.log_id = req_meta.log_id

    # rpcz server span with propagated trace (baidu_rpc_protocol.cpp:382)
    from incubator_brpc_tpu.observability.span import Span, swap_current_span

    ctrl._span = Span.create_server(
        req_meta.service_name, req_meta.method_name,
        req_meta.trace_id, req_meta.span_id,
    )
    if ctrl._span is not None:
        ctrl._span.remote_side = str(sock.remote or "")
        ctrl._span.request_size = len(msg.payload)
        ctrl._span.adopt_message_stamps(msg)
    if server is None or not server.is_running():
        ctrl.set_failed(errors.ELOGOFF, "server stopped")
        return send_response(ctrl, None)
    # rpc_dump sampling gate (reference baidu_rpc_protocol.cpp:329-339)
    if server._rpc_dump_ctx is not None:
        server._rpc_dump_ctx.sample_request(req_meta, msg.payload)
    method = server.find_method(req_meta.service_name, req_meta.method_name)
    if method is None:
        has_service = server.has_service(req_meta.service_name)
        ctrl.set_failed(
            errors.ENOMETHOD if has_service else errors.ENOSERVICE,
            f"unknown {req_meta.service_name}.{req_meta.method_name}",
        )
        return send_response(ctrl, None)
    status = server.method_status(method.full_name)
    # ONE admission decision point before user code (server/admission.py,
    # docs/overload.md): concurrency gate + tier shares + tenant quotas,
    # shed codes from the unified mapping (EOVERCROWDED = retry
    # elsewhere, ELIMIT = drop)
    verdict = server.admission.admit(
        method.full_name, status, req_meta.tenant
    )
    if not verdict.admitted:
        ctrl.set_failed(verdict.code, verdict.reason)
        return send_response(ctrl, None)
    if verdict.tier is not None:
        ctrl._admission_tier = verdict.tier
        ctrl._admission_ticket = verdict.ticket
    # hedge-cancellation registry: cancel frames resolve their target
    # through this per-connection map (cleared in send_response)
    reg = getattr(sock, "_srv_inflight", None)
    if reg is None:
        reg = {}
        try:
            sock._srv_inflight = reg
        except AttributeError:
            reg = None  # facade sockets without attribute storage
    if reg is not None:
        reg[cid] = ctrl
    start_ns = time.monotonic_ns()

    # decompress + parse request (baidu_rpc_protocol.cpp:484-491)
    payload = msg.payload
    att_size = meta.attachment_size
    body = payload
    if att_size:
        body = IOBuf()
        payload.cutn(body, len(payload) - att_size)
        ctrl.request_attachment = payload
    if meta.compress_type:
        body = compress_mod.decompress(body, meta.compress_type)
        if body is None:
            ctrl.set_failed(errors.EREQUEST, "unsupported compress type")
            if status is not None:
                status.on_response(0, error=True)
            return send_response(ctrl, None)
    request = method.request_class()
    try:
        request.ParseFromString(body.as_view())
    except Exception as e:  # noqa: BLE001
        ctrl.set_failed(errors.EREQUEST, f"parse request failed: {e}")
        if status is not None:
            status.on_response(0, error=True)
        return send_response(ctrl, None)
    if meta.HasField("stream_settings"):
        ctrl._remote_stream_settings = meta.stream_settings
    response = method.response_class()

    sent = [False]

    def done():
        if sent[0]:
            return
        sent[0] = True
        if ctrl._span is not None:
            ctrl._span.callback_done_us = time.time_ns() // 1000
        latency_us = (time.monotonic_ns() - start_ns) // 1000
        if status is not None:
            status.on_response(latency_us, error=ctrl.failed())
        # per-tier observed latency (server/admission.py): feeds the
        # latency-fed auto limiter; no-op unless a tier was stamped
        from incubator_brpc_tpu.server import admission as _admission

        _admission.note_controller_latency(ctrl, latency_us)
        send_response(ctrl, response)

    # Micro-batching gate (batching/, docs/batching.md): a method with
    # a live Batcher coalesces into a fused batched execution — the
    # Batcher stamps callback entry and fans completion back through
    # this same done().  Disabled cost: one empty-dict truth test.
    if server._batchers and server.submit_batched(
        method, ctrl, request, response, done
    ):
        return

    # Scope the server span as the task-local parent for the handler:
    # nested client calls and fabric legs made inside it join this
    # trace; restored after so later work on this task can't misparent
    # into a finished trace. Callback-entry stamping + the exception
    # fence live in the server layer.
    prev_parent = (
        swap_current_span(ctrl._span) if ctrl._span is not None else None
    )
    try:
        exc = server.run_user_method(method, ctrl, request, response, done)
        if exc is not None and not sent[0]:
            ctrl.set_failed(errors.EINTERNAL, f"method raised: {exc}")
            done()
    finally:
        if ctrl._span is not None:
            swap_current_span(prev_parent)


def send_response(ctrl, response) -> None:
    """SendRpcResponse analog (baidu_rpc_protocol.cpp:139)."""
    ctrl._release_session_local()  # handler is done: pool the user data
    # admission bookkeeping: the tier/tenant inflight ticket releases
    # exactly once, on whichever path ends the request (idempotent pop)
    ticket = ctrl.__dict__.pop("_admission_ticket", None)
    if ticket is not None:
        ticket.release()
    span = getattr(ctrl, "_span", None)
    if span is not None and span.kind != "server":
        span = None
    sock = ctrl._server_socket
    reg = getattr(sock, "_srv_inflight", None) if sock is not None else None
    if reg is not None:
        reg.pop(ctrl._server_cid, None)
    if ctrl.__dict__.get("_cancel_requested"):
        # hedge loser: the client already completed on another replica
        # (or gave up) — writing the reply would be pure waste
        if span is not None:
            span.end(errors.ECANCELED)
        return
    if sock is None or sock.failed:
        if span is not None:
            span.end(errors.EFAILEDSOCKET)
        return
    if getattr(ctrl, "_close_connection_after_response", False):
        # Controller::CloseConnection: drop the connection, no response
        sock.set_failed(errors.ECLOSE, "closed by server handler")
        if span is not None:
            span.end(errors.ECLOSE)
        return
    meta = pb.RpcMeta()
    meta.correlation_id = ctrl._server_cid
    meta.response.error_code = ctrl.error_code
    if ctrl.error_code:
        meta.response.error_text = ctrl.error_text()
    if ctrl._server_recv_ns:
        # server's own elapsed time rides back in the response meta so
        # the client can split its leg latency into server vs wire+queue
        meta.response.server_time_us = (
            time.monotonic_ns() - ctrl._server_recv_ns
        ) // 1000
    body = IOBuf()
    if response is not None and not ctrl.failed():
        raw = response.SerializeToString()
        ctype = ctrl.response_compress_type
        if ctype:
            compressed = compress_mod.compress(IOBuf(raw), ctype)
            if compressed is not None:
                meta.compress_type = ctype
                body.append(compressed)
            else:
                body.append(raw)
        else:
            body.append(raw)
        att = ctrl.response_attachment
        if len(att):
            meta.attachment_size = len(att)
            body.append(att)
    if ctrl._response_stream is not None:
        meta.stream_settings.CopyFrom(ctrl._response_stream.fill_settings())
    if span is not None:
        # response_size covers the full serialized body (attachment
        # included); the span closes at WRITE COMPLETION via the
        # socket's write_done hook, so server latency includes
        # serialization and send — not just the callback
        span.response_size = len(body)
        span.error_code = ctrl.error_code
        span.response_write_us = time.time_ns() // 1000
    sock.write(_frame(meta, body), ignore_eovercrowded=True, span=span)


def verify(msg: "TpuStdMessage", sock) -> bool:
    """First-message auth on a server connection (reference
    input_messenger.cpp:282-300 + baidu_std verify callback). With no
    server authenticator every connection passes; with one, the meta's
    auth_data must verify or the connection dies with ERPCAUTH."""
    server = sock.server
    auth = getattr(getattr(server, "options", None), "auth", None)
    if auth is None:
        return True
    from incubator_brpc_tpu.protocols import _call_verify_credential

    rc, _ = _call_verify_credential(auth, msg.meta.auth_data or "", sock)
    return rc == 0


PROTOCOL = Protocol(
    name="tpu_std",
    parse=parse,
    serialize_request=serialize_request,
    pack_request=pack_request,
    process_request=process_request,
    process_response=process_response,
    verify=verify,
    pack_cancel=pack_cancel,
)


def register():
    register_protocol(PROTOCOL)
