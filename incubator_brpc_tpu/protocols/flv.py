"""FLV remux — tag writer/reader over the RTMP message layer.

Analog of reference FlvWriter/FlvReader (rtmp.h:379-460, implementation
in rtmp.cpp): RTMP audio/video/script-data messages and an FLV byte
stream are trivially interconvertible — an FLV file is a 9-byte header
followed by (11-byte tag header + payload + u32 previous-tag-size)
records whose type/timestamp/payload map 1:1 onto RtmpMessage fields.

Wire layout (Adobe FLV spec v10.1, annex E):

    header:  "FLV" u8(version=1) u8(flags) u32(header_size=9)
             u32(previous_tag_size0 = 0)
    tag:     u8(type) u24(data_size) u24(timestamp) u8(timestamp_ext)
             u24(stream_id = 0) data  u32(previous_tag_size = 11 + size)

The reader mirrors the reference's EAGAIN contract: ``read()`` returns
None when the buffer holds no complete tag yet (wait for more bytes and
call again), and raises ValueError on structural corruption.
"""

from __future__ import annotations

import struct
from typing import Optional, Tuple

from incubator_brpc_tpu.protocols.rtmp import (
    MSG_AUDIO,
    MSG_DATA_AMF0,
    MSG_VIDEO,
    RtmpMessage,
)

# FlvHeaderFlags (rtmp.h:379-383)
FLV_CONTENT_VIDEO = 0x01
FLV_CONTENT_AUDIO = 0x04
FLV_CONTENT_AUDIO_AND_VIDEO = 0x05

# FlvTagType (rtmp.h:395-399) — identical to the RTMP message type ids
FLV_TAG_AUDIO = 8
FLV_TAG_VIDEO = 9
FLV_TAG_SCRIPT_DATA = 18

_HEADER_SIZE = 9
_TAG_HEADER = 11


class FlvWriter:
    """Append RTMP messages to a growing FLV byte stream.  The 9-byte
    file header is emitted before the first tag (FlvWriter ctor writes
    it lazily in the reference too — _write_header flag)."""

    def __init__(self, content_type: int = FLV_CONTENT_AUDIO_AND_VIDEO):
        self._content_type = content_type
        self._header_written = False
        self._out = bytearray()

    def write_message(self, msg: RtmpMessage) -> None:
        """Append an RTMP audio/video/script message as one FLV tag."""
        if msg.type_id not in (MSG_AUDIO, MSG_VIDEO, MSG_DATA_AMF0):
            raise ValueError(f"not an FLV-taggable message: {msg.type_id}")
        self.write_tag(msg.type_id, msg.timestamp, msg.payload)

    def write_tag(self, tag_type: int, timestamp: int, payload: bytes) -> None:
        if len(payload) > 0xFFFFFF:
            # u24 data_size: silently truncating would desync every
            # following tag (previous_tag_size is 32-bit and would lie)
            raise ValueError(f"FLV tag payload too large: {len(payload)}")
        if not self._header_written:
            self._header_written = True
            self._out += b"FLV\x01"
            self._out.append(self._content_type)
            self._out += struct.pack(">I", _HEADER_SIZE)
            self._out += struct.pack(">I", 0)  # previous_tag_size0
        ts = timestamp & 0xFFFFFFFF
        self._out.append(tag_type)
        self._out += struct.pack(">I", len(payload))[1:]  # u24 size
        self._out += struct.pack(">I", ts & 0xFFFFFF)[1:]  # u24 ts low
        self._out.append((ts >> 24) & 0xFF)  # ts extension
        self._out += b"\x00\x00\x00"  # stream id
        self._out += payload
        self._out += struct.pack(">I", _TAG_HEADER + len(payload))

    def take(self) -> bytes:
        """Drain everything written so far (progressive-download body
        chunks ride this)."""
        out, self._out = bytes(self._out), bytearray()
        return out

    def getvalue(self) -> bytes:
        return bytes(self._out)


class FlvReader:
    """Incremental FLV parser; feed() bytes, read() complete tags."""

    def __init__(self):
        self._buf = bytearray()
        self._header_parsed = False
        self.content_type = 0

    def feed(self, data: bytes) -> None:
        self._buf += data

    def peek_type(self) -> Optional[int]:
        """Next tag's type, or None until one is buffered (the
        reference's PeekMessageType EAGAIN contract)."""
        if not self._ensure_header():
            return None
        if len(self._buf) < 1:
            return None
        t = self._buf[0]
        if t not in (FLV_TAG_AUDIO, FLV_TAG_VIDEO, FLV_TAG_SCRIPT_DATA):
            raise ValueError(f"bad FLV tag type {t}")
        return t

    def read(self) -> Optional[Tuple[int, int, bytes]]:
        """→ (tag_type, timestamp_ms, payload) or None if incomplete."""
        t = self.peek_type()  # validates type byte + header
        if t is None or len(self._buf) < _TAG_HEADER:
            return None
        size = int.from_bytes(self._buf[1:4], "big")
        total = _TAG_HEADER + size + 4  # + previous_tag_size
        if len(self._buf) < total:
            return None
        ts = int.from_bytes(self._buf[4:7], "big") | (self._buf[7] << 24)
        payload = bytes(self._buf[_TAG_HEADER : _TAG_HEADER + size])
        prev = int.from_bytes(self._buf[total - 4 : total], "big")
        if prev != _TAG_HEADER + size:
            raise ValueError(f"bad previous_tag_size {prev}")
        del self._buf[:total]
        return t, ts, payload

    def read_message(self) -> Optional[RtmpMessage]:
        got = self.read()
        if got is None:
            return None
        t, ts, payload = got
        return RtmpMessage(t, 1, ts, payload)

    def _ensure_header(self) -> bool:
        if self._header_parsed:
            return True
        if len(self._buf) < _HEADER_SIZE + 4:
            return False
        if self._buf[:3] != b"FLV" or self._buf[3] != 1:
            raise ValueError("not an FLV stream")
        hdr_size = struct.unpack_from(">I", self._buf, 5)[0]
        if hdr_size < _HEADER_SIZE:
            raise ValueError(f"bad FLV header size {hdr_size}")
        if len(self._buf) < hdr_size + 4:
            return False
        self.content_type = self._buf[4]
        del self._buf[: hdr_size + 4]
        self._header_parsed = True
        return True
