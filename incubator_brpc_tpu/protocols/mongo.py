"""Mongo wire protocol — server side.

Analog of reference policy/mongo_protocol.cpp + mongo_head.h +
mongo_service_adaptor.h: the server answers MongoDB wire-protocol
clients. Standard header (16 bytes LE: messageLength, requestID,
responseTo, opCode); supported ops: OP_MSG (2013, modern — kind-0 body
section) answered with OP_MSG, and legacy OP_QUERY (2004) answered with
OP_REPLY (1). Documents are (de)serialized by the minimal BSON codec
below (dict ↔ bytes; the subset of types drivers use for commands).

User surface mirrors the reference's MongoServiceAdaptor: subclass
MongoServiceAdaptor, implement ``handle(controller, doc) -> doc``.
"""

from __future__ import annotations

import struct
from typing import Dict, List, Optional, Tuple

from incubator_brpc_tpu import errors
from incubator_brpc_tpu.protocols import ParseResult, Protocol, register_protocol
from incubator_brpc_tpu.utils.iobuf import IOBuf
from incubator_brpc_tpu.utils.logging import log_error

OP_REPLY = 1
OP_QUERY = 2004
OP_GET_MORE = 2005
OP_MSG = 2013

_KNOWN_OPS = {OP_REPLY, OP_QUERY, OP_GET_MORE, OP_MSG, 2001, 2002, 2006, 2007, 2010, 2011}
_MAX_MESSAGE = 48 << 20  # mongo's own wire limit


# ---------------------------------------------------------------------------
# minimal BSON
# ---------------------------------------------------------------------------
def bson_encode(doc: Dict) -> bytes:
    body = b"".join(_bson_element(k, v) for k, v in doc.items())
    return struct.pack("<i", len(body) + 5) + body + b"\x00"


def _bson_element(key: str, v) -> bytes:
    name = key.encode() + b"\x00"
    if isinstance(v, bool):  # before int: bool is an int subclass
        return b"\x08" + name + (b"\x01" if v else b"\x00")
    if isinstance(v, float):
        return b"\x01" + name + struct.pack("<d", v)
    if isinstance(v, int):
        if -(2**31) <= v < 2**31:
            return b"\x10" + name + struct.pack("<i", v)
        return b"\x12" + name + struct.pack("<q", v)
    if isinstance(v, str):
        raw = v.encode()
        return b"\x02" + name + struct.pack("<i", len(raw) + 1) + raw + b"\x00"
    if isinstance(v, bytes):
        return b"\x05" + name + struct.pack("<i", len(v)) + b"\x00" + v
    if v is None:
        return b"\x0a" + name
    if isinstance(v, dict):
        return b"\x03" + name + bson_encode(v)
    if isinstance(v, (list, tuple)):
        arr = {str(i): item for i, item in enumerate(v)}
        return b"\x04" + name + bson_encode(arr)
    raise TypeError(f"bson: unsupported type {type(v)}")


def bson_decode(data: bytes, pos: int = 0) -> Tuple[Dict, int]:
    """→ (doc, next_pos)."""
    (length,) = struct.unpack_from("<i", data, pos)
    if length < 5 or pos + length > len(data):
        raise ValueError("bson document truncated")
    end = pos + length - 1  # the trailing 0x00
    cur = pos + 4
    doc: Dict = {}
    while cur < end:
        etype = data[cur]
        cur += 1
        zero = data.index(b"\x00", cur)
        key = data[cur:zero].decode("utf-8", "replace")
        cur = zero + 1
        if etype == 0x01:
            (val,) = struct.unpack_from("<d", data, cur)
            cur += 8
        elif etype == 0x02:
            (n,) = struct.unpack_from("<i", data, cur)
            val = data[cur + 4 : cur + 4 + n - 1].decode("utf-8", "replace")
            cur += 4 + n
        elif etype in (0x03, 0x04):
            val, nxt = bson_decode(data, cur)
            if etype == 0x04:
                val = [val[k] for k in sorted(val, key=lambda s: int(s or 0))]
            cur = nxt
        elif etype == 0x05:
            (n,) = struct.unpack_from("<i", data, cur)
            val = data[cur + 5 : cur + 5 + n]
            cur += 5 + n
        elif etype == 0x07:  # ObjectId
            val = data[cur : cur + 12]
            cur += 12
        elif etype == 0x08:
            val = data[cur] != 0
            cur += 1
        elif etype == 0x09:  # UTC datetime (ms)
            (val,) = struct.unpack_from("<q", data, cur)
            cur += 8
        elif etype == 0x0A:
            val = None
        elif etype == 0x10:
            (val,) = struct.unpack_from("<i", data, cur)
            cur += 4
        elif etype == 0x12:
            (val,) = struct.unpack_from("<q", data, cur)
            cur += 8
        else:
            raise ValueError(f"bson: unsupported element type 0x{etype:02x}")
        doc[key] = val
    return doc, pos + length


# ---------------------------------------------------------------------------
# wire messages
# ---------------------------------------------------------------------------
class MongoMessage:
    __slots__ = ("request_id", "response_to", "op_code", "doc", "collection")

    def __init__(self, request_id: int, response_to: int, op_code: int,
                 doc: Optional[Dict], collection: str = ""):
        self.request_id = request_id
        self.response_to = response_to
        self.op_code = op_code
        self.doc = doc
        self.collection = collection


def parse(buf: IOBuf, sock, read_eof: bool) -> ParseResult:
    head = buf.fetch(16)
    if head is None:
        got = buf.fetch(min(len(buf), 16)) or b""
        if len(got) >= 16:
            return ParseResult.try_others()
        # can't rule mongo out until the op_code bytes arrive
        return ParseResult.not_enough() if _plausible(got) else ParseResult.try_others()
    length, request_id, response_to, op_code = struct.unpack("<iiii", head)
    if op_code not in _KNOWN_OPS:
        return ParseResult.try_others()
    if length < 16 or length > _MAX_MESSAGE:
        return ParseResult.bad()
    if len(buf) < length:
        return ParseResult.not_enough()
    buf.pop_front(16)
    body = buf.cut_bytes(length - 16)
    try:
        if op_code == OP_MSG:
            # u32 flagBits, then sections; kind 0 = one BSON body
            if len(body) < 5 or body[4] != 0:
                return ParseResult.bad()
            doc, _ = bson_decode(body, 5)
            return ParseResult.ok(MongoMessage(request_id, response_to, op_code, doc))
        if op_code == OP_QUERY:
            # i32 flags, cstring collection, i32 skip, i32 nreturn, BSON
            zero = body.index(b"\x00", 4)
            collection = body[4:zero].decode("utf-8", "replace")
            doc, _ = bson_decode(body, zero + 1 + 8)
            return ParseResult.ok(
                MongoMessage(request_id, response_to, op_code, doc, collection)
            )
    except (ValueError, IndexError, struct.error) as e:
        log_error("bad mongo message: %r", e)
        return ParseResult.bad()
    # other legacy ops: acknowledge with an error document
    return ParseResult.ok(MongoMessage(request_id, response_to, op_code, None))


def _plausible(got: bytes) -> bool:
    if len(got) < 4:
        return True
    (length,) = struct.unpack_from("<i", got, 0)
    return 16 <= length <= _MAX_MESSAGE


def pack_op_msg(response_to: int, doc: Dict, request_id: int = 0) -> bytes:
    body = struct.pack("<I", 0) + b"\x00" + bson_encode(doc)
    return (
        struct.pack("<iiii", 16 + len(body), request_id, response_to, OP_MSG)
        + body
    )


def pack_op_reply(response_to: int, docs: List[Dict], request_id: int = 0) -> bytes:
    payload = b"".join(bson_encode(d) for d in docs)
    body = struct.pack("<iqii", 0, 0, 0, len(docs)) + payload
    return (
        struct.pack("<iiii", 16 + len(body), request_id, response_to, OP_REPLY)
        + body
    )


# ---------------------------------------------------------------------------
# server side
# ---------------------------------------------------------------------------
class MongoServiceAdaptor:
    """Subclass and register as ServerOptions.mongo_service_adaptor
    (reference mongo_service_adaptor.h). ``handle`` receives the
    command/query document and returns the reply document."""

    def handle(self, controller, doc: Dict) -> Dict:
        raise NotImplementedError


def process_request(msg: MongoMessage, sock) -> None:
    from incubator_brpc_tpu.client.controller import Controller

    server = sock.server
    adaptor = getattr(getattr(server, "options", None), "mongo_service_adaptor", None)
    reply_id = msg.request_id
    if adaptor is None or msg.doc is None:
        err = {"ok": 0.0, "errmsg": "no mongo service" if adaptor is None
               else f"unsupported opcode {msg.op_code}", "code": 59}
        wire = (
            pack_op_reply(reply_id, [err])
            if msg.op_code != OP_MSG
            else pack_op_msg(reply_id, err)
        )
        sock.write(IOBuf(wire), ignore_eovercrowded=True)
        return
    ctrl = Controller()
    ctrl.server = server
    ctrl._server_socket = sock
    ctrl.remote_side = sock.remote
    ctrl.service_name = "mongo"
    ctrl.method_name = msg.collection or str(msg.doc and next(iter(msg.doc), ""))
    try:
        reply = adaptor.handle(ctrl, msg.doc)
    except Exception as e:  # noqa: BLE001
        log_error("mongo adaptor raised: %r", e)
        reply = {"ok": 0.0, "errmsg": f"handler raised: {e}", "code": 8}
    ctrl._release_session_local()  # handler done: pool the user data
    if ctrl.failed():
        reply = {"ok": 0.0, "errmsg": ctrl.error_text(), "code": ctrl.error_code}
    if not isinstance(reply, dict):
        reply = {"ok": 1.0}
    if msg.op_code == OP_MSG:
        wire = pack_op_msg(reply_id, reply)
    else:
        wire = pack_op_reply(reply_id, [reply])
    sock.write(IOBuf(wire), ignore_eovercrowded=True)


PROTOCOL = Protocol(
    name="mongo",
    parse=parse,
    process_request=process_request,
)


def register():
    register_protocol(PROTOCOL)
