"""MPEG-TS muxer + HLS segmenter — the HTTP-Live-Streaming half of the
media stack.

Analog of reference ts.{h,cpp} (SRS-derived TsPacket/TsChannelGroup/
TsWriter: PAT/PMT tables, PES encapsulation with PTS/DTS, PCR on
keyframes, 188-byte packets with continuity counters and stuffing) plus
the hls segment cutting its users build on top.  Same wire constants:
sync 0x47, PAT pid 0x0000, PMT pid 0x1001 (ts.cpp TS_PID_PMT), video
pid 0x0100 / audio pid 0x0101, stream types H264=0x1B AAC=0x0F
(ts.h Table 2-29), program/PMT number 1.

Input is the RTMP/FLV media model (protocols/rtmp.py RtmpMessage whose
payloads carry FLV VideoTagHeader/AudioTagHeader): the muxer performs
the same remux steps as the reference —

- H.264: AVCDecoderConfigurationRecord (AVC sequence header) supplies
  SPS/PPS + NALU length size; length-prefixed AVCC NALUs convert to
  AnnexB start codes, SPS/PPS re-injected before every keyframe.
- AAC: AudioSpecificConfig (AAC sequence header) supplies
  profile/rate/channels; every raw frame gets an ADTS header.
- PTS = (timestamp + composition_time) * 90, DTS = timestamp * 90
  (90 kHz clock); PCR rides the keyframe's first TS packet.
"""

from __future__ import annotations

import struct
from typing import Dict, List, Optional, Tuple

from incubator_brpc_tpu.protocols.rtmp import MSG_AUDIO, MSG_VIDEO, RtmpMessage

TS_PACKET_SIZE = 188
TS_SYNC_BYTE = 0x47
TS_PID_PAT = 0x0000
TS_PID_PMT = 0x1001
TS_PID_VIDEO = 0x0100
TS_PID_AUDIO = 0x0101
TS_PMT_NUMBER = 1
TS_STREAM_VIDEO_H264 = 0x1B
TS_STREAM_AUDIO_AAC = 0x0F

_PES_VIDEO_SID = 0xE0
_PES_AUDIO_SID = 0xC0

# ADTS sampling_frequency_index table (ISO 14496-3)
_ADTS_RATES = [
    96000, 88200, 64000, 48000, 44100, 32000, 24000, 22050,
    16000, 12000, 11025, 8000, 7350,
]


def crc32_mpeg(data: bytes) -> int:
    """CRC-32/MPEG-2 over PSI sections (poly 0x04C11DB7, init all-ones,
    MSB-first, no reflection, no final xor) — ts.cpp crc32 table."""
    crc = 0xFFFFFFFF
    for b in data:
        crc ^= b << 24
        for _ in range(8):
            crc = ((crc << 1) ^ 0x04C11DB7 if crc & 0x80000000 else crc << 1)
            crc &= 0xFFFFFFFF
    return crc


def _psi_packet(pid: int, table: bytes, cc: int) -> bytes:
    """One TS packet carrying a PSI section (PAT/PMT): pointer_field 0,
    section, then 0xFF stuffing to 188 bytes."""
    out = bytearray()
    out.append(TS_SYNC_BYTE)
    out += struct.pack(">H", 0x4000 | (pid & 0x1FFF))  # PUSI=1
    out.append(0x10 | (cc & 0x0F))  # payload only
    out.append(0x00)  # pointer_field
    out += table
    out += b"\xff" * (TS_PACKET_SIZE - len(out))
    return bytes(out)


def build_pat(cc: int = 0) -> bytes:
    """PAT: program TS_PMT_NUMBER → TS_PID_PMT (ts.cpp CreateAsPAT)."""
    body = struct.pack(">HH", TS_PMT_NUMBER, 0xE000 | TS_PID_PMT)
    return _finish_section(0x00, body, TS_PID_PAT, cc)


def build_pmt(cc: int = 0, has_video: bool = True, has_audio: bool = True) -> bytes:
    """PMT listing the H264/AAC elementary streams; PCR rides the video
    pid when present, else audio (ts.cpp CreateAsPMT:408-416)."""
    pcr_pid = TS_PID_VIDEO if has_video else TS_PID_AUDIO
    body = bytearray()
    body += struct.pack(">H", 0xE000 | pcr_pid)
    body += struct.pack(">H", 0xF000)  # program_info_length 0
    if has_video:
        body.append(TS_STREAM_VIDEO_H264)
        body += struct.pack(">HH", 0xE000 | TS_PID_VIDEO, 0xF000)
    if has_audio:
        body.append(TS_STREAM_AUDIO_AAC)
        body += struct.pack(">HH", 0xE000 | TS_PID_AUDIO, 0xF000)
    return _finish_section(0x02, bytes(body), TS_PID_PMT, cc)


def _finish_section(table_id: int, body: bytes, pid: int, cc: int) -> bytes:
    """Wrap a PSI body: header (id/length/number/version/sections) +
    CRC-32/MPEG, then packetize."""
    inner = struct.pack(">HBB", TS_PMT_NUMBER if table_id == 0x02 else 1,
                        0xC1, 0x00) + b"\x00" + body
    # section_length = inner + crc
    sec = bytearray([table_id])
    sec += struct.pack(">H", 0xB000 | (len(inner) + 4))
    sec += inner
    sec += struct.pack(">I", crc32_mpeg(bytes(sec)))
    return _psi_packet(pid, bytes(sec), cc)


def _pes_header(stream_id: int, pts: int, dts: Optional[int],
                payload_len: int) -> bytes:
    """PES packet header with PTS (and DTS when it differs)."""
    flags = 0x80 if dts is None or dts == pts else 0xC0
    hdr_data_len = 5 if flags == 0x80 else 10
    # PES_packet_length: 0 allowed (unbounded) for video; exact for audio
    total = 3 + hdr_data_len + payload_len
    pes_len = 0 if stream_id == _PES_VIDEO_SID and total > 0xFFFF else total
    out = bytearray(b"\x00\x00\x01")
    out.append(stream_id)
    out += struct.pack(">H", pes_len)
    out.append(0x80)  # marker bits
    out.append(flags)
    out.append(hdr_data_len)
    out += _encode_timestamp(pts, 0x2 if flags == 0x80 else 0x3)
    if flags == 0xC0:
        out += _encode_timestamp(dts, 0x1)
    return bytes(out)


def _encode_timestamp(ts: int, prefix: int) -> bytes:
    ts &= (1 << 33) - 1
    return bytes(
        [
            (prefix << 4) | (((ts >> 30) & 0x7) << 1) | 1,
            (ts >> 22) & 0xFF,
            (((ts >> 15) & 0x7F) << 1) | 1,
            (ts >> 7) & 0xFF,
            ((ts & 0x7F) << 1) | 1,
        ]
    )


class TsMuxer:
    """Packetize PES payloads into 188-byte TS packets.  Stateful per
    output stream: continuity counters per pid, PAT/PMT emitted at each
    segment start (TsChannelGroup analog)."""

    def __init__(self, has_video: bool = True, has_audio: bool = True):
        self._cc: Dict[int, int] = {}
        self.has_video = has_video
        self.has_audio = has_audio

    def _next_cc(self, pid: int) -> int:
        cc = self._cc.get(pid, 0)
        self._cc[pid] = (cc + 1) & 0x0F
        return cc

    def psi(self, has_video: Optional[bool] = None,
            has_audio: Optional[bool] = None) -> bytes:
        """PAT + PMT pair (segment preamble).  The flags may be decided
        per segment: a PMT declaring a phantom stream would point
        PCR_PID at a pid that never carries packets (strict demuxers
        then never clock-sync)."""
        hv = self.has_video if has_video is None else has_video
        ha = self.has_audio if has_audio is None else has_audio
        return build_pat(self._next_cc(TS_PID_PAT)) + build_pmt(
            self._next_cc(TS_PID_PMT), hv, ha
        )

    def mux_pes(self, pid: int, stream_id: int, pts: int,
                dts: Optional[int], es: bytes, pcr: Optional[int] = None) -> bytes:
        """One PES packet → N TS packets (write_pes analog,
        ts.cpp:424-...): PUSI on the first, PCR adaptation field if
        given, stuffing via adaptation field on the tail."""
        data = _pes_header(stream_id, pts, dts, len(es)) + es
        out = bytearray()
        pos = 0
        first = True
        n = len(data)
        while pos < n:
            header = bytearray()
            header.append(TS_SYNC_BYTE)
            header += struct.pack(
                ">H", (0x4000 if first else 0) | (pid & 0x1FFF)
            )
            remain = n - pos
            af = bytearray()
            want_pcr = first and pcr is not None
            space = TS_PACKET_SIZE - 4
            if want_pcr:
                base = pcr & ((1 << 33) - 1)
                af_body = bytearray([0x10])  # PCR flag
                af_body += bytes(
                    [
                        (base >> 25) & 0xFF,
                        (base >> 17) & 0xFF,
                        (base >> 9) & 0xFF,
                        (base >> 1) & 0xFF,
                        ((base & 1) << 7) | 0x7E,  # ext high bits
                        0x00,  # ext low
                    ]
                )
                af = bytearray([len(af_body)]) + af_body
                space -= len(af)
            if remain < space:
                # stuff through the adaptation field to fill 188
                pad = space - remain
                if not af:
                    if pad == 1:
                        af = bytearray([0x00])  # af_length=0 (one byte)
                        pad = 0
                    else:
                        af = bytearray([1, 0x00])  # length + flags
                        pad -= 2
                af += b"\xff" * pad
                if len(af) >= 2:
                    af[0] = len(af) - 1
                space = remain
            header.append(
                (0x30 if af else 0x10) | self._next_cc(pid)
            )
            out += header
            out += af
            out += data[pos : pos + space]
            pos += space
            first = False
        return bytes(out)


class _AvcConfig:
    """Parsed AVCDecoderConfigurationRecord (ISO 14496-15)."""

    def __init__(self, record: bytes):
        if len(record) < 7:
            raise ValueError("short avcC record")
        self.nalu_len_size = (record[4] & 0x03) + 1
        self.sps: List[bytes] = []
        self.pps: List[bytes] = []
        pos = 5
        nsps = record[pos] & 0x1F
        pos += 1
        for _ in range(nsps):
            (ln,) = struct.unpack_from(">H", record, pos)
            pos += 2
            self.sps.append(record[pos : pos + ln])
            pos += ln
        npps = record[pos]
        pos += 1
        for _ in range(npps):
            (ln,) = struct.unpack_from(">H", record, pos)
            pos += 2
            self.pps.append(record[pos : pos + ln])
            pos += ln


def avcc_to_annexb(data: bytes, nalu_len_size: int) -> bytes:
    """Length-prefixed AVCC NALUs → AnnexB start-code stream."""
    out = bytearray()
    pos = 0
    n = len(data)
    while pos + nalu_len_size <= n:
        ln = int.from_bytes(data[pos : pos + nalu_len_size], "big")
        pos += nalu_len_size
        if ln == 0 or pos + ln > n:
            break
        out += b"\x00\x00\x00\x01"
        out += data[pos : pos + ln]
        pos += ln
    return bytes(out)


def adts_header(asc: bytes, frame_len: int) -> bytes:
    """7-byte ADTS header from a 2-byte AudioSpecificConfig.  Raises
    ValueError for frames the 13-bit length field can't express and for
    reserved sampling-rate indices — silently wrapping either corrupts
    the whole elementary stream."""
    profile = (asc[0] >> 3) & 0x1F  # audioObjectType
    rate_idx = ((asc[0] & 0x07) << 1) | ((asc[1] >> 7) & 0x01)
    channels = (asc[1] >> 3) & 0x0F
    if rate_idx >= len(_ADTS_RATES):
        raise ValueError(f"reserved ADTS sampling index {rate_idx}")
    total = frame_len + 7
    if total > 0x1FFF:
        raise ValueError(f"AAC frame too large for ADTS: {frame_len}")
    hdr = bytearray(7)
    hdr[0] = 0xFF
    hdr[1] = 0xF1  # MPEG-4, no CRC
    hdr[2] = (((profile - 1) & 0x03) << 6) | ((rate_idx & 0x0F) << 2) | (
        (channels >> 2) & 0x01
    )
    hdr[3] = ((channels & 0x03) << 6) | ((total >> 11) & 0x03)
    hdr[4] = (total >> 3) & 0xFF
    hdr[5] = ((total & 0x07) << 5) | 0x1F
    hdr[6] = 0xFC
    return bytes(hdr)


class HlsSegment:
    def __init__(self, seq: int, first_ts_ms: int):
        self.seq = seq
        self.first_ts_ms = first_ts_ms
        self.last_ts_ms = first_ts_ms
        self.data = bytearray()
        # which elementary streams this segment's PMT declared (set at
        # PSI time); a frame of an undeclared kind forces a segment cut
        self.declared = (False, False)

    @property
    def duration_s(self) -> float:
        return max(0.0, (self.last_ts_ms - self.first_ts_ms) / 1000.0)


class HlsSegmenter:
    """RTMP media stream → rolling .ts segments + m3u8 playlist.

    Feed RtmpMessages (as delivered by the RTMP relay's on_frame);
    segments cut at video keyframes once ``target_duration_s`` is
    reached (audio-only streams cut on any frame).  Keeps the last
    ``window`` segments, live-HLS style."""

    def __init__(self, target_duration_s: float = 4.0, window: int = 5):
        self.target = target_duration_s
        self.window = window
        self.segments: List[HlsSegment] = []
        self._cur: Optional[HlsSegment] = None
        self._seq = 0
        self._mux = TsMuxer()
        self._avc: Optional[_AvcConfig] = None
        self._asc: Optional[bytes] = None

    # ---- ingest -------------------------------------------------------------
    def on_message(self, msg: RtmpMessage) -> None:
        if msg.type_id == MSG_VIDEO:
            self._on_video(msg.timestamp, msg.payload)
        elif msg.type_id == MSG_AUDIO:
            self._on_audio(msg.timestamp, msg.payload)

    def _on_video(self, ts_ms: int, payload: bytes) -> None:
        if len(payload) < 5:
            return
        frame_type = payload[0] >> 4
        codec = payload[0] & 0x0F
        if codec != 7:  # AVC only (reference hls path likewise)
            return
        pkt_type = payload[1]
        cts = int.from_bytes(payload[2:5], "big", signed=False)
        if cts & 0x800000:
            cts -= 1 << 24  # signed 24-bit composition offset
        body = payload[5:]
        if pkt_type == 0:  # AVC sequence header
            self._avc = _AvcConfig(body)
            return
        if pkt_type != 1 or self._avc is None:
            return
        keyframe = frame_type == 1
        annexb = avcc_to_annexb(body, self._avc.nalu_len_size)
        if keyframe:
            # re-inject SPS/PPS so every segment decodes standalone
            prefix = bytearray(b"\x00\x00\x00\x01\x09\xf0")  # AUD
            for nal in self._avc.sps + self._avc.pps:
                prefix += b"\x00\x00\x00\x01" + nal
            annexb = bytes(prefix) + annexb
        pts = (ts_ms + cts) * 90
        dts = ts_ms * 90
        self._cut_if_due(ts_ms, keyframe)
        self._ensure_declared(ts_ms, want_video=True)
        seg = self._segment(ts_ms)
        seg.data += self._mux.mux_pes(
            TS_PID_VIDEO, _PES_VIDEO_SID, pts, dts, annexb,
            pcr=dts if keyframe else None,
        )
        seg.last_ts_ms = max(seg.last_ts_ms, ts_ms)

    def _on_audio(self, ts_ms: int, payload: bytes) -> None:
        if len(payload) < 2:
            return
        fmt = payload[0] >> 4
        if fmt != 10:  # AAC only
            return
        if payload[1] == 0:  # AAC sequence header
            self._asc = payload[2:4]
            return
        if self._asc is None or len(self._asc) < 2:
            return
        frame = payload[2:]
        try:
            es = adts_header(self._asc, len(frame)) + frame
        except ValueError:
            return  # unframeable frame: drop it, keep the stream alive
        video_present = self._avc is not None
        if not video_present:
            self._cut_if_due(ts_ms, True)  # audio-only: cut anywhere
        self._ensure_declared(ts_ms, want_video=False)
        seg = self._segment(ts_ms)
        pts = ts_ms * 90
        seg.data += self._mux.mux_pes(
            TS_PID_AUDIO, _PES_AUDIO_SID, pts, None, es,
            pcr=None if video_present else pts,
        )
        seg.last_ts_ms = max(seg.last_ts_ms, ts_ms)

    # ---- segmentation -------------------------------------------------------
    def _segment(self, ts_ms: int) -> HlsSegment:
        if self._cur is None:
            hv = self._avc is not None
            ha = self._asc is not None
            self._cur = HlsSegment(self._seq, ts_ms)
            self._seq += 1
            # declare only the streams actually present (sequence
            # headers seen) so PCR_PID matches a live pid
            self._cur.data += self._mux.psi(has_video=hv, has_audio=ha)
            self._cur.declared = (hv, ha)
        return self._cur

    def _ensure_declared(self, ts_ms: int, want_video: bool) -> None:
        """A frame kind the open segment's PMT didn't declare (its
        sequence header arrived after the segment started) forces a cut:
        strict demuxers discard packets on undeclared pids, so the
        stream's first frames would silently vanish."""
        cur = self._cur
        if cur is None:
            return
        hv, ha = cur.declared
        if (want_video and not hv) or (not want_video and not ha):
            self.finish_segment(ts_ms)

    def _cut_if_due(self, ts_ms: int, at_boundary: bool) -> None:
        cur = self._cur
        if (
            cur is not None
            and at_boundary
            and ts_ms - cur.first_ts_ms >= self.target * 1000
        ):
            self.finish_segment(ts_ms)

    def finish_segment(self, ts_ms: Optional[int] = None) -> Optional[HlsSegment]:
        """Seal the open segment (stream end or keyframe cut)."""
        cur, self._cur = self._cur, None
        if cur is None:
            return None
        if ts_ms is not None:
            cur.last_ts_ms = max(cur.last_ts_ms, ts_ms)
        self.segments.append(cur)
        if len(self.segments) > self.window:
            del self.segments[: len(self.segments) - self.window]
        return cur

    # ---- playlist -----------------------------------------------------------
    def playlist(self, uri_prefix: str = "", end: bool = False) -> str:
        """m3u8 media playlist over the current window."""
        segs = self.segments
        target = max(
            [int(s.duration_s + 0.999) for s in segs] + [int(self.target)]
        )
        lines = [
            "#EXTM3U",
            "#EXT-X-VERSION:3",
            f"#EXT-X-TARGETDURATION:{target}",
            f"#EXT-X-MEDIA-SEQUENCE:{segs[0].seq if segs else 0}",
        ]
        for s in segs:
            lines.append(f"#EXTINF:{s.duration_s:.3f},")
            lines.append(f"{uri_prefix}seg{s.seq}.ts")
        if end:
            lines.append("#EXT-X-ENDLIST")
        return "\n".join(lines) + "\n"
