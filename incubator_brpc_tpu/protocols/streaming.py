"""Streaming RPC wire protocol — frames multiplexed on the RPC socket.

Analog of reference policy/streaming_rpc_protocol.cpp (:61-165):
after a stream is negotiated inside a normal RPC (stream_settings in
RpcMeta, baidu_rpc_protocol.cpp:212-264), DATA/FEEDBACK/RST/CLOSE
frames ride the same connection and are routed to the Stream by id.

Framing: b"TSTM" + stream_id(u64 BE) + frame_type(u8) + size(u32 BE)
+ payload. Over the ICI transport the payload IOBuf may carry device
segments — chunked ring-style neighbor exchange of HBM tensors uses
exactly this path.
"""

from __future__ import annotations

import struct

from incubator_brpc_tpu.protocols import ParseResult, Protocol, register_protocol
from incubator_brpc_tpu.utils.iobuf import IOBuf

MAGIC = b"TSTM"
HEADER_SIZE = 17

FRAME_DATA = 0
FRAME_RST = 1
FRAME_CLOSE = 2
FRAME_FEEDBACK = 3  # payload: consumed bytes (u64 BE)


class StreamFrame:
    __slots__ = ("stream_id", "frame_type", "payload")

    def __init__(self, stream_id: int, frame_type: int, payload: IOBuf):
        self.stream_id = stream_id
        self.frame_type = frame_type
        self.payload = payload


def pack_frame(stream_id: int, frame_type: int, payload=None) -> IOBuf:
    payload = payload if payload is not None else IOBuf()
    out = IOBuf()
    out.append(MAGIC + struct.pack(">QBI", stream_id, frame_type, len(payload)))
    out.append(payload)
    return out


def parse(buf: IOBuf, sock, read_eof: bool) -> ParseResult:
    header = buf.fetch(HEADER_SIZE)
    if header is None:
        got = buf.fetch(min(len(buf), 4)) or b""
        if MAGIC.startswith(got[:4]) and len(got) < 4 or got.startswith(MAGIC):
            return ParseResult.not_enough()
        return ParseResult.try_others()
    if header[:4] != MAGIC:
        return ParseResult.try_others()
    stream_id, frame_type, size = struct.unpack_from(">QBI", header, 4)
    if len(buf) < HEADER_SIZE + size:
        return ParseResult.not_enough()
    buf.pop_front(HEADER_SIZE)
    payload = IOBuf()
    buf.cutn(payload, size)
    return ParseResult.ok(StreamFrame(stream_id, frame_type, payload))


def process_frame(msg: StreamFrame, sock) -> None:
    """Route the frame to the Stream registered on this socket
    (ParseStreamingMessage routing, streaming_rpc_protocol.cpp:61)."""
    stream = sock.stream_map.get(msg.stream_id)
    if stream is None:
        if msg.frame_type == FRAME_DATA:
            # unknown stream: tell the peer to stop (SendStreamRst)
            sock.write(pack_frame(msg.stream_id, FRAME_RST))
        return
    stream.on_frame(msg)


PROTOCOL = Protocol(
    name="streaming_rpc",
    parse=parse,
    process_request=process_frame,
    process_response=process_frame,
    support_client=True,
    support_server=True,
    process_in_place=True,
)


def register():
    register_protocol(PROTOCOL)
