"""Streaming RPC wire protocol — frames multiplexed on the RPC socket.

Analog of reference policy/streaming_rpc_protocol.cpp (:61-165):
after a stream is negotiated inside a normal RPC (stream_settings in
RpcMeta, baidu_rpc_protocol.cpp:212-264), DATA/FEEDBACK/RST/CLOSE
frames ride the same connection and are routed to the Stream by id.

Framing: b"TSTM" + stream_id(u64 BE) + frame_type(u8) + size(u32 BE)
+ payload. Over the ICI transport the payload IOBuf may carry device
segments — chunked ring-style neighbor exchange of HBM tensors uses
exactly this path (the fabric's staging-ring pipeline chunks them;
see docs/streaming.md).  Host payloads larger than the shared wire
chunk are split by the Stream into DATA_PART frames closed by one
DATA frame, so message boundaries survive segmentation.
"""

from __future__ import annotations

import struct

from incubator_brpc_tpu.protocols import ParseResult, Protocol, register_protocol
from incubator_brpc_tpu.utils.iobuf import IOBuf

MAGIC = b"TSTM"
HEADER_SIZE = 17

FRAME_DATA = 0
FRAME_RST = 1
FRAME_CLOSE = 2
FRAME_FEEDBACK = 3  # payload: consumed bytes (u64 BE)
FRAME_HALF_CLOSE = 4  # sender finished writing; still reads
FRAME_DATA_PART = 5  # one chunk of a segmented message (DATA closes it)

_VALID_FRAME_TYPES = frozenset(
    (FRAME_DATA, FRAME_RST, FRAME_CLOSE, FRAME_FEEDBACK,
     FRAME_HALF_CLOSE, FRAME_DATA_PART)
)

FRAME_NAMES = {
    FRAME_DATA: "data",
    FRAME_RST: "rst",
    FRAME_CLOSE: "close",
    FRAME_FEEDBACK: "feedback",
    FRAME_HALF_CLOSE: "half_close",
    FRAME_DATA_PART: "data_part",
}

# wire-controlled length guard: a frame bigger than this is framing
# corruption, not a legitimate message (bulk device payloads ride the
# fabric's own chunking, host payloads are segmented into wire chunks
# well below this)
MAX_FRAME_SIZE = 256 << 20


class StreamFrame:
    __slots__ = ("stream_id", "frame_type", "payload")

    def __init__(self, stream_id: int, frame_type: int, payload: IOBuf):
        self.stream_id = stream_id
        self.frame_type = frame_type
        self.payload = payload


def pack_frame(stream_id: int, frame_type: int, payload=None) -> IOBuf:
    payload = payload if payload is not None else IOBuf()
    out = IOBuf()
    out.append(MAGIC + struct.pack(">QBI", stream_id, frame_type, len(payload)))
    out.append(payload)
    return out


def parse(buf: IOBuf, sock, read_eof: bool) -> ParseResult:
    header = buf.fetch(HEADER_SIZE)
    if header is None:
        # fewer than HEADER_SIZE bytes buffered: claim the connection
        # only when what we have is consistent with our magic
        got = buf.fetch(min(len(buf), len(MAGIC))) or b""
        if len(got) < len(MAGIC):
            # partial prefix: b"TS" may still become b"TSTM"
            if MAGIC.startswith(got):
                return ParseResult.not_enough()
            return ParseResult.try_others()
        if got == MAGIC:
            return ParseResult.not_enough()
        return ParseResult.try_others()
    if header[:4] != MAGIC:
        return ParseResult.try_others()
    stream_id, frame_type, size = struct.unpack_from(">QBI", header, 4)
    # wire-controlled fields are validated before any allocation uses
    # them: an alien type byte or an absurd length is corruption — kill
    # the connection rather than stall waiting for 4GB that never comes
    if frame_type not in _VALID_FRAME_TYPES:
        return ParseResult.bad()
    if size > MAX_FRAME_SIZE:
        return ParseResult.bad()
    if len(buf) < HEADER_SIZE + size:
        return ParseResult.not_enough()
    buf.pop_front(HEADER_SIZE)
    payload = IOBuf()
    buf.cutn(payload, size)
    return ParseResult.ok(StreamFrame(stream_id, frame_type, payload))


def process_frame(msg: StreamFrame, sock) -> None:
    """Route the frame to the Stream registered on this socket
    (ParseStreamingMessage routing, streaming_rpc_protocol.cpp:61)."""
    stream = sock.stream_map.get(msg.stream_id)
    if stream is None:
        if msg.frame_type in (FRAME_DATA, FRAME_DATA_PART):
            # unknown stream: tell the peer to stop (SendStreamRst).
            # The wire carries no source id, so the only address we can
            # answer with is the one the DATA arrived under — which is
            # the SENDER's remote_stream_id, not its own id.
            sock.write(pack_frame(msg.stream_id, FRAME_RST))
        elif msg.frame_type == FRAME_RST:
            # …which is why an RST that misses the map by id is matched
            # by remote id: the sender registered itself under its OWN
            # id, and this RST is addressed with the id IT sends under
            for s in list(sock.stream_map.values()):
                if s.remote_stream_id == msg.stream_id:
                    s.on_frame(msg)
                    return
        return
    stream.on_frame(msg)


PROTOCOL = Protocol(
    name="streaming_rpc",
    parse=parse,
    process_request=process_frame,
    process_response=process_frame,
    support_client=True,
    support_server=True,
    process_in_place=True,
)


def register():
    register_protocol(PROTOCOL)
