"""Legacy pb protocol family — hulu, sofa, nshead, nova, public, esp.

Analogs of the reference's legacy ecosystem protocols (SURVEY §2.5,
policy/{hulu,sofa,nova,public}_pbrpc_protocol.cpp, nshead_service.h,
policy/esp_protocol.cpp). Wire facts mirrored from the public formats:

  hulu:   12B header  b"HULU" u32le(body_size) u32le(meta_size),
          body = HuluRpcRequestMeta/ResponseMeta + user message.
  sofa:   24B header  b"SOFA" u32le(meta_size) u64le(body_size)
          u64le(meta_size+body_size), then SofaRpcMeta + user message.
  nshead: 36B struct  <u16 id, u16 version, u32 log_id, char[16]
          provider, u32 magic=0xfb709394, u32 reserved, u32 body_len>,
          then body_len bytes. The base for nova/public framing.
  nova:   nshead whose body is the pb request; method index rides
          head.reserved.
  public: nshead whose body is a PublicPbrpcRequest/Response pb.
  esp:    32B head <u64 from, u64 to, u32 msg, u64 msg_id, i32
          body_len> then body (client side, msg_id correlates).

All integer fields are little-endian (these protocols predate
network-order discipline — reference notes the same).
"""

from __future__ import annotations

import struct
from typing import Dict, Optional, Tuple

from incubator_brpc_tpu import errors
from incubator_brpc_tpu.protocols import ParseResult, Protocol, register_protocol
from incubator_brpc_tpu.protos import legacy_meta_pb2 as pb
from incubator_brpc_tpu.runtime.call_id import default_pool as _id_pool
from incubator_brpc_tpu.runtime.call_id import wire_cid32
from incubator_brpc_tpu.utils.iobuf import IOBuf
from incubator_brpc_tpu.utils.logging import log_error

NSHEAD_MAGIC = 0xFB709394
_NSHEAD_FMT = "<HHI16sIII"
NSHEAD_SIZE = struct.calcsize(_NSHEAD_FMT)  # 36
_ESP_FMT = "<QQIQi"
ESP_HEAD_SIZE = struct.calcsize(_ESP_FMT)  # 32
_MAX_BODY = 512 << 20


def _method_by_index(server, service_name: str, index: int):
    svc = server.services().get(service_name)
    if svc is None:
        return None
    names = sorted(svc.method_specs())
    if 0 <= index < len(names):
        return server.find_method(service_name, names[index])
    return None


def _run_method(server, method, payload: IOBuf, ctrl, respond):
    """Shared dispatch tail: parse request, run user code, respond(ctrl,
    response_bytes|None) exactly once."""
    import time as _time

    status = server.method_status(method.full_name)
    # legacy protocols carry no tenant metadata: admitted as the
    # default tier through the same unified decision point
    verdict = server.admission.admit(method.full_name, status)
    if not verdict.admitted:
        ctrl.set_failed(verdict.code, verdict.reason)
        return respond(ctrl, None)
    if verdict.ticket is not None:
        ctrl._admission_ticket = verdict.ticket

    def _respond(ctrl_, body):
        # release the admission ticket on whichever path ends the
        # request (idempotent pop; only active policies mint tickets)
        ticket = ctrl_.__dict__.pop("_admission_ticket", None)
        if ticket is not None:
            ticket.release()
        return respond(ctrl_, body)

    start = _time.monotonic_ns()
    request = method.request_class()
    try:
        request.ParseFromString(payload.as_view())
    except Exception as e:  # noqa: BLE001
        ctrl.set_failed(errors.EREQUEST, f"parse request failed: {e}")
        if status is not None:
            status.on_response(0, error=True)
        return _respond(ctrl, None)
    response = method.response_class()
    sent = [False]

    def done():
        if sent[0]:
            return
        sent[0] = True
        if status is not None:
            status.on_response(
                (_time.monotonic_ns() - start) // 1000, error=ctrl.failed()
            )
        _respond(ctrl, None if ctrl.failed() else response.SerializeToString())
        ctrl._release_session_local()  # handler done: pool the user data

    try:
        method.fn(ctrl, request, response, done)
    except Exception as e:  # noqa: BLE001
        log_error("handler %s raised: %r", method.full_name, e)
        if not sent[0]:
            ctrl.set_failed(errors.EINTERNAL, f"handler raised: {e}")
            done()


def _server_controller(sock, server):
    from incubator_brpc_tpu.client.controller import Controller

    ctrl = Controller()
    ctrl.server = server
    ctrl._server_socket = sock
    ctrl.remote_side = sock.remote
    return ctrl


# ===========================================================================
# hulu_pbrpc
# ===========================================================================
class HuluMessage:
    __slots__ = ("meta_bytes", "payload")

    def __init__(self, meta_bytes: bytes, payload: IOBuf):
        self.meta_bytes = meta_bytes
        self.payload = payload


def hulu_parse(buf: IOBuf, sock, read_eof: bool) -> ParseResult:
    head = buf.fetch(12)
    if head is None:
        got = buf.fetch(min(len(buf), 4)) or b""
        if b"HULU".startswith(got):
            return ParseResult.not_enough()
        return ParseResult.try_others()
    if head[:4] != b"HULU":
        return ParseResult.try_others()
    body_size, meta_size = struct.unpack_from("<II", head, 4)
    if body_size > _MAX_BODY or meta_size > body_size:
        return ParseResult.bad()
    if len(buf) < 12 + body_size:
        return ParseResult.not_enough()
    buf.pop_front(12)
    meta_bytes = buf.cut_bytes(meta_size)
    payload = IOBuf()
    buf.cutn(payload, body_size - meta_size)
    return ParseResult.ok(HuluMessage(meta_bytes, payload))


def _hulu_frame(meta_bytes: bytes, payload) -> IOBuf:
    out = IOBuf()
    body_size = len(meta_bytes) + len(payload)
    out.append(b"HULU" + struct.pack("<II", body_size, len(meta_bytes)) + meta_bytes)
    out.append(payload)
    return out


def hulu_serialize_request(request, controller) -> IOBuf:
    return IOBuf(request.SerializeToString())


def hulu_pack_request(request_buf, wire_cid, method_spec, controller) -> IOBuf:
    meta = pb.HuluRpcRequestMeta()
    meta.service_name = method_spec.service_name
    meta.method_index = 0  # resolved by name server-side (field 14)
    meta.method_name = method_spec.method_name
    meta.correlation_id = wire_cid
    meta.log_id = controller.log_id
    return _hulu_frame(meta.SerializeToString(), request_buf)


def hulu_process_request(msg: HuluMessage, sock) -> None:
    server = sock.server
    meta = pb.HuluRpcRequestMeta()
    try:
        meta.ParseFromString(msg.meta_bytes)
    except Exception:  # noqa: BLE001
        sock.set_failed(errors.EREQUEST, "bad hulu meta")
        return
    ctrl = _server_controller(sock, server)
    ctrl.service_name = meta.service_name
    cid = meta.correlation_id

    def respond(ctrl, response_bytes):
        rmeta = pb.HuluRpcResponseMeta()
        rmeta.correlation_id = cid
        if ctrl.failed():
            rmeta.error_code = ctrl.error_code
            rmeta.error_text = ctrl.error_text()
        sock.write(
            _hulu_frame(rmeta.SerializeToString(), response_bytes or b""),
            ignore_eovercrowded=True,
        )

    if meta.method_name:
        method = server.find_method(meta.service_name, meta.method_name)
    else:
        method = _method_by_index(server, meta.service_name, meta.method_index)
    if method is None:
        ctrl.set_failed(
            errors.ENOMETHOD,
            f"unknown {meta.service_name}#{meta.method_index}/{meta.method_name}",
        )
        return respond(ctrl, None)
    ctrl.method_name = method.method_name
    _run_method(server, method, msg.payload, ctrl, respond)


def hulu_process_response(msg: HuluMessage, sock) -> None:
    meta = pb.HuluRpcResponseMeta()
    try:
        meta.ParseFromString(msg.meta_bytes)
    except Exception:  # noqa: BLE001
        # the correlation id lives IN the meta: with it unparseable the
        # waiting RPC can never be completed individually, and silently
        # dropping the frame would leave it hanging to timeout.  The
        # response stream is corrupt — fail the socket so every waiter
        # completes promptly with EFAILEDSOCKET.
        sock.set_failed(errors.ERESPONSE, "unparseable hulu response meta")
        return
    cid = meta.correlation_id
    ctrl = _id_pool().lock(cid)
    if ctrl is None:
        return
    if meta.error_code:
        ctrl.set_failed(meta.error_code, meta.error_text)
    else:
        try:
            if ctrl._response is not None:
                ctrl._response.ParseFromString(msg.payload.as_view())
        except Exception as e:  # noqa: BLE001
            ctrl.set_failed(errors.ERESPONSE, f"parse response failed: {e}")
    ctrl._finalize_locked(cid)


HULU = Protocol(
    name="hulu_pbrpc",
    parse=hulu_parse,
    serialize_request=hulu_serialize_request,
    pack_request=hulu_pack_request,
    process_request=hulu_process_request,
    process_response=hulu_process_response,
)


# ===========================================================================
# sofa_pbrpc
# ===========================================================================
class SofaMessage:
    __slots__ = ("meta", "payload")

    def __init__(self, meta, payload: IOBuf):
        self.meta = meta
        self.payload = payload


def sofa_parse(buf: IOBuf, sock, read_eof: bool) -> ParseResult:
    head = buf.fetch(24)
    if head is None:
        got = buf.fetch(min(len(buf), 4)) or b""
        if b"SOFA".startswith(got):
            return ParseResult.not_enough()
        return ParseResult.try_others()
    if head[:4] != b"SOFA":
        return ParseResult.try_others()
    meta_size, body_size, message_size = struct.unpack_from("<IQQ", head, 4)
    if message_size != meta_size + body_size or message_size > _MAX_BODY:
        return ParseResult.bad()
    if len(buf) < 24 + message_size:
        return ParseResult.not_enough()
    buf.pop_front(24)
    meta_bytes = buf.cut_bytes(meta_size)
    payload = IOBuf()
    buf.cutn(payload, body_size)
    meta = pb.SofaRpcMeta()
    try:
        meta.ParseFromString(meta_bytes)
    except Exception:  # noqa: BLE001
        return ParseResult.bad()
    return ParseResult.ok(SofaMessage(meta, payload))


def _sofa_frame(meta: pb.SofaRpcMeta, payload) -> IOBuf:
    meta_bytes = meta.SerializeToString()
    out = IOBuf()
    out.append(
        b"SOFA"
        + struct.pack(
            "<IQQ", len(meta_bytes), len(payload), len(meta_bytes) + len(payload)
        )
        + meta_bytes
    )
    out.append(payload)
    return out


def sofa_serialize_request(request, controller) -> IOBuf:
    return IOBuf(request.SerializeToString())


def sofa_pack_request(request_buf, wire_cid, method_spec, controller) -> IOBuf:
    meta = pb.SofaRpcMeta()
    meta.type = pb.SofaRpcMeta.REQUEST
    meta.sequence_id = wire_cid
    meta.method = f"{method_spec.service_name}.{method_spec.method_name}"
    return _sofa_frame(meta, request_buf)


def sofa_process_request(msg: SofaMessage, sock) -> None:
    server = sock.server
    ctrl = _server_controller(sock, server)
    seq = msg.meta.sequence_id

    def respond(ctrl, response_bytes):
        rmeta = pb.SofaRpcMeta()
        rmeta.type = pb.SofaRpcMeta.RESPONSE
        rmeta.sequence_id = seq
        if ctrl.failed():
            rmeta.failed = True
            rmeta.error_code = ctrl.error_code
            rmeta.reason = ctrl.error_text()
        sock.write(_sofa_frame(rmeta, response_bytes or b""), ignore_eovercrowded=True)

    full = msg.meta.method
    service_name, _, method_name = full.rpartition(".")
    # sofa uses package-qualified names: try the last two components
    method = server.find_method(service_name.rpartition(".")[2], method_name)
    if method is None:
        ctrl.set_failed(errors.ENOMETHOD, f"unknown method {full}")
        return respond(ctrl, None)
    ctrl.service_name = method.service_name
    ctrl.method_name = method.method_name
    _run_method(server, method, msg.payload, ctrl, respond)


def sofa_process_response(msg: SofaMessage, sock) -> None:
    cid = msg.meta.sequence_id
    ctrl = _id_pool().lock(cid)
    if ctrl is None:
        return
    if msg.meta.failed:
        ctrl.set_failed(msg.meta.error_code or errors.ERESPONSE, msg.meta.reason)
    else:
        try:
            if ctrl._response is not None:
                ctrl._response.ParseFromString(msg.payload.as_view())
        except Exception as e:  # noqa: BLE001
            ctrl.set_failed(errors.ERESPONSE, f"parse response failed: {e}")
    ctrl._finalize_locked(cid)


SOFA = Protocol(
    name="sofa_pbrpc",
    parse=sofa_parse,
    serialize_request=sofa_serialize_request,
    pack_request=sofa_pack_request,
    process_request=sofa_process_request,
    process_response=sofa_process_response,
)


# ===========================================================================
# nshead (+ NsheadService) — the base framing for nova/public
# ===========================================================================
class NsheadMessage:
    __slots__ = ("id", "version", "log_id", "provider", "reserved", "body")

    def __init__(self, id=0, version=0, log_id=0, provider=b"", reserved=0,
                 body: Optional[IOBuf] = None):
        self.id = id
        self.version = version
        self.log_id = log_id
        self.provider = provider
        self.reserved = reserved
        self.body = body if body is not None else IOBuf()

    def pack(self) -> IOBuf:
        out = IOBuf()
        out.append(
            struct.pack(
                _NSHEAD_FMT,
                self.id & 0xFFFF,
                self.version & 0xFFFF,
                self.log_id & 0xFFFFFFFF,
                (self.provider or b"")[:16].ljust(16, b"\x00"),
                NSHEAD_MAGIC,
                self.reserved & 0xFFFFFFFF,
                len(self.body),
            )
        )
        out.append(self.body)
        return out


def nshead_parse(buf: IOBuf, sock, read_eof: bool) -> ParseResult:
    head = buf.fetch(NSHEAD_SIZE)
    if head is None:
        # magic sits at offset 24: can't rule nshead out before that
        got = buf.fetch(min(len(buf), 28)) or b""
        if len(got) >= 28:
            (magic,) = struct.unpack_from("<I", got, 24)
            if magic != NSHEAD_MAGIC:
                return ParseResult.try_others()
        return ParseResult.not_enough()
    mid, version, log_id, provider, magic, reserved, body_len = struct.unpack(
        _NSHEAD_FMT, head
    )
    if magic != NSHEAD_MAGIC:
        return ParseResult.try_others()
    if body_len > _MAX_BODY:
        return ParseResult.bad()
    if len(buf) < NSHEAD_SIZE + body_len:
        return ParseResult.not_enough()
    buf.pop_front(NSHEAD_SIZE)
    body = IOBuf()
    buf.cutn(body, body_len)
    return ParseResult.ok(
        NsheadMessage(mid, version, log_id, provider.rstrip(b"\x00"), reserved, body)
    )


class NsheadService:
    """Raw nshead server (reference nshead_service.h): subclass,
    implement ``process(controller, request: NsheadMessage) ->
    NsheadMessage`` and register as ServerOptions.nshead_service."""

    def process(self, controller, request: NsheadMessage) -> NsheadMessage:
        raise NotImplementedError


def nshead_process_request(msg: NsheadMessage, sock) -> None:
    server = sock.server
    opts = getattr(server, "options", None)
    # a configured raw NsheadService owns ALL nshead traffic
    svc = getattr(opts, "nshead_service", None)
    if isinstance(svc, NsheadService):
        ctrl = _server_controller(sock, server)
        try:
            reply = svc.process(ctrl, msg)
        except Exception as e:  # noqa: BLE001
            log_error("nshead service raised: %r", e)
            reply = NsheadMessage(id=msg.id, log_id=msg.log_id)
        if reply is not None:
            reply.log_id = reply.log_id or msg.log_id
            sock.write(reply.pack(), ignore_eovercrowded=True)
        return
    # nova and public share the framing: discriminate by the BODY (a
    # valid PublicPbrpcRequest with a service-named body = public),
    # so one server can face both client kinds at once
    req = pb.PublicPbrpcRequest()
    try:
        req.ParseFromString(msg.body.as_view())
        if req.requestBody and req.requestBody[0].service:
            return _public_process_request(msg, sock, req)
    except Exception:  # noqa: BLE001 — not a public request
        pass
    if getattr(opts, "nova_service", None) is not None:
        return _nova_process_request(msg, sock)
    _public_process_request(msg, sock)  # answers with a public error


def nshead_process_response(msg: NsheadMessage, sock) -> None:
    """Client side: every nshead-framed protocol's responses land here.
    Routing is strict when the socket's issuing protocol is known
    (ubrpc/nshead_mcpack/public/nova each get exactly their own
    semantics — a late reply must never be parsed under another
    protocol's rules); only a plain/unknown nshead socket uses the
    body-shape heuristic, and there a public envelope is accepted only
    when its ids are cids this socket is actually waiting on (arbitrary
    nova payload bytes can parse as an all-optional proto2 message)."""
    proto = getattr(sock, "last_protocol", "")
    if proto in ("ubrpc", "nshead_mcpack"):
        if _mcpack_response_finish(msg, sock, proto):
            return
    with sock._write_lock:
        waiting = set(sock.waiting_cids)
    if proto == "public_pbrpc":
        # strict: a public socket's replies are ALWAYS the pb envelope;
        # falling through to nova parsing would bind a late reply (its
        # ids already finalized) to a newer RPC on a recycled id slot
        resp = pb.PublicPbrpcResponse()
        try:
            resp.ParseFromString(msg.body.as_view())
            if resp.responseBody:
                return _public_finish(resp)
        except Exception:  # noqa: BLE001
            pass
        # unusable reply: fail the correlated RPC fast via the echoed
        # log_id (lock()'s gen/version check rejects stale bindings)
        cid = msg.log_id
        for full in waiting:
            if wire_cid32(full) == cid:
                cid = full
                break
        ctrl = _id_pool().lock(cid)
        if ctrl is not None:
            ctrl.set_failed(errors.ERESPONSE, "unparseable public_pbrpc reply")
            ctrl._finalize_locked(cid)
        else:
            log_error("unparseable public_pbrpc reply dropped")
        return
    if proto != "nova_pbrpc":
        # plain nshead channel or unknown: best-effort heuristic
        resp = pb.PublicPbrpcResponse()
        try:
            resp.ParseFromString(msg.body.as_view())
            bodies = list(resp.responseBody)
            if bodies and all(rb.id in waiting for rb in bodies):
                return _public_finish(resp)
        except Exception:  # noqa: BLE001
            pass
    # nova-style: correlate by log_id (the gen-mixed 32-bit cid form;
    # nshead has no wider field — recover the full versioned id from
    # this socket's waiting set)
    cid = msg.log_id
    for full in waiting:
        if wire_cid32(full) == cid:
            cid = full
            break
    ctrl = _id_pool().lock(cid)
    if ctrl is None:
        return
    if msg.reserved:
        # nova replies signal failure through head.reserved (our framing
        # convention: nshead has no error field of its own)
        ctrl.set_failed(int(msg.reserved), "nova server error")
    else:
        try:
            if ctrl._response is not None:
                ctrl._response.ParseFromString(msg.body.as_view())
        except Exception as e:  # noqa: BLE001
            ctrl.set_failed(errors.ERESPONSE, f"parse response failed: {e}")
    ctrl._finalize_locked(cid)


NSHEAD = Protocol(
    name="nshead",
    parse=nshead_parse,
    serialize_request=lambda request, controller: IOBuf(
        request.SerializeToString()
        if hasattr(request, "SerializeToString")
        else bytes(request)
    ),
    pack_request=lambda request_buf, cid, spec, ctrl: NsheadMessage(
        log_id=wire_cid32(cid), body=request_buf
    ).pack(),
    process_request=nshead_process_request,
    process_response=nshead_process_response,
)


# ===========================================================================
# nova_pbrpc — nshead + pb body, method index in head.reserved
# ===========================================================================
def nova_pack_request(request_buf, wire_cid, method_spec, controller) -> IOBuf:
    nmsg = NsheadMessage(log_id=wire_cid32(wire_cid), body=request_buf)
    nmsg.reserved = getattr(method_spec, "_nova_index", 0)
    nmsg.provider = b"nova-pbrpc"
    return nmsg.pack()


def _nova_process_request(msg: NsheadMessage, sock) -> None:
    server = sock.server
    svc = getattr(server.options, "nova_service", None)
    ctrl = _server_controller(sock, server)
    method = None
    if svc is not None:
        names = sorted(svc.method_specs())
        if 0 <= msg.reserved < len(names):
            method = server.find_method(svc.service_name(), names[msg.reserved])

    def respond(ctrl, response_bytes):
        reply = NsheadMessage(id=msg.id, log_id=msg.log_id)
        if ctrl.failed():
            # nshead has no error field: reserved carries the code
            reply.reserved = ctrl.error_code & 0xFFFFFFFF
        reply.body.append(response_bytes or b"")
        sock.write(reply.pack(), ignore_eovercrowded=True)

    if method is None:
        ctrl.set_failed(errors.ENOMETHOD, f"unknown nova method {msg.reserved}")
        return respond(ctrl, None)
    ctrl.service_name = method.service_name
    ctrl.method_name = method.method_name
    _run_method(server, method, msg.body, ctrl, respond)


NOVA = Protocol(
    name="nova_pbrpc",
    parse=nshead_parse,
    serialize_request=lambda request, controller: IOBuf(request.SerializeToString()),
    pack_request=nova_pack_request,
    process_request=nshead_process_request,
    process_response=nshead_process_response,
)


# ===========================================================================
# public_pbrpc — nshead + PublicPbrpcRequest/Response
# ===========================================================================
def public_pack_request(request_buf, wire_cid, method_spec, controller) -> IOBuf:
    req = pb.PublicPbrpcRequest()
    req.requestHead.from_host = "tpubrpc"
    body = req.requestBody.add()
    body.version = "1.0"
    body.charset = "utf8"
    body.service = method_spec.service_name
    body.method_id = getattr(method_spec, "_public_method_id", 0)
    body.id = wire_cid
    body.serialized_request = bytes(request_buf.as_view())
    return NsheadMessage(
        log_id=wire_cid32(wire_cid), body=IOBuf(req.SerializeToString())
    ).pack()


def _public_process_request(msg: NsheadMessage, sock, req=None) -> None:
    server = sock.server
    if req is None:
        req = pb.PublicPbrpcRequest()
        try:
            req.ParseFromString(msg.body.as_view())
        except Exception:  # noqa: BLE001
            sock.set_failed(errors.EREQUEST, "bad nshead body")
            return
    if not req.requestBody:
        sock.set_failed(errors.EREQUEST, "empty public_pbrpc request")
        return
    body = req.requestBody[0]
    ctrl = _server_controller(sock, server)
    ctrl.service_name = body.service
    rid = body.id

    def respond(ctrl, response_bytes):
        resp = pb.PublicPbrpcResponse()
        head = resp.responseHead
        head.code = -ctrl.error_code if ctrl.failed() else 0
        if ctrl.failed():
            head.text = ctrl.error_text()
        rb = resp.responseBody.add()
        rb.id = rid
        if response_bytes:
            rb.serialized_response = response_bytes
        if ctrl.failed():
            rb.error = ctrl.error_code
        reply = NsheadMessage(id=msg.id, log_id=msg.log_id)
        reply.body.append(resp.SerializeToString())
        sock.write(reply.pack(), ignore_eovercrowded=True)

    method = _method_by_index(server, body.service, body.method_id)
    if method is None:
        ctrl.set_failed(
            errors.ENOMETHOD, f"unknown {body.service}#{body.method_id}"
        )
        return respond(ctrl, None)
    ctrl.method_name = method.method_name
    _run_method(server, method, IOBuf(body.serialized_request), ctrl, respond)


def _public_finish(resp: pb.PublicPbrpcResponse) -> None:
    for rb in resp.responseBody:
        cid = rb.id
        ctrl = _id_pool().lock(cid)
        if ctrl is None:
            continue
        if rb.error or (resp.HasField("responseHead") and resp.responseHead.code < 0):
            ctrl.set_failed(
                rb.error or errors.ERESPONSE,
                resp.responseHead.text if resp.HasField("responseHead") else "",
            )
        else:
            try:
                if ctrl._response is not None:
                    ctrl._response.ParseFromString(rb.serialized_response)
            except Exception as e:  # noqa: BLE001
                ctrl.set_failed(errors.ERESPONSE, f"parse response failed: {e}")
        ctrl._finalize_locked(cid)


PUBLIC = Protocol(
    name="public_pbrpc",
    parse=nshead_parse,
    serialize_request=lambda request, controller: IOBuf(request.SerializeToString()),
    pack_request=public_pack_request,
    process_request=nshead_process_request,
    process_response=nshead_process_response,
)


# ===========================================================================
# ubrpc + nshead_mcpack — mcpack bodies over nshead (reference
# policy/ubrpc2pb_protocol.cpp, policy/nshead_mcpack_protocol.cpp; both
# are NsheadService adaptors there too)
# ===========================================================================
class UbrpcAdaptor(NsheadService):
    """ubrpc (mcpack2 format): body is an mcpack object
    {content: [{service_name, method, id, params: [args...]}]}; the
    reply mirrors {content: [{id, result | error_code/error_text}]}.
    Register as ServerOptions.nshead_service."""

    def __init__(self, server=None):
        self._server = server  # resolved lazily from the controller

    def process(self, controller, request: NsheadMessage):
        from incubator_brpc_tpu.serialization import mcpack

        server = controller.server or self._server
        sock = controller._server_socket

        def send_content(content_obj: dict):
            reply = NsheadMessage(id=request.id, log_id=request.log_id)
            reply.body.append(mcpack.dumps({"content": [content_obj]}))
            sock.write(reply.pack(), ignore_eovercrowded=True)

        try:
            doc = mcpack.loads(bytes(request.body.as_view()))
            content = doc["content"][0]
            service_name = content["service_name"]
            method_name = content["method"]
            rid = int(content.get("id", 0))
            params = content.get("params") or []
        except (KeyError, IndexError, TypeError, ValueError, struct.error) as e:
            send_content({"id": 0, "error_code": errors.EREQUEST,
                          "error_text": f"bad ubrpc request: {e}"})
            return None
        method = server.find_method(service_name, method_name)
        if method is None:
            send_content({"id": rid, "error_code": errors.ENOMETHOD,
                          "error_text": f"unknown {service_name}.{method_name}"})
            return None
        controller.service_name = service_name
        controller.method_name = method_name

        # mcpack params → pb bytes so _run_method (done contract +
        # method_status accounting) serves this protocol like the rest
        req_msg = method.request_class()
        try:
            mcpack._dict_to_msg(params[0] if params else {}, req_msg)
        except (TypeError, ValueError, AttributeError) as e:
            send_content({"id": rid, "error_code": errors.EREQUEST,
                          "error_text": f"params do not fit request: {e}"})
            return None

        def respond(ctrl, response_bytes):
            if ctrl.failed():
                send_content({"id": rid, "error_code": ctrl.error_code,
                              "error_text": ctrl.error_text()})
                return
            resp_msg = method.response_class()
            if response_bytes:
                resp_msg.ParseFromString(response_bytes)
            send_content({"id": rid, "result": mcpack._msg_to_dict(resp_msg)})

        _run_method(server, method, IOBuf(req_msg.SerializeToString()),
                    controller, respond)
        return None  # replies are sent by respond(), possibly async


class NsheadMcpackAdaptor(NsheadService):
    """nshead_mcpack: the body IS the mcpack-serialized pb message;
    every request routes to the server's FIRST service's FIRST method
    (reference NsheadMcpackAdaptor semantics). Correlation rides
    nshead.log_id (echoed back)."""

    def __init__(self):
        self._method = None  # routing target is fixed per server

    def _resolve(self, server):
        if self._method is None:
            for name in sorted(server.services()):
                specs = sorted(server.services()[name].method_specs())
                if specs:
                    self._method = server.find_method(name, specs[0])
                    break
        return self._method

    def process(self, controller, request: NsheadMessage):
        from incubator_brpc_tpu.serialization import mcpack

        server = controller.server
        sock = controller._server_socket
        method = self._resolve(server)
        empty = NsheadMessage(id=request.id, log_id=request.log_id)
        if method is None:
            return empty  # no service: empty reply (ref closes the conn)
        req_msg = method.request_class()
        ok, err = mcpack.mcpack_to_proto(bytes(request.body.as_view()), req_msg)
        if not ok:
            log_error("nshead_mcpack request rejected: %s", err)
            return empty
        controller.service_name = method.service_name
        controller.method_name = method.method_name

        def respond(ctrl, response_bytes):
            reply = NsheadMessage(id=request.id, log_id=request.log_id)
            if not ctrl.failed() and response_bytes:
                resp_msg = method.response_class()
                resp_msg.ParseFromString(response_bytes)
                reply.body.append(mcpack.proto_to_mcpack(resp_msg))
            sock.write(reply.pack(), ignore_eovercrowded=True)

        # through _run_method: done contract + method_status accounting
        _run_method(server, method, IOBuf(req_msg.SerializeToString()),
                    controller, respond)
        return None


def ubrpc_pack_request(request_buf, wire_cid, method_spec, controller) -> IOBuf:
    from incubator_brpc_tpu.serialization import mcpack

    req_msg = controller._ubrpc_request
    body = mcpack.dumps(
        {
            "content": [
                {
                    "service_name": method_spec.service_name,
                    "method": method_spec.method_name,
                    "id": wire_cid,
                    "params": [mcpack._msg_to_dict(req_msg)],
                }
            ]
        }
    )
    return NsheadMessage(log_id=wire_cid32(wire_cid), body=IOBuf(body)).pack()


def _ubrpc_serialize(request, controller) -> IOBuf:
    # the mcpack encoding needs the MESSAGE, not pb bytes: stash it
    controller._ubrpc_request = request
    return IOBuf()


def _mcpack_response_finish(msg: NsheadMessage, sock, protocol: str) -> bool:
    """Client completion for ubrpc / nshead_mcpack responses. → handled."""
    from incubator_brpc_tpu.serialization import mcpack

    with sock._write_lock:
        waiting = set(sock.waiting_cids)
    if protocol == "ubrpc":
        try:
            doc = mcpack.loads(bytes(msg.body.as_view()))
            content = doc["content"][0]
        except (KeyError, IndexError, TypeError, ValueError, struct.error) as e:
            # an unusable ubrpc reply must FAIL the RPC here — falling
            # through to nova semantics would parse garbage (or empty
            # bytes) into the response and report silent success
            cid = msg.log_id
            for full in waiting:
                if wire_cid32(full) == cid:
                    cid = full
                    break
            ctrl = _id_pool().lock(cid)
            if ctrl is not None:
                ctrl.set_failed(errors.ERESPONSE, f"bad ubrpc reply: {e}")
                ctrl._finalize_locked(cid)
            return True
        cid = int(content.get("id", 0))
        if cid not in waiting:
            for full in waiting:
                if wire_cid32(full) == msg.log_id:
                    cid = full
                    break
        ctrl = _id_pool().lock(cid)
        if ctrl is None:
            return True
        if content.get("error_code"):
            ctrl.set_failed(int(content["error_code"]),
                            str(content.get("error_text", "")))
        else:
            try:
                if ctrl._response is not None:
                    mcpack._dict_to_msg(content.get("result") or {}, ctrl._response)
            except (TypeError, ValueError, AttributeError) as e:
                ctrl.set_failed(errors.ERESPONSE, f"bad ubrpc result: {e}")
        ctrl._finalize_locked(cid)
        return True
    # nshead_mcpack: correlate via log_id (gen-mixed 32-bit form)
    cid = msg.log_id
    for full in waiting:
        if wire_cid32(full) == cid:
            cid = full
            break
    ctrl = _id_pool().lock(cid)
    if ctrl is None:
        return True
    if len(msg.body) == 0:
        ctrl.set_failed(errors.ERESPONSE, "empty nshead_mcpack reply")
    else:
        ok, err = mcpack.mcpack_to_proto(
            bytes(msg.body.as_view()), ctrl._response
        ) if ctrl._response is not None else (True, "")
        if not ok:
            ctrl.set_failed(errors.ERESPONSE, f"bad mcpack response: {err}")
    ctrl._finalize_locked(cid)
    return True


UBRPC = Protocol(
    name="ubrpc",
    parse=nshead_parse,
    serialize_request=_ubrpc_serialize,
    pack_request=ubrpc_pack_request,
    process_request=nshead_process_request,
    process_response=nshead_process_response,
)

def _nshead_mcpack_serialize(request, controller) -> IOBuf:
    from incubator_brpc_tpu.serialization import mcpack

    return IOBuf(mcpack.proto_to_mcpack(request))


NSHEAD_MCPACK = Protocol(
    name="nshead_mcpack",
    parse=nshead_parse,
    serialize_request=_nshead_mcpack_serialize,
    pack_request=lambda request_buf, cid, spec, ctrl: NsheadMessage(
        log_id=wire_cid32(cid), body=request_buf
    ).pack(),
    process_request=nshead_process_request,
    process_response=nshead_process_response,
)


# ===========================================================================
# esp — 32-byte head, client side (reference policy/esp_protocol.cpp)
# ===========================================================================
class EspMessage:
    __slots__ = ("to", "msg", "msg_id", "body")

    def __init__(self, to=0, msg=0, msg_id=0, body=b""):
        self.to = to
        self.msg = msg
        self.msg_id = msg_id
        self.body = body


def esp_parse(buf: IOBuf, sock, read_eof: bool) -> ParseResult:
    """esp frames carry NO magic: the protocol owns a socket's bytes
    only when the last request sent on it was esp (recorded by the
    issue path). A well-formed frame with an unknown msg_id (a late
    response to a timed-out RPC) is consumed and dropped downstream —
    failing the socket would kill every other in-flight RPC on it."""
    if sock.is_server_side or getattr(sock, "last_protocol", "") != "esp":
        return ParseResult.try_others()
    head = buf.fetch(ESP_HEAD_SIZE)
    if head is None:
        return ParseResult.not_enough()
    frm, to, msg, msg_id, body_len = struct.unpack(_ESP_FMT, head)
    if body_len < 0 or body_len > _MAX_BODY:
        return ParseResult.bad()
    if len(buf) < ESP_HEAD_SIZE + body_len:
        return ParseResult.not_enough()
    buf.pop_front(ESP_HEAD_SIZE)
    body = buf.cut_bytes(body_len)
    return ParseResult.ok(EspMessage(to, msg, msg_id, body))


def esp_serialize_request(request, controller) -> IOBuf:
    if isinstance(request, EspMessage):
        controller._esp_to = request.to
        controller._esp_msg = request.msg
        return IOBuf(request.body)
    return IOBuf(bytes(request))


def esp_pack_request(request_buf, wire_cid, method_spec, controller) -> IOBuf:
    channel = controller._channel
    auth = channel.options.auth if channel is not None else None
    if auth is not None:
        # reference PackEspRequest prepends the authenticator's
        # credential raw on the connection's first request
        # (policy/esp_protocol.cpp:109-114, EspAuthenticator's magic +
        # local port); the conn_preamble mechanism guarantees exactly
        # one writer sends it first.  No reply is generated for it.
        cred = auth.generate_credential()
        controller._conn_preamble = (IOBuf(cred.encode("latin1")), [])
    head = struct.pack(
        _ESP_FMT,
        0,
        getattr(controller, "_esp_to", 0),
        getattr(controller, "_esp_msg", 0),
        wire_cid,
        len(request_buf),
    )
    out = IOBuf(head)
    out.append(request_buf)
    return out


def esp_process_response(msg: EspMessage, sock) -> None:
    ctrl = _id_pool().lock(msg.msg_id)
    if ctrl is None:
        return
    ctrl.response_attachment = IOBuf(msg.body)
    ctrl._finalize_locked(msg.msg_id)


ESP = Protocol(
    name="esp",
    parse=esp_parse,
    serialize_request=esp_serialize_request,
    pack_request=esp_pack_request,
    process_response=esp_process_response,
    support_server=False,
)


def register():
    register_protocol(HULU)
    register_protocol(SOFA)
    register_protocol(NSHEAD)
    register_protocol(NOVA)
    register_protocol(PUBLIC)
    register_protocol(UBRPC)
    register_protocol(NSHEAD_MCPACK)
    register_protocol(ESP)  # must be LAST: headerless, self-validating
