"""Continuous-batched token-streaming inference — the streaming
subsystem's flagship workload (ROADMAP open item 2; docs/streaming.md).

Two pieces:

``DecodeLoop`` — the serving engine.  One driver thread runs decode
STEPS: every step stacks the states of all live rows into ONE padded
device execution (batching.fused.FusedKernel, padded up to the
policy's bucket so jit retraces stay bounded exactly like PR 5's
batchers), derives one token per row, and emits it.  This is
continuous batching:

  * a request ADMITTED while others are mid-generation joins the very
    next step's fused window (no waiting for the batch to drain);
  * a row that finishes (max_tokens) or cancels (client disconnect,
    slow-consumer eviction, emit failure) RETIRES between steps,
    freeing its slot within one step;
  * one row's emit failure never poisons its step-mates (the per-row
    isolation contract mirrors PR 5's _Scatter).

``GenerateService`` — the RPC surface, three shapes over one loop:

  * ``Generate`` with a negotiated stream: one token FRAME per step on
    the stream, final frame then server-side CLOSE.  Tokens traverse a
    per-row outbox (ExecutionQueue) so a slow consumer's flow-control
    backpressure blocks ITS writer task, never the decode loop; past
    ``outbox_max_tokens`` the row is evicted.
  * ``Generate`` without a stream: unary fallback — the full
    generation (still continuously batched) returns in one response.
  * ``GenerateSSE`` (HTTP): ``data: <token>\\n\\n`` events on a
    chunked ``text/event-stream`` response — a browser-shaped client
    observes tokens progressively with zero framework code.

The "model" is a deterministic toy recurrence (state = tanh(S @ W),
token = f(state)): real transformer decode plugs into ``step_fn``
without touching the serving machinery.
"""

from __future__ import annotations

import hashlib
import itertools
import threading
import time as _time
from collections import deque
from typing import Callable, List, Optional

import numpy as np

from incubator_brpc_tpu import errors
from incubator_brpc_tpu.analysis.device_witness import allowed_transfer
from incubator_brpc_tpu.batching.fused import FusedKernel
from incubator_brpc_tpu.batching.policy import BatchPolicy
from incubator_brpc_tpu.observability.profiling import hbm_account, kernel_section
from incubator_brpc_tpu.protos.echo_pb2 import EchoRequest, EchoResponse
from incubator_brpc_tpu.runtime.execution_queue import ExecutionQueue
from incubator_brpc_tpu.server.service import Service, ServiceStub, rpc_method
from incubator_brpc_tpu.streaming.stream import Stream, StreamHandler, StreamOptions
from incubator_brpc_tpu.utils.logging import log_error

# Default decode-window contract: fuse up to 32 live rows per step,
# padded to power-of-two buckets so the step kernel retraces at most
# 6 times (the PR 5 bucket discipline applied to the decode loop).
GenPolicy = BatchPolicy(
    max_batch_size=32,
    max_wait_us=0,
    padding_buckets=(1, 2, 4, 8, 16, 32),
)

_row_uid = itertools.count(1)

# HBM heap profiler (observability/profiling.py): each live row's
# device-resident state row charges here from its first device step
# until retire — /hotspots/hbm shows what continuous batching pins
_ROW_ACCT = hbm_account("decode.rows")


class _Row:
    __slots__ = (
        "uid", "slot", "prompt", "state", "max_tokens", "tokens_done",
        "emit", "on_finish", "cancelled", "cancel_reason", "admitted_step",
        "loop", "hbm_charge",
    )

    def __init__(self, prompt: str, max_tokens: int, emit, on_finish, loop):
        self.uid = next(_row_uid)
        self.slot = -1
        self.prompt = prompt
        self.state = None
        self.max_tokens = max_tokens
        self.tokens_done = 0
        self.emit = emit
        self.on_finish = on_finish
        self.cancelled = False
        self.cancel_reason = ""
        self.admitted_step = -1
        self.loop = loop
        self.hbm_charge = 0  # _ROW_ACCT adopt return (released at retire)

    def cancel(self, reason: str = "cancelled") -> None:
        """Retire this row at the next step boundary (frees its slot
        within one step).  Callable from any thread — the stream's
        on_closed/on_failed path calls it on client disconnect."""
        if self.cancelled:
            return
        self.cancelled = True
        self.cancel_reason = reason
        loop = self.loop
        if loop is not None:
            loop._kick()


class DecodeLoop:
    """One process-wide decode engine; see the module docstring."""

    def __init__(
        self,
        policy: Optional[BatchPolicy] = None,
        dim: int = 16,
        vocab: int = 32000,
        step_delay_s: float = 0.0,
        step_fn: Optional[Callable] = None,
    ):
        self.policy = policy or GenPolicy
        self.dim = dim
        self.vocab = vocab
        # artificial inter-step pacing (tests/examples that need to
        # observe mid-stream admission deterministically); 0 in prod
        self.step_delay_s = step_delay_s
        # the step kernel returns (new_states, per-row sums) so token
        # derivation needs ONE tiny (pad,) pull per step instead of the
        # full padded state matrix; buckets arm the retrace witness
        self._kernel = FusedKernel(
            self._with_token_sums(step_fn or self._default_step),
            label="decode.step",
            batch_buckets=self.policy.padding_buckets or None,
        )
        rng = np.random.default_rng(1234)
        self._w = (rng.standard_normal((dim, dim)) / np.sqrt(dim)).astype(
            np.float32
        )
        self._w_dev = None  # device-resident weights (placed lazily)
        self._pad_row = None  # cached device zero row for padding
        self._cv = threading.Condition()
        self._pending: deque = deque()
        self._live: List[_Row] = []
        self._thread: Optional[threading.Thread] = None
        self._stopped = False
        # -- stats (tests + bench + /status assertions) --
        self.steps = 0
        self.rows_admitted = 0
        self.rows_retired = 0
        self.rows_cancelled = 0
        # rows that joined a step while others were already live — the
        # continuous-batching signature the bench guard pins
        self.mid_stream_joins = 0
        self.max_fused = 0
        # (step_idx, (row uids fused)) ring for the sharing assertions
        self.step_log: deque = deque(maxlen=1024)

    @staticmethod
    def _default_step(w, s):
        import jax.numpy as jnp

        return jnp.tanh(s @ w)

    @staticmethod
    def _with_token_sums(fn):
        """Fuse the per-row sum the token hash needs into the step
        kernel itself, so the host only ever pulls a (pad,) vector."""

        def step(w, s):
            import jax.numpy as jnp

            new = fn(w, s)
            return new, jnp.sum(new, axis=-1)

        return step

    def _ensure_w(self):
        """Weights live on device once: without this, the numpy `_w`
        would re-cross host→device on EVERY step dispatch."""
        if self._w_dev is None:
            import jax

            self._w_dev = jax.device_put(self._w)
        return self._w_dev

    # ---- admission ----------------------------------------------------------
    def admit(
        self,
        prompt: str,
        max_tokens: int,
        emit: Callable,
        on_finish: Optional[Callable] = None,
        state=None,
    ) -> _Row:
        """Queue one generation request; it joins the next decode
        step's fused window (or waits for a free slot under full load).
        ``emit(token, row)`` runs on the decode thread per token and
        MUST NOT block; ``on_finish(row, ok)`` runs once at retire.

        ``state`` injects a (dim,) device-resident starting state
        instead of the prompt-derived init — the disaggregated path
        (serving/decode.py) admits with KV pulled from the cache tier,
        so the array joins the fused window without ever crossing to
        host."""
        row = _Row(prompt, max(1, int(max_tokens)), emit, on_finish, self)
        if state is not None:
            row.state = state
        else:
            seed = int.from_bytes(
                hashlib.blake2s(prompt.encode(), digest_size=8).digest(),
                "big",
            )
            rng = np.random.default_rng(seed)
            row.state = rng.standard_normal(self.dim).astype(np.float32)
        with self._cv:
            if self._stopped:
                row.cancelled = True
                row.cancel_reason = "decode loop stopped"
            else:
                self._pending.append(row)
                self._ensure_thread_locked()
            self._cv.notify_all()
        if row.cancelled and row.on_finish is not None:
            row.on_finish(row, False)
        return row

    def _ensure_thread_locked(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._drive, name="decode-loop", daemon=True
            )
            self._thread.start()

    def _kick(self) -> None:
        with self._cv:
            self._cv.notify_all()

    def live_rows(self) -> int:
        with self._cv:
            return len(self._live)

    def pending_rows(self) -> int:
        with self._cv:
            return len(self._pending)

    def describe(self) -> dict:
        return {
            "steps": self.steps,
            "live": self.live_rows(),
            "pending": self.pending_rows(),
            "admitted": self.rows_admitted,
            "retired": self.rows_retired,
            "cancelled": self.rows_cancelled,
            "mid_stream_joins": self.mid_stream_joins,
            "max_fused": self.max_fused,
        }

    def prewarm(self) -> None:
        """Trace the step kernel at every padding bucket so no jit
        compile lands inside a serving (or measured) window."""
        import jax.numpy as jnp

        w = self._ensure_w()
        for b in self.policy.padding_buckets or (self.policy.max_batch_size,):
            self._kernel(w, jnp.zeros((b, self.dim), jnp.float32))

    def stop(self) -> None:
        """Cancel everything and stop the driver (idempotent)."""
        with self._cv:
            self._stopped = True
            rows = list(self._pending) + list(self._live)
            self._cv.notify_all()
            thread = self._thread
        for row in rows:
            row.cancel("decode loop stopped")
        if thread is not None and thread.is_alive():
            thread.join(timeout=5.0)

    # ---- the decode driver --------------------------------------------------
    def _drive(self) -> None:
        while True:
            with self._cv:
                while not self._stopped and not self._pending and not self._live:
                    self._cv.wait()
                stopped = self._stopped
                if stopped:
                    to_finish = list(self._pending) + list(self._live)
                    self._pending.clear()
                    self._live = []
                else:
                    to_finish = self._admit_and_retire_locked()
                rows = list(self._live)
            # user callbacks (socket writes, done()) never run under
            # the loop lock — they may be slow or re-enter admit()
            for row in to_finish:
                self._finish_row(row, ok=False)
            if stopped:
                return
            if not rows:
                continue
            try:
                self._step(rows)
            except Exception as e:  # noqa: BLE001 — a step-level fault
                # (kernel failure) retires the whole window as failed,
                # but the loop itself survives for future admissions
                log_error("decode step raised: %r", e)
                for row in rows:
                    row.cancel(f"decode step failed: {e}")
            if self.step_delay_s:
                _time.sleep(self.step_delay_s)

    def _admit_and_retire_locked(self) -> List[_Row]:
        """Runs under the cv.  Returns rows to finish OUTSIDE the lock.
        Retire runs before admit so freed slots are admittable in the
        SAME pass — "a cancel at step k frees the slot within one
        step"."""
        to_finish = []
        kept = []
        for row in self._live:
            (to_finish if row.cancelled else kept).append(row)
        self._live = kept
        while self._pending and len(self._live) < self.policy.max_batch_size:
            row = self._pending.popleft()
            if row.cancelled:
                to_finish.append(row)
                continue
            row.admitted_step = self.steps
            if self._live:
                self.mid_stream_joins += 1
            self._live.append(row)
            self.rows_admitted += 1
        return to_finish

    def _finish_row(self, row: _Row, ok: bool) -> None:
        if row.hbm_charge:
            _ROW_ACCT.release(row.hbm_charge)
            row.hbm_charge = 0
        self.rows_retired += 1
        if not ok:
            self.rows_cancelled += 1
        fin, row.on_finish = row.on_finish, None
        if fin is not None:
            try:
                fin(row, ok)
            except Exception as e:  # noqa: BLE001
                log_error("generate on_finish raised: %r", e)

    def _step(self, rows: List[_Row]) -> None:
        """ONE fused padded device execution for every live row, one
        token emitted per row."""
        import jax.numpy as jnp

        n = len(rows)
        pad_to = self.policy.bucket_for(n)
        # states stay device-resident across steps: stack on device, run
        # the fused kernel, keep the new states on device — only the
        # (pad,) token sums cross to the host, under a manifested scope
        states = [row.state for row in rows]
        if pad_to > n:
            if self._pad_row is None or self._pad_row.shape[0] != self.dim:
                self._pad_row = jnp.zeros((self.dim,), jnp.float32)
            states.extend([self._pad_row] * (pad_to - n))
        # device window: stack + fused step + the manifested (pad,)
        # token-sums pull is the sanctioned completion point
        with kernel_section("decode.step"):
            stacked = jnp.stack(states)
            out, sums = self._kernel(self._ensure_w(), stacked)
            with allowed_transfer("decode.token-sums"):
                sums_host = np.asarray(sums)
        step_idx = self.steps
        self.steps += 1
        self.step_log.append((step_idx, tuple(r.uid for r in rows)))
        if n > self.max_fused:
            self.max_fused = n
        finished = []
        for i, row in enumerate(rows):
            if row.cancelled:
                continue
            row.state = out[i]
            if not row.hbm_charge:
                # first device-resident state: one (dim,) row joins the
                # ledger (adopt reads .nbytes — metadata only)
                row.hbm_charge = _ROW_ACCT.adopt(row.state)
            token = f"t{int(abs(float(sums_host[i])) * 1e4) % self.vocab}"
            row.tokens_done += 1
            try:
                row.emit(token, row)  # ← per-row sink; must not block
            except Exception as e:  # noqa: BLE001 — isolation: one
                # row's sink failure never poisons its step-mates
                log_error("generate emit raised: %r", e)
                row.cancel(f"emit failed: {e}")
                continue
            if row.tokens_done >= row.max_tokens:
                finished.append(row)
        if finished:
            with self._cv:
                for row in finished:
                    if row in self._live:
                        self._live.remove(row)
            for row in finished:
                self._finish_row(row, ok=True)


class _StreamSession(StreamHandler):
    """Per-request glue between one decode row and its stream: a
    bounded outbox (ExecutionQueue) keeps token ORDER while moving the
    flow-control blocking off the decode thread — the decode loop
    emits into the queue and returns immediately; the queue's consumer
    task does the (possibly StreamWait-blocked) stream.write.  Client
    disconnect (CLOSE/RST/socket death) cancels the row; an outbox
    deeper than ``max_tokens_queued`` evicts the slow consumer."""

    def __init__(self, service: "GenerateService", max_tokens_queued: int):
        self._service = service
        self._max_queued = max_tokens_queued
        self._q = ExecutionQueue(self._drain)
        self._lock = threading.Lock()
        self._depth = 0
        self._dead = False
        self.stream: Optional[Stream] = None
        self.row: Optional[_Row] = None

    # -- decode-thread side (never blocks) --
    def emit(self, token: str, row: _Row) -> None:
        with self._lock:
            if self._dead:
                row.cancel("stream gone")
                return
            self._depth += 1
            if self._depth > self._max_queued:
                # slow consumer: its backlog must not pin memory while
                # the decode loop keeps producing for everyone else
                self._dead = True
                row.cancel("slow consumer: outbox overflow")
                return
        self._q.execute(("tok", token))

    def finish(self, row: _Row, ok: bool) -> None:
        self._q.execute(("fin", ok))

    # -- outbox consumer (may block in StreamWait) --
    def _drain(self, batch) -> None:
        for kind, val in batch:
            stream = self.stream
            if kind == "tok":
                with self._lock:
                    self._depth -= 1
                    if self._dead:
                        continue
                rc = stream.write(val) if stream is not None else errors.ECLOSE
                if rc != 0:
                    with self._lock:
                        self._dead = True
                    if self.row is not None:
                        self.row.cancel(f"stream write failed: {rc}")
            else:  # fin — after every queued token, in order
                ok = val
                with self._lock:
                    dead, self._dead = self._dead, True
                if stream is not None and not dead:
                    if ok:
                        stream.close()  # clean close = generation complete
                    else:
                        # truncated generation (decode fault / loop
                        # stopped) must surface as an ERROR on the
                        # client, not a clean end-of-stream
                        stream.reset(
                            errors.ECANCELED,
                            (self.row.cancel_reason if self.row else "")
                            or "generation aborted",
                        )

    # -- peer events --
    def on_closed(self, stream: Stream) -> None:
        with self._lock:
            self._dead = True
        if self.row is not None:
            self.row.cancel("client closed stream")

    def on_failed(self, stream: Stream, code: int, text: str) -> None:
        with self._lock:
            self._dead = True
        if self.row is not None:
            self.row.cancel(f"stream failed: {text}")


class GenerateService(Service):
    """Token-streaming generation over the decode loop (see module
    docstring).  EchoRequest.message = prompt, EchoRequest.code =
    token count (default_tokens when 0)."""

    SERVICE_NAME = "GenerateService"

    def __init__(
        self,
        loop: Optional[DecodeLoop] = None,
        default_tokens: int = 16,
        outbox_max_tokens: int = 1024,
        stream_options: Optional[StreamOptions] = None,
    ):
        self.loop = loop or DecodeLoop()
        self.default_tokens = default_tokens
        self.outbox_max_tokens = outbox_max_tokens
        self._stream_options = stream_options
        # fallback-shape counters (the bench smoke guard pins these: a
        # "streaming" bench whose rows all land here is lying)
        self.streamed_rows = 0
        self.unary_rows = 0
        self.sse_rows = 0

    def close(self) -> None:
        self.loop.stop()

    def _tokens_for(self, request) -> int:
        return int(request.code) if request.code > 0 else self.default_tokens

    @rpc_method(EchoRequest, EchoResponse)
    def Generate(self, controller, request, response, done):
        n_tokens = self._tokens_for(request)
        if controller._remote_stream_settings is None:
            # unary fallback: still continuously batched, one response
            self.unary_rows += 1
            tokens: List[str] = []

            def emit(tok, row):
                tokens.append(tok)

            def finish(row, ok, controller=controller, response=response):
                if not ok:
                    controller.set_failed(
                        errors.ECANCELED, row.cancel_reason or "cancelled"
                    )
                else:
                    response.message = " ".join(tokens)
                    response.code = len(tokens)
                done()

            self.loop.admit(request.message, n_tokens, emit, finish)
            return
        self.streamed_rows += 1
        session = _StreamSession(self, self.outbox_max_tokens)
        opts = self._stream_options or StreamOptions()
        stream = Stream.accept(controller, session, opts)
        session.stream = stream
        response.message = "streaming"
        response.code = n_tokens
        # respond FIRST: the response frame (carrying our stream
        # settings) must precede the first token frame on the wire, or
        # the client would RST the unknown stream id
        done()
        session.row = self.loop.admit(
            request.message, n_tokens, session.emit, session.finish
        )

    @rpc_method(EchoRequest, EchoResponse)
    def GenerateSSE(self, controller, request, response, done):
        """HTTP progressive path: Server-Sent Events on a chunked
        text/event-stream response — ``data: <token>`` per step,
        ``data: [DONE]`` then close at the end."""
        self.sse_rows += 1
        pa = controller.create_progressive_attachment(
            content_type="text/event-stream"
        )
        # slow-consumer bound, mirroring the stream path's outbox
        # eviction: past this many unsent bytes on the connection the
        # row is evicted instead of growing the socket queue forever
        backlog_cap = max(64, self.outbox_max_tokens) * 64

        def emit(tok, row, pa=pa):
            if pa.backlog_bytes() > backlog_cap:
                row.cancel("sse client too slow: backlog over cap")
                return
            if pa.write(f"data: {tok}\n\n") != 0:
                row.cancel("sse client gone")

        def finish(row, ok, pa=pa):
            if ok:
                pa.write("data: [DONE]\n\n")
            pa.close()

        self.loop.admit(request.message, self._tokens_for(request), emit, finish)
        done()


def generate_stub(channel) -> ServiceStub:
    return ServiceStub(channel, GenerateService)
