"""Streaming observability — process-wide live-stream registry and the
``rpc_stream_*`` variables.

One registry serves three consumers:

  * ``/metrics``  — ``rpc_stream_live`` (live streams right now),
    ``rpc_stream_blocked_writers`` (writers currently parked in
    StreamWait), ``rpc_stream_feedback_rtt_us`` (time from the last
    DATA write to the FEEDBACK that acknowledged it — the flow-control
    loop's round trip), and frame counters in/out.
  * ``/status``   — the per-method live-stream table
    (:func:`streams_by_method`).
  * tests/bench   — the same numbers, read directly.

Registration is owned by streaming.stream: a Stream registers at
establish() and deregisters at close, so a stream that never
establishes (failed negotiation) never appears here.
"""

from __future__ import annotations

import threading
from typing import Dict, List

from incubator_brpc_tpu.metrics.passive_status import PassiveStatus
from incubator_brpc_tpu.metrics.recorder import IntRecorder
from incubator_brpc_tpu.metrics.reducer import Adder

_lock = threading.Lock()
_live: dict = {}  # stream_id -> Stream (weak coupling: read-only views)

# frames that reached the wire / were routed to a stream, all methods
frames_out = Adder(0).expose("rpc_stream_frames_out_total")
frames_in = Adder(0).expose("rpc_stream_frames_in_total")
# writers currently blocked past the remote's unconsumed backlog
blocked_writers = Adder(0).expose("rpc_stream_blocked_writers")
# last-DATA→FEEDBACK round trip, microseconds (approximate by
# construction: feedback acknowledges consumption, not one frame)
feedback_rtt_us = IntRecorder().expose("rpc_stream_feedback_rtt_us")


def _live_count() -> int:
    return len(_live)


live_streams = PassiveStatus(_live_count).expose("rpc_stream_live")


def register(stream) -> None:
    with _lock:
        _live[stream.stream_id] = stream


def deregister(stream) -> None:
    with _lock:
        _live.pop(stream.stream_id, None)


def live() -> List:
    with _lock:
        return list(_live.values())


def streams_by_method() -> Dict[str, List[dict]]:
    """Live streams grouped by the negotiating RPC's full method name
    (the /status table)."""
    out: Dict[str, List[dict]] = {}
    for s in live():
        out.setdefault(s.method or "?", []).append(s.describe())
    return out
