"""Streaming RPC subsystem — flow-controlled streams over the shared
connection (host TCP or ICI/DCN fabric) plus the token-streaming
generate service built on them.

Layers (docs/streaming.md):
  protocols/streaming.py   wire frames (DATA/DATA_PART/FEEDBACK/RST/
                           CLOSE/HALF_CLOSE) multiplexed on the socket
  streaming/stream.py      the Stream state machine: StreamWait flow
                           control, half-close, idle timeout, chunked
                           writes via the shared segmentation policy
  streaming/observe.py     live-stream registry + rpc_stream_* metrics
  streaming/generate.py    continuous-batched token-streaming
                           inference: DecodeLoop + GenerateService
"""

from incubator_brpc_tpu.streaming.stream import (  # noqa: F401
    Stream,
    StreamHandler,
    StreamOptions,
)


def __getattr__(name):
    # generate.py pulls in jax/numpy via batching.fused — lazy so that
    # plain stream users never pay for it
    if name in ("GenerateService", "DecodeLoop", "GenPolicy"):
        from incubator_brpc_tpu.streaming import generate

        return getattr(generate, name)
    raise AttributeError(name)
