"""Streaming RPC — ordered, flow-controlled, bidirectional streams.

Analog of reference stream.{h,cpp} (stream.h:90-130) and
stream_impl.h:30: a Stream is negotiated inside a normal RPC (the id
rides RpcMeta.stream_settings), then DATA frames flow on the host
connection with consumed-bytes feedback flow control
(min_buf_size/max_buf_size, stream.h:50-67): the writer blocks in
``write`` when the remote's unconsumed backlog would exceed
max_buf_size — the reference's StreamWait semantics — and wakes on the
peer's FEEDBACK.

Beyond the reference skeleton this implementation carries (see
docs/streaming.md for the full contract):

  * per-direction stream-id namespaces — client-created streams take
    odd ids, server-created even (the h2 discipline), so two peers on
    one connection can never mint colliding ids;
  * message segmentation — host payloads larger than the shared wire
    chunk (utils/segmentation.py) are split into DATA_PART frames
    closed by one DATA frame, so one oversized write can neither stall
    the connection's writer role nor deadlock against max_buf_size;
    device payloads are NEVER split here — over an ICI socket the
    fabric's chunked staging-ring pipeline (PR 4) moves them zero-copy
    with chained checksums;
  * feedback batching — a receiver accumulates consumed bytes until
    ``min_buf_size`` before sending FEEDBACK (capped at half the
    peer's max_buf_size so batching can never starve a blocked
    writer);
  * half-close — ``close_write()`` sends HALF_CLOSE: this side stops
    writing but keeps reading; the stream fully closes when both
    directions are done;
  * idle timeout — ``idle_timeout_s`` of no frame traffic fails the
    stream with ERPCTIMEDOUT and RSTs the peer.  This is also the
    deadlock escape when FEEDBACK is lost (chaos site stream.frame):
    a writer blocked on a window that will never reopen is released
    in bounded time;
  * RST isolation — either side's failure resets THE STREAM, never
    the shared socket: other streams and in-flight RPCs on the
    connection are untouched.

Usage (mirrors StreamCreate/StreamAccept/StreamWrite/StreamClose):
    client:  stream = Stream.create(ctrl, handler, opts)
             stub.Method(ctrl, req)           # negotiates the stream
             stream.write(IOBuf(b"chunk"))
    server:  stream = Stream.accept(ctrl, handler, opts)  # in handler
             done()                           # response carries settings
"""

from __future__ import annotations

import itertools
import threading
import time as _time
from dataclasses import dataclass
from typing import List, Optional

from incubator_brpc_tpu import errors
from incubator_brpc_tpu.chaos import injector as _chaos
from incubator_brpc_tpu.protocols import streaming as wire
from incubator_brpc_tpu.protos import rpc_meta_pb2 as pb
from incubator_brpc_tpu.runtime.execution_queue import ExecutionQueue
from incubator_brpc_tpu.runtime.timer_thread import get_timer_thread
from incubator_brpc_tpu.streaming import observe
from incubator_brpc_tpu.utils.iobuf import IOBuf
from incubator_brpc_tpu.utils.logging import log_error
from incubator_brpc_tpu.utils.segmentation import WIRE_CHUNK_BYTES, plan_chunks

# Per-direction id namespaces (the h2 discipline, protocols/h2.py
# next_stream_id): the client mints odd ids, the server even.  Each
# peer draws from its own process's counter, so without the parity
# split two processes on one connection both start at 1 and the
# second stream registered under a colliding id hijacks the first's
# frames.
_client_id_seq = itertools.count(1, 2)
_server_id_seq = itertools.count(2, 2)


class StreamHandler:
    """Analog of brpc::StreamInputHandler."""

    def on_received_messages(self, stream: "Stream", messages: List[IOBuf]):
        pass

    def on_closed(self, stream: "Stream"):
        pass

    def on_failed(self, stream: "Stream", error_code: int, error_text: str):
        pass

    def on_half_close(self, stream: "Stream"):
        """Peer finished writing (HALF_CLOSE); it still reads."""


@dataclass
class StreamOptions:
    # writer blocks past this unconsumed backlog at the peer
    max_buf_size: int = 2 << 20
    # receiver-side feedback batching: consumed bytes accumulate to at
    # least this before a FEEDBACK frame goes out (0 = immediate).
    # Effective threshold is capped at half the PEER's max_buf_size so
    # batching can never park its writer forever.
    min_buf_size: int = 0
    # no frame traffic for this long fails the stream (ERPCTIMEDOUT)
    # and RSTs the peer; 0 disables.  Also the lost-FEEDBACK escape.
    idle_timeout_s: float = 0.0
    # host payloads above this split into DATA_PART chunks (shared
    # wire-chunk policy); device payloads never split here
    write_chunk_bytes: int = WIRE_CHUNK_BYTES
    handler: Optional[StreamHandler] = None


class Stream:
    def __init__(self, options: StreamOptions, is_server: bool):
        self.stream_id = next(_server_id_seq if is_server else _client_id_seq)
        self.options = options
        self.is_server = is_server
        self.remote_stream_id = 0
        self.method = ""  # negotiating RPC's full method name (observe)
        self._ctrl = None  # negotiating controller, held until establish
        self._sock = None
        self._established = threading.Event()
        self._closed = False
        self._failed = (0, "")
        # half-close state machine: OPEN → {local,remote} write-closed
        # → CLOSED once both directions are done
        self._local_write_closed = False
        self._remote_write_closed = False
        # flow control (consumed feedback, stream.h:50-67)
        self._unconsumed = 0
        self._flow_cond = threading.Condition()
        self._peer_max_buf = 0  # peer's advertised max_buf_size
        self._consumed_pending = 0  # receiver-side feedback batching
        # guards the pending-feedback swap: close()/close_write() flush
        # from user threads while the rx consumer flushes post-handler —
        # an unguarded read-then-zero could send the same credit twice,
        # over-crediting the peer's window
        self._fb_lock = threading.Lock()
        # receiver reassembly of segmented messages (DATA_PART…DATA)
        self._part_acc: Optional[IOBuf] = None
        # idle timeout
        self._last_activity_ns = _time.monotonic_ns()
        self._idle_timer = 0
        # stats (rpcz annotations + /status rows)
        self.frames_sent = 0
        self.frames_received = 0
        self.bytes_sent = 0
        self.bytes_received = 0
        self.consumed_bytes = 0  # bytes this side consumed + fed back
        self.writer_blocked_ns = 0
        self._last_data_ns = 0  # feedback-RTT probe: last DATA sent
        self._span = None  # "stream" rpcz span joined to the RPC's trace
        # ordered delivery through an execution queue (stream.cpp uses
        # bthread::ExecutionQueue for exactly this); items are
        # (message, deferred_feedback_bytes)
        self._rx = ExecutionQueue(self._consume_batch)

    # ---- negotiation --------------------------------------------------------
    @classmethod
    def create(cls, controller, handler: StreamHandler, options=None) -> "Stream":
        """Client side, BEFORE issuing the RPC (StreamCreate, stream.h:90)."""
        opts = options or StreamOptions()
        opts.handler = handler or opts.handler
        stream = cls(opts, is_server=False)
        controller._request_stream = stream
        stream._adopt_controller(controller)
        return stream

    @classmethod
    def accept(cls, controller, handler: StreamHandler, options=None) -> "Stream":
        """Server side, inside the method handler (StreamAccept, stream.h:97)."""
        opts = options or StreamOptions()
        opts.handler = handler or opts.handler
        stream = cls(opts, is_server=True)
        controller._response_stream = stream
        stream._adopt_controller(controller)
        req_settings = controller._remote_stream_settings
        if req_settings is not None:
            stream.establish(
                controller._server_socket, req_settings.stream_id, req_settings
            )
        return stream

    def _adopt_controller(self, controller):
        """Remember the negotiating controller until establish: on the
        client its method spec and rpcz span don't exist yet at
        Stream.create (they are built inside _start_call)."""
        self._ctrl = controller

    def _resolve_identity(self):
        """Pick up the negotiating RPC's identity at establish time:
        method name for the /status table and the trace for the
        stream's rpcz span.  The controller reference is dropped here —
        pooled controllers are released after done() and must not be
        pinned by a long-lived stream."""
        controller, self._ctrl = self._ctrl, None
        if controller is None:
            return
        spec = getattr(controller, "_method_spec", None)
        if spec is not None:
            self.method = spec.full_name
        elif getattr(controller, "service_name", ""):
            self.method = f"{controller.service_name}.{controller.method_name}"
        parent = getattr(controller, "_span", None)
        if parent is not None:
            from incubator_brpc_tpu.observability.span import Span

            # joined to the negotiating RPC's trace: /rpcz?trace= shows
            # the stream's whole life under the RPC that created it
            service, _, method = self.method.partition(".")
            span = Span("stream", service, method)
            span.trace_id = parent.trace_id
            span.parent_span_id = parent.span_id
            span.annotate(f"stream id={self.stream_id} created")
            self._span = span

    def fill_settings(self) -> pb.StreamSettings:
        ss = pb.StreamSettings()
        ss.stream_id = self.stream_id
        ss.need_feedback = True
        ss.max_buf_size = self.options.max_buf_size
        ss.min_buf_size = self.options.min_buf_size
        return ss

    def establish(self, sock, remote_stream_id: int, remote_settings=None):
        """Wire the stream onto the connection once the peer's id is
        known (client: response meta arrived; server: request meta)."""
        self._sock = sock
        self.remote_stream_id = remote_stream_id
        if remote_settings is not None:
            self._peer_max_buf = int(remote_settings.max_buf_size or 0)
        self._resolve_identity()
        sock.stream_map[self.stream_id] = self
        self._touch()
        observe.register(self)
        if self._span is not None:
            self._span.remote_side = str(getattr(sock, "remote", "") or "")
            self._span.annotate(
                f"established remote_id={remote_stream_id} "
                f"peer_max_buf={self._peer_max_buf}"
            )
        self._established.set()
        self._arm_idle_timer()

    def wait_established(self, timeout: float = 5.0) -> bool:
        return self._established.wait(timeout)

    # ---- frame egress (chaos chokepoint) ------------------------------------
    def _send_frame(self, frame_type: int, payload=None) -> int:
        """Every outgoing frame funnels through here: chaos site
        ``stream.frame`` (direction = frame kind) + frame counters."""
        if _chaos.armed:
            spec = _chaos.check(
                "stream.frame",
                peer=getattr(self._sock, "remote", None),
                direction=wire.FRAME_NAMES.get(frame_type),
            )
            if spec is not None:
                act = spec.action
                if act == "delay_us":
                    _chaos.sleep_us(spec.arg)
                elif act == "drop":
                    # the frame silently vanishes — a dropped FEEDBACK
                    # must be survivable via the idle-timeout escape
                    return 0
                elif act == "reorder":
                    stashed = self._swap_reorder_stash(frame_type, payload)
                    if stashed:
                        return 0
                elif act == "reset":
                    # stream-level fault: RST THIS stream, keep the
                    # socket (and its other streams / RPCs) alive
                    self._send_raw(wire.FRAME_RST)
                    self._mark_failed(errors.ECLOSE, "chaos: injected stream reset")
                    return errors.ECLOSE
        return self._send_raw(frame_type, payload)

    def _send_raw(self, frame_type: int, payload=None) -> int:
        sock = self._sock
        if sock is None or sock.failed:
            return errors.EFAILEDSOCKET
        rc = sock.write(wire.pack_frame(self.remote_stream_id, frame_type, payload))
        if rc == 0:
            self.frames_sent += 1
            if payload is not None:
                self.bytes_sent += len(payload)
            observe.frames_out << 1
            self._touch()
        return rc

    def _swap_reorder_stash(self, frame_type: int, payload) -> bool:
        """Chaos reorder (the dcn.send stash-swap shape): hold one
        frame back; the NEXT frame through releases it after itself."""
        stash = getattr(self, "_reorder_stash", None)
        if stash is None:
            self._reorder_stash = (frame_type, payload)
            return True
        self._reorder_stash = None
        self._send_raw(frame_type, payload)  # the newer frame first
        self._send_raw(*stash)  # then the stashed one
        return False

    # ---- writing (StreamWrite + StreamWait flow control) --------------------
    def write(self, data, timeout: Optional[float] = 10.0) -> int:
        if isinstance(data, (bytes, str)):
            data = IOBuf(data)
        rc = self._writable_or_error()
        if rc:
            return rc
        if not self._established.wait(timeout or 10.0):
            return errors.ERPCTIMEDOUT
        size = len(data)
        # effective chunk never exceeds the flow window: with the
        # defaults (4MB wire chunk > 2MB max_buf) an unsegmented 3MB
        # frame could otherwise never satisfy StreamWait
        chunk = min(self.options.write_chunk_bytes, self.options.max_buf_size)
        if size > chunk and not data.has_device_payload():
            return self._write_segmented(data, size, chunk, timeout)
        rc = self._flow_wait(size, timeout)
        if rc:
            return rc
        self._last_data_ns = _time.monotonic_ns()
        return self._send_frame(wire.FRAME_DATA, data)

    def write_device(self, array, timeout: Optional[float] = 10.0) -> int:
        """Stream one HBM-resident array as a single message.  Over an
        ICI socket the payload rides the fabric's chunked staging-ring
        pipeline zero-copy with chained checksums (docs/ici_pipeline.md)
        — this layer never splits or materializes device payloads."""
        buf = IOBuf()
        buf.append_device(array)
        return self.write(buf, timeout)

    def _write_segmented(self, data: IOBuf, size: int, chunk: int, timeout) -> int:
        """Split one host message into DATA_PART frames closed by a
        DATA frame (the shared chunk plan, utils/segmentation.py):
        flow control is exerted PER CHUNK, so a message larger than
        max_buf_size streams through the window instead of deadlocking
        against it, and the socket's writer role is never held for one
        giant frame.  Message boundaries survive — the receiver
        reassembles and delivers ONE message."""
        plan = plan_chunks(size, chunk)
        for idx, (_, length) in enumerate(plan):
            rc = self._flow_wait(length, timeout)
            if rc == 0:
                part = IOBuf()
                data.cutn(part, length)  # ref-sharing cut, no copy
                last = idx == len(plan) - 1
                self._last_data_ns = _time.monotonic_ns()
                rc = self._send_frame(
                    wire.FRAME_DATA if last else wire.FRAME_DATA_PART, part
                )
            if rc:
                if idx > 0 and not self._closed:
                    # chunks 0..idx-1 are already in the peer's
                    # reassembly buffer: the message can never complete,
                    # and leaving the half-message there would splice
                    # its prefix onto the NEXT message.  A mid-message
                    # abort is unrecoverable — reset the stream.
                    self.reset(rc, "segmented write aborted mid-message")
                return rc
            if self._span is not None:
                self._span.chunk_mark("stream", idx, len(plan), length)
        return 0

    def _writable_or_error(self) -> int:
        if self._failed[0]:
            return self._failed[0]
        if self._closed:
            return errors.ECLOSE
        if self._local_write_closed:
            return errors.ECLOSE
        return 0

    def _flow_wait(self, size: int, timeout) -> int:
        """Block while the peer's unconsumed backlog would exceed
        max_buf_size (StreamWait).  Wakes on FEEDBACK, close or
        failure; the idle timer bounds a wait whose FEEDBACK was lost.
        A single frame larger than the whole window (an unsplittable
        device payload) is admitted when the window is EMPTY — at most
        one such message in flight, instead of never."""

        def admissible():
            return (
                self._unconsumed + size <= self.options.max_buf_size
                or self._unconsumed == 0
            )

        with self._flow_cond:
            if not (self._closed or self._failed[0] or admissible()):
                observe.blocked_writers << 1
                t0 = _time.monotonic_ns()
                try:
                    ok = self._flow_cond.wait_for(
                        lambda: self._closed or self._failed[0] or admissible(),
                        timeout,
                    )
                finally:
                    blocked = _time.monotonic_ns() - t0
                    self.writer_blocked_ns += blocked
                    observe.blocked_writers << -1
                if not ok:
                    return errors.ERPCTIMEDOUT  # reference EAGAIN after StreamWait
            if self._failed[0]:
                return self._failed[0]
            if self._closed or self._local_write_closed:
                return errors.ECLOSE
            self._unconsumed += size
        return 0

    # ---- receiving ----------------------------------------------------------
    def on_frame(self, frame: wire.StreamFrame):
        self._touch()
        self.frames_received += 1
        observe.frames_in << 1
        ftype = frame.frame_type
        if ftype == wire.FRAME_DATA or ftype == wire.FRAME_DATA_PART:
            if self._remote_write_closed:
                # data after the peer declared its write side done is a
                # protocol violation: reset the stream, not the socket
                self._send_raw(wire.FRAME_RST)
                self._mark_failed(errors.EREQUEST, "DATA after half-close")
                return
            self.bytes_received += len(frame.payload)
            if ftype == wire.FRAME_DATA_PART:
                if self._part_acc is None:
                    self._part_acc = IOBuf()
                self._part_acc.append(frame.payload)
                # reassembly counts as consumption — a message larger
                # than the writer's max_buf_size must keep flowing
                self._note_consumed(len(frame.payload))
                return
            msg = frame.payload
            deferred = len(msg)
            if self._part_acc is not None:
                acc, self._part_acc = self._part_acc, None
                acc.append(msg)
                msg = acc
            self._rx.execute((msg, deferred))
        elif ftype == wire.FRAME_FEEDBACK:
            consumed = int.from_bytes(frame.payload.to_bytes()[:8], "big")
            if self._last_data_ns:
                rtt_us = (_time.monotonic_ns() - self._last_data_ns) // 1000
                observe.feedback_rtt_us << rtt_us
            with self._flow_cond:
                self._unconsumed = max(0, self._unconsumed - consumed)
                self._flow_cond.notify_all()
        elif ftype == wire.FRAME_HALF_CLOSE:
            self._on_remote_half_close()
        elif ftype == wire.FRAME_CLOSE:
            self._mark_closed()
        elif ftype == wire.FRAME_RST:
            self._mark_failed(errors.ECLOSE, "stream reset by peer")

    def _consume_batch(self, batch):
        items = list(batch)
        if not items:
            return
        msgs = [m for m, _ in items]
        handler = self.options.handler
        if handler is not None:
            try:
                handler.on_received_messages(self, msgs)
            except Exception as e:  # noqa: BLE001
                log_error("stream handler raised: %r", e)
        # consumed-bytes feedback unblocks the remote writer
        self._note_consumed(sum(fb for _, fb in items))

    def _note_consumed(self, n: int) -> None:
        """Accumulate consumed bytes; FEEDBACK goes out once the batch
        reaches the min_buf_size threshold (capped so batching can
        never exceed half the peer's window — a starved writer would
        otherwise wait on feedback that is itself waiting on more
        consumption)."""
        if n <= 0:
            return
        self.consumed_bytes += n
        threshold = self.options.min_buf_size
        if self._peer_max_buf:
            threshold = min(threshold, self._peer_max_buf // 2)
        with self._fb_lock:
            # part-arrival (parse thread) and post-handler (rx consumer)
            # credits race here; the lock keeps the accumulator exact
            self._consumed_pending += n
            below = self._consumed_pending < max(1, threshold)
        if below:
            return
        self._flush_feedback()

    def _flush_feedback(self) -> None:
        with self._fb_lock:
            pending, self._consumed_pending = self._consumed_pending, 0
        if pending <= 0:
            return
        if self._sock is not None and not self._sock.failed and not self._closed:
            self._send_frame(
                wire.FRAME_FEEDBACK, IOBuf(pending.to_bytes(8, "big"))
            )

    # ---- idle timeout -------------------------------------------------------
    def _touch(self) -> None:
        self._last_activity_ns = _time.monotonic_ns()

    def _arm_idle_timer(self) -> None:
        t = self.options.idle_timeout_s
        if t <= 0 or self._closed:
            return
        self._idle_timer = get_timer_thread().schedule(self._on_idle_timer, t)

    def _on_idle_timer(self) -> None:
        if self._closed or self._failed[0]:
            return
        idle_s = (_time.monotonic_ns() - self._last_activity_ns) / 1e9
        remaining = self.options.idle_timeout_s - idle_s
        if remaining > 0.001:
            self._idle_timer = get_timer_thread().schedule(
                self._on_idle_timer, remaining
            )
            return
        # never run teardown (socket writes, user callbacks) on the
        # process-wide timer thread
        from incubator_brpc_tpu.runtime import scheduler

        scheduler.spawn(self._fail_idle)

    def _fail_idle(self) -> None:
        if self._closed or self._failed[0]:
            return
        self._send_raw(wire.FRAME_RST)
        self._mark_failed(
            errors.ERPCTIMEDOUT,
            f"stream idle for {self.options.idle_timeout_s:.1f}s",
        )

    # ---- teardown -----------------------------------------------------------
    def close_write(self) -> None:
        """Half-close: no more writes from this side; reads continue
        (HALF_CLOSE frame).  The stream fully closes once the peer
        half-closes too."""
        if self._closed or self._local_write_closed:
            return
        self._local_write_closed = True
        self._flush_feedback()
        self._send_frame(wire.FRAME_HALF_CLOSE)
        with self._flow_cond:
            self._flow_cond.notify_all()  # release writers: ECLOSE
        if self._remote_write_closed:
            self._mark_closed()

    def _on_remote_half_close(self) -> None:
        self._remote_write_closed = True
        handler = self.options.handler
        if handler is not None:
            from incubator_brpc_tpu.runtime import scheduler

            def _notify(h=handler, s=self):
                try:
                    h.on_half_close(s)
                except Exception as e:  # noqa: BLE001
                    log_error("stream on_half_close raised: %r", e)

            scheduler.spawn(_notify)
        if self._local_write_closed:
            self._mark_closed()

    def close(self):
        """StreamClose: notify the peer and tear down."""
        if self._closed:
            return
        self._flush_feedback()
        if self._sock is not None and not self._sock.failed:
            # through the chaos chokepoint: a lost/delayed CLOSE is an
            # injectable fault (direction "close"); RST frames are NOT
            # injectable — they ARE the failure path
            self._send_frame(wire.FRAME_CLOSE)
        self._mark_closed()

    def reset(self, code: int = errors.ECLOSE, text: str = "stream reset"):
        """Abort the stream: RST the peer and fail locally.  The shared
        socket (and every other stream/RPC on it) is untouched — this
        is how an aborted generation or an unrecoverable mid-message
        fault surfaces as an ERROR on the peer, distinguishable from a
        clean CLOSE."""
        if self._closed:
            return
        self._send_raw(wire.FRAME_RST)
        self._mark_failed(code, text)

    def _close_span(self, error_code: int = 0) -> None:
        span = self._span
        if span is None:
            return
        self._span = None
        span.annotate(
            f"frames sent={self.frames_sent} received={self.frames_received} "
            f"bytes sent={self.bytes_sent} received={self.bytes_received} "
            f"consumed={self.consumed_bytes} "
            f"writer_blocked={self.writer_blocked_ns // 1000}us"
        )
        span.end(error_code)

    def _mark_closed(self):
        if self._closed:
            return
        self._closed = True
        if self._idle_timer:
            get_timer_thread().unschedule(self._idle_timer)
            self._idle_timer = 0
        with self._flow_cond:
            self._flow_cond.notify_all()
        if self._sock is not None:
            self._sock.stream_map.pop(self.stream_id, None)
        observe.deregister(self)
        self._close_span(self._failed[0])
        handler = self.options.handler
        if handler is not None:
            # spawned, never inline: a CLOSE frame may be processed on
            # the SENDER's thread (ici inline client-port delivery), and
            # user code blocking there would wedge the sender — the
            # reference likewise runs stream callbacks on bthread
            # workers, not the IO thread (stream.cpp on_closed path)
            from incubator_brpc_tpu.runtime import scheduler

            def _notify(h=handler, s=self):
                try:
                    h.on_closed(s)
                except Exception as e:  # noqa: BLE001
                    log_error("stream on_closed raised: %r", e)

            scheduler.spawn(_notify)

    def _mark_failed(self, code: int, text: str):
        self._failed = (code, text)
        with self._flow_cond:
            self._flow_cond.notify_all()
        handler = self.options.handler
        if handler is not None:
            # spawned for the same reason as on_closed above
            from incubator_brpc_tpu.runtime import scheduler

            def _notify(h=handler, s=self):
                try:
                    h.on_failed(s, code, text)
                except Exception as e:  # noqa: BLE001
                    log_error("stream on_failed raised: %r", e)

            scheduler.spawn(_notify)
        self._mark_closed()

    def on_socket_failed(self, code: int, text: str):
        """Called by Socket.set_failed for attached streams."""
        self._mark_failed(code, text)

    @property
    def closed(self) -> bool:
        return self._closed

    @property
    def failed_code(self) -> int:
        return self._failed[0]

    def unconsumed(self) -> int:
        """Writer-side view of the peer's unconsumed backlog."""
        with self._flow_cond:
            return self._unconsumed

    def describe(self) -> dict:
        """One /status row."""
        return {
            "id": self.stream_id,
            "remote_id": self.remote_stream_id,
            "server": self.is_server,
            "peer": str(getattr(self._sock, "remote", "") or ""),
            "frames_sent": self.frames_sent,
            "frames_received": self.frames_received,
            "unconsumed": self._unconsumed,
            "consumed_bytes": self.consumed_bytes,
            "writer_blocked_us": self.writer_blocked_ns // 1000,
        }
