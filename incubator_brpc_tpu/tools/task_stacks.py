"""task_stacks — dump every runtime thread/task stack.

Analog of the reference's tools/gdb_bthread_stack.py (a gdb plugin that
walks bthread stacks of a live process): in this runtime tasks run on
worker threads, so ``sys._current_frames`` reaches every live stack
without gdb. Usable three ways:

  * library: ``dump_stacks() -> str``
  * builtin service: GET /bthreads on any server
  * CLI: ``python -m incubator_brpc_tpu.tools.task_stacks <pid>``
    (sends SIGUSR1 to a cooperating process — servers install the
    handler at start — which writes the dump to its stderr).
"""

from __future__ import annotations

import signal
import sys
import threading
import traceback


def dump_stacks() -> str:
    """All thread stacks, runtime workers annotated."""
    frames = sys._current_frames()
    by_id = {t.ident: t for t in threading.enumerate()}
    out = []
    for tid, frame in sorted(frames.items()):
        t = by_id.get(tid)
        name = t.name if t else "?"
        daemon = " daemon" if (t and t.daemon) else ""
        kind = ""
        if name.startswith("tpubrpc-worker"):
            kind = " [runtime worker]"
        elif name.startswith("tpubrpc"):
            kind = " [runtime]"
        out.append(f"--- thread {tid} {name}{daemon}{kind}")
        out.extend(
            line.rstrip() for line in traceback.format_stack(frame)
        )
    return "\n".join(out)


def install_sigusr1_handler():
    """Make SIGUSR1 print the dump to stderr (live-process debugging,
    the gdb-plugin use case without gdb)."""

    def _handler(signum, frame):
        sys.stderr.write(dump_stacks() + "\n")
        sys.stderr.flush()

    try:
        signal.signal(signal.SIGUSR1, _handler)
        return True
    except (ValueError, OSError):  # not the main thread / unsupported
        return False


def main(argv=None):
    import os

    args = argv if argv is not None else sys.argv[1:]
    if not args:
        print(dump_stacks())
        return
    pid = int(args[0])
    os.kill(pid, signal.SIGUSR1)
    print(f"sent SIGUSR1 to {pid}; dump goes to its stderr")


if __name__ == "__main__":
    main()
