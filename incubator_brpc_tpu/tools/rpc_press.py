"""rpc_press — protocol-generic load generator.

Analog of reference tools/rpc_press (rpc_press.cpp:98): drives a
service from a JSON request at a target qps with live qps/latency
reporting from the channel's LatencyRecorder (the reference's
InfoThread).
"""

from __future__ import annotations

import argparse
import importlib
import json
import sys
import threading
import time


def resolve_message(spec: str):
    """"module:ClassName" → message class."""
    mod, _, cls = spec.partition(":")
    return getattr(importlib.import_module(mod), cls)


def load_chaos_plan(spec: str):
    """``--chaos-plan`` value → FaultPlan.  Accepts inline JSON or
    ``@path/to/plan.json`` (see docs/chaos.md for the schema)."""
    from incubator_brpc_tpu.chaos.plan import FaultPlan

    if spec.startswith("@"):
        with open(spec[1:], "r", encoding="utf-8") as f:
            spec = f.read()
    return FaultPlan.from_json(spec)


def _arm_chaos(chaos_plan: str, report):
    """Load + arm a ``--chaos-plan`` value.  Returns the armed plan,
    or None after reporting the error (callers bail out)."""
    from incubator_brpc_tpu.chaos import injector as chaos_injector

    try:
        plan = load_chaos_plan(chaos_plan)
        chaos_injector.arm(plan)
    except (OSError, TypeError, ValueError, KeyError, RuntimeError) as e:
        report(f"bad chaos plan: {e}")
        return None
    report(f"chaos plan armed: sites={plan.sites()} seed={plan.seed}")
    return plan


def _finish_chaos():
    """Collect the armed plan's per-site hits and disarm."""
    from incubator_brpc_tpu.chaos import injector as chaos_injector

    hits = chaos_injector.site_hits()
    chaos_injector.disarm()
    return hits


def press(
    server: str,
    service: str,
    method: str,
    request_json: str = "{}",
    qps: int = 100,
    duration_s: float = 5.0,
    threads: int = 4,
    request_cls=None,
    response_cls=None,
    lb: str = None,
    report=print,
    chaos_plan: str = None,
):
    from incubator_brpc_tpu.client.channel import Channel, ChannelOptions
    from incubator_brpc_tpu.client.controller import Controller
    from incubator_brpc_tpu.serialization.json2pb import json_to_proto
    from incubator_brpc_tpu.server.service import MethodSpec

    if request_cls is None or response_cls is None:
        from incubator_brpc_tpu.protos.echo_pb2 import EchoRequest, EchoResponse

        request_cls = request_cls or EchoRequest
        response_cls = response_cls or EchoResponse
    spec = MethodSpec(service, method, request_cls, response_cls)
    ch = Channel(ChannelOptions(timeout_ms=5000))
    rc = ch.init(server, lb)
    if rc != 0:
        report(f"channel init failed: {rc}")
        return None
    request = request_cls()
    ok, err = json_to_proto(request_json, request)
    if not ok:
        report(f"bad request json: {err}")
        return None

    plan = None
    if chaos_plan:
        plan = _arm_chaos(chaos_plan, report)
        if plan is None:
            return None

    stop = time.monotonic() + duration_s
    sent = [0]
    errors_n = [0]
    lock = threading.Lock()
    interval = threads / max(qps, 1)

    def worker():
        nxt = time.monotonic()
        while time.monotonic() < stop:
            nxt += interval
            c = Controller()
            resp = response_cls()
            ch.call_method(spec, c, request, resp, None)
            with lock:
                sent[0] += 1
                if c.failed():
                    errors_n[0] += 1
            delay = nxt - time.monotonic()
            if delay > 0:
                time.sleep(delay)

    ts = [threading.Thread(target=worker, daemon=True) for _ in range(threads)]
    t0 = time.monotonic()
    try:
        for t in ts:
            t.start()

        # live report (InfoThread analog)
        while time.monotonic() < stop:
            left = stop - time.monotonic()
            # `left` may have gone <= 0 since the loop check (more
            # likely under an armed chaos plan): sleep() would raise
            time.sleep(min(1.0, left) if left > 0 else 0.05)
            rec = ch.latency_recorder()
            report(
                f"sent={sent[0]} errors={errors_n[0]} qps={rec.qps():.0f} "
                f"avg={rec.latency():.0f}us p99={rec.latency_percentile(0.99):.0f}us"
            )
        for t in ts:
            t.join(5)
    finally:
        chaos_hits = _finish_chaos() if plan is not None else None
    wall = time.monotonic() - t0
    rec = ch.latency_recorder()
    result = {
        "sent": sent[0],
        "errors": errors_n[0],
        "wall_s": round(wall, 2),
        "achieved_qps": round(sent[0] / wall, 1),
        "avg_us": round(rec.latency()),
        "p99_us": round(rec.latency_percentile(0.99)),
    }
    if chaos_hits is not None:
        result["chaos_hits"] = chaos_hits
    report(json.dumps(result))
    return result


def press_native(
    server: str,
    service: str = "EchoService",
    method: str = "Echo",
    payload_len: int = 4096,
    concurrency: int = 8,
    duration_s: float = 5.0,
    depth: int = 1,
    conns: int = 1,
    report=print,
    chaos_plan: str = None,
):
    """Max-throughput mode on the C++ engine (nc_bench_echo): both ends
    native, zero Python per RPC — the reference's rpc_press is likewise
    a native tool. No qps pacing: measures capacity.

    ``chaos_plan`` arms a FaultPlan in THIS process for the run: its
    ``native.*`` sites hit a co-located engine server; a remote server
    is armed via its ``/chaos`` builtin instead."""
    from incubator_brpc_tpu import native

    if not native.available():
        report(f"native engine unavailable: {native.unavailable_reason()}")
        return None
    plan = None
    if chaos_plan:
        plan = _arm_chaos(chaos_plan, report)
        if plan is None:
            return None
    host, _, port = server.partition(":")
    try:
        result = native.bench_echo(
            host, int(port), payload_len, concurrency,
            int(duration_s * 1000), depth, conns, service, method,
        )
    finally:
        chaos_hits = _finish_chaos() if plan is not None else None
    if chaos_hits is not None:
        result["chaos_hits"] = chaos_hits
    report(json.dumps(result))
    return result


def main(argv=None):
    ap = argparse.ArgumentParser(description="rpc_press load generator")
    ap.add_argument("--server", required=True, help="ip:port | ici://... | naming url")
    ap.add_argument("--service", default="EchoService")
    ap.add_argument("--method", default="Echo")
    ap.add_argument("--request", default='{"message": "press"}', help="request JSON")
    ap.add_argument("--qps", type=int, default=100)
    ap.add_argument("--duration", type=float, default=5.0)
    ap.add_argument("--threads", type=int, default=4)
    ap.add_argument("--lb", default=None)
    ap.add_argument("--proto", default=None, help="module:RequestClass,module:ResponseClass")
    ap.add_argument(
        "--native", action="store_true",
        help="max-throughput mode on the C++ engine (no qps pacing)",
    )
    ap.add_argument("--payload", type=int, default=4096,
                    help="--native mode: echo message size in bytes")
    ap.add_argument("--depth", type=int, default=1,
                    help="--native mode: pipelined in-flight RPCs per worker")
    ap.add_argument(
        "--chaos-plan", default=None, metavar="JSON|@FILE",
        help="run the load under a chaos FaultPlan (inline JSON or "
        "@file; armed for the run, disarmed after — docs/chaos.md)",
    )
    args = ap.parse_args(argv)
    if args.native:
        press_native(
            args.server, args.service, args.method, args.payload,
            args.threads, args.duration, args.depth,
            chaos_plan=args.chaos_plan,
        )
        return
    req_cls = res_cls = None
    if args.proto:
        a, _, b = args.proto.partition(",")
        req_cls, res_cls = resolve_message(a), resolve_message(b)
    press(
        args.server, args.service, args.method, args.request,
        args.qps, args.duration, args.threads, req_cls, res_cls, args.lb,
        chaos_plan=args.chaos_plan,
    )


if __name__ == "__main__":
    main()
