"""Operator tools (reference tools/): rpc_press load generator,
rpc_replay for rpc_dump samples, rpc_view builtin-page proxy,
parallel_http mass fetcher. Each is runnable:
``python -m incubator_brpc_tpu.tools.rpc_press --help``."""
