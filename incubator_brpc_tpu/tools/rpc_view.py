"""rpc_view — fetch / proxy another server's builtin pages.

Analog of reference tools/rpc_view (rpc_view.cpp): the reference runs
its own brpc server whose pages PROXY a target server, so an operator
browses `http://rpc_view_host:port/...` and sees the target's
observability surface (useful when the target's port is reachable only
from the bastion running rpc_view).  Same shape here — ``serve()``
starts one of this framework's servers whose builtin paths forward to
the target — plus the one-shot ``fetch_page`` CLI mode.

    python -m incubator_brpc_tpu.tools.rpc_view --server host:port [--page status]
    python -m incubator_brpc_tpu.tools.rpc_view --server host:port --port 8888  # proxy mode
"""

from __future__ import annotations

import argparse
import socket as _pysocket
from typing import Tuple

# pages the proxy mirrors (the reference forwards the same builtin set)
PROXY_PAGES = (
    "/", "/index", "/status", "/vars", "/metrics", "/flags",
    "/connections", "/rpcz", "/health", "/version", "/list", "/threads",
    "/bthreads", "/ids", "/sockets", "/protobufs", "/dir",
    "/hotspots/cpu", "/hotspots/contention", "/hotspots/heap",
    "/hotspots/growth", "/pprof/profile", "/vlog",
    "/rpcz/export", "/cluster/export", "/cluster/metrics",
    "/cluster/latency_breakdown", "/cluster/stragglers", "/rpc_dump",
)


def fetch_page_full(
    server: str, page: str = "status", timeout: float = 3.0, retries: int = 5
) -> Tuple[int, str, bytes]:
    """GET one page → (status, content_type, body_bytes).  A raw fetch
    can race the server's accept loop right after start; connect-phase
    failures retry, a hung response does not."""
    host, _, port = server.partition(":")
    for attempt in range(retries + 1):
        try:
            conn = _pysocket.create_connection((host, int(port)), timeout=timeout)
            break
        except OSError:
            if attempt == retries:
                raise
            import time

            time.sleep(0.05 * (2**attempt))
    with conn as s:
        req = (
            f"GET /{page.lstrip('/')} HTTP/1.1\r\nHost: {server}\r\n"
            "Connection: close\r\n\r\n"
        )
        s.sendall(req.encode())
        data = b""
        while True:
            head, sep, body = data.partition(b"\r\n\r\n")
            if sep:
                clen = None
                for line in head.split(b"\r\n"):
                    if line.lower().startswith(b"content-length:"):
                        clen = int(line.split(b":")[1])
                if clen is not None and len(body) >= clen:
                    break
            chunk = s.recv(65536)
            if not chunk:
                break
            data += chunk
    head, _, body = data.partition(b"\r\n\r\n")
    status = 502
    ctype = "text/plain"
    for i, line in enumerate(head.split(b"\r\n")):
        if i == 0 and line.startswith(b"HTTP/"):
            parts = line.split()
            if len(parts) >= 2 and parts[1].isdigit():
                status = int(parts[1])
        elif line.lower().startswith(b"content-type:"):
            ctype = line.split(b":", 1)[1].strip().decode("latin-1")
    return status, ctype, body


def fetch_page(
    server: str, page: str = "status", timeout: float = 3.0, retries: int = 5
) -> str:
    """Body-only fetch (the one-shot CLI mode and test helper)."""
    return fetch_page_full(server, page, timeout, retries)[2].decode(
        "utf-8", errors="replace"
    )


def make_proxy_server(target: str, timeout: float = 5.0):
    """Build (not start) a Server whose builtin paths proxy `target`
    (reference rpc_view.cpp: a brpc server forwarding to -target)."""
    from urllib.parse import urlencode

    from incubator_brpc_tpu.server.server import Server, ServerOptions

    # has_builtin_services=False: start() must not overwrite the proxy
    # handlers with this server's OWN pages
    srv = Server(
        ServerOptions(
            server_info_name=f"rpc_view -> {target}",
            has_builtin_services=False,
        )
    )

    def proxy(server, msg):
        page = msg.path
        if msg.query:
            page += "?" + urlencode(msg.query)
        try:
            # retries=0: the retry loop exists for the just-started-
            # server race in one-shot mode; a proxy must fail fast or a
            # down target serializes every worker behind backoff sleeps
            status, ctype, body = fetch_page_full(
                target, page, timeout, retries=0
            )
        except OSError as e:
            return 502, f"rpc_view: {target} unreachable: {e}", "text/plain"
        return status, body, ctype

    # builtin registration replaces this server's own pages with the
    # proxied ones — the same inversion the reference performs
    for path in PROXY_PAGES:
        srv.add_builtin_handler(path, proxy)
    return srv


def serve(target: str, port: int = 8888, timeout: float = 5.0):
    srv = make_proxy_server(target, timeout)
    rc = srv.start(port)
    if rc != 0:
        raise RuntimeError(f"rpc_view proxy failed to start on :{port}")
    return srv


def main(argv=None):
    ap = argparse.ArgumentParser(description="rpc_view")
    ap.add_argument("--server", required=True, help="target host:port")
    ap.add_argument("--page", default=None, help="one-shot: fetch this page")
    ap.add_argument(
        "--port", type=int, default=None,
        help="proxy mode: serve the target's pages on this local port",
    )
    args = ap.parse_args(argv)
    if args.port is not None and args.page is not None:
        ap.error("--page (one-shot) and --port (proxy mode) conflict")
    if args.port is not None:
        srv = serve(args.server, args.port)
        print(f"proxying {args.server} on http://0.0.0.0:{srv.port}/ — Ctrl-C stops")
        try:
            import time

            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            srv.stop()
        return
    print(fetch_page(args.server, args.page or "status"))


if __name__ == "__main__":
    main()
