"""rpc_view — fetch/pretty-print another server's builtin pages.

Analog of reference tools/rpc_view: proxies a target server's
observability pages (/status /vars /rpcz ...) to the terminal.
"""

from __future__ import annotations

import argparse
import socket as _pysocket


def fetch_page(
    server: str, page: str = "status", timeout: float = 3.0, retries: int = 5
) -> str:
    # A raw fetch can race the server's accept loop right after start;
    # retry connect-phase failures only — a hung response is not retried.
    host, _, port = server.partition(":")
    for attempt in range(retries + 1):
        try:
            conn = _pysocket.create_connection((host, int(port)), timeout=timeout)
            break
        except OSError:
            if attempt == retries:
                raise
            import time

            time.sleep(0.05 * (2**attempt))
    with conn as s:
        req = f"GET /{page.lstrip('/')} HTTP/1.1\r\nHost: {server}\r\nConnection: close\r\n\r\n"
        s.sendall(req.encode())
        data = b""
        while True:
            head, sep, body = data.partition(b"\r\n\r\n")
            if sep:
                clen = None
                for line in head.split(b"\r\n"):
                    if line.lower().startswith(b"content-length:"):
                        clen = int(line.split(b":")[1])
                if clen is not None and len(body) >= clen:
                    break
            chunk = s.recv(65536)
            if not chunk:
                break
            data += chunk
    head, _, body = data.partition(b"\r\n\r\n")
    return body.decode("utf-8", errors="replace")


def main(argv=None):
    ap = argparse.ArgumentParser(description="rpc_view")
    ap.add_argument("--server", required=True, help="host:port")
    ap.add_argument("--page", default="status")
    args = ap.parse_args(argv)
    print(fetch_page(args.server, args.page))


if __name__ == "__main__":
    main()
