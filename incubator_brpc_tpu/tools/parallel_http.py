"""parallel_http — mass concurrent HTTP fetcher.

Analog of reference tools/parallel_http/parallel_http.cpp: fetch many
URLs concurrently on the runtime's worker pool with a bounded
in-flight window, live 1 Hz progress (done/total, qps), per-fetch
latency percentiles, status/error accounting, and optional body output
to a directory (the reference's -output).
"""

from __future__ import annotations

import argparse
import os
import threading
import time
from typing import Dict, Optional


class FetchStats:
    """Aggregate of one fetch_all run."""

    def __init__(self):
        self.ok = 0
        self.failed = 0
        self.bytes = 0
        self.status_counts: Dict[int, int] = {}
        self.latencies_us: list = []
        self.wall_s = 0.0

    def percentile(self, ratio: float) -> int:
        if not self.latencies_us:
            return -1
        xs = sorted(self.latencies_us)
        return xs[min(len(xs) - 1, int(len(xs) * ratio))]

    def summary(self) -> str:
        total = self.ok + self.failed
        qps = total / self.wall_s if self.wall_s > 0 else 0.0
        return (
            f"fetched {self.ok}/{total} ok ({self.bytes} bytes) in "
            f"{self.wall_s:.2f}s ({qps:.1f} fetch/s)  latency_us "
            f"p50={self.percentile(0.5)} p90={self.percentile(0.9)} "
            f"p99={self.percentile(0.99)}  statuses={dict(sorted(self.status_counts.items()))}"
        )


def fetch_all(
    urls,
    concurrency: int = 16,
    timeout: float = 5.0,
    output_dir: Optional[str] = None,
    report=print,
    progress_interval_s: float = 1.0,
):
    """Fetch every `url` ("host:port/path") with at most `concurrency`
    in flight. Returns (results, stats) where results[url] = (ok, body
    or error-repr)."""
    from incubator_brpc_tpu.runtime.scheduler import get_task_control
    from incubator_brpc_tpu.runtime.sync import CountdownEvent
    from incubator_brpc_tpu.tools.rpc_view import fetch_page_full

    ctrl = get_task_control()
    results = {}
    stats = FetchStats()
    lock = threading.Lock()
    done = CountdownEvent(len(urls))
    window = threading.Semaphore(max(1, concurrency))  # bounded in-flight
    if output_dir:
        os.makedirs(output_dir, exist_ok=True)

    def one(idx, url):
        t0 = time.perf_counter_ns()
        try:
            server, _, page = url.partition("/")
            # retries=0: the connect-retry loop exists for the just-
            # started-server race in tests; a mass fetcher must not
            # serialize its window behind backoff sleeps to dead hosts
            # (and the latency percentiles must measure the fetch)
            status, ctype, body = fetch_page_full(
                server, page or "/", timeout, retries=0
            )
            us = (time.perf_counter_ns() - t0) // 1000
            # body write BEFORE the success accounting: a failed write
            # must count the url as failed, not as both
            if output_dir:
                with open(os.path.join(output_dir, f"{idx:06d}.body"), "wb") as f:
                    f.write(body)
            text = body.decode("utf-8", errors="replace")
            with lock:
                results[url] = (True, text)
                stats.ok += 1
                stats.bytes += len(body)
                stats.latencies_us.append(us)
                stats.status_counts[status] = (
                    stats.status_counts.get(status, 0) + 1
                )
        except Exception as e:  # noqa: BLE001 — per-url failure isolation
            with lock:
                results[url] = (False, repr(e))
                stats.failed += 1
        finally:
            window.release()
            done.signal()

    t0 = time.monotonic()
    stop_progress = threading.Event()

    def progress():
        while not stop_progress.wait(progress_interval_s):
            with lock:
                n = stats.ok + stats.failed
            el = time.monotonic() - t0
            report(f"... {n}/{len(urls)} ({n / el:.1f}/s)")

    ticker = threading.Thread(target=progress, daemon=True)
    ticker.start()
    for idx, url in enumerate(urls):
        window.acquire()  # backpressure: the submit loop IS the window
        ctrl.spawn(one, idx, url)
    completed = done.wait(timeout * max(1, len(urls)))
    stop_progress.set()
    stats.wall_s = time.monotonic() - t0
    if not completed:
        # stragglers still mutate the live objects: hand back a
        # DETACHED snapshot (copied under the lock) with the pending
        # fetches counted as failed, so the caller's view is stable
        # and ok+failed == len(urls)
        import copy

        with lock:
            snap = copy.deepcopy(stats)  # plain ints/list/dict only
            snap_results = dict(results)
        pending = len(urls) - (snap.ok + snap.failed)
        snap.failed += pending
        report(
            f"TIMED OUT with {pending} fetches still in flight "
            "(counted as failed)"
        )
        report(snap.summary())
        return snap_results, snap
    report(stats.summary())
    return results, stats


def main(argv=None):
    ap = argparse.ArgumentParser(description="parallel_http")
    ap.add_argument("urls", nargs="*", help="host:port/path entries")
    ap.add_argument("--file", help="file with one url per line")
    ap.add_argument("--concurrency", type=int, default=16)
    ap.add_argument("--timeout", type=float, default=5.0)
    ap.add_argument("--output", help="directory to save response bodies")
    args = ap.parse_args(argv)
    urls = list(args.urls)
    if args.file:
        urls += [l.strip() for l in open(args.file) if l.strip()]
    if not urls:
        ap.error("no urls")
    fetch_all(
        urls, args.concurrency, timeout=args.timeout, output_dir=args.output
    )


if __name__ == "__main__":
    main()
