"""parallel_http — mass concurrent HTTP fetcher.

Analog of reference tools/parallel_http/parallel_http.cpp: fetch many
URLs concurrently on the runtime's worker pool and report progress.
"""

from __future__ import annotations

import argparse
import sys
import time


def fetch_all(urls, concurrency: int = 16, timeout: float = 5.0, report=print):
    from incubator_brpc_tpu.runtime.scheduler import get_task_control
    from incubator_brpc_tpu.runtime.sync import CountdownEvent
    from incubator_brpc_tpu.tools.rpc_view import fetch_page

    ctrl = get_task_control()
    results = {}
    done = CountdownEvent(len(urls))

    def one(url):
        try:
            server, _, page = url.partition("/")
            results[url] = (True, fetch_page(server, page or "/", timeout))
        except Exception as e:  # noqa: BLE001
            results[url] = (False, repr(e))
        finally:
            done.signal()

    t0 = time.monotonic()
    for url in urls:
        ctrl.spawn(one, url)
    done.wait(timeout * len(urls))
    ok = sum(1 for s, _ in results.values() if s)
    report(f"fetched {ok}/{len(urls)} in {time.monotonic() - t0:.2f}s")
    return results


def main(argv=None):
    ap = argparse.ArgumentParser(description="parallel_http")
    ap.add_argument("urls", nargs="*", help="host:port/path entries")
    ap.add_argument("--file", help="file with one url per line")
    ap.add_argument("--concurrency", type=int, default=16)
    args = ap.parse_args(argv)
    urls = list(args.urls)
    if args.file:
        urls += [l.strip() for l in open(args.file) if l.strip()]
    if not urls:
        ap.error("no urls")
    fetch_all(urls, args.concurrency)


if __name__ == "__main__":
    main()
