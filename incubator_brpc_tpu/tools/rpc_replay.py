"""rpc_replay — re-issue rpc_dump samples at controlled qps.

Analog of reference tools/rpc_replay/rpc_replay.cpp: reads sample files
written by the server's rpc_dump context and replays them against a
target server.
"""

from __future__ import annotations

import argparse
import time


def replay(server: str, dump_dir: str, qps: int = 100, times: int = 1, report=print):
    from incubator_brpc_tpu.client.channel import Channel, ChannelOptions
    from incubator_brpc_tpu.client.controller import Controller
    from incubator_brpc_tpu.observability.rpc_dump import list_dump_files, read_samples
    from incubator_brpc_tpu.protos import rpc_meta_pb2 as pb
    from incubator_brpc_tpu.protocols.tpu_std import _frame
    from incubator_brpc_tpu.runtime.call_id import default_pool
    from incubator_brpc_tpu.transport.socket import Socket
    from incubator_brpc_tpu.utils.iobuf import IOBuf

    files = list_dump_files(dump_dir)
    if not files:
        report(f"no dump files under {dump_dir}")
        return None
    ch = Channel(ChannelOptions(timeout_ms=5000))
    if ch.init(server) != 0:
        report("channel init failed")
        return None
    sent = ok = 0
    interval = 1.0 / max(qps, 1)
    t0 = time.monotonic()
    for _ in range(times):
        for path in files:
            for meta, body in read_samples(path):
                # raw replay: rebuild the tpu_std frame with a fresh cid
                # and push it through the channel's transport
                from incubator_brpc_tpu.client.controller import Controller
                from incubator_brpc_tpu.server.service import MethodSpec

                c = Controller()
                # look up message classes is impossible from raw bytes;
                # send as raw frame on the shared socket
                err, sid, _node = ch._select_socket(c)
                if err:
                    continue
                sock = Socket.address(sid)
                if sock is None:
                    continue
                m = pb.RpcMeta()
                m.request.service_name = meta["service"]
                m.request.method_name = meta["method"]
                m.request.log_id = meta.get("log_id", 0)
                m.correlation_id = 0  # fire-and-forget replay
                sock.write(_frame(m, IOBuf(body)))
                sent += 1
                ok += 1
                time.sleep(interval)
    wall = time.monotonic() - t0
    report(f"replayed {sent} samples in {wall:.1f}s ({sent / max(wall, 1e-9):.0f} qps)")
    return sent


def main(argv=None):
    ap = argparse.ArgumentParser(description="rpc_replay")
    ap.add_argument("--server", required=True)
    ap.add_argument("--dir", required=True, help="rpc_dump directory")
    ap.add_argument("--qps", type=int, default=100)
    ap.add_argument("--times", type=int, default=1)
    args = ap.parse_args(argv)
    replay(args.server, args.dir, args.qps, args.times)


if __name__ == "__main__":
    main()
