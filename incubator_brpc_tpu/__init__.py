"""incubator-brpc_tpu — a TPU-native RPC framework.

A ground-up rebuild of the capabilities of Apache bRPC (incubating,
reference: hongliuliao/incubator-brpc) designed TPU-first:

- ``utils``     — base library (butil analog): IOBuf zero-copy segmented
                  buffers whose blocks may be HBM-resident ``jax.Array``s,
                  resource pools with versioned ids, EndPoint including
                  ``ici://slice/chip`` coordinates, read-mostly containers.
- ``runtime``   — M:N-style task runtime (bthread analog): work-stealing
                  worker groups, butex wait/wake, versioned correlation ids
                  (CallId), execution queues, timer thread.
- ``metrics``   — lock-free-style metrics (bvar analog): Adder/Maxer/Miner,
                  Window/PerSecond, LatencyRecorder with log-bucketed
                  percentiles, PassiveStatus, MultiDimension, Collector.
- ``transport`` — Socket / EventDispatcher / InputMessenger / Acceptor /
                  SocketMap; wait-free-style write path with KeepWrite.
- ``protocols`` — pluggable Protocol vtable; tpu_std (baidu_std analog),
                  streaming frames, HTTP/1.x, redis, memcache.
- ``client``    — Channel, Controller, load balancers, naming services,
                  retry/backup-request, circuit breaker, health check,
                  combo channels (Parallel/Selective/Partition).
- ``server``    — Server, method status, concurrency limiters, builtin
                  observability services.
- ``parallel``  — the TPU data plane: ICI endpoints over a
                  ``jax.sharding.Mesh``, fan-out lowered to XLA collectives
                  (psum / all_gather / ppermute / all_to_all), ring
                  streaming for >HBM payloads.
- ``ops``       — device-side ops (Pallas/jnp): framing, checksum, merge.
- ``models``    — example service families: echo, streaming echo,
                  parameter server.

The public API re-exports the common entry points, mirroring how brpc's
``#include <brpc/server.h>`` / ``<brpc/channel.h>`` surface works.
"""

__version__ = "0.1.0"

from incubator_brpc_tpu.utils.iobuf import IOBuf  # noqa: F401
from incubator_brpc_tpu.utils.endpoint import EndPoint  # noqa: F401


def _lazy(name):
    import importlib

    return importlib.import_module(name)


def __getattr__(name):
    # Lazy imports keep `import incubator_brpc_tpu` light (no jax import).
    mapping = {
        "Server": ("incubator_brpc_tpu.server.server", "Server"),
        "ServerOptions": ("incubator_brpc_tpu.server.server", "ServerOptions"),
        "Channel": ("incubator_brpc_tpu.client.channel", "Channel"),
        "ChannelOptions": ("incubator_brpc_tpu.client.channel", "ChannelOptions"),
        "Controller": ("incubator_brpc_tpu.client.controller", "Controller"),
        "Authenticator": ("incubator_brpc_tpu.client.auth", "Authenticator"),
        "AuthContext": ("incubator_brpc_tpu.client.auth", "AuthContext"),
        "ParallelChannel": ("incubator_brpc_tpu.client.combo", "ParallelChannel"),
        "SelectiveChannel": ("incubator_brpc_tpu.client.combo", "SelectiveChannel"),
        "PartitionChannel": ("incubator_brpc_tpu.client.combo", "PartitionChannel"),
    }
    if name in mapping:
        mod, attr = mapping[name]
        return getattr(_lazy(mod), attr)
    raise AttributeError(name)
