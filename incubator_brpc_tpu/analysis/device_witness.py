"""Runtime transfer-guard + retrace witness for the device plane.

`BRPC_TRANSFER_WITNESS=1` (``make witness-device``) runs tier-1 with
this lane armed.  Two mechanisms back it:

1. **Transfer guard.**  ``enable()`` sets jax's global
   ``jax_transfer_guard_device_to_host`` to ``"disallow"`` — on real
   accelerators any implicit device→host copy raises inside XLA.  On
   the CPU backend tier-1 runs on, device→host reads are zero-copy and
   XLA's guard never fires, so the lane adds its own teeth: while
   enabled, ``numpy.asarray``/``numpy.array``/``numpy.ascontiguousarray``
   are wrapped, and a call whose *call site* is package code, with a
   jax array argument, outside any ``allowed_transfer`` scope, records
   a violation and raises :class:`TransferWitnessError`.  Call-site
   scoping (not thread scoping) keeps test assertions free to pull
   results while every package path stays guarded.

2. **Retrace witness.**  ``FusedKernel`` reports each retrace via
   :func:`note_trace` with a shape *family* (argument shapes/dtypes
   with the batch arg's leading dim wildcarded).  A family retracing
   more times than the kernel's padding-bucket count contradicts the
   bounded-retrace invariant and fails the lane.

Justified transfers wrap the pull in ``allowed_transfer(key)``; the key
must exist in the checked-in ``device_transfers.json`` (the same file
the static transfer-manifest rule checks).  An unknown key raises —
the manifest is the single source of truth in both lanes.
"""

from __future__ import annotations

import json
import os
import sys
import threading
from typing import Dict, List, Optional

_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_ANALYSIS_DIR = os.path.dirname(os.path.abspath(__file__))

_NP_FUNCS = ("asarray", "array", "ascontiguousarray")


class TransferWitnessError(RuntimeError):
    """An unmanifested device→host transfer on a guarded call site."""


_state_lock = threading.Lock()
_enabled = False
_scope_roots: List[str] = []  # call-site roots under guard
_manifest_keys: set = set()
_orig_np: Dict[str, object] = {}
_prev_guard: Optional[str] = None

_violations: List[dict] = []
_scope_uses: Dict[str, int] = {}
# label -> {family(str): {"count": int, "bound": int}}
_kernels: Dict[str, Dict[str, dict]] = {}

_tls = threading.local()


def enabled() -> bool:
    return _enabled


def reset() -> None:
    with _state_lock:
        _violations.clear()
        _scope_uses.clear()
        _kernels.clear()


# ---------------------------------------------------------------------------
# the numpy-level d2h guard
# ---------------------------------------------------------------------------


def _is_device_value(a) -> bool:
    mod = type(a).__module__
    return mod.startswith("jaxlib") or mod.startswith("jax.")


def _guarded_callsite() -> Optional[str]:
    """Return "relpath:line" when the frame that called the wrapped
    numpy function lives under a guarded root (package code), else
    None.  The witness's own plumbing (analysis/) is never guarded."""
    f = sys._getframe(3)  # _guarded_callsite <- wrapper <- caller
    fn = f.f_code.co_filename
    for root in _scope_roots:
        if fn.startswith(root + os.sep) or fn == root:
            if fn.startswith(_ANALYSIS_DIR + os.sep):
                return None
            return f"{os.path.relpath(fn, root)}:{f.f_lineno}"
    return None


def _check_transfer(a) -> None:
    if not _enabled or not _is_device_value(a):
        return
    if getattr(_tls, "allow_depth", 0) > 0:
        return
    site = _guarded_callsite()
    if site is None:
        return
    v = {
        "kind": "transfer",
        "site": site,
        "thread": threading.current_thread().name,
        "type": type(a).__name__,
    }
    with _state_lock:
        _violations.append(v)
    raise TransferWitnessError(
        f"unmanifested device→host transfer at {site}: wrap the pull in "
        f"allowed_transfer(<key>) and justify the key in "
        f"device_transfers.json, or keep the value device-resident"
    )


def _make_wrapper(orig):
    def _witnessed(a, *args, **kwargs):
        _check_transfer(a)
        return orig(a, *args, **kwargs)

    _witnessed.__wrapped__ = orig
    return _witnessed


# ---------------------------------------------------------------------------
# allow scopes
# ---------------------------------------------------------------------------


class _AllowScope:
    __slots__ = ("key", "_jax_cm")

    def __init__(self, key: str):
        self.key = key
        self._jax_cm = None

    def __enter__(self):
        if not _enabled:
            return self
        if self.key not in _manifest_keys:
            v = {"kind": "unknown-scope-key", "key": self.key}
            with _state_lock:
                _violations.append(v)
            raise TransferWitnessError(
                f"allowed_transfer({self.key!r}): key is not in "
                f"device_transfers.json — add a manifest entry with a why"
            )
        with _state_lock:
            _scope_uses[self.key] = _scope_uses.get(self.key, 0) + 1
        _tls.allow_depth = getattr(_tls, "allow_depth", 0) + 1
        try:
            import jax

            self._jax_cm = jax.transfer_guard_device_to_host("allow")
            self._jax_cm.__enter__()
        except Exception:
            self._jax_cm = None
        return self

    def __exit__(self, *exc):
        if not _enabled:
            return False
        _tls.allow_depth = getattr(_tls, "allow_depth", 1) - 1
        if self._jax_cm is not None:
            self._jax_cm.__exit__(*exc)
            self._jax_cm = None
        return False


def allowed_transfer(key: str) -> _AllowScope:
    """Justification scope for a manifested device→host transfer.

    Disarmed (the default, witness off) this is a no-op context
    manager with near-zero cost; armed, it validates `key` against the
    manifest, counts the use, and opens a thread-local allow window
    for both the numpy-level guard and jax's transfer guard."""
    return _AllowScope(key)


# ---------------------------------------------------------------------------
# retrace witness
# ---------------------------------------------------------------------------


def note_trace(label: str, family, count: int, bound: int) -> None:
    """Called by FusedKernel on every retrace: `count` traces have now
    occurred for `family` on the kernel `label`, whose padding policy
    bounds retraces to `bound` per family."""
    if not _enabled:
        return
    fam = repr(family)
    with _state_lock:
        fams = _kernels.setdefault(label, {})
        rec = fams.setdefault(fam, {"count": 0, "bound": bound})
        rec["count"] = max(rec["count"], count)
        rec["bound"] = bound


def retrace_contradictions() -> List[dict]:
    out = []
    with _state_lock:
        for label, fams in _kernels.items():
            for fam, rec in fams.items():
                if rec["count"] > rec["bound"]:
                    out.append(
                        {
                            "kind": "retrace",
                            "kernel": label,
                            "family": fam,
                            "count": rec["count"],
                            "bound": rec["bound"],
                        }
                    )
    return out


# ---------------------------------------------------------------------------
# lifecycle
# ---------------------------------------------------------------------------


def enable(extra_scopes=None, manifest_path: Optional[str] = None) -> None:
    """Arm the lane.  Must run before package hot paths execute (the
    conftest enables it before any test imports run device code).

    extra_scopes: additional call-site roots to guard (tests use a
    tmp dir to seed synthetic violations)."""
    global _enabled, _prev_guard
    with _state_lock:
        if _enabled:
            if extra_scopes:
                for p in extra_scopes:
                    p = os.path.abspath(p)
                    if p not in _scope_roots:
                        _scope_roots.append(p)
            return
        from incubator_brpc_tpu.analysis.devicegraph import (
            MANIFEST_PATH,
            load_device_manifest,
        )

        manifest = load_device_manifest(manifest_path or MANIFEST_PATH)
        _manifest_keys.clear()
        _manifest_keys.update(manifest.keys())
        _scope_roots.clear()
        _scope_roots.append(_PKG_ROOT)
        for p in extra_scopes or ():
            _scope_roots.append(os.path.abspath(p))

        import numpy as np

        _orig_np.clear()
        for name in _NP_FUNCS:
            orig = getattr(np, name)
            _orig_np[name] = orig
            setattr(np, name, _make_wrapper(orig))

        # real teeth on accelerators; inert on CPU where d2h is
        # zero-copy (the numpy wrappers above carry the lane there)
        try:
            import jax

            _prev_guard = jax.config.jax_transfer_guard_device_to_host
            jax.config.update("jax_transfer_guard_device_to_host", "disallow")
        except Exception:
            _prev_guard = None
        _enabled = True


def disable() -> None:
    global _enabled, _prev_guard
    with _state_lock:
        if not _enabled:
            return
        import numpy as np

        for name, orig in _orig_np.items():
            setattr(np, name, orig)
        _orig_np.clear()
        if _prev_guard is not None:
            try:
                import jax

                jax.config.update(
                    "jax_transfer_guard_device_to_host", _prev_guard
                )
            except Exception:
                pass
            _prev_guard = None
        _enabled = False


def cross_check() -> dict:
    """Session-end summary: recorded violations (including ones raised
    into `except` blocks that swallowed them), per-key scope uses, and
    retrace contradictions."""
    retrace = retrace_contradictions()
    with _state_lock:
        return {
            "enabled": _enabled,
            "violations": list(_violations),
            "scope_uses": dict(_scope_uses),
            "kernels": {k: dict(v) for k, v in _kernels.items()},
            "retrace_contradictions": retrace,
        }


def write_report(path: str) -> dict:
    result = cross_check()
    with open(path, "w", encoding="utf-8") as f:
        json.dump(result, f, indent=2, default=repr)
    return result
