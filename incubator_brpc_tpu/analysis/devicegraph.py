"""AST census of every device-interaction site + the device-plane rules.

The lock toolchain (inventory/lockgraph) cannot see the part of the
codebase the paper's north star actually lives in: the device plane.  A
single stray ``np.asarray``/``.item()``/``float(x.sum())`` in the
dispatcher/batcher/stream path silently reintroduces a device→host
round trip, and a raw ``jax.jit`` outside the padding-bucket policy
reintroduces unbounded retraces.  This module is the static half of the
same census → justified-manifest → runtime-witness pattern PR 7 built
for locks.

Census kinds (``DeviceSite.kind``):

- ``jit``            ``jax.jit(...)`` call / decorator (incl. through
                     ``functools.partial``)
- ``fused-kernel``   ``FusedKernel``/``ShardedFusedKernel`` construction
- ``device-put``     ``jax.device_put`` / ``device_get`` (explicit,
                     guard-exempt transfers)
- ``collective``     ``psum``/``all_gather``/``all_to_all``/``ppermute``
                     / ``shard_map`` lowering sites
- ``pallas-call``    ``pl.pallas_call`` kernel construction (bare,
                     aliased, and ``functools.partial`` spellings) —
                     the hand-rolled device dispatch the Pallas DMA
                     data plane is built from; falls under the same
                     raw-jit-retrace / dispatch-under-lock rules as
                     ``jit``
- ``donation``       a jit carrying ``donate_argnums`` (the donated
                     buffer is consumed — reading it afterwards is UB)
- ``slot-acquire`` / ``slot-release``
                     StagingRing-shaped pool traffic (receiver name
                     contains ring/staging/freelist)
- ``host-sync``      a construct that forces device→host sync:
                     ``np.asarray``/``np.array``/``np.ascontiguousarray``
                     (``sync="asarray"``), ``.block_until_ready()``
                     (``"block"``), ``.item()`` (``"item"``),
                     ``float()/int()/bool()`` over a reduction like
                     ``x.sum()`` (``"coerce"``), ``jax.debug.*``
                     (``"debug"``)
- ``allow-scope``    a ``with allowed_transfer("key"):`` justification
                     scope (analysis/device_witness.py)

Rules emitted (all as Findings, allowlistable by stable key):

- ``host-sync-on-hot-path``    a host-sync construct inside a
  dispatcher/batcher/streaming/parallel/server module, outside any
  ``allowed_transfer`` scope.  Fix it (keep the value device-resident)
  or justify it in the transfer manifest and wrap the site.
- ``transfer-manifest``        an ``allowed_transfer`` scope names a key
  absent from the checked-in ``device_transfers.json``.
- ``transfer-manifest-stale``  a manifest entry matched by no scope in
  the tree — the justified transfer is gone, remove the entry.
  (Entries with ``"external": true`` — scopes living outside the
  package scan, e.g. the bench harness — are exempt.)
- ``raw-jit-retrace``          a ``jax.jit`` call in a request-path
  module outside the fused-kernel infrastructure: nothing bounds its
  trace cache, so route it through FusedKernel/padding buckets or
  allowlist it with a why.
- ``slot-lifecycle``           a staging-slot ``acquire`` whose result
  is never released, donated, or returned in the same function.
- ``read-after-donate``        a buffer passed at a donated position is
  read again after the donating call.
- ``device-dispatch-under-lock`` (``run_dispatch_under_lock``) a fused
  kernel dispatch / device transfer runs while a package lock is held —
  the device-plane extension of PR 7's blocking-under-lock.
"""

from __future__ import annotations

import ast
import json
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from incubator_brpc_tpu.analysis.findings import Finding

# directories never scanned (generated code, caches, and this toolchain
# itself — the witness plumbing would self-report)
SKIP_DIRS = {"__pycache__", "protos", "analysis"}

MANIFEST_PATH = os.path.join(os.path.dirname(__file__), "device_transfers.json")

# request-path module prefixes: a host sync here stalls a dispatcher,
# batcher, decode step, transport hop, or recorder — the paths the north
# star says stay HBM-resident end to end
HOT_PREFIXES = (
    "batching/",
    "streaming/",
    "runtime/",
    "server/",
    "transport/",
    "parallel/",
    "observability/",
    "models/",
    "cache/",
)

# fused-kernel infrastructure: jit here IS the bounded-retrace mechanism
# (FusedKernel's bucket-counted jit, the shard_map lowering, the
# per-mesh collective factories)
JIT_EXEMPT_MODULES = {
    "batching/fused.py",
    "batching/sharded.py",
    "parallel/collectives.py",
}

# leaf callables that dispatch device work (for the under-lock rule);
# any leaf containing "kernel" (self._kernel(...), kernel(w, X)) counts
DEVICE_DISPATCH_LEAFS = {
    "fused_stack_rows",
    "device_put",
    "psum",
    "all_gather",
    "block_until_ready",
    "pallas_call",
}

_COLLECTIVE_LEAFS = {
    "psum", "all_gather", "all_to_all", "ppermute", "psum_scatter",
    "shard_map", "shard_map_relaxed",
}

_REDUCER_ATTRS = {"sum", "mean", "max", "min", "prod", "dot"}

_RING_RECEIVER_HINTS = ("ring", "staging", "freelist")


@dataclass
class DeviceSite:
    kind: str
    module: str  # path relative to the scan root
    func: str  # "Cls.meth", "name", or "<module>"
    line: int
    detail: str = ""  # callee text / scope key / receiver
    sync: str = ""  # host-sync flavor (see module docstring)
    scope_key: str = ""  # enclosing allowed_transfer key, if any


@dataclass
class DeviceCensus:
    root: str
    sites: List[DeviceSite] = field(default_factory=list)
    # donating callee name -> donated positional-arg indices
    donating: Dict[str, Tuple[int, ...]] = field(default_factory=dict)

    def by_kind(self, kind: str) -> List[DeviceSite]:
        return [s for s in self.sites if s.kind == kind]


# ---------------------------------------------------------------------------
# transfer manifest (device_transfers.json)
# ---------------------------------------------------------------------------


@dataclass
class DeviceManifest:
    """entries: [{"key", "site", "why"[, "external"]}] — every justified
    device↔host transfer scope, each with a one-line why.  Blank whys
    are refused at load, exactly like the allowlist."""

    entries: List[dict] = field(default_factory=list)
    path: str = MANIFEST_PATH

    def __post_init__(self):
        seen = set()
        for e in self.entries:
            key = e.get("key", "")
            if not key.strip():
                raise ValueError(
                    f"device-transfer manifest entry in {self.path} has an "
                    f"empty key"
                )
            if not e.get("why", "").strip():
                raise ValueError(
                    f"device-transfer manifest entry {key!r} in {self.path} "
                    f"has no justification ('why')"
                )
            if key in seen:
                raise ValueError(
                    f"device-transfer manifest entry {key!r} in {self.path} "
                    f"is duplicated"
                )
            seen.add(key)

    def keys(self) -> Set[str]:
        return {e["key"] for e in self.entries}

    def internal_keys(self) -> Set[str]:
        """Keys whose scope must appear in the package scan (entries
        with "external": true live outside it, e.g. bench.py)."""
        return {e["key"] for e in self.entries if not e.get("external")}


def load_device_manifest(path: str = MANIFEST_PATH) -> DeviceManifest:
    if not os.path.exists(path):
        return DeviceManifest([], path)
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    return DeviceManifest(data.get("transfers", []), path)


# ---------------------------------------------------------------------------
# per-module walker
# ---------------------------------------------------------------------------


def _iter_py_files(root: str) -> List[str]:
    out = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d not in SKIP_DIRS]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                out.append(os.path.join(dirpath, fn))
    return out


class _ModuleAliases:
    """numpy / jax / jax.numpy / functools import aliases in one module."""

    def __init__(self, tree: ast.Module):
        self.np: Set[str] = set()
        self.jax: Set[str] = set()
        self.jnp: Set[str] = set()
        self.functools: Set[str] = set()
        self.jit_names: Set[str] = set()  # from jax import jit [as j]
        self.devput_names: Set[str] = set()
        # from jax.experimental import pallas as pl / import
        # jax.experimental.pallas as X
        self.pallas: Set[str] = set()
        # from jax.experimental.pallas import pallas_call [as pc]
        self.pallas_call_names: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    name, asname = a.name, a.asname or a.name.split(".")[0]
                    if name == "numpy":
                        self.np.add(asname)
                    elif name == "jax":
                        self.jax.add(asname)
                    elif name == "jax.numpy":
                        self.jnp.add(a.asname or "jax")
                    elif name == "functools":
                        self.functools.add(asname)
                    elif name == "jax.experimental.pallas" and a.asname:
                        self.pallas.add(a.asname)
            elif isinstance(node, ast.ImportFrom):
                if node.module == "jax":
                    for a in node.names:
                        if a.name == "numpy":
                            self.jnp.add(a.asname or "numpy")
                        elif a.name == "jit":
                            self.jit_names.add(a.asname or "jit")
                        elif a.name in ("device_put", "device_get"):
                            self.devput_names.add(a.asname or a.name)
                elif node.module == "jax.experimental":
                    for a in node.names:
                        if a.name == "pallas":
                            self.pallas.add(a.asname or "pallas")
                elif node.module == "jax.experimental.pallas":
                    for a in node.names:
                        if a.name == "pallas_call":
                            self.pallas_call_names.add(a.asname or a.name)
                elif node.module == "numpy":
                    for a in node.names:
                        # from numpy import asarray — rare; track the
                        # alias as a bare-name numpy "module" is wrong,
                        # so record under np with the function name
                        pass


def _attr_chain(node: ast.expr) -> List[str]:
    """a.b.c -> ["a", "b", "c"]; returns [] for non-name chains."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        parts.reverse()
        return parts
    return []


class _DeviceWalker:
    def __init__(self, census: DeviceCensus, module: str, tree: ast.Module):
        self.census = census
        self.module = module
        self.aliases = _ModuleAliases(tree)
        self.tree = tree
        # function ast nodes for the second-pass rules
        self.func_nodes: List[Tuple[str, ast.AST]] = []

    # ---- classification helpers ----
    def _is_jit_call(self, call: ast.Call) -> bool:
        chain = _attr_chain(call.func)
        if len(chain) == 2 and chain[0] in self.aliases.jax and chain[1] == "jit":
            return True
        if len(chain) == 1 and chain[0] in self.aliases.jit_names:
            return True
        # functools.partial(jax.jit, ...)
        if (
            chain
            and chain[-1] == "partial"
            and (len(chain) == 1 or chain[0] in self.aliases.functools)
            and call.args
        ):
            inner = _attr_chain(call.args[0])
            if (
                len(inner) == 2
                and inner[0] in self.aliases.jax
                and inner[1] == "jit"
            ) or (len(inner) == 1 and inner[0] in self.aliases.jit_names):
                return True
        return False

    def _is_pallas_call(self, call: ast.Call) -> bool:
        """``pl.pallas_call`` / bare ``pallas_call`` (from-import) /
        ``jax.experimental.pallas.pallas_call`` /
        ``functools.partial(pl.pallas_call, ...)``."""

        def _resolves(chain: List[str]) -> bool:
            if not chain:
                return False
            if len(chain) == 1:
                return chain[0] in self.aliases.pallas_call_names
            if chain[-1] != "pallas_call":
                return False
            if len(chain) == 2:
                return chain[0] in self.aliases.pallas
            return (  # fully qualified through the jax alias
                len(chain) == 4
                and chain[0] in self.aliases.jax
                and chain[1] == "experimental"
                and chain[2] == "pallas"
            )

        chain = _attr_chain(call.func)
        if _resolves(chain):
            return True
        # functools.partial(pl.pallas_call, ...)
        if (
            chain
            and chain[-1] == "partial"
            and (len(chain) == 1 or chain[0] in self.aliases.functools)
            and call.args
        ):
            return _resolves(_attr_chain(call.args[0]))
        return False

    def _donate_argnums(self, call: ast.Call) -> Optional[Tuple[int, ...]]:
        for kw in call.keywords:
            if kw.arg == "donate_argnums":
                v = kw.value
                if isinstance(v, ast.Constant) and isinstance(v.value, int):
                    return (v.value,)
                if isinstance(v, (ast.Tuple, ast.List)):
                    out = []
                    for el in v.elts:
                        if isinstance(el, ast.Constant) and isinstance(
                            el.value, int
                        ):
                            out.append(el.value)
                    return tuple(out)
                return ()
        return None

    def _scope_key_of(self, item: ast.withitem) -> Optional[str]:
        """`with allowed_transfer("key")` / `with dw.allowed_transfer("key")`."""
        ctx = item.context_expr
        if not isinstance(ctx, ast.Call):
            return None
        chain = _attr_chain(ctx.func)
        if not chain or chain[-1] != "allowed_transfer":
            return None
        if ctx.args and isinstance(ctx.args[0], ast.Constant) and isinstance(
            ctx.args[0].value, str
        ):
            return ctx.args[0].value
        return ""  # non-literal key: recorded, flagged by the manifest rule

    # ---- walk ----
    def walk_module(self):
        self._walk_body(self.tree.body, func="<module>", cls=None, scope="")

    def _walk_body(self, body, func: str, cls: Optional[str], scope: str):
        for stmt in body:
            self._stmt(stmt, func, cls, scope)

    def _stmt(self, stmt: ast.stmt, func: str, cls: Optional[str], scope: str):
        if isinstance(stmt, ast.ClassDef):
            for sub in stmt.body:
                self._stmt(sub, func="<class>", cls=stmt.name, scope=scope)
            return
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            qual = f"{cls}.{stmt.name}" if cls else stmt.name
            self.func_nodes.append((qual, stmt))
            for dec in stmt.decorator_list:
                self._decorator(dec, qual, scope)
            self._walk_body(stmt.body, func=qual, cls=cls, scope=scope)
            return
        if isinstance(stmt, ast.With):
            new_scope = scope
            for item in stmt.items:
                key = self._scope_key_of(item)
                if key is not None:
                    self._add("allow-scope", func, stmt.lineno, detail=key,
                              scope=scope)
                    new_scope = key
                else:
                    self._expr(item.context_expr, func, scope)
            self._walk_body(stmt.body, func, cls, new_scope)
            return
        # donation map: name = jax.jit(fn, donate_argnums=...)
        if isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Call):
            if self._is_jit_call(stmt.value):
                argnums = self._donate_argnums(stmt.value)
                if argnums:
                    for t in stmt.targets:
                        if isinstance(t, ast.Name):
                            self.census.donating[t.id] = argnums
        # scan expressions, then recurse into block bodies with the same
        # scope (an allow scope does not cross a nested `with` boundary
        # other than its own body, handled above)
        for fld, value in ast.iter_fields(stmt):
            if fld in ("body", "orelse", "finalbody", "handlers"):
                continue
            if isinstance(value, ast.expr):
                self._expr(value, func, scope)
            elif isinstance(value, list):
                for v in value:
                    if isinstance(v, ast.expr):
                        self._expr(v, func, scope)
        for fld in ("body", "orelse", "finalbody"):
            sub = getattr(stmt, fld, None)
            if sub:
                self._walk_body(sub, func, cls, scope)
        for h in getattr(stmt, "handlers", []) or []:
            self._walk_body(h.body, func, cls, scope)

    def _decorator(self, dec: ast.expr, qual: str, scope: str):
        # @jax.jit (bare) or @functools.partial(jax.jit, ...) / @jit
        chain = _attr_chain(dec)
        if (
            len(chain) == 2 and chain[0] in self.aliases.jax and chain[1] == "jit"
        ) or (len(chain) == 1 and chain[0] in self.aliases.jit_names):
            self._add("jit", qual, dec.lineno, detail="@jit", scope=scope)
            return
        if isinstance(dec, ast.Call) and self._is_jit_call(dec):
            self._add("jit", qual, dec.lineno, detail="@jit", scope=scope)
            argnums = self._donate_argnums(dec)
            if argnums:
                self._add("donation", qual, dec.lineno,
                          detail=f"donate_argnums={argnums}", scope=scope)
                # the decorated function becomes a donating callee
                self.census.donating[qual.rsplit(".", 1)[-1]] = argnums

    def _expr(self, expr: ast.expr, func: str, scope: str):
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            self._call(node, func, scope)

    def _call(self, call: ast.Call, func: str, scope: str):
        chain = _attr_chain(call.func)
        leaf = chain[-1] if chain else ""
        # jit (incl. partial(jax.jit, ...))
        if self._is_jit_call(call):
            self._add("jit", func, call.lineno, detail=".".join(chain),
                      scope=scope)
            argnums = self._donate_argnums(call)
            if argnums:
                self._add("donation", func, call.lineno,
                          detail=f"donate_argnums={argnums}", scope=scope)
            return
        # hand-rolled Pallas kernel construction (incl. partial)
        if self._is_pallas_call(call):
            self._add("pallas-call", func, call.lineno,
                      detail=".".join(chain), scope=scope)
            return
        # fused-kernel construction
        if leaf in ("FusedKernel", "ShardedFusedKernel"):
            self._add("fused-kernel", func, call.lineno, detail=leaf,
                      scope=scope)
            return
        # explicit transfers
        if leaf in ("device_put", "device_get") or (
            len(chain) == 1 and leaf in self.aliases.devput_names
        ):
            self._add("device-put", func, call.lineno, detail=leaf,
                      scope=scope)
            return
        # collectives
        if leaf in _COLLECTIVE_LEAFS:
            self._add("collective", func, call.lineno, detail=leaf,
                      scope=scope)
            return
        # staging-slot traffic
        if leaf in ("acquire", "release") and len(chain) >= 2:
            recv = ".".join(chain[:-1]).lower()
            if any(h in recv for h in _RING_RECEIVER_HINTS):
                self._add(f"slot-{leaf}", func, call.lineno,
                          detail=".".join(chain[:-1]), scope=scope)
                return
        # host syncs
        if leaf in ("asarray", "array", "ascontiguousarray") and (
            len(chain) == 2 and chain[0] in self.aliases.np
        ):
            self._add("host-sync", func, call.lineno, detail=leaf,
                      sync="asarray", scope=scope)
            return
        # method syncs match on the attribute itself, not the chain —
        # `fn(x).block_until_ready()` has no resolvable name chain but
        # still syncs
        if (
            isinstance(call.func, ast.Attribute)
            and call.func.attr == "block_until_ready"
        ):
            self._add("host-sync", func, call.lineno,
                      detail="block_until_ready", sync="block", scope=scope)
            return
        if isinstance(call.func, ast.Attribute) and call.func.attr == "item":
            self._add("host-sync", func, call.lineno, detail=".item()",
                      sync="item", scope=scope)
            return
        if (
            isinstance(call.func, ast.Name)
            and call.func.id in ("float", "int", "bool")
            and call.args
            and self._contains_reduction(call.args[0])
        ):
            self._add("host-sync", func, call.lineno,
                      detail=f"{call.func.id}(…{self._reduction_attr(call.args[0])}())",
                      sync="coerce", scope=scope)
            return
        if len(chain) >= 3 and chain[0] in self.aliases.jax and chain[1] == "debug":
            self._add("host-sync", func, call.lineno,
                      detail=".".join(chain), sync="debug", scope=scope)
            return

    @staticmethod
    def _contains_reduction(expr: ast.expr) -> bool:
        for node in ast.walk(expr):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _REDUCER_ATTRS
            ):
                return True
        return False

    @staticmethod
    def _reduction_attr(expr: ast.expr) -> str:
        for node in ast.walk(expr):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _REDUCER_ATTRS
            ):
                return node.func.attr
        return ""

    def _add(self, kind, func, line, detail="", sync="", scope=""):
        self.census.sites.append(
            DeviceSite(
                kind=kind,
                module=self.module,
                func=func,
                line=line,
                detail=detail,
                sync=sync,
                scope_key=scope,
            )
        )


def build_device_census(root: str) -> DeviceCensus:
    """Scan every .py under `root` (the package directory)."""
    census = DeviceCensus(root=root)
    walkers: List[_DeviceWalker] = []
    for path in _iter_py_files(root):
        rel = os.path.relpath(path, root)
        with open(path, "r", encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=path)
        w = _DeviceWalker(census, rel, tree)
        w.walk_module()
        walkers.append(w)
    census._walkers = walkers  # kept for the second-pass rules
    return census


# ---------------------------------------------------------------------------
# rules
# ---------------------------------------------------------------------------


def _is_hot(module: str, hot_prefixes) -> bool:
    return any(module.startswith(p) for p in hot_prefixes)


def run_device_rules(
    census: DeviceCensus,
    manifest: Optional[DeviceManifest] = None,
    hot_prefixes=HOT_PREFIXES,
    jit_exempt=JIT_EXEMPT_MODULES,
) -> List[Finding]:
    if manifest is None:
        manifest = load_device_manifest()
    findings: List[Finding] = []

    # host-sync-on-hot-path: occurrence-indexed keys so two same-kind
    # syncs in one function stay separately allowlistable
    occ: Dict[Tuple[str, str, str], int] = {}
    for s in census.sites:
        if s.kind != "host-sync":
            continue
        if not _is_hot(s.module, hot_prefixes):
            continue
        if s.scope_key:
            continue  # justified via the manifest (checked below)
        k = (s.module, s.func, s.sync)
        n = occ.get(k, 0)
        occ[k] = n + 1
        findings.append(
            Finding(
                rule="host-sync-on-hot-path",
                key=f"{s.module}:{s.func}:{s.sync}:{n}",
                message=(
                    f"{s.module}:{s.func} forces a device→host sync "
                    f"({s.detail}) on a request path — keep the value "
                    f"device-resident or wrap the site in "
                    f"allowed_transfer(<key>) with a manifest entry"
                ),
                file=s.module,
                line=s.line,
            )
        )

    # transfer-manifest: scope keys ↔ manifest entries, both directions
    used_keys: Set[str] = set()
    for s in census.by_kind("allow-scope"):
        key = s.detail
        if not key:
            findings.append(
                Finding(
                    rule="transfer-manifest",
                    key=f"{s.module}:{s.func}:<non-literal>",
                    message=(
                        f"{s.module}:{s.func} enters allowed_transfer with a "
                        f"non-literal key — the manifest can only justify "
                        f"string-literal keys"
                    ),
                    file=s.module,
                    line=s.line,
                )
            )
            continue
        used_keys.add(key)
        if key not in manifest.keys():
            findings.append(
                Finding(
                    rule="transfer-manifest",
                    key=f"{s.module}:{s.func}:{key}",
                    message=(
                        f"{s.module}:{s.func} justifies a transfer under key "
                        f"{key!r} but {os.path.basename(manifest.path)} has "
                        f"no such entry — add it with a 'why'"
                    ),
                    file=s.module,
                    line=s.line,
                )
            )
    for key in sorted(manifest.internal_keys() - used_keys):
        findings.append(
            Finding(
                rule="transfer-manifest-stale",
                key=key,
                message=(
                    f"device-transfer manifest entry {key!r} matches no "
                    f"allowed_transfer scope in the tree — remove it (the "
                    f"justified transfer is gone)"
                ),
            )
        )

    # raw-jit-retrace — pallas_call sites trace and compile exactly like
    # jit (each new (shape, dtype, static-arg) combination lowers a new
    # Mosaic kernel), so they ride the same rule with their own key
    # suffix
    for s in census.by_kind("jit") + census.by_kind("pallas-call"):
        if not _is_hot(s.module, hot_prefixes) or s.module in jit_exempt:
            continue
        what = "jit" if s.kind == "jit" else "pallas_call"
        findings.append(
            Finding(
                rule="raw-jit-retrace",
                key=f"{s.module}:{s.func}:{what}",
                message=(
                    f"{s.module}:{s.func} builds a raw jax.{what} on a "
                    f"request path — nothing bounds its trace cache; route "
                    f"it through FusedKernel/padding buckets or allowlist "
                    f"with a why"
                ),
                file=s.module,
                line=s.line,
            )
        )

    # slot-lifecycle + read-after-donate need function-local dataflow
    for w in getattr(census, "_walkers", []):
        for qual, node in w.func_nodes:
            findings.extend(
                _slot_and_donate_rules(census, w.module, qual, node)
            )

    return findings


def _slot_and_donate_rules(
    census: DeviceCensus, module: str, qual: str, node: ast.AST
) -> List[Finding]:
    findings: List[Finding] = []
    acquired: Dict[str, int] = {}  # name -> line
    released: Set[str] = set()
    release_receivers = False
    donated_args: List[Tuple[str, int, str]] = []  # (name, line, callee)
    returned: Set[str] = set()

    for sub in ast.walk(node):
        if isinstance(sub, ast.Assign) and isinstance(sub.value, ast.Call):
            chain = _attr_chain(sub.value.func)
            if (
                chain
                and chain[-1] == "acquire"
                and len(chain) >= 2
                and any(h in ".".join(chain[:-1]).lower()
                        for h in _RING_RECEIVER_HINTS)
            ):
                for t in sub.targets:
                    if isinstance(t, ast.Name):
                        acquired[t.id] = sub.lineno
        if isinstance(sub, ast.Call):
            chain = _attr_chain(sub.func)
            leaf = chain[-1] if chain else ""
            if (
                leaf == "release"
                and len(chain) >= 2
                and any(h in ".".join(chain[:-1]).lower()
                        for h in _RING_RECEIVER_HINTS)
            ):
                release_receivers = True
                for a in sub.args:
                    if isinstance(a, ast.Name):
                        released.add(a.id)
            argnums = census.donating.get(leaf)
            if argnums:
                # a multi-line call's own arguments are not "reads
                # after" the donation — anchor on the call's END line
                end = getattr(sub, "end_lineno", sub.lineno) or sub.lineno
                for i in argnums:
                    if i < len(sub.args) and isinstance(sub.args[i], ast.Name):
                        donated_args.append(
                            (sub.args[i].id, end, leaf)
                        )
        if isinstance(sub, ast.Return) and sub.value is not None:
            for n2 in ast.walk(sub.value):
                if isinstance(n2, ast.Name):
                    returned.add(n2.id)

    donated_names = {name for name, _, _ in donated_args}
    for name, line in sorted(acquired.items()):
        if name in released or name in donated_names or name in returned:
            continue
        # `for oc in outs: ring.release(oc)` — releasing through a loop
        # variable still proves intent; only a function with NO release
        # call on a ring receiver trips
        if release_receivers:
            continue
        findings.append(
            Finding(
                rule="slot-lifecycle",
                key=f"{module}:{qual}:{name}",
                message=(
                    f"{module}:{qual} acquires staging slot {name!r} but "
                    f"never releases, donates, or returns it — the ring "
                    f"leaks one slot per call"
                ),
                file=module,
                line=line,
            )
        )

    for name, line, callee in donated_args:
        for sub in ast.walk(node):
            if (
                isinstance(sub, ast.Name)
                and sub.id == name
                and isinstance(sub.ctx, ast.Load)
                and sub.lineno > line
            ):
                findings.append(
                    Finding(
                        rule="read-after-donate",
                        key=f"{module}:{qual}:{name}:{callee}",
                        message=(
                            f"{module}:{qual} reads {name!r} at line "
                            f"{sub.lineno} after donating it to {callee}() "
                            f"at line {line} — donated buffers are consumed"
                        ),
                        file=module,
                        line=sub.lineno,
                    )
                )
                break
    return findings


def run_dispatch_under_lock(graph) -> List[Finding]:
    """Device-dispatch-under-lock: consume the lockgraph's held-set call
    sites (PR 7's walker already threads lock context through every
    call) and flag fused-kernel dispatch / device transfers under a
    package lock."""
    findings: List[Finding] = []
    for key, info in graph.funcs.items():
        module, _, fname = key
        for c in info.calls:
            if not c.held:
                continue
            if not (
                c.leaf in DEVICE_DISPATCH_LEAFS or "kernel" in c.leaf.lower()
            ):
                continue
            lockset = ",".join(c.held)
            findings.append(
                Finding(
                    rule="device-dispatch-under-lock",
                    key=f"{module}:{fname}:{c.leaf}:{lockset}",
                    message=(
                        f"{module}:{fname} dispatches device work "
                        f"({c.leaf}) while holding [{lockset}] — the lock is "
                        f"pinned for the whole device round trip"
                    ),
                    file=module,
                    line=c.line,
                )
            )
    return findings
