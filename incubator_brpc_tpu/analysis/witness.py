"""Runtime lock-witness mode — validate the static graph by execution.

``enable()`` replaces ``threading.Lock``/``RLock``/``Condition`` with
factories that wrap locks CREATED BY PACKAGE CODE (decided by the
caller's filename) in thin recording proxies; all other creators get
the raw primitive, so pytest/jax/stdlib locks pay nothing.  Each
witnessed lock is keyed by its creation site (``relpath:lineno``) —
exactly the key the static inventory records — so runtime acquisition
orders join 1:1 onto static lock names.

While enabled, every successful acquisition records one edge per
currently-held witnessed lock: *site A was held when site B was
acquired*, with reentrant re-acquisition (RLock/Condition) folded out.
``cross_check()`` then maps the witnessed edges onto canonical lock
names and verifies none CONTRADICTS the checked-in manifest order — a
witnessed B→A where the manifest orders A→B is a runtime-proven
inversion.  Witnessed edges the static pass missed are reported as
``new_edges`` (the analyzer's blind spots, e.g. acquisitions through
dynamically-dispatched calls), not failures.

Enable BEFORE the package creates locks: tests/conftest.py does this
when ``BRPC_LOCK_WITNESS=1`` is set.  Known limitation: module-level
locks created by importing ``incubator_brpc_tpu`` itself (today only
``utils/iobuf.py:_SSL_LOCK_GUARD``) predate the patch and go
unwitnessed.

Direct factories (``make_lock``/``make_rlock``/``make_condition``) let
tests witness specific locks without patching ``threading`` globally.
"""

from __future__ import annotations

import _thread
import json
import os
import threading
from typing import Dict, List, Optional, Tuple

_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock
_REAL_CONDITION = threading.Condition

_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_state_lock = _thread.allocate_lock()
_enabled = False
_scopes: List[str] = [_PKG_ROOT]
# (src_site, dst_site) -> count
_edges: Dict[Tuple[str, str], int] = {}
_sites_seen: Dict[str, int] = {}
_local = threading.local()


def _held_stack() -> list:
    st = getattr(_local, "stack", None)
    if st is None:
        st = []
        _local.stack = st
    return st


def _site_of_caller(depth: int = 2) -> Optional[str]:
    import sys

    try:
        frame = sys._getframe(depth)
    except ValueError:
        return None
    fn = frame.f_code.co_filename
    for scope in _scopes:
        if fn.startswith(scope + os.sep) or fn == scope:
            rel = os.path.relpath(fn, scope)
            return f"{rel}:{frame.f_lineno}"
    return None


class _WitnessBase:
    __slots__ = ("_real", "site")

    def __init__(self, real, site: str):
        self._real = real
        self.site = site
        with _state_lock:
            _sites_seen[site] = _sites_seen.get(site, 0) + 1

    def acquire(self, blocking=True, timeout=-1):
        ok = self._real.acquire(blocking, timeout)
        if ok:
            self._note_acquired()
        return ok

    acquire_lock = acquire  # old-style alias some code paths use

    def _note_acquired(self):
        stack = _held_stack()
        if any(e is self for e in stack):
            stack.append(self)  # reentrant: push for balanced release,
            return  # but record no self-edge
        if stack:
            with _state_lock:
                for held in _dedupe(stack):
                    if held.site != self.site:
                        key = (held.site, self.site)
                        _edges[key] = _edges.get(key, 0) + 1
        stack.append(self)

    def release(self):
        stack = _held_stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is self:
                del stack[i]
                break
        self._real.release()

    release_lock = release

    def locked(self):
        return self._real.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<witness {self.site} of {self._real!r}>"


def _dedupe(stack):
    seen = set()
    out = []
    for e in stack:
        if id(e) not in seen:
            seen.add(id(e))
            out.append(e)
    return out


class _WitnessLock(_WitnessBase):
    __slots__ = ()


class _WitnessRLock(_WitnessBase):
    __slots__ = ()

    def _is_owned(self):  # Condition uses this when available
        return self._real._is_owned()


def make_lock(site: str):
    return _WitnessLock(_REAL_LOCK(), site)


def make_rlock(site: str):
    return _WitnessRLock(_REAL_RLOCK(), site)


def make_condition(site: str, lock=None):
    if lock is None:
        lock = _WitnessRLock(_REAL_RLOCK(), site)
    return _REAL_CONDITION(lock)


# ---------------------------------------------------------------------------
# global patch
# ---------------------------------------------------------------------------


def _lock_factory():
    site = _site_of_caller()
    if site is None:
        return _REAL_LOCK()
    return _WitnessLock(_REAL_LOCK(), site)


def _rlock_factory():
    site = _site_of_caller()
    if site is None:
        return _REAL_RLOCK()
    return _WitnessRLock(_REAL_RLOCK(), site)


def _condition_factory(lock=None):
    if lock is not None:
        return _REAL_CONDITION(lock)
    site = _site_of_caller()
    if site is None:
        return _REAL_CONDITION()
    return _REAL_CONDITION(_WitnessRLock(_REAL_RLOCK(), site))


def enable(extra_scopes: Optional[List[str]] = None) -> None:
    """Patch threading's lock factories.  Idempotent."""
    global _enabled
    if extra_scopes:
        for s in extra_scopes:
            add_scope(s)
    if _enabled:
        return
    threading.Lock = _lock_factory
    threading.RLock = _rlock_factory
    threading.Condition = _condition_factory
    _enabled = True


def disable() -> None:
    global _enabled
    threading.Lock = _REAL_LOCK
    threading.RLock = _REAL_RLOCK
    threading.Condition = _REAL_CONDITION
    _enabled = False


def add_scope(path: str) -> None:
    p = os.path.abspath(path)
    if p not in _scopes:
        _scopes.append(p)


def enabled() -> bool:
    return _enabled


def reset() -> None:
    with _state_lock:
        _edges.clear()
        _sites_seen.clear()


def edges() -> Dict[Tuple[str, str], int]:
    with _state_lock:
        return dict(_edges)


def sites_seen() -> Dict[str, int]:
    with _state_lock:
        return dict(_sites_seen)


# ---------------------------------------------------------------------------
# cross-check against the static manifest
# ---------------------------------------------------------------------------


def cross_check(
    pkg_root: Optional[str] = None,
    manifest_pairs: Optional[set] = None,
) -> dict:
    """Map witnessed edges onto canonical lock names and verify none
    contradicts the manifest partial order.

    Returns {"checked": n, "contradictions": [...], "new_edges": [...],
    "witnessed_sites": n, "unmapped_sites": [...]}.
    """
    from incubator_brpc_tpu.analysis.inventory import build_inventory
    from incubator_brpc_tpu.analysis.manifest import load_manifest

    pkg_root = pkg_root or _PKG_ROOT
    inv = build_inventory(pkg_root)
    if manifest_pairs is None:
        manifest_pairs = load_manifest().pairs()

    # reachability over the manifest order
    adj: Dict[str, set] = {}
    for a, b in manifest_pairs:
        adj.setdefault(a, set()).add(b)

    def reachable(a: str, b: str) -> bool:
        seen, todo = set(), [a]
        while todo:
            n = todo.pop()
            if n == b:
                return True
            if n in seen:
                continue
            seen.add(n)
            todo.extend(adj.get(n, ()))
        return False

    def map_site(site: str) -> Optional[str]:
        rel, _, line = site.rpartition(":")
        try:
            key = (rel, int(line))
        except ValueError:
            return None
        s = inv.by_creation.get(key)
        return s.base() if s is not None else None

    contradictions, new_edges, unmapped = [], [], []
    checked = 0
    for (src_site, dst_site), count in edges().items():
        src, dst = map_site(src_site), map_site(dst_site)
        if src is None or dst is None:
            for site, name in ((src_site, src), (dst_site, dst)):
                if name is None and site not in unmapped:
                    unmapped.append(site)
            continue
        if src == dst:
            continue  # alias fold: condition over its own base lock
        checked += 1
        if reachable(dst, src):
            contradictions.append(
                {
                    "witnessed": f"{src} -> {dst}",
                    "manifest_orders": f"{dst} -> {src}",
                    "count": count,
                    "sites": f"{src_site} -> {dst_site}",
                }
            )
        elif (src, dst) not in manifest_pairs:
            new_edges.append({"edge": f"{src} -> {dst}", "count": count})
    return {
        "checked": checked,
        "contradictions": contradictions,
        "new_edges": new_edges,
        "witnessed_sites": len(sites_seen()),
        "unmapped_sites": sorted(unmapped),
    }


def write_report(path: str, result: Optional[dict] = None) -> dict:
    if result is None:
        result = cross_check()
    with open(path, "w", encoding="utf-8") as f:
        json.dump(result, f, indent=2, sort_keys=True)
    return result
