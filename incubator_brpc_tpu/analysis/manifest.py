"""The canonical lock-order manifest (lock_order.json).

The manifest is the reviewed, checked-in statement of which lock may be
held while which other lock is acquired — every edge carries a one-line
justification.  The check is three-way:

- every STATIC edge must appear in the manifest
  (``lock-order-new-edge`` otherwise: a new cross-lock acquisition is a
  reviewable diff, never silent);
- the union of manifest + static edges must be acyclic
  (``lock-order-cycle``: an inversion);
- WITNESSED runtime edges must not contradict the manifest order
  (checked by analysis.witness.cross_check).

Manifest edges no longer seen statically are reported as stale
warnings so the file cannot rot into fiction.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import List, Set, Tuple

from incubator_brpc_tpu.analysis.findings import Finding, TODO_REVIEW_MARKER
from incubator_brpc_tpu.analysis.lockgraph import GraphResult, find_cycles

DEFAULT_PATH = os.path.join(os.path.dirname(__file__), "lock_order.json")


@dataclass
class Manifest:
    edges: List[dict] = field(default_factory=list)  # {from, to, why}
    path: str = DEFAULT_PATH

    def __post_init__(self):
        for e in self.edges:
            if not e.get("why", "").strip():
                raise ValueError(
                    f"manifest edge {e.get('from')} -> {e.get('to')} in "
                    f"{self.path} has no justification ('why')"
                )

    def pairs(self) -> Set[Tuple[str, str]]:
        return {(e["from"], e["to"]) for e in self.edges}


def load_manifest(path: str = DEFAULT_PATH) -> Manifest:
    if not os.path.exists(path):
        return Manifest([], path)
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    return Manifest(data.get("edges", []), path)


def save_manifest(manifest: Manifest, path: str = DEFAULT_PATH) -> None:
    edges = sorted(manifest.edges, key=lambda e: (e["from"], e["to"]))
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"edges": edges}, f, indent=2, sort_keys=True)
        f.write("\n")


def todo_review_findings(manifest: Manifest) -> List[Finding]:
    """Edges whose `why` still contains the ``TODO review`` placeholder
    update_manifest_from_graph writes: the --update-manifest flow says
    'edit before commit', and this is what makes skipping that edit a
    violation instead of a silently permanent non-justification."""
    out: List[Finding] = []
    for e in manifest.edges:
        if TODO_REVIEW_MARKER in e.get("why", ""):
            out.append(
                Finding(
                    rule="todo-review-why",
                    key=f"lock-order/{e.get('from')}->{e.get('to')}",
                    message=(
                        f"manifest edge {e.get('from')} -> {e.get('to')} "
                        f"still carries a '{TODO_REVIEW_MARKER}' "
                        f"placeholder why — review the edge and write the "
                        f"real justification"
                    ),
                    file=manifest.path,
                )
            )
    return out


def check_graph_against_manifest(
    graph: GraphResult, manifest: Manifest
) -> Tuple[List[Finding], List[str]]:
    """→ (findings, stale_warnings)."""
    findings: List[Finding] = []
    static_pairs = graph.edge_pairs()
    manifest_pairs = manifest.pairs()

    for e in sorted(graph.edges, key=lambda e: (e.src, e.dst)):
        if (e.src, e.dst) not in manifest_pairs:
            via = f" via {e.via}" if e.via else ""
            findings.append(
                Finding(
                    rule="lock-order-new-edge",
                    key=f"{e.src}->{e.dst}",
                    message=(
                        f"new lock-order edge {e.src} -> {e.dst}"
                        f" (first seen {e.module}:{e.line}{via}) — review "
                        f"it, then add it to lock_order.json with a 'why' "
                        f"or restructure the acquisition"
                    ),
                    file=e.module,
                    line=e.line,
                )
            )

    union = static_pairs | manifest_pairs
    for cyc in find_cycles(union):
        findings.append(
            Finding(
                rule="lock-order-cycle",
                key="->".join(cyc),
                message=f"lock-order inversion: {' -> '.join(cyc)}",
            )
        )

    # witness-sourced edges are invisible to the static pass by nature
    # (dynamic dispatch, data-driven calls) — only static-sourced edges
    # can go stale
    static_sourced = {
        (e["from"], e["to"])
        for e in manifest.edges
        if e.get("source") != "witness"
    }
    stale = [
        f"manifest edge {a} -> {b} no longer observed statically"
        for (a, b) in sorted(static_sourced - static_pairs)
    ]
    return findings, stale


def update_manifest_from_graph(
    graph: GraphResult, manifest: Manifest, path: str = DEFAULT_PATH
) -> int:
    """Add missing static edges with a placeholder why (to be edited by
    the reviewer).  Returns the number added."""
    manifest_pairs = manifest.pairs()
    added = 0
    for e in sorted(graph.edges, key=lambda e: (e.src, e.dst)):
        if (e.src, e.dst) in manifest_pairs:
            continue
        via = f" via {e.via}" if e.via else " (direct nested acquisition)"
        manifest.edges.append(
            {
                "from": e.src,
                "to": e.dst,
                "why": f"TODO review: first seen {e.module}:{e.line}{via}",
            }
        )
        manifest_pairs.add((e.src, e.dst))
        added += 1
    if added:
        save_manifest(manifest, path)
    return added
