"""Project-invariant lints.

These encode contracts the subsystems rely on but nothing previously
enforced:

- ``chaos-site-doc`` / ``chaos-site-test`` — every site registered in
  ``chaos.injector.SITES`` has a row in docs/chaos.md and at least one
  test referencing it (a site nobody documents or exercises is a fault
  path nobody proved).
- ``metrics-unrenderable`` — every variable registered in the metrics
  registry renders on /metrics: numeric ``get_value()`` or a
  MultiDimension family.  A string-valued PassiveStatus silently
  vanishes from the Prometheus exposition — that must be a deliberate,
  allowlisted choice.
- ``tls-restore`` — a function that stores to a ``_tls`` slot must
  restore it in a ``finally`` of the same function (the nested-inline
  save/restore discipline PR 5's review pass introduced), unless the
  store is a thread-lifetime initialization (allowlisted).
- ``completion-guard`` — configured completion paths (batcher scatter,
  stream close, decode-row finish) carry their exactly-once guard:
  a flag checked-then-set, or a callback swap-to-None.  Controller
  rows must resolve exactly once; fan-out ``done()`` loops must wrap
  each row in try/except so one row's failure cannot strand its
  batch-mates.
- ``except-swallow`` — a broad ``except Exception`` in protocols/ or
  streaming/ whose handler neither re-raises, completes a controller
  (``set_failed``), returns an error sentinel, nor logs, swallows
  ERPC-coded failures into silence.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Tuple

from incubator_brpc_tpu.analysis.findings import Finding
from incubator_brpc_tpu.analysis.inventory import iter_py_files

# ---------------------------------------------------------------------------
# chaos sites
# ---------------------------------------------------------------------------


def check_chaos_sites(
    sites: Dict[str, str], docs_text: str, tests_text: str
) -> List[Finding]:
    """`sites` is the injector's SITES dict; `docs_text` the content of
    docs/chaos.md; `tests_text` the concatenated test sources."""
    out: List[Finding] = []
    for site in sorted(sites):
        if f"`{site}`" not in docs_text:
            out.append(
                Finding(
                    rule="chaos-site-doc",
                    key=site,
                    message=f"chaos site {site} has no docs/chaos.md row",
                    file="docs/chaos.md",
                )
            )
        # quoted-token match, not substring: `socket.write` must not
        # earn credit from a test that only mentions `socket.write_io`
        if not re.search(rf"""['"]{re.escape(site)}['"]""", tests_text):
            out.append(
                Finding(
                    rule="chaos-site-test",
                    key=site,
                    message=f"chaos site {site} is referenced by no test",
                    file="tests/",
                )
            )
    return out


def run_chaos_site_lint(repo_root: str) -> List[Finding]:
    from incubator_brpc_tpu.chaos import injector

    docs = _read(os.path.join(repo_root, "docs", "chaos.md"))
    tests = []
    tdir = os.path.join(repo_root, "tests")
    if os.path.isdir(tdir):
        for p in iter_py_files(tdir):
            tests.append(_read(p))
    return check_chaos_sites(injector.SITES, docs, "\n".join(tests))


def _read(path: str) -> str:
    if not os.path.exists(path):
        return ""
    with open(path, "r", encoding="utf-8") as f:
        return f.read()


# ---------------------------------------------------------------------------
# metrics render on /metrics
# ---------------------------------------------------------------------------

# modules that register variables at import time, jax-free so the lint
# can run anywhere
METRIC_MODULES = (
    "incubator_brpc_tpu.metrics.default_variables",
    "incubator_brpc_tpu.transport.socket",
    "incubator_brpc_tpu.chaos.injector",
    "incubator_brpc_tpu.streaming.observe",
    "incubator_brpc_tpu.server.admission",
    "incubator_brpc_tpu.observability.cluster",
    "incubator_brpc_tpu.cache.store",
    "incubator_brpc_tpu.resharding.migration",
    "incubator_brpc_tpu.replication.metrics",
    "incubator_brpc_tpu.observability.profiling",
    "incubator_brpc_tpu.parallel.ici",
    "incubator_brpc_tpu.metrics.ring_metrics",
    "incubator_brpc_tpu.serving.metrics",
)


def run_metrics_lint() -> List[Finding]:
    import importlib

    for m in METRIC_MODULES:
        importlib.import_module(m)
    from incubator_brpc_tpu.metrics.multi_dimension import MultiDimension
    from incubator_brpc_tpu.metrics.variable import _registry, list_exposed

    out: List[Finding] = []
    for name in list_exposed():
        var = _registry.get(name)
        if var is None:
            continue
        if isinstance(var, MultiDimension):
            continue  # renders one line per labeled sub-variable
        try:
            v = var.get_value()
        except Exception as e:  # noqa: BLE001 — a raising variable IS the bug
            out.append(
                Finding(
                    rule="metrics-unrenderable",
                    key=name,
                    message=f"exposed variable {name}.get_value() raised {e!r}",
                )
            )
            continue
        if isinstance(v, bool) or isinstance(v, (int, float)):
            continue
        out.append(
            Finding(
                rule="metrics-unrenderable",
                key=name,
                message=(
                    f"exposed variable {name} has non-numeric value "
                    f"{type(v).__name__} — it will not render on /metrics"
                ),
            )
        )
    return out


# ---------------------------------------------------------------------------
# _tls save/restore balance
# ---------------------------------------------------------------------------


def _is_tls_store(node: ast.stmt) -> List[str]:
    """Return the _tls attribute names stored by this statement."""
    out = []
    if isinstance(node, ast.Assign):
        targets = node.targets
    elif isinstance(node, ast.AnnAssign) and node.value is not None:
        targets = [node.target]
    else:
        return out
    for t in targets:
        if isinstance(t, ast.Attribute):
            v = t.value
            if isinstance(v, ast.Name) and v.id == "_tls":
                out.append(t.attr)
            elif (
                isinstance(v, ast.Attribute)
                and v.attr == "_tls"
                and isinstance(v.value, ast.Name)
                and v.value.id == "self"
            ):
                out.append(t.attr)
        elif isinstance(t, ast.Tuple):
            for el in t.elts:
                out.extend(_is_tls_store_target(el))
    return out


def _is_tls_store_target(t: ast.expr) -> List[str]:
    if isinstance(t, ast.Attribute):
        v = t.value
        if isinstance(v, ast.Name) and v.id == "_tls":
            return [t.attr]
    return []


def run_tls_lint(pkg_root: str) -> List[Finding]:
    out: List[Finding] = []
    for path in iter_py_files(pkg_root):
        rel = os.path.relpath(path, pkg_root)
        tree = ast.parse(_read(path), filename=path)
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            plain: Dict[str, int] = {}  # attr -> first store line
            restored: Dict[str, bool] = {}
            for sub in _walk_shallow(node):
                if isinstance(sub, ast.Try):
                    for fin_stmt in sub.finalbody:
                        for st in ast.walk(fin_stmt):
                            if isinstance(st, ast.stmt):
                                for a in _is_tls_store(st):
                                    restored[a] = True
                if isinstance(sub, ast.stmt):
                    for a in _is_tls_store(sub):
                        plain.setdefault(a, sub.lineno)
            for attr, line in plain.items():
                if not restored.get(attr):
                    out.append(
                        Finding(
                            rule="tls-restore",
                            key=f"{rel}:{node.name}:{attr}",
                            message=(
                                f"{rel}:{node.name} stores _tls.{attr} with "
                                f"no restoring store in a finally block"
                            ),
                            file=rel,
                            line=line,
                        )
                    )
    return out


# ---------------------------------------------------------------------------
# completion guards (exactly-once resolution)
# ---------------------------------------------------------------------------

# Each entry names a completion path and how its exactly-once guard
# must look.  types:
#   flag-guard  — method starts by returning early when self.<attr> is
#                 already set, and sets self.<attr> before fan-out
#   none-swap   — the callback attr is swapped to None before invocation
#   fanout-try  — every call to <leaf>() inside a for-loop is wrapped in
#                 try/except so one row cannot strand the rest
COMPLETION_GUARDS = (
    {
        "module": "batching/batcher.py",
        "qualname": "_Scatter.__call__",
        "type": "flag-guard",
        "attr": "called",
    },
    {
        "module": "batching/batcher.py",
        "qualname": "_Scatter.__call__",
        "type": "fanout-try",
        "leaf": "done",
    },
    {
        "module": "batching/batcher.py",
        "qualname": "Batcher._shed",
        "type": "fanout-try",
        "leaf": "done",
    },
    {
        "module": "streaming/stream.py",
        "qualname": "Stream._mark_closed",
        "type": "flag-guard",
        "attr": "_closed",
    },
    {
        "module": "streaming/generate.py",
        "qualname": "DecodeLoop._finish_row",
        "type": "none-swap",
        "attr": "on_finish",
    },
)


def _find_method(tree: ast.Module, qualname: str) -> Optional[ast.AST]:
    parts = qualname.split(".")
    scope: List[ast.stmt] = tree.body
    node: Optional[ast.AST] = None
    for i, part in enumerate(parts):
        node = None
        for n in scope:
            if (
                isinstance(n, (ast.ClassDef, ast.FunctionDef, ast.AsyncFunctionDef))
                and n.name == part
            ):
                node = n
                break
        if node is None:
            return None
        scope = getattr(node, "body", [])
    return node


def _check_flag_guard(fn: ast.AST, attr: str) -> bool:
    """Early return conditioned on self.<attr> (possibly under a lock),
    and a `self.<attr> = True` store."""
    has_guard = False
    has_set = False
    for node in ast.walk(fn):
        if isinstance(node, ast.If):
            for t in ast.walk(node.test):
                if (
                    isinstance(t, ast.Attribute)
                    and t.attr == attr
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    if any(isinstance(s, ast.Return) for s in node.body):
                        has_guard = True
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if (
                    isinstance(t, ast.Attribute)
                    and t.attr == attr
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    has_set = True
    return has_guard and has_set


def _check_none_swap(fn: ast.AST, attr: str) -> bool:
    """A store that Nones <obj>.<attr> (plain or tuple-swap form)."""
    for node in ast.walk(fn):
        if not isinstance(node, ast.Assign):
            continue
        for t in node.targets:
            elts = t.elts if isinstance(t, ast.Tuple) else [t]
            vals = (
                node.value.elts
                if isinstance(node.value, ast.Tuple)
                else [node.value]
            )
            for el, val in zip(elts, vals):
                if (
                    isinstance(el, ast.Attribute)
                    and el.attr == attr
                    and isinstance(val, ast.Constant)
                    and val.value is None
                ):
                    return True
    return False


def _check_fanout_try(fn: ast.AST, leaf: str) -> bool:
    """Every <row>.<leaf>() call inside a for-loop is under a Try."""
    ok = True
    for node in ast.walk(fn):
        if not isinstance(node, ast.For):
            continue
        for sub in ast.walk(node):
            if (
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr == leaf
            ):
                # is this call lexically inside a Try within the loop?
                if not _inside_try(node, sub):
                    ok = False
    return ok


def _inside_try(root: ast.AST, target: ast.AST) -> bool:
    found = [False]

    def walk(n, in_try):
        if n is target:
            found[0] = found[0] or in_try
            return
        for child in ast.iter_child_nodes(n):
            walk(child, in_try or isinstance(n, ast.Try))

    walk(root, False)
    return found[0]


def run_completion_lint(pkg_root: str, guards=COMPLETION_GUARDS) -> List[Finding]:
    out: List[Finding] = []
    trees: Dict[str, ast.Module] = {}
    for g in guards:
        mod = g["module"]
        if mod not in trees:
            path = os.path.join(pkg_root, mod)
            if not os.path.exists(path):
                out.append(
                    Finding(
                        rule="completion-guard",
                        key=f"{mod}:{g['qualname']}",
                        message=f"configured completion path {mod} missing",
                        file=mod,
                    )
                )
                continue
            trees[mod] = ast.parse(_read(path), filename=path)
        fn = _find_method(trees[mod], g["qualname"])
        if fn is None:
            out.append(
                Finding(
                    rule="completion-guard",
                    key=f"{mod}:{g['qualname']}",
                    message=(
                        f"completion path {g['qualname']} not found in {mod} "
                        f"— update analysis config if it moved"
                    ),
                    file=mod,
                )
            )
            continue
        kind = g["type"]
        if kind == "flag-guard":
            ok = _check_flag_guard(fn, g["attr"])
            desc = f"exactly-once flag guard on self.{g['attr']}"
        elif kind == "none-swap":
            ok = _check_none_swap(fn, g["attr"])
            desc = f"swap-to-None of .{g['attr']} before invocation"
        elif kind == "fanout-try":
            ok = _check_fanout_try(fn, g["leaf"])
            desc = (
                f"per-row try/except around .{g['leaf']}() fan-out (one "
                f"row's failure must not strand its batch-mates)"
            )
        else:
            raise ValueError(kind)
        if not ok:
            out.append(
                Finding(
                    rule="completion-guard",
                    key=f"{mod}:{g['qualname']}:{kind}",
                    message=f"{mod}:{g['qualname']} lost its {desc}",
                    file=mod,
                    line=getattr(fn, "lineno", 0),
                )
            )
    return out


# ---------------------------------------------------------------------------
# except-swallow (protocols/ + streaming/)
# ---------------------------------------------------------------------------

EXCEPT_DIRS = ("protocols", "streaming")

# a handler containing any of these is considered to surface the error
_SURFACING_LEAFS = {
    "set_failed",
    "log_error",
    "log_warn",
    "log_info",
    "bad",
    "try_others",
    "not_enough",
    "reset",
    "cancel",
}


def _walk_shallow(fn: ast.AST):
    """ast.walk that does not descend into nested function defs — a
    nested def's handlers belong to the nested def, not its parent."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        n = stack.pop()
        yield n
        if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            stack.extend(ast.iter_child_nodes(n))


def run_except_lint(pkg_root: str, dirs=EXCEPT_DIRS) -> List[Finding]:
    out: List[Finding] = []
    for d in dirs:
        droot = os.path.join(pkg_root, d)
        if not os.path.isdir(droot):
            continue
        for path in iter_py_files(droot):
            rel = os.path.join(d, os.path.relpath(path, droot))
            tree = ast.parse(_read(path), filename=path)
            # map handlers to their INNERMOST enclosing function
            for node in ast.walk(tree):
                if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                for sub in _walk_shallow(node):
                    if not isinstance(sub, ast.Try):
                        continue
                    for h in sub.handlers:
                        if not _is_broad(h):
                            continue
                        if _handler_surfaces(h):
                            continue
                        out.append(
                            Finding(
                                rule="except-swallow",
                                key=f"{rel}:{node.name}:{h.lineno}",
                                message=(
                                    f"{rel}:{node.name} broad except at line "
                                    f"{h.lineno} swallows the failure "
                                    f"(no re-raise / set_failed / error "
                                    f"sentinel / log)"
                                ),
                                file=rel,
                                line=h.lineno,
                            )
                        )
    return out


def _is_broad(h: ast.excepthandler) -> bool:
    if h.type is None:
        return True
    t = h.type
    names = []
    if isinstance(t, ast.Name):
        names = [t.id]
    elif isinstance(t, ast.Tuple):
        names = [e.id for e in t.elts if isinstance(e, ast.Name)]
    return any(n in ("Exception", "BaseException") for n in names)


def _handler_surfaces(h: ast.excepthandler) -> bool:
    for node in ast.walk(h):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Return) and node.value is not None:
            # returning a value (error code / sentinel) surfaces it,
            # unless it is literally `return None`
            if not (
                isinstance(node.value, ast.Constant)
                and node.value.value is None
            ):
                return True
        if isinstance(node, ast.Call):
            f = node.func
            leaf = (
                f.attr
                if isinstance(f, ast.Attribute)
                else f.id if isinstance(f, ast.Name) else ""
            )
            if leaf in _SURFACING_LEAFS:
                return True
    return False


# ---------------------------------------------------------------------------
# aggregate
# ---------------------------------------------------------------------------


def run_all(repo_root: str, pkg_root: str) -> List[Finding]:
    out: List[Finding] = []
    out.extend(run_chaos_site_lint(repo_root))
    out.extend(run_metrics_lint())
    out.extend(run_tls_lint(pkg_root))
    out.extend(run_completion_lint(pkg_root))
    out.extend(run_except_lint(pkg_root))
    return out
