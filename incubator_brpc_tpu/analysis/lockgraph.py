"""Lock-acquisition graph + the three lock-discipline rules.

Pass 1 walks every function with a lexical "held set": a ``with
<lock>`` body extends the held set; acquisitions, calls, and flagged
operations are recorded against the locks held at that point.

Pass 2 resolves calls (``self.m()``, same-module functions, imported
package modules, known factory idioms like ``get_timer_thread()``) and
computes each function's transitive may-acquire set, producing
inter-module edges: *lock A is held while lock B is acquired*.

Rules emitted (as findings, allowlistable by stable key):

- ``lock-order-cycle``      the edge graph (static ∪ manifest) has a
                            cycle — a real inversion.
- ``lock-order-new-edge``   a static edge absent from the checked-in
                            manifest (``lock_order.json``) — review it,
                            then either fix the code or add the edge
                            with a justification.  Violations are
                            diffs, not noise.
- ``blocking-under-lock``   a blocking operation (sleep, socket send,
                            ``StreamWait``/flow wait, ``condition.wait``
                            on a FOREIGN lock, device dispatch, join)
                            runs while a lock is held.
- ``callback-under-lock``   a user/foreign callback (``done()``, stream
                            handler hooks, hook slots, observers) is
                            invoked while an internal lock is held.

Resolution is deliberately conservative: an attribute acquisition on an
object of unknown type resolves only when the attribute name maps to
exactly one lock in the whole package.  Unresolved acquisitions are
counted (see ``GraphResult.unresolved``) but never guessed.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from incubator_brpc_tpu.analysis.findings import Finding
from incubator_brpc_tpu.analysis.inventory import (
    Inventory,
    _ctor_kind,
    _threading_aliases,
    iter_py_files,
)

# ---------------------------------------------------------------------------
# rule configuration
# ---------------------------------------------------------------------------

# leaf callable names considered blocking.  `wait`/`wait_for` get the
# own-condition exemption (waiting on a held lock's OWN condition
# releases it — that is what conditions are for).
BLOCKING_LEAFS = {
    "sleep": "time.sleep",
    "sleep_us": "chaos sleep",
    "wait": "wait on a lock/event",
    "wait_for": "condition wait",
    "join": "thread/task join",
    "sendall": "socket send",
    "connect": "socket connect",
    "accept": "socket accept",
    "recv": "socket recv",
    "select": "fd select",
    "run": None,  # only subprocess.run (checked by receiver) blocks
    "write": "socket/stream write",  # transport sends; IOBuf has no write()
    "write_device": "stream device write",
    "block_until_ready": "device sync",
    "device_put": "device transfer",
    "wait_established": "stream establish wait",
}

# receivers whose `.run(` IS blocking
_BLOCKING_RUN_RECEIVERS = {"subprocess"}

# leaf names that are user/foreign callbacks when invoked as a bare
# statement (for effect).  `done()` status *checks* appear in
# conditions, not statements, so they never match.
CALLBACK_LEAFS = {
    "done",
    "on_received_messages",
    "on_closed",
    "on_failed",
    "on_half_close",
    "on_frame",
    "on_finish",
    "emit",
    "_consumer",
    "_batch_fn",
    "_chaos_hook",
    "_dispatcher_hook",
    "_scheduler_hook",
    "_wait_recorder",
    "_task_queue_observer",
    "callback",
    "cb",
}

# factory idiom → (module, class) of the returned object
FACTORIES = {
    "get_timer_thread": ("runtime/timer_thread.py", "TimerThread"),
    "get_task_control": ("runtime/scheduler.py", "TaskControl"),
}

# call depth for blocking propagation: direct + callees that directly
# block.  Deeper chains surface as lock edges instead (a deep block
# almost always involves a condition/lock we can see).
_BLOCK_DEPTH = 1


@dataclass
class Acq:
    lock: str  # canonical base lock name
    line: int


@dataclass
class CallSite:
    callee: Optional[Tuple[str, Optional[str], str]]  # (module, cls, name)
    leaf: str
    receiver: Optional[str]  # textual receiver root, best-effort
    recv_lock: Optional[str]  # receiver resolved to a lock (for .wait)
    line: int
    held: Tuple[str, ...]
    is_stmt: bool  # standalone expression statement


@dataclass
class FuncInfo:
    key: Tuple[str, Optional[str], str]
    direct: List[Acq] = field(default_factory=list)  # acquisitions (any held)
    acq_under: List[Tuple[str, Acq]] = field(default_factory=list)  # (held, acq)
    calls: List[CallSite] = field(default_factory=list)
    blocks_at: List[Tuple[str, int]] = field(default_factory=list)  # (what, line)


@dataclass
class Edge:
    src: str
    dst: str
    module: str
    line: int
    via: str  # "" for a direct nested with, else the call chain


@dataclass
class GraphResult:
    edges: List[Edge]
    findings: List[Finding]
    funcs: Dict[Tuple[str, Optional[str], str], FuncInfo]
    unresolved: List[Tuple[str, int, str]]  # (module, line, expr text)

    def edge_pairs(self) -> Set[Tuple[str, str]]:
        return {(e.src, e.dst) for e in self.edges}


# ---------------------------------------------------------------------------
# per-module function walker
# ---------------------------------------------------------------------------


class _FuncWalker:
    """Walks one function body threading the lexical held set."""

    def __init__(self, scan: "_GraphScan", key, cls: Optional[str]):
        self.scan = scan
        self.inv = scan.inv
        self.module = scan.module
        self.cls = cls
        self.info = FuncInfo(key=key)
        self.local_types: Dict[str, Tuple[str, Optional[str]]] = {}

    # ---- lock reference resolution ----
    def resolve_lock(self, expr: ast.expr) -> Optional[str]:
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
        ):
            root = expr.value.id
            if root == "self" and self.cls:
                site = self.inv.lookup_attr(self.module, self.cls, expr.attr)
                if site is not None:
                    return site.base()
                return None
            # module-alias global: mod._lock
            target = self.scan.imports.get(root)
            if target is not None:
                site = self.inv.lookup_attr(target, None, expr.attr)
                if site is not None:
                    return site.base()
            # typed local: obj._lock where obj's class is tracked
            lt = self.local_types.get(root)
            if lt is not None:
                site = self.inv.lookup_attr(lt[0], lt[1], expr.attr)
                if site is not None:
                    return site.base()
            # unique attribute name anywhere in the package
            site = self.inv.unique_attr(expr.attr)
            if site is not None:
                return site.base()
            return None
        if isinstance(expr, ast.Name):
            site = self.inv.lookup_attr(self.module, None, expr.id)
            if site is not None:
                return site.base()
            site = self.inv.lookup_attr(
                self.module, None if self.cls is None else self.cls, expr.id
            )
            if site is not None:
                return site.base()
            # function-local lock
            fname = self.info.key[2]
            s = self.inv.by_owner.get((self.module, self.cls, expr.id))
            if s is not None:
                return s.base()
            local = f"{self.module}:{fname}.{expr.id}"
            for site2 in self.inv.sites:
                if site2.name == local:
                    return site2.base()
        return None

    # ---- call resolution ----
    def resolve_call(self, call: ast.Call):
        """→ (callee key or None, leaf name, receiver root, recv_lock)."""
        f = call.func
        if isinstance(f, ast.Name):
            leaf = f.id
            key = (self.module, None, leaf)
            if key in self.scan.all_funcs:
                return key, leaf, None, None
            imported = self.scan.from_imports.get(leaf)
            if imported is not None:
                return imported, leaf, None, None
            return None, leaf, None, None
        if isinstance(f, ast.Attribute):
            leaf = f.attr
            recv = f.value
            recv_lock = None
            if isinstance(recv, ast.Name):
                root = recv.id
                if root == "self" and self.cls:
                    key = self._class_method(self.module, self.cls, leaf)
                    if key is not None:
                        return key, leaf, "self", None
                    return None, leaf, "self", None
                target = self.scan.imports.get(root)
                if target is not None:
                    key = (target, None, leaf)
                    if key in self.scan.all_funcs:
                        return key, leaf, root, None
                    return None, leaf, root, None
                lt = self.local_types.get(root)
                if lt is not None:
                    key = self._class_method(lt[0], lt[1], leaf)
                    if key is not None:
                        return key, leaf, root, None
                return None, leaf, root, None
            if isinstance(recv, ast.Attribute):
                # self._cond.wait() — resolve the receiver as a lock
                recv_lock = self.resolve_lock(recv)
                # self.attr.method(): try unique-class resolution off the
                # attr's tracked type? conservative: no
                root = None
                if isinstance(recv.value, ast.Name):
                    root = f"{recv.value.id}.{recv.attr}"
                return None, leaf, root, recv_lock
            if isinstance(recv, ast.Call):
                # factory idiom: get_timer_thread().schedule(...)
                rf = recv.func
                fname = rf.id if isinstance(rf, ast.Name) else (
                    rf.attr if isinstance(rf, ast.Attribute) else None
                )
                if fname in FACTORIES:
                    mod, cls = FACTORIES[fname]
                    key = self._class_method(mod, cls, leaf)
                    if key is not None:
                        return key, leaf, fname + "()", None
                return None, leaf, None, None
            return None, leaf, None, None
        return None, "", None, None

    def _class_method(self, module, cls, name):
        key = (module, cls, name)
        if key in self.scan.all_funcs:
            return key
        for b in self.inv.bases.get((module, cls), []):
            k = self._class_method(module, b, name)
            if k is not None:
                return k
        return None

    # ---- body walk ----
    def walk(self, body: List[ast.stmt], held: Tuple[str, ...]):
        for stmt in body:
            self._stmt(stmt, held)

    def _stmt(self, stmt: ast.stmt, held: Tuple[str, ...]):
        if isinstance(stmt, ast.With):
            new_held = held
            for item in stmt.items:
                self._scan_expr(item.context_expr, new_held, is_stmt=False)
                lk = self.resolve_lock(item.context_expr)
                if lk is None and isinstance(
                    item.context_expr, (ast.Attribute, ast.Name)
                ):
                    txt = ast.unparse(item.context_expr)
                    if "lock" in txt.lower() or "cond" in txt.lower():
                        self.scan.unresolved.append(
                            (self.module, stmt.lineno, txt)
                        )
                if lk is not None:
                    acq = Acq(lk, stmt.lineno)
                    self.info.direct.append(acq)
                    for h in new_held:
                        if h != lk:
                            self.info.acq_under.append((h, acq))
                    if lk not in new_held:
                        new_held = new_held + (lk,)
            self.walk(stmt.body, new_held)
            return
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested def: body runs later, not under the current held
            # set — walk it with an empty held set as its own scope
            self.walk(stmt.body, ())
            return
        if isinstance(stmt, ast.ClassDef):
            return
        # track simple local types: x = Factory() / x = pkgClass(...)
        if isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Call):
            fn = stmt.value.func
            fname = fn.id if isinstance(fn, ast.Name) else None
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    if fname in FACTORIES:
                        self.local_types[t.id] = FACTORIES[fname]
                    elif fname in self.scan.imported_classes:
                        self.local_types[t.id] = self.scan.imported_classes[
                            fname
                        ]
                    elif fname in self.scan.local_classes:
                        self.local_types[t.id] = (self.module, fname)
        # expression statements: callback detection needs stmt context
        if isinstance(stmt, ast.Expr):
            self._scan_expr(stmt.value, held, is_stmt=True)
        else:
            for fld, value in ast.iter_fields(stmt):
                if fld in ("body", "orelse", "finalbody"):
                    continue
                if isinstance(value, ast.expr):
                    self._scan_expr(value, held, is_stmt=False)
                elif isinstance(value, list):
                    for v in value:
                        if isinstance(v, ast.expr):
                            self._scan_expr(v, held, is_stmt=False)
                        elif isinstance(v, ast.excepthandler):
                            pass
        # recurse into block bodies with the same held set
        for fld in ("body", "orelse", "finalbody"):
            sub = getattr(stmt, fld, None)
            if sub:
                self.walk(sub, held)
        for h in getattr(stmt, "handlers", []) or []:
            self.walk(h.body, held)

    def _scan_expr(self, expr: ast.expr, held: Tuple[str, ...], is_stmt: bool):
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            callee, leaf, recv, recv_lock = self.resolve_call(node)
            # lambda bodies execute later — but ast.walk(expr) still
            # reaches them; accept the small over-approximation (a
            # lambda built under a lock usually runs related code)
            self.info.calls.append(
                CallSite(
                    callee=callee,
                    leaf=leaf,
                    receiver=recv,
                    recv_lock=recv_lock,
                    line=node.lineno,
                    held=held,
                    is_stmt=is_stmt and node is expr,
                )
            )


# ---------------------------------------------------------------------------
# module scan: function discovery + imports
# ---------------------------------------------------------------------------


class _GraphScan:
    def __init__(self, inv: Inventory, module: str, tree: ast.Module, pkg: str):
        self.inv = inv
        self.module = module
        self.pkg = pkg  # e.g. "incubator_brpc_tpu"
        self.imports: Dict[str, str] = {}  # alias -> module relpath
        self.from_imports: Dict[str, Tuple[str, Optional[str], str]] = {}
        self.imported_classes: Dict[str, Tuple[str, Optional[str]]] = {}
        self.local_classes: Dict[str, bool] = {}
        self.all_funcs: Set[Tuple[str, Optional[str], str]] = set()
        self.func_nodes: List[Tuple[Tuple[str, Optional[str], str], Optional[str], ast.AST]] = []
        self.unresolved: List[Tuple[str, int, str]] = []
        self.tree = tree
        self.mod_aliases, self.ctor_names = _threading_aliases(tree)
        self._collect(tree)

    def _relmod(self, dotted: str) -> Optional[str]:
        if not dotted.startswith(self.pkg + "."):
            return None
        rel = dotted[len(self.pkg) + 1 :].replace(".", "/") + ".py"
        return rel

    def _collect(self, tree: ast.Module):
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    rel = self._relmod(a.name)
                    if rel is not None:
                        self.imports[(a.asname or a.name.rsplit(".", 1)[-1])] = rel
            elif isinstance(node, ast.ImportFrom):
                if node.module is None:
                    continue
                rel = self._relmod(node.module)
                for a in node.names:
                    alias = a.asname or a.name
                    if rel is not None:
                        # `from pkg.mod import thing`: thing may be a
                        # function (call target) or a class
                        self.from_imports[alias] = (rel, None, a.name)
                        if a.name[:1].isupper():
                            self.imported_classes[alias] = (rel, a.name)
                    else:
                        sub = self._relmod(f"{node.module}.{a.name}")
                        if sub is not None:
                            self.imports[alias] = sub
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                key = (self.module, None, node.name)
                self.all_funcs.add(key)
                self.func_nodes.append((key, None, node))
            elif isinstance(node, ast.ClassDef):
                self.local_classes[node.name] = True
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        key = (self.module, node.name, sub.name)
                        self.all_funcs.add(key)
                        self.func_nodes.append((key, node.name, sub))


# ---------------------------------------------------------------------------
# build + rules
# ---------------------------------------------------------------------------


def build_graph(
    inv: Inventory,
    pkg_name: str = "incubator_brpc_tpu",
    root: Optional[str] = None,
) -> GraphResult:
    root = root or inv.root
    scans: List[_GraphScan] = []
    for path in iter_py_files(root):
        rel = os.path.relpath(path, root)
        with open(path, "r", encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=path)
        scans.append(_GraphScan(inv, rel, tree, pkg_name))

    all_funcs: Set[Tuple[str, Optional[str], str]] = set()
    for s in scans:
        all_funcs.update(s.all_funcs)
    for s in scans:
        s.all_funcs = all_funcs  # cross-module call resolution

    funcs: Dict[Tuple[str, Optional[str], str], FuncInfo] = {}
    unresolved: List[Tuple[str, int, str]] = []
    for s in scans:
        for key, cls, node in s.func_nodes:
            w = _FuncWalker(s, key, cls)
            w.walk(node.body, ())
            funcs[key] = w.info
        unresolved.extend(s.unresolved)

    # transitive may-acquire (memoized DFS, cycle-safe)
    memo: Dict[Tuple[str, Optional[str], str], Dict[str, str]] = {}

    def may_acquire(key, stack=()):
        if key in memo:
            return memo[key]
        if key in stack:
            return {}
        info = funcs.get(key)
        if info is None:
            return {}
        out: Dict[str, str] = {}
        for acq in info.direct:
            out.setdefault(acq.lock, "")
        for c in info.calls:
            if c.callee is None:
                continue
            sub = may_acquire(c.callee, stack + (key,))
            label = _fmt_key(c.callee)
            for lk, via in sub.items():
                out.setdefault(lk, label + (" -> " + via if via else ""))
        memo[key] = out
        return out

    # direct-block set (for _BLOCK_DEPTH=1 propagation)
    def directly_blocks(info: FuncInfo) -> Optional[str]:
        for c in info.calls:
            what = _blocking_kind(c)
            if what is not None:
                return what
        return None

    blocks: Dict[Tuple[str, Optional[str], str], str] = {}
    for key, info in funcs.items():
        w = directly_blocks(info)
        if w is not None:
            blocks[key] = w

    edges: List[Edge] = []
    findings: List[Finding] = []
    for key, info in funcs.items():
        module = key[0]
        # direct nested-with edges
        for held, acq in info.acq_under:
            edges.append(Edge(held, acq.lock, module, acq.line, ""))
        for c in info.calls:
            # transitive lock edges through resolved calls
            if c.callee is not None and c.held:
                for lk, via in may_acquire(c.callee).items():
                    for h in c.held:
                        if h != lk:
                            chain = _fmt_key(c.callee) + (
                                " -> " + via if via else ""
                            )
                            edges.append(Edge(h, lk, module, c.line, chain))
            # blocking-under-lock
            if c.held:
                what = _blocking_kind(c)
                if what is None and c.callee is not None and _BLOCK_DEPTH:
                    if c.callee in blocks and c.callee != key:
                        what = f"calls {_fmt_key(c.callee)} which {blocks[c.callee]}"
                if what is not None:
                    lockset = ",".join(c.held)
                    findings.append(
                        Finding(
                            rule="blocking-under-lock",
                            key=f"{module}:{key[2]}:{c.leaf}:{lockset}",
                            message=(
                                f"{_fmt_key(key)} holds [{lockset}] while "
                                f"{c.leaf}() may block ({what})"
                            ),
                            file=module,
                            line=c.line,
                        )
                    )
            # callback-under-lock
            if c.held and c.is_stmt and c.leaf in CALLBACK_LEAFS:
                lockset = ",".join(c.held)
                findings.append(
                    Finding(
                        rule="callback-under-lock",
                        key=f"{module}:{key[2]}:{c.leaf}:{lockset}",
                        message=(
                            f"{_fmt_key(key)} invokes callback {c.leaf}() "
                            f"while holding [{lockset}]"
                        ),
                        file=module,
                        line=c.line,
                    )
                )

    # dedupe edges on (src, dst), keeping the first example
    seen: Dict[Tuple[str, str], Edge] = {}
    for e in edges:
        seen.setdefault((e.src, e.dst), e)
    return GraphResult(
        edges=list(seen.values()),
        findings=findings,
        funcs=funcs,
        unresolved=unresolved,
    )


def _fmt_key(key) -> str:
    module, cls, name = key
    return f"{module}:{cls + '.' if cls else ''}{name}"


def _blocking_kind(c: CallSite) -> Optional[str]:
    if c.leaf not in BLOCKING_LEAFS:
        return None
    what = BLOCKING_LEAFS[c.leaf]
    if c.leaf == "run":
        if c.receiver in _BLOCKING_RUN_RECEIVERS:
            return "subprocess.run"
        return None
    if c.leaf in ("wait", "wait_for"):
        # waiting on the OWN condition of the sole held lock releases it
        if c.recv_lock is not None and c.held == (c.recv_lock,):
            return None
        if c.recv_lock is not None and c.recv_lock in c.held and len(c.held) > 1:
            others = [h for h in c.held if h != c.recv_lock]
            return f"cond wait releases only {c.recv_lock}; still holds {others}"
        if c.recv_lock is None and c.receiver in ("self", None):
            # unresolved receiver on self: likely an Event — still a
            # block while holding a lock
            return what
        if c.recv_lock is not None and c.recv_lock not in c.held:
            return f"wait on foreign lock {c.recv_lock}"
        return what
    return what


# ---------------------------------------------------------------------------
# cycle detection over static ∪ manifest edges
# ---------------------------------------------------------------------------


def find_cycles(pairs: Set[Tuple[str, str]]) -> List[List[str]]:
    graph: Dict[str, List[str]] = {}
    for a, b in pairs:
        graph.setdefault(a, []).append(b)
        graph.setdefault(b, [])
    WHITE, GREY, BLACK = 0, 1, 2
    color = {n: WHITE for n in graph}
    cycles: List[List[str]] = []
    path: List[str] = []

    def dfs(n):
        color[n] = GREY
        path.append(n)
        for m in graph[n]:
            if color[m] == GREY:
                i = path.index(m)
                cyc = path[i:] + [m]
                cycles.append(cyc)
            elif color[m] == WHITE:
                dfs(m)
        path.pop()
        color[n] = BLACK

    for n in sorted(graph):
        if color[n] == WHITE:
            dfs(n)
    return cycles
