"""Concurrency-correctness toolchain (static analysis + runtime witness).

Six PRs of growth made this a deeply concurrent system: window chaining
in the batcher, StreamWait flow control, ExecutionQueue bursts,
TimerThread re-aiming, chaos hook slots.  The last two review passes
each caught latent races by hand; this package replaces reviewer
heroics with machine-checked discipline:

- ``inventory``   — AST census of every ``Lock``/``RLock``/``Condition``
                    construction site in the package (~100+ sites), with
                    ``Condition(existing_lock)`` aliasing resolved.
- ``lockgraph``   — the inter-module lock-acquisition graph (which lock
                    is taken while which is held, including transitive
                    acquisitions through resolved calls), plus the
                    blocking-under-lock and callback-under-lock rules.
- ``invariants``  — project-invariant lints: chaos sites are documented
                    and tested, registered metrics render on /metrics,
                    ``_tls`` saves restore on all paths, completion
                    paths resolve each row exactly once, and broad
                    ``except Exception`` handlers in protocols/streaming
                    cannot swallow ERPC-coded failures.
- ``witness``     — runtime lock-witness mode: records ACTUAL
                    acquisition orders while the test suite runs and
                    cross-checks them against the static manifest, so
                    the analyzer is validated by execution.

The canonical lock-order manifest (``lock_order.json``) and the
violation allowlist (``allowlist.json``) are checked in next to this
file: new acquisitions show up as diffs, not noise.  Drive everything
through ``tools/check.py`` (see docs/analysis.md).
"""

from incubator_brpc_tpu.analysis.findings import (  # noqa: F401
    Allowlist,
    Finding,
    load_allowlist,
)
from incubator_brpc_tpu.analysis.inventory import (  # noqa: F401
    LockSite,
    build_inventory,
)

PACKAGE_ROOT = __name__.rsplit(".", 1)[0]  # "incubator_brpc_tpu"
