"""Finding/Allowlist plumbing shared by every analyzer rule.

A Finding is one violation with a STABLE key, so the checked-in
allowlist can name it exactly and a new violation is always a diff.
Allowlist entries must carry a one-line justification and must all be
USED — a stale entry (its violation no longer exists) fails the check,
keeping the list honest in both directions.
"""

from __future__ import annotations

import fnmatch
import json
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


@dataclass
class Finding:
    rule: str  # e.g. "blocking-under-lock"
    key: str  # stable id used for allowlisting
    message: str
    file: str = ""
    line: int = 0

    def format(self) -> str:
        loc = f"{self.file}:{self.line}: " if self.file else ""
        return f"{loc}[{self.rule}] {self.message}  (key: {self.key})"


@dataclass
class Allowlist:
    """entries: [{"rule": ..., "key": ..., "why": ...}] — key may be an
    fnmatch pattern.  Every entry must justify itself and must match at
    least one finding when `strict_unused` reporting runs."""

    entries: List[dict] = field(default_factory=list)
    path: str = ""

    def __post_init__(self):
        for e in self.entries:
            if not e.get("why", "").strip():
                raise ValueError(
                    f"allowlist entry {e.get('rule')}/{e.get('key')} in "
                    f"{self.path} has no justification ('why')"
                )

    def match(self, finding: Finding) -> Optional[dict]:
        for e in self.entries:
            if e.get("rule") not in (finding.rule, "*"):
                continue
            if fnmatch.fnmatchcase(finding.key, e.get("key", "")):
                return e
        return None

    def split(
        self, findings: List[Finding]
    ) -> Tuple[List[Finding], List[Finding], List[dict]]:
        """→ (violations, allowed, unused_entries)."""
        used: Dict[int, bool] = {}
        violations, allowed = [], []
        for f in findings:
            e = self.match(f)
            if e is None:
                violations.append(f)
            else:
                allowed.append(f)
                used[id(e)] = True
        unused = [e for e in self.entries if id(e) not in used]
        return violations, allowed, unused


def load_allowlist(path: str) -> Allowlist:
    if not os.path.exists(path):
        return Allowlist([], path)
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    return Allowlist(data.get("entries", []), path)


# marker --update-manifest (and hand-copied entries) leave in a not-yet
# -reviewed justification; see todo_review_findings below
TODO_REVIEW_MARKER = "TODO review"


def todo_review_findings(allowlist: Allowlist) -> List[Finding]:
    """Entries whose `why` still contains the auto-generated
    ``TODO review`` placeholder: a justification nobody wrote yet is
    not a justification, and without this check the placeholder would
    silently become permanent."""
    out: List[Finding] = []
    for e in allowlist.entries:
        if TODO_REVIEW_MARKER in e.get("why", ""):
            out.append(
                Finding(
                    rule="todo-review-why",
                    key=f"allowlist/{e.get('rule')}/{e.get('key')}",
                    message=(
                        f"allowlist entry [{e.get('rule')}] "
                        f"{e.get('key')!r} still carries a "
                        f"'{TODO_REVIEW_MARKER}' placeholder why — write "
                        f"the real justification"
                    ),
                    file=allowlist.path,
                )
            )
    return out
