"""AST census of every Lock/RLock/Condition construction in the package.

Each construction site gets a canonical name::

    <relpath>:<Class>.<attr>       instance attr  (self._lock = Lock())
    <relpath>:<module>.<name>      module global  (_lock = Lock())
    <relpath>:<func>.<name>        function local (rare)

``threading.Condition(self._lock)`` is recorded as an ALIAS of the
wrapped lock — acquiring the condition IS acquiring that lock, so the
graph pass folds aliases onto their base lock and never reports a
self-inversion between a lock and its own condition.

The census is also the bridge between the static and runtime views:
witness mode keys runtime acquisitions by creation ``file:line``, which
maps 1:1 onto these sites.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

LOCK_CTORS = ("Lock", "RLock", "Condition")

# directories never scanned (generated code, caches)
SKIP_DIRS = {"__pycache__", "protos"}


@dataclass
class LockSite:
    name: str  # canonical name (see module docstring)
    kind: str  # "lock" | "rlock" | "condition"
    module: str  # path relative to the scan root, e.g. "batching/batcher.py"
    cls: Optional[str]  # enclosing class, or None
    attr: str  # attribute / variable name
    line: int
    alias_of: Optional[str] = None  # canonical name of the wrapped lock

    def base(self) -> str:
        """The lock this site ultimately guards (alias folded)."""
        return self.alias_of or self.name


@dataclass
class Inventory:
    root: str
    sites: List[LockSite] = field(default_factory=list)
    # (module, cls, attr) -> site  — cls None for module globals
    by_owner: Dict[Tuple[str, Optional[str], str], LockSite] = field(
        default_factory=dict
    )
    # creation (module, line) -> site — the witness-mode join key
    by_creation: Dict[Tuple[str, int], LockSite] = field(default_factory=dict)
    # single-module class inheritance: (module, cls) -> [base names]
    bases: Dict[Tuple[str, str], List[str]] = field(default_factory=dict)

    def add(self, site: LockSite) -> None:
        self.sites.append(site)
        self.by_owner[(site.module, site.cls, site.attr)] = site
        self.by_creation[(site.module, site.line)] = site

    def lookup_attr(
        self, module: str, cls: Optional[str], attr: str
    ) -> Optional[LockSite]:
        """Resolve self.<attr> in (module, cls), walking same-module
        base classes (a subclass acquiring an inherited lock)."""
        site = self.by_owner.get((module, cls, attr))
        if site is not None:
            return site
        if cls is not None:
            for b in self.bases.get((module, cls), []):
                site = self.lookup_attr(module, b, attr)
                if site is not None:
                    return site
        return None

    def unique_attr(self, attr: str) -> Optional[LockSite]:
        """Resolve obj.<attr> when the attr names exactly ONE lock in
        the whole package (e.g. `_registry_lock`); ambiguous names like
        `_lock` stay unresolved rather than guessed."""
        found = [s for s in self.sites if s.attr == attr]
        return found[0] if len(found) == 1 else None


def iter_py_files(root: str) -> List[str]:
    out = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d not in SKIP_DIRS]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                out.append(os.path.join(dirpath, fn))
    return out


def _threading_aliases(tree: ast.Module) -> Tuple[set, set]:
    """→ (module aliases for `threading`, directly imported ctor names)."""
    mod_aliases, ctor_names = set(), set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "threading":
                    mod_aliases.add(a.asname or "threading")
        elif isinstance(node, ast.ImportFrom) and node.module == "threading":
            for a in node.names:
                if a.name in LOCK_CTORS:
                    ctor_names.add(a.asname or a.name)
    return mod_aliases, ctor_names


def _ctor_kind(call: ast.expr, mod_aliases: set, ctor_names: set) -> Optional[str]:
    if not isinstance(call, ast.Call):
        return None
    f = call.func
    name = None
    if (
        isinstance(f, ast.Attribute)
        and isinstance(f.value, ast.Name)
        and f.value.id in mod_aliases
    ):
        name = f.attr
    elif isinstance(f, ast.Name) and f.id in ctor_names:
        name = f.id
    if name in LOCK_CTORS:
        return name.lower()
    return None


class _ModuleScan(ast.NodeVisitor):
    def __init__(self, inv: Inventory, module: str, tree: ast.Module):
        self.inv = inv
        self.module = module
        self.mod_aliases, self.ctor_names = _threading_aliases(tree)
        self.cls: Optional[str] = None
        self.func: Optional[str] = None
        self._pending_aliases: List[Tuple[LockSite, ast.expr]] = []

    # ---- scope tracking ----
    def visit_ClassDef(self, node: ast.ClassDef):
        prev = self.cls
        self.cls = node.name
        self.inv.bases[(self.module, node.name)] = [
            b.id for b in node.bases if isinstance(b, ast.Name)
        ]
        self.generic_visit(node)
        self.cls = prev

    def _visit_func(self, node):
        prev = self.func
        self.func = node.name
        self.generic_visit(node)
        self.func = prev

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    # ---- lock constructions ----
    def visit_Assign(self, node: ast.Assign):
        self._check_assign(node.targets, node.value)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign):
        if node.value is not None:
            self._check_assign([node.target], node.value)
        self.generic_visit(node)

    def _check_assign(self, targets: List[ast.expr], value: ast.expr):
        kind = _ctor_kind(value, self.mod_aliases, self.ctor_names)
        if kind is None:
            return
        for t in targets:
            owner_cls, attr = None, None
            if (
                isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id == "self"
                and self.cls
            ):
                owner_cls, attr = self.cls, t.attr
            elif isinstance(t, ast.Name):
                if self.cls and self.func is None:
                    owner_cls, attr = self.cls, t.id  # class-body attr
                elif self.func is None:
                    owner_cls, attr = None, t.id  # module global
                else:
                    # function-local lock: still a site (census + witness
                    # join), scoped by the enclosing function's name
                    site = LockSite(
                        name=f"{self.module}:{self.func}.{t.id}",
                        kind=kind,
                        module=self.module,
                        cls=self.cls,
                        attr=t.id,
                        line=value.lineno,
                    )
                    self.inv.add(site)
                    continue
            else:
                continue
            scope = owner_cls if owner_cls else "<module>"
            site = LockSite(
                name=f"{self.module}:{scope}.{attr}",
                kind=kind,
                module=self.module,
                cls=owner_cls,
                attr=attr,
                line=value.lineno,
            )
            self.inv.add(site)
            if kind == "condition" and isinstance(value, ast.Call) and value.args:
                self._pending_aliases.append((site, value.args[0]))

    def resolve_aliases(self):
        for site, arg in self._pending_aliases:
            base: Optional[LockSite] = None
            if (
                isinstance(arg, ast.Attribute)
                and isinstance(arg.value, ast.Name)
                and arg.value.id == "self"
            ):
                base = self.inv.lookup_attr(self.module, site.cls, arg.attr)
            elif isinstance(arg, ast.Name):
                base = self.inv.lookup_attr(self.module, None, arg.id)
            if base is not None:
                site.alias_of = base.base()


def build_inventory(root: str) -> Inventory:
    """Scan every .py under `root` (a package directory)."""
    inv = Inventory(root=root)
    scans = []
    for path in iter_py_files(root):
        rel = os.path.relpath(path, root)
        with open(path, "r", encoding="utf-8") as f:
            src = f.read()
        tree = ast.parse(src, filename=path)
        scan = _ModuleScan(inv, rel, tree)
        scan.visit(tree)
        scans.append(scan)
    for scan in scans:
        scan.resolve_aliases()
    return inv
