"""SocketMap — process-global connection sharing + pooling.

Analog of reference SocketMap (socket_map.h:32-80) plus the pooled /
short connection acquisition of socket_inl.h (GetPooledSocket /
GetShortSocket, channel.h:84-89):

- "single" (default): one shared multiplexed connection per
  (EndPoint, channel signature); a non-empty ``connection_group``
  splits sharing (channel.h:130-134).
- "pooled": a free-list of connections per key; each RPC borrows one
  exclusively and returns it when done — the reference's fix for
  correlation-less protocols (HTTP), where responses match by FIFO
  order on the connection.
- "short": a fresh connection per RPC, closed on completion (callers
  use Socket.connect directly; nothing to share here).

Failed sockets are replaced on next acquisition; the old one is handed
to health checking by the caller.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Deque, Dict, Optional, Tuple

from incubator_brpc_tpu import errors
from incubator_brpc_tpu.transport.socket import Socket
from incubator_brpc_tpu.utils.endpoint import EndPoint


class SocketMap:
    def __init__(self):
        self._map: Dict[Tuple[EndPoint, str], int] = {}
        self._pools: Dict[Tuple[EndPoint, str], Deque[int]] = {}
        self._lock = threading.Lock()

    def get_or_create(
        self,
        remote: EndPoint,
        messenger,
        signature: str = "",
        user=None,
        connect_timeout_s: float = 3.0,
        ssl_params=None,
    ) -> Tuple[int, int]:
        """Returns (error_code, sid). Creates/replaces the shared socket
        when missing, failed, or draining."""
        key = (remote, signature)
        with self._lock:
            sid = self._map.get(key)
        if sid is not None:
            sock = Socket.address(sid)
            if sock is not None and not sock.failed and not sock.draining:
                return 0, sid
        # connect outside the map lock (reference creates then inserts)
        err, new_sid = Socket.connect(
            remote, messenger, timeout_s=connect_timeout_s, user=user,
            ssl_params=ssl_params,
        )
        if err:
            return err, 0
        with self._lock:
            cur = self._map.get(key)
            if cur is not None and cur != sid:
                cur_sock = Socket.address(cur)
                if cur_sock is not None and not cur_sock.failed and not cur_sock.draining:
                    # lost the race: keep theirs, drop ours
                    mine = Socket.address(new_sid)
                    if mine is not None:
                        mine.set_failed(0, "duplicate connection")
                        mine.recycle()
                    return 0, cur
            self._map[key] = new_sid
        return 0, new_sid

    # ---- pooled (GetPooledSocket, socket_inl.h) -----------------------------
    def get_pooled(
        self,
        remote: EndPoint,
        messenger,
        signature: str = "",
        user=None,
        connect_timeout_s: float = 3.0,
        ssl_params=None,
    ) -> Tuple[int, int]:
        """Borrow an idle pooled connection or create a fresh one. The
        caller owns the socket exclusively until return_pooled."""
        key = (remote, signature)
        while True:
            with self._lock:
                dq = self._pools.get(key)
                sid = dq.popleft() if dq else None
            if sid is None:
                break
            sock = Socket.address(sid)
            if sock is not None and not sock.failed and not sock.draining:
                return 0, sid
            # dead entry: recycle its slot, then try the next
            if sock is not None:
                if not sock.failed:
                    sock.set_failed(errors.ECLOSE, "pooled entry dead")
                sock.recycle()
        return Socket.connect(
            remote, messenger, timeout_s=connect_timeout_s, user=user,
            connection_type="pooled", ssl_params=ssl_params,
        )

    def return_pooled(self, remote: EndPoint, signature: str, sid: int) -> None:
        """Give a borrowed connection back. Only a CLEAN socket returns
        to the free list: one with a response still owed (written
        request that never answered — timeout, backup loser) would hand
        the NEXT borrower a stale response, the FIFO-misroute this
        connection type exists to prevent."""
        sock = Socket.address(sid)
        if sock is None:
            return
        dirty = (
            sock.failed
            or sock.draining
            or bool(sock.pipelined_info)
            or bool(sock.waiting_cids)
            or not sock.read_buf.empty()
        )
        if dirty:
            if not sock.failed:
                sock.set_failed(errors.ECLOSE, "pooled connection not clean")
            sock.recycle()
            return
        with self._lock:
            self._pools.setdefault((remote, signature), deque()).append(sid)

    def pooled_count(self, remote: EndPoint, signature: str = "") -> int:
        with self._lock:
            return len(self._pools.get((remote, signature), ()))

    def remove(self, remote: EndPoint, signature: str = ""):
        with self._lock:
            self._map.pop((remote, signature), None)
            pool = self._pools.pop((remote, signature), None)
        for sid in pool or ():
            sock = Socket.address(sid)
            if sock is not None:
                if not sock.failed:
                    sock.set_failed(errors.ECLOSE, "socket map entry removed")
                sock.recycle()

    def count(self) -> int:
        return len(self._map)


def acquire_socket(
    endpoint, messenger, signature, connection_type, connect_timeout_s,
    controller, ssl_params=None,
):
    """Connection acquisition by type (reference controller.cpp:1073-1111:
    single | GetPooledSocket | GetShortSocket). Pooled/short borrows are
    recorded on the controller (which releases them at finalize); if the
    RPC finalized while this attempt was connecting, the borrow is
    released right here instead of leaking."""
    smap = get_socket_map()
    if connection_type == "pooled":
        err, sid = smap.get_pooled(
            endpoint, messenger, signature=signature,
            connect_timeout_s=connect_timeout_s, ssl_params=ssl_params,
        )
        if err == 0:
            entry = ("pooled", sid, endpoint, signature)
            if not controller.try_record_owned(entry):
                release_owned_socket(entry)
                return errors.ECANCELED, 0
        return err, sid
    if connection_type == "short":
        err, sid = Socket.connect(
            endpoint, messenger, timeout_s=connect_timeout_s,
            connection_type="short", ssl_params=ssl_params,
        )
        if err == 0:
            entry = ("short", sid, endpoint, signature)
            if not controller.try_record_owned(entry):
                release_owned_socket(entry)
                return errors.ECANCELED, 0
        return err, sid
    return smap.get_or_create(
        endpoint, messenger, signature=signature,
        connect_timeout_s=connect_timeout_s, ssl_params=ssl_params,
    )


def release_owned_socket(entry) -> None:
    """Give back a pooled borrow / close a short connection."""
    kind, sid, remote, signature = entry
    if kind == "pooled":
        get_socket_map().return_pooled(remote, signature, sid)
        return
    sock = Socket.address(sid)
    if sock is not None:
        if not sock.failed:
            sock.set_failed(0, "short connection done")
        sock.recycle()


_global_map: Optional[SocketMap] = None
_global_lock = threading.Lock()


def get_socket_map() -> SocketMap:
    global _global_map
    if _global_map is None:
        with _global_lock:
            if _global_map is None:
                _global_map = SocketMap()
    return _global_map
