"""SocketMap — process-global connection sharing.

Analog of reference SocketMap (socket_map.h:32-80): maps
(EndPoint, connection signature) → SocketId so channels to the same
server share one connection ("single" connection type); a non-empty
``connection_group`` splits sharing (channel.h:130-134). Failed sockets
are replaced on next acquisition; the old one is handed to health
checking by the caller.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

from incubator_brpc_tpu.transport.socket import Socket
from incubator_brpc_tpu.utils.endpoint import EndPoint


class SocketMap:
    def __init__(self):
        self._map: Dict[Tuple[EndPoint, str], int] = {}
        self._lock = threading.Lock()

    def get_or_create(
        self, remote: EndPoint, messenger, signature: str = "", user=None
    ) -> Tuple[int, int]:
        """Returns (error_code, sid). Creates/replaces the shared socket
        when missing or failed."""
        key = (remote, signature)
        with self._lock:
            sid = self._map.get(key)
        if sid is not None:
            sock = Socket.address(sid)
            if sock is not None and not sock.failed and not sock.draining:
                return 0, sid
        # connect outside the map lock (reference creates then inserts)
        err, new_sid = Socket.connect(remote, messenger, user=user)
        if err:
            return err, 0
        with self._lock:
            cur = self._map.get(key)
            if cur is not None and cur != sid:
                cur_sock = Socket.address(cur)
                if cur_sock is not None and not cur_sock.failed:
                    # lost the race: keep theirs, drop ours
                    mine = Socket.address(new_sid)
                    if mine is not None:
                        mine.set_failed(0, "duplicate connection")
                        mine.recycle()
                    return 0, cur
            self._map[key] = new_sid
        return 0, new_sid

    def remove(self, remote: EndPoint, signature: str = ""):
        with self._lock:
            self._map.pop((remote, signature), None)

    def count(self) -> int:
        return len(self._map)


_global_map: Optional[SocketMap] = None
_global_lock = threading.Lock()


def get_socket_map() -> SocketMap:
    global _global_map
    if _global_map is None:
        with _global_lock:
            if _global_map is None:
                _global_map = SocketMap()
    return _global_map
