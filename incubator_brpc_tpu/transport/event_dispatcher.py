"""EventDispatcher — edge-triggered epoll loop feeding the runtime.

Analog of reference EventDispatcher (event_dispatcher.h:31-102,
event_dispatcher_epoll.cpp): a dedicated loop runs epoll_wait; IN
events hand the socket to the runtime via spawn_urgent (the reference's
bthread_start_urgent in Socket::StartInputEvent, socket.cpp:2083); OUT
events wake the socket's epollout butex so a parked KeepWrite task
resumes (socket.cpp WaitEpollOut).

The TPU twist lands in parallel/ici.py: the same Dispatcher
interface is implemented over device completion events instead of
epoll, preserving the one-read-task-per-socket invariant the reference
derives from edge-triggered semantics (SURVEY.md §7 hard parts).
"""

from __future__ import annotations

import os
import select
import threading
import time as _time
from typing import Dict, Optional

from incubator_brpc_tpu.utils.flags import get_flag
from incubator_brpc_tpu.utils.logging import log_error

_EPOLLIN = select.EPOLLIN
_EPOLLOUT = select.EPOLLOUT
_EPOLLET = select.EPOLLET
_EPOLLERR = select.EPOLLERR | select.EPOLLHUP

_tls = threading.local()

# chaos hook slot: set by chaos.injector while an armed plan targets
# the "dispatcher.dispatch" site (this module sits below the metrics
# stack, so the injector reaches down rather than being imported);
# disarmed cost is one `is None` check per IN event.
_chaos_hook = None


def set_chaos_hook(cb) -> None:
    global _chaos_hook
    _chaos_hook = cb


def in_dispatcher() -> bool:
    """True when called on an event-dispatcher thread — code that could
    block (id locks, connects) must re-dispatch to a worker instead."""
    return getattr(_tls, "in_dispatcher", False)


class EventDispatcher:
    def __init__(self, name: str = "tpubrpc-dispatcher"):
        self._epoll = select.epoll()
        self._handlers: Dict[int, object] = {}  # fd -> Socket-like consumer
        self._lock = threading.Lock()
        # self-pipe to interrupt epoll_wait for shutdown
        self._wake_r, self._wake_w = os.pipe()
        os.set_blocking(self._wake_r, False)
        self._epoll.register(self._wake_r, _EPOLLIN | _EPOLLET)
        self._stopped = False
        self._thread = threading.Thread(target=self._run, daemon=True, name=name)
        self._thread.start()

    # consumer must provide: _on_epoll_in(), _on_epoll_out(), _on_epoll_err()
    def add_consumer(self, fd: int, consumer) -> bool:
        """Analog of EventDispatcher::AddConsumer — register for ET IN."""
        with self._lock:
            self._handlers[fd] = consumer
        try:
            self._epoll.register(fd, _EPOLLIN | _EPOLLET)
            return True
        except OSError as e:
            log_error("epoll register fd=%d failed: %r", fd, e)
            with self._lock:
                self._handlers.pop(fd, None)
            return False

    def enable_epollout(self, fd: int) -> bool:
        """Add OUT interest (KeepWrite parked on EAGAIN);
        reference RegisterEvent with pollout."""
        try:
            self._epoll.modify(fd, _EPOLLIN | _EPOLLOUT | _EPOLLET)
            return True
        except OSError:
            return False

    def disable_epollout(self, fd: int) -> None:
        try:
            self._epoll.modify(fd, _EPOLLIN | _EPOLLET)
        except OSError:
            pass

    def remove_consumer(self, fd: int) -> None:
        try:
            self._epoll.unregister(fd)
        except OSError:
            pass
        with self._lock:
            self._handlers.pop(fd, None)

    def _run(self):
        _tls.in_dispatcher = True
        while not self._stopped:
            try:
                events = self._epoll.poll(1.0)
            except (OSError, ValueError):
                if self._stopped:
                    return
                continue
            for fd, ev in events:
                if fd == self._wake_r:
                    try:
                        os.read(self._wake_r, 4096)
                    except BlockingIOError:
                        pass
                    continue
                with self._lock:
                    consumer = self._handlers.get(fd)
                if consumer is None:
                    continue
                try:
                    if ev & _EPOLLERR:
                        consumer._on_epoll_err()
                        continue
                    if ev & _EPOLLOUT:
                        consumer._on_epoll_out()
                    if ev & _EPOLLIN:
                        hook = _chaos_hook  # snapshot: disarm() races
                        if hook is not None:
                            try:
                                hook()  # injected dispatch delay
                            except Exception:  # noqa: BLE001 — a chaos
                                pass  # bug must not eat an ET edge
                        self._stamp_receive(consumer)
                        consumer._on_epoll_in()
                except Exception as e:  # noqa: BLE001
                    log_error("dispatcher handler fd=%d raised: %r", fd, e)

    @staticmethod
    def _stamp_receive(consumer):
        """rpcz receive stamp: the earliest host-visible moment of this
        batch's bytes (span received_us; reference stamps in
        StartInputEvent). Slotted non-Socket consumers (fd waiters)
        simply don't carry it."""
        try:
            consumer.last_read_event_us = _time.time_ns() // 1000
        except AttributeError:
            pass

    def stop(self):
        self._stopped = True
        try:
            os.write(self._wake_w, b"x")
        except OSError:
            pass
        if threading.current_thread() is self._thread:
            return  # the loop itself cannot join/close safely
        self._thread.join(timeout=2)
        # Release the epoll fd and self-pipe (tests/teardown must not
        # leak 3 fds per loop) — but ONLY after a confirmed thread
        # exit: closing under a still-running loop would hand the fd
        # numbers to unrelated sockets the loop then reads.  Idempotent
        # via _fds_closed so a second stop() never double-closes.
        if self._thread.is_alive() or getattr(self, "_fds_closed", False):
            return
        self._fds_closed = True
        try:
            self._epoll.close()
        except OSError:
            pass
        for fd in (self._wake_r, self._wake_w):
            try:
                os.close(fd)
            except OSError:
                pass


_dispatchers: Optional[list] = None
_dispatcher_lock = threading.Lock()


def get_dispatcher(fd: int = 0) -> EventDispatcher:
    """The dispatcher owning ``fd`` (reference -event_dispatcher_num,
    event_dispatcher.cpp:30-45: an array of N epoll loops with fds
    assigned by hash).  The pool size comes from the
    ``event_dispatcher_num`` flag at first use; a given fd always maps
    to the same dispatcher (fd % N), so register/arm/remove stay
    consistent.  N defaults to 1 — on a single-core host extra loops
    only add context switches; multi-core deployments raise the flag
    before the first socket is created."""
    global _dispatchers
    if _dispatchers is None:
        with _dispatcher_lock:
            if _dispatchers is None:
                try:
                    n = max(1, int(get_flag("event_dispatcher_num", 1)))
                except (TypeError, ValueError):
                    n = 1
                _dispatchers = [
                    EventDispatcher(name=f"tpubrpc-dispatcher-{i}")
                    for i in range(n)
                ]
    ds = _dispatchers
    return ds[fd % len(ds)]
