"""InputMessenger — protocol-agnostic read loop + message cutter.

Analog of reference InputMessenger (input_messenger.{h,cpp}):
``on_new_messages`` (OnNewMessages, input_messenger.cpp:317-382) reads
adaptively into the socket's IOBuf, then ``_cut_input_message``
(CutInputMessage, :205-315) tries registered protocol parsers with the
per-socket cached index; each parsed message is dispatched to a new
task, the last one processed in place (QueueMessage batching,
:169-190). First-message auth runs through the protocol's verify
callback (:282-300).
"""

from __future__ import annotations

from typing import List, Optional

from incubator_brpc_tpu import errors
from incubator_brpc_tpu.protocols import ParseError, Protocol, list_protocols
from incubator_brpc_tpu.runtime import scheduler
from incubator_brpc_tpu.transport import socket as socket_mod
from incubator_brpc_tpu.utils.logging import log_error, log_verbose

_READ_CHUNK = 1 << 16


class InputMessenger:
    def __init__(self, protocols: Optional[List[Protocol]] = None):
        self._protocols = protocols  # None = use global registry at read time

    def protocols(self) -> List[Protocol]:
        return self._protocols if self._protocols is not None else list_protocols()

    # runs inside the socket's single read task
    def on_new_messages(self, sock) -> None:
        eof = False
        while not sock.failed:
            # 1. read until EAGAIN (edge-triggered contract)
            try:
                n = sock.read_buf.append_from_socket(sock.fd, _READ_CHUNK)
                socket_mod.g_in_bytes << n
                if n == 0:
                    eof = True
            except (BlockingIOError, InterruptedError):
                n = -1
            except OSError as e:
                sock.set_failed(errors.EFAILEDSOCKET, f"read failed: {e}")
                return
            # 2. cut as many complete messages as the buffer holds
            self.cut_and_dispatch(sock, eof)
            if eof:
                sock.set_failed(errors.ECLOSE, "remote closed connection")
                return
            if n < 0:  # EAGAIN: wait for next edge event
                return

    def cut_and_dispatch(self, sock, read_eof: bool = False) -> None:
        """Cut every complete message in sock.read_buf and dispatch each
        to a fresh task, with the first-message auth gate. Shared by the
        TCP read loop and the ICI completion drain (one protocol path,
        two transports)."""
        while not sock.failed:
            result, proto = self._cut_input_message(sock, read_eof)
            if result is None:
                return
            socket_mod.g_in_messages << 1
            msg = result.message
            # auth gate on first message of a server connection
            if (
                sock.is_server_side
                and not sock.auth_done
                and proto.verify is not None
            ):
                if not proto.verify(msg, sock):
                    sock.set_failed(errors.ERPCAUTH, "authentication failed")
                    return
            sock.auth_done = True
            process = (
                proto.process_request if sock.is_server_side else proto.process_response
            )
            if process is None:
                process = proto.process_request or proto.process_response
            if proto.process_in_place:
                # ordered protocols (streaming frames) are routed here in
                # the read task; the handler only enqueues, so this stays
                # cheap and order-preserving
                self._process_safely(process, msg, sock)
            else:
                # dispatch into a fresh task (reference: one bthread per
                # message, input_messenger.cpp:169-190)
                scheduler.spawn(self._process_safely, process, msg, sock)

    @staticmethod
    def _process_safely(process, msg, sock):
        try:
            process(msg, sock)
        except Exception as e:  # noqa: BLE001
            log_error("protocol process raised: %r", e)

    def _cut_input_message(self, sock, read_eof: bool):
        """Try parsers, starting from the cached per-socket index
        (CutInputMessage, input_messenger.cpp:205-315)."""
        if sock.read_buf.empty():
            return None, None
        protos = self.protocols()
        order = range(len(protos))
        if sock.parse_index is not None and sock.parse_index < len(protos):
            cached = sock.parse_index
            order = [cached] + [i for i in range(len(protos)) if i != cached]
        for idx in order:
            proto = protos[idx]
            if proto.parse is None:
                continue
            result = proto.parse(sock.read_buf, sock, read_eof)
            if result.error == ParseError.OK:
                sock.parse_index = idx
                return result, proto
            if result.error == ParseError.NOT_ENOUGH_DATA:
                sock.parse_index = idx
                return None, None
            if result.error == ParseError.BAD_FORMAT:
                sock.set_failed(errors.EREQUEST, f"bad {proto.name} message")
                return None, None
            # TRY_OTHERS: fall through
        # nothing matched
        if len(sock.read_buf) > 0:
            log_verbose("unknown protocol on socket %x, closing", sock.sid)
            sock.set_failed(errors.EREQUEST, "message matched no protocol")
        return None, None
