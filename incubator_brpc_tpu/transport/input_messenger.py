"""InputMessenger — protocol-agnostic read loop + message cutter.

Analog of reference InputMessenger (input_messenger.{h,cpp}):
``on_new_messages`` (OnNewMessages, input_messenger.cpp:317-382) reads
adaptively into the socket's IOBuf, then ``_cut_input_message``
(CutInputMessage, :205-315) tries registered protocol parsers with the
per-socket cached index; each parsed message is dispatched to a new
task, the last one processed in place (QueueMessage batching,
:169-190). First-message auth runs through the protocol's verify
callback (:282-300).
"""

from __future__ import annotations

import time as _time
from typing import List, Optional

from incubator_brpc_tpu import errors
from incubator_brpc_tpu.chaos import injector as _chaos
from incubator_brpc_tpu.protocols import ParseError, Protocol, list_protocols
from incubator_brpc_tpu.runtime import scheduler
from incubator_brpc_tpu.transport import socket as socket_mod
from incubator_brpc_tpu.utils.logging import log_error, log_verbose

_READ_CHUNK = 1 << 16


class InputMessenger:
    def __init__(self, protocols: Optional[List[Protocol]] = None):
        self._protocols = protocols  # None = use global registry at read time

    def protocols(self) -> List[Protocol]:
        return self._protocols if self._protocols is not None else list_protocols()

    # runs inside the socket's single read task
    def on_new_messages(self, sock) -> None:
        eof = False
        pending = None  # held-back last message, flushed at batch end
        while not sock.failed:
            # 1. read until EAGAIN (edge-triggered contract)
            read_chunk = _READ_CHUNK
            drop_round = False
            if _chaos.armed:
                spec = _chaos.check("socket.read", peer=sock.remote)
                if spec is not None:
                    act = spec.action
                    if act == "short_read":
                        # cap this round's recv: a frame bigger than the
                        # cap now completes across many partial reads
                        # (clamped to the normal chunk, matching the
                        # native site — a large arg must never ENLARGE
                        # the read)
                        read_chunk = min(max(1, spec.arg), _READ_CHUNK)
                    elif act == "delay_us":
                        _chaos.sleep_us(spec.arg)
                    elif act == "eagain_storm":
                        # the kernel "has nothing for us" this round:
                        # hold the read loop for arg µs (default 1ms)
                        # then re-evaluate.  A bare `continue` would be
                        # an unobservable no-op burning the hit budget;
                        # a `return` under ET epoll could strand
                        # buffered bytes until the next edge.  Bounded:
                        # specs default max_hits=64 for this action.
                        _chaos.sleep_us(spec.arg or 1000)
                        continue
                    elif act == "drop":
                        drop_round = True
                    elif act == "reset":
                        self._fail_behind_ordered(
                            sock, errors.EFAILEDSOCKET,
                            "chaos: injected reset",
                        )
                        return
            try:
                if drop_round:
                    # read bytes off the wire and discard them: the
                    # stream loses data mid-flight (peer must recover
                    # via deadline/close, parser may see garbage next)
                    from incubator_brpc_tpu.utils.iobuf import IOBuf

                    n = IOBuf().append_from_socket(sock.fd, read_chunk)
                else:
                    n = sock.read_buf.append_from_socket(sock.fd, read_chunk)
                socket_mod.g_in_bytes << n
                if n > 0:
                    sock.last_active_s = _time.monotonic()
                if n == 0:
                    eof = True
            except (BlockingIOError, InterruptedError):
                n = -1
            except OSError as e:
                self._fail_behind_ordered(
                    sock, errors.EFAILEDSOCKET, f"read failed: {e}"
                )
                return
            # 2. cut as many complete messages as the buffer holds
            pending = self._cut_and_queue(sock, eof, pending)
            if eof or n < 0:
                break
        # batch exhausted (EAGAIN/EOF): the LAST message runs in place —
        # only now, so a slow in-place handler can't delay reading
        # requests already queued in the kernel buffer (the reference
        # flushes QueueMessage the same way, input_messenger.cpp:169-190)
        if pending is not None:
            self._stamp(pending[1], "enqueued_us")  # runs in place now
            self._process_safely(*pending)
        if eof and not sock.failed:
            self._fail_behind_ordered(sock, errors.ECLOSE, "remote closed connection")

    def cut_and_dispatch(self, sock, read_eof: bool = False) -> None:
        """Cut + dispatch everything currently buffered, processing the
        last message in place. Entry point for the ICI completion drain
        (one frame per call — the common case pays zero task handoffs)."""
        pending = self._cut_and_queue(sock, read_eof, None)
        if pending is not None:
            self._stamp(pending[1], "enqueued_us")
            self._process_safely(*pending)

    def _cut_and_queue(self, sock, read_eof: bool, pending):
        """Cut every complete message; dispatch each to a fresh task
        except the last, which is returned for the caller to run in
        place at batch end (QueueMessage, input_messenger.cpp:169-190).
        Ordered (process_in_place) protocol frames flush `pending` first
        in place, so cross-protocol arrival order is preserved."""
        while not sock.failed:
            result, proto = self._cut_input_message(sock, read_eof)
            if result is None:
                break
            socket_mod.g_in_messages << 1
            msg = result.message
            # rpcz phase stamps ride on the message to the server span:
            # received = the IN event that carried these bytes (stamped
            # by the dispatcher / fabric delivery), parse_done = now.
            # One fused try/one clock read — this runs per message.
            try:
                now = _time.time_ns() // 1000
                msg.received_us = sock.last_read_event_us or now
                msg.parse_done_us = now
            except AttributeError:
                pass  # message type without stamp slots
            # auth gate on first message of a server connection
            if sock.is_server_side and not sock.auth_done:
                if proto.verify is not None:
                    try:
                        ok = proto.verify(msg, sock)
                    except Exception as e:  # noqa: BLE001
                        # an exception out of verify must CLOSE the
                        # connection, not wedge the read task
                        log_error("%s verify raised: %r", proto.name, e)
                        ok = False
                    if not ok:
                        sock.set_failed(errors.ERPCAUTH, "authentication failed")
                        return None
                elif not proto.auth_in_protocol:
                    # no verify hook and no in-protocol auth: on an
                    # auth-enforcing server this protocol would be a
                    # silent bypass — refuse the connection instead
                    server_auth = getattr(
                        getattr(sock.server, "options", None), "auth", None
                    )
                    if server_auth is not None:
                        sock.set_failed(
                            errors.ERPCAUTH,
                            f"protocol {proto.name} cannot authenticate",
                        )
                        return None
            sock.auth_done = True
            process = (
                proto.process_request if sock.is_server_side else proto.process_response
            )
            if process is None:
                process = proto.process_request or proto.process_response
            if proto.process_in_place:
                # ordered protocols (streaming frames) run here in the
                # read task; anything held back must run FIRST — e.g. the
                # stream-establishing RPC response must precede the first
                # stream DATA frame that follows it in the same batch
                if pending is not None:
                    self._process_safely(*pending)
                    pending = None
                self._stamp(msg, "enqueued_us")  # in place: zero queue wait
                self._process_safely(process, msg, sock)
                continue
            if proto.process_ordered:
                # correlation-less protocols (HTTP/1.x): serialize this
                # connection's messages on its ExecutionQueue so request
                # k's response is written before request k+1's, matching
                # the client's FIFO response matching — without stalling
                # the read task on a slow handler
                if pending is not None:
                    self._process_safely(*pending)
                    pending = None
                # hold the socket in-use per queued item: the queue's
                # consumer runs detached from the read task, and without
                # a hold the slot could be recycled+reborn while items
                # are pending — they'd then run against the new
                # connection occupying the same object
                if sock._inuse_acquire():
                    # inline when idle: the one-outstanding-request case
                    # (the dominant HTTP pattern) pays no task handoff
                    self._stamp(msg, "enqueued_us")
                    self._ordered_queue(sock).execute_or_inline(
                        (process, msg, sock)
                    )
                continue
            if pending is not None:
                self._stamp(pending[1], "enqueued_us")
                scheduler.spawn(self._process_safely, *pending)
            pending = (process, msg, sock)
        return pending

    @staticmethod
    def _stamp(msg, field: str, value: int = 0):
        """Set an rpcz phase stamp on a parsed message; protocols whose
        message types don't carry the slots simply don't get phases."""
        try:
            setattr(msg, field, value or _time.time_ns() // 1000)
        except AttributeError:
            pass

    @staticmethod
    def _fail_behind_ordered(sock, code, text):
        """set_failed, but sequenced AFTER any messages still pending on
        the socket's ordered queue — a response fully received before
        EOF/read-error must reach its RPC, not be erased by the failure
        sweep (set_failed clears pipelined_info and errors waiters)."""
        q = sock.ordered_exec
        if q is not None and sock._inuse_acquire():
            def do_fail(_msg, s):
                s.set_failed(code, text)

            if q.execute_or_inline((do_fail, None, sock)):
                return
            sock._inuse_release()
        sock.set_failed(code, text)

    @staticmethod
    def _ordered_queue(sock):
        q = sock.ordered_exec
        if q is None:
            from incubator_brpc_tpu.observability.latency_breakdown import (
                queue_wait_recorder,
            )
            from incubator_brpc_tpu.runtime.execution_queue import ExecutionQueue

            def consume(batch):
                for process, msg, s in batch:
                    try:
                        InputMessenger._process_safely(process, msg, s)
                    finally:
                        s._inuse_release()

            q = sock.ordered_exec = ExecutionQueue(
                consume, wait_recorder=queue_wait_recorder("ordered_queue")
            )
        return q

    @staticmethod
    def _process_safely(process, msg, sock):
        try:
            process(msg, sock)
        except Exception as e:  # noqa: BLE001
            log_error("protocol process raised: %r", e)

    def _cut_input_message(self, sock, read_eof: bool):
        """Try parsers, starting from the cached per-socket index
        (CutInputMessage, input_messenger.cpp:205-315)."""
        if sock.read_buf.empty():
            return None, None
        protos = self.protocols()
        order = range(len(protos))
        if sock.parse_index is not None and sock.parse_index < len(protos):
            cached = sock.parse_index
            order = [cached] + [i for i in range(len(protos)) if i != cached]
        for idx in order:
            proto = protos[idx]
            if proto.parse is None:
                continue
            result = proto.parse(sock.read_buf, sock, read_eof)
            if result.error == ParseError.OK:
                sock.parse_index = idx
                return result, proto
            if result.error == ParseError.NOT_ENOUGH_DATA:
                sock.parse_index = idx
                return None, None
            if result.error == ParseError.BAD_FORMAT:
                sock.set_failed(errors.EREQUEST, f"bad {proto.name} message")
                return None, None
            # TRY_OTHERS: fall through
        # nothing matched
        if len(sock.read_buf) > 0:
            log_verbose("unknown protocol on socket %x, closing", sock.sid)
            sock.set_failed(errors.EREQUEST, "message matched no protocol")
        return None, None
