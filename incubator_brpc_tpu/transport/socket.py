"""Socket — the central connection abstraction.

Analog of reference brpc::Socket (socket.h:205, socket.cpp): lives in a
ResourcePool addressed by versioned SocketId (socket.h:335), so stale
ids fail address() after recycling; lock-free failure marking
(SetFailed, socket.h:352-364) notifies every queued write's CallId and
hands the socket to health checking.

Write path mirrors StartWrite/KeepWrite (socket.cpp:1584-1790): the
calling task appends to the write queue and, if no writer is active,
becomes the writer and writes inline until EAGAIN or empty; leftover is
drained by a background KeepWrite task that parks on the epollout butex
(WaitEpollOut). The reference achieves this wait-free via an atomic
exchange on _write_head; under the GIL a short lock is the equivalent
(the structural property kept: writers never block each other beyond
queue append, and at most one task writes to the fd at a time).

Read path mirrors StartInputEvent (socket.cpp:2045): ET events bump an
event counter; only the first schedules a read task — the
one-read-task-per-socket invariant.
"""

from __future__ import annotations

import errno as _errno
import socket as _pysocket
import threading
import time as _time
from collections import deque
from typing import Callable, Optional

from incubator_brpc_tpu import errors
from incubator_brpc_tpu.chaos import injector as _chaos
from incubator_brpc_tpu.metrics.reducer import Adder
from incubator_brpc_tpu.runtime import scheduler
from incubator_brpc_tpu.runtime.butex import Butex
from incubator_brpc_tpu.runtime.call_id import default_pool as _id_pool
from incubator_brpc_tpu.utils.endpoint import EndPoint
from incubator_brpc_tpu.utils.iobuf import IOBuf
from incubator_brpc_tpu.utils.logging import log_error, log_verbose
from incubator_brpc_tpu.utils.resource_pool import ResourcePool

import os as _os

# escape hatch: TPUBRPC_NO_INLINE_READ=1 restores spawn-per-read-event
_INLINE_READ_DISABLED = _os.environ.get("TPUBRPC_NO_INLINE_READ") == "1"

# per-iteration write cap: how many bytes one _do_write_once round may
# hand the kernel before re-checking the queue.  The effective cap is
# min(shared wire-chunk policy, 1MB): 1MB is this layer's own fairness
# bound (one oversized writev round holds the writer role — and any
# pipelined peer — longer than it saves), so ENLARGING the policy in
# utils/segmentation.py deliberately does not enlarge this, while
# SHRINKING it below 1MB propagates here so all three bulk layers
# chunk no coarser than the operator asked for.
from incubator_brpc_tpu.utils.segmentation import WIRE_CHUNK_BYTES

WRITE_CHUNK_BYTES = min(WIRE_CHUNK_BYTES, 1 << 20)

# global socket stats (reference SocketVarsCollector, socket.h:123-154)
g_connections = Adder(0)
g_in_bytes = Adder(0)
g_out_bytes = Adder(0)
g_in_messages = Adder(0)
g_out_messages = Adder(0)

DEFAULT_OVERCROWD_LIMIT = 64 << 20  # unwritten bytes before EOVERCROWDED


class SocketOptions:
    def __init__(
        self,
        fd: Optional[_pysocket.socket] = None,
        remote: Optional[EndPoint] = None,
        messenger=None,  # InputMessenger consuming parsed input
        on_edge_triggered_events: Optional[Callable] = None,  # raw IN handler
        server=None,
        user=None,  # SocketUser: health-check hooks
        connection_type: str = "single",
    ):
        self.fd = fd
        self.remote = remote
        self.messenger = messenger
        self.on_edge_triggered_events = on_edge_triggered_events
        self.server = server
        self.user = user
        self.connection_type = connection_type


class Socket:
    _pool: ResourcePool = None  # class-level, initialised below

    def __init__(self):
        # survives slot reuse: one lock per pool OBJECT, so a stale
        # holder and the object's next life serialize on the same lock
        self._life_lock = threading.Lock()
        self._reset_fields()

    def _reset_fields(self):
        self.sid = 0
        self.fd: Optional[_pysocket.socket] = None
        self.remote: Optional[EndPoint] = None
        self.local: Optional[EndPoint] = None
        self.messenger = None
        self.on_edge_triggered_events = None
        self.server = None
        self.user = None
        self.connection_type = "single"
        self.is_server_side = False
        self.failed = False
        self.error_code = 0
        self.error_text = ""
        # read side
        self.read_buf = IOBuf()
        # wall-clock us of the latest IN event (rpcz received_us source;
        # set by the event dispatcher / fabric delivery)
        self.last_read_event_us = 0
        self.parse_index: Optional[int] = None  # cached protocol index
        self.last_protocol = ""  # protocol of the last request sent
        # HTTP per-connection parse state: MUST reset on slot reuse or a
        # reborn socket resumes the dead connection's chunked body
        self._http_chunk_ctx = None
        self._http_exclusive_stream = False
        self._rtmp_conn = None  # RTMP handshake/chunk state
        self._read_events = 0
        self._read_active = False
        self._read_lock = threading.Lock()
        # write side
        self._write_q: deque = deque()  # (IOBuf, notify_cid, rpcz span|None)
        # reentrant: an ICI inline response delivered on the sending
        # thread re-enters accumulate_pipelined under this lock
        self._write_lock = threading.RLock()
        self._writing = False
        self._unwritten = 0
        # deferred graceful close: (code, text) once the write queue
        # drains (close_after_flush)
        self._close_after_flush = None
        self._epollout = Butex(0)
        # ICI mode (fd is None): frames ride the fabric, not a kernel fd
        self.ici_port = None
        self.ici_peer_coords = None
        # health / lifecycle
        self._closed = False
        # in-use guard (SocketUniquePtr-lite, reference socket.h:335-343):
        # long-running holders of this OBJECT (read task, KeepWrite,
        # accept loop) take a count; recycle() defers slot reuse until
        # they drain, so a stale holder can never close/poison a REBORN
        # socket occupying the same pool slot (the ABA the reference's
        # refcounted SocketUniquePtr exists to prevent)
        self._inuse = 0
        self._recycle_pending = False
        self._dying = False  # set under _life_lock once recycle is chosen
        # correlation ids awaiting a response on this socket (reference
        # notifies in-flight RPCs on SetFailed so they don't wait for the
        # deadline when the connection breaks)
        self.waiting_cids: set = set()
        self.pipelined_info: deque = deque()  # (cid, count) for pipelined protos
        self._pipelined_acc = []  # partial replies of the FIFO-front RPC
        self._preamble_done = False  # connection preamble (AUTH) written
        self.stream_map = {}  # stream_id -> Stream (streaming RPC)
        self.auth_done = False
        self.auth_context = None  # set by a passing verify_credential
        self.h2_ctx = None  # per-connection HTTP/2 state (protocols/h2.py)
        self.ordered_exec = None  # per-connection in-order processing queue
        # draining (h2 GOAWAY): in-flight work finishes on this
        # connection but SocketMap stops handing it to new RPCs
        self.draining = False
        # last read/write activity (idle-connection reaper,
        # reference acceptor.cpp:130 ListConnections idle check)
        self.last_active_s = _time.monotonic()
        # Read-dispatch policy. True: run the read/cut/process loop
        # inline in the event-dispatcher thread (two fewer scheduler
        # handoffs per message — the dominant per-RPC cost in this
        # runtime). Client sockets default to inline: the sync response
        # path never blocks (user done callbacks are spawned by
        # _finalize_locked). Server sockets stay spawned unless
        # ServerOptions.usercode_in_dispatcher opts in — the analog of
        # the reference's threading-model tuning (docs/cn/benchmark.md),
        # inverse of -usercode_in_pthread.
        self.inline_read = False

    # ---- creation / addressing (Socket::Create/Address, socket.h:335-343) --
    @classmethod
    def create(cls, options: SocketOptions) -> int:
        sid, sock = cls._pool.get_resource()
        sock._reset_fields()
        sock.sid = sid
        sock.fd = options.fd
        sock.remote = options.remote
        sock.messenger = options.messenger
        sock.on_edge_triggered_events = options.on_edge_triggered_events
        sock.server = options.server
        sock.user = options.user
        sock.connection_type = options.connection_type
        sock.is_server_side = options.server is not None
        if _INLINE_READ_DISABLED:
            sock.inline_read = False
        elif sock.is_server_side:
            sock.inline_read = bool(
                getattr(options.server.options, "usercode_in_dispatcher", False)
            )
        else:
            sock.inline_read = options.on_edge_triggered_events is None
        if sock.fd is not None:
            sock.fd.setblocking(False)
            from incubator_brpc_tpu.transport.event_dispatcher import get_dispatcher

            fd_no = sock.fd.fileno()
            get_dispatcher(fd_no).add_consumer(fd_no, sock)
        g_connections << 1
        return sid

    @classmethod
    def address(cls, sid: int) -> Optional["Socket"]:
        """Resolve SocketId → Socket; None if recycled. Callers must
        check .failed (reference returns the socket for health checking)."""
        return cls._pool.address(sid)

    # ---- write path (StartWrite socket.cpp:1584, KeepWrite :1685) ----------
    def write(
        self,
        buf: IOBuf,
        notify_cid: int = 0,
        ignore_eovercrowded: bool = False,
        pipelined_entries=None,
        conn_preamble=None,
        span=None,
    ) -> int:
        """Queue buf for writing. Returns 0 or an error code. On socket
        failure, notify_cid receives EFAILEDSOCKET via the CallId pool.
        ``span`` (rpcz) gets write_done() when buf fully reaches the
        kernel/fabric — server spans close there, so their latency
        includes serialization and send."""
        if _chaos.armed:
            spec = _chaos.check("socket.write", peer=self.remote)
            if spec is not None:
                act = spec.action
                if act == "delay_us":
                    _chaos.sleep_us(spec.arg)
                elif act == "drop":
                    # the frame silently vanishes: the peer never sees
                    # it and this RPC must recover via its deadline
                    if span is not None:
                        span.write_done(0)
                    return 0
                elif act == "corrupt":
                    raw = bytearray(buf.to_bytes())
                    if raw:
                        raw[spec.arg % len(raw)] ^= 0xFF
                    buf = IOBuf(bytes(raw))
                elif act == "reset":
                    self.set_failed(
                        errors.EFAILEDSOCKET, "chaos: injected reset"
                    )
        if self.failed:
            if notify_cid:
                _id_pool().error(notify_cid, errors.EFAILEDSOCKET, self.error_text)
            if span is not None:
                span.write_done(errors.EFAILEDSOCKET)
            return errors.EFAILEDSOCKET
        if not ignore_eovercrowded and self._unwritten > DEFAULT_OVERCROWD_LIMIT:
            if notify_cid:
                _id_pool().error(notify_cid, errors.EOVERCROWDED, "write queue full")
            if span is not None:
                span.write_done(errors.EOVERCROWDED)
            return errors.EOVERCROWDED
        if self.ici_port is not None:
            # ICI data path: enqueue on the peer's completion queue; device
            # segments move zero-copy / via device-to-device transfer
            if pipelined_entries or conn_preamble is not None:
                # correlation-less (FIFO) protocols: registration must
                # be atomic with frame order on the fabric, exactly like
                # the TCP branch below
                rc = self._ici_write_pipelined(
                    buf, pipelined_entries, conn_preamble,
                    ignore_eovercrowded,
                )
            else:
                rc = self.ici_port.fabric.send(
                    buf, self.ici_peer_coords, self.ici_port.coords,
                    ignore_eovercrowded=ignore_eovercrowded,
                )
            if rc == errors.EOVERCROWDED:
                # transient receive-window backpressure: the peer port
                # is congested, NOT gone — the connection stays healthy
                # (socket.cpp _overcrowded semantics)
                if notify_cid:
                    _id_pool().error(
                        notify_cid, rc, "ici peer receive window full"
                    )
                if span is not None:
                    span.write_done(rc)
                return rc
            if rc == errors.EINTERNAL:
                # the FRAME failed (a fault mid-placement — e.g. chunk k
                # of a chunked pipeline): the fabric connection is
                # virtual and still healthy, so this RPC gets ONE error
                # and the socket (plus every other in-flight RPC on it)
                # stays up
                if notify_cid:
                    _id_pool().error(
                        notify_cid, rc, "ici frame placement failed"
                    )
                if span is not None:
                    span.write_done(rc)
                return rc
            if rc:
                self.set_failed(rc, "ici send failed: peer gone")
                if notify_cid:
                    _id_pool().error(notify_cid, rc, "ici send failed")
            if span is not None:
                span.write_done(rc)
            return rc
        size = len(buf)
        become_writer = False
        self.last_active_s = _time.monotonic()
        with self._write_lock:
            # Connection preamble (redis AUTH): exactly ONE writer gets
            # to prepend it, decided here under the lock — deciding at
            # pack time would let a concurrent packet overtake it and
            # reach the server's first-message gate un-authenticated.
            if conn_preamble is not None and not self._preamble_done:
                self._preamble_done = True
                pre_buf, pre_entries = conn_preamble
                if pre_entries:
                    self.pipelined_info.extend(pre_entries)
                self._write_q.append((pre_buf, 0, None))
                self._unwritten += len(pre_buf)
            # FIFO registration MUST be atomic with write-queue order:
            # registering outside this lock lets two RPCs enqueue their
            # packets in the opposite order of their pipelined entries,
            # misrouting every response on a correlation-less protocol
            if pipelined_entries:
                self.pipelined_info.extend(pipelined_entries)
            self._write_q.append((buf, notify_cid, span))
            self._unwritten += size
            if not self._writing:
                self._writing = True
                become_writer = True
        if become_writer:
            # First writer writes inline (the reference's fast path);
            # leftovers continue in a KeepWrite task.
            if not self._do_write_once():
                if self._inuse_acquire():
                    scheduler.spawn(self._keep_write_guarded)
        return 0

    def _ici_write_pipelined(
        self, buf, pipelined_entries, conn_preamble, ignore_eovercrowded
    ) -> int:
        """FIFO-correlated frame over the fabric: the whole
        register+send runs under the (reentrant) write lock so two
        RPCs can't ship frames in the opposite order of their
        pipelined entries.  A frame the fabric refuses deregisters its
        entries — the peer never saw it, so leaving them queued would
        misroute every later reply on this socket by one slot."""
        with self._write_lock:
            if conn_preamble is not None and not self._preamble_done:
                self._preamble_done = True
                pre_buf, pre_entries = conn_preamble
                if pre_entries:
                    self.pipelined_info.extend(pre_entries)
                rc = self.ici_port.fabric.send(
                    pre_buf, self.ici_peer_coords, self.ici_port.coords,
                    ignore_eovercrowded=True,
                )
                if rc:
                    for _ in pre_entries or ():
                        self.pipelined_info.pop()
                    return rc
            if pipelined_entries:
                self.pipelined_info.extend(pipelined_entries)
            rc = self.ici_port.fabric.send(
                buf, self.ici_peer_coords, self.ici_port.coords,
                ignore_eovercrowded=ignore_eovercrowded,
            )
            if rc and pipelined_entries:
                for _ in pipelined_entries:
                    self.pipelined_info.pop()
            return rc

    def _keep_write_guarded(self):
        try:
            self._keep_write()
        finally:
            self._inuse_release()

    def _do_write_once(self) -> bool:
        """Drain as much as possible without blocking. Returns True if the
        queue went empty (writer role released), False if a KeepWrite
        task must take over."""
        while True:
            with self._write_lock:
                if not self._write_q:
                    self._writing = False
                    pending_close = self._close_after_flush
                    self._close_after_flush = None
                    drained = True
                else:
                    drained = False
                    head, cid, span = self._write_q[0]
            if drained:
                if pending_close is not None:
                    # graceful close requested while writes were still
                    # queued: the last byte just reached the kernel
                    self.set_failed(pending_close[0], pending_close[1])
                return True
            try:
                while not head.empty():
                    cap = WRITE_CHUNK_BYTES
                    injected_short = False
                    if _chaos.armed:
                        spec = _chaos.check(
                            "socket.write_io", peer=self.remote
                        )
                        if spec is not None:
                            if spec.action == "eagain_storm":
                                # pretend the kernel buffer is full: a
                                # KeepWrite task takes over and parks
                                # on (an immediately ready) epollout
                                return False
                            if spec.action == "short_write":
                                # explicit flag (not a cap sentinel):
                                # arg >= the write chunk must still
                                # divert the remainder to KeepWrite
                                cap = min(max(1, spec.arg), WRITE_CHUNK_BYTES)
                                injected_short = True
                    n = head.cut_into_socket(self.fd, cap)
                    with self._write_lock:
                        self._unwritten -= n
                    g_out_bytes << n
                    if injected_short and not head.empty():
                        # injected partial write: hand the remainder to
                        # the KeepWrite path like a real short write
                        return False
            except (BlockingIOError, InterruptedError):
                return False
            except OSError as e:
                self.set_failed(errors.EFAILEDSOCKET, f"write failed: {e}")
                return True
            with self._write_lock:
                if self._write_q and self._write_q[0][0] is head:
                    self._write_q.popleft()
            if span is not None:
                # the message's last byte reached the kernel: stamp
                # sent_us; server spans close here (rpcz send phase)
                span.write_done(0)
            g_out_messages << 1

    def _keep_write(self):
        """Background writer parked on epollout (KeepWrite loop)."""
        from incubator_brpc_tpu.transport.event_dispatcher import get_dispatcher

        while True:
            if self.failed:
                return
            if self._do_write_once():
                return
            with self._write_lock:
                caf = self._close_after_flush
            if caf is not None and _time.monotonic_ns() > caf[2]:
                # graceful-close drain deadline: the peer stopped
                # reading — stop polling for it and close abortively
                # (frees the fd + this KeepWrite task)
                self.set_failed(caf[0], caf[1] + " (drain timed out)")
                return
            # EAGAIN: wait for epollout
            expected = self._epollout.value
            fd_no = self.fd.fileno()
            get_dispatcher(fd_no).enable_epollout(fd_no)
            self._epollout.wait(expected, timeout=1.0)

    def _on_epoll_out(self):
        from incubator_brpc_tpu.transport.event_dispatcher import get_dispatcher

        fd_no = self.fd.fileno()
        get_dispatcher(fd_no).disable_epollout(fd_no)
        self._epollout.fetch_add(1)
        self._epollout.wake_all()

    # ---- read path (StartInputEvent socket.cpp:2045) -----------------------
    def _on_epoll_in(self):
        if self.on_edge_triggered_events is not None:
            # raw handler (Acceptor's OnNewConnections)
            if self._inuse_acquire():
                scheduler.spawn_urgent(self._run_edge_handler)
            return
        with self._read_lock:
            self._read_events += 1
            if self._read_active:
                return
            self._read_active = True
        # hold the object across the read task so a concurrent recycle
        # can't hand this slot to a new socket mid-read
        if not self._inuse_acquire():
            with self._read_lock:
                self._read_active = False
            return
        if self.inline_read:
            self._process_event_guarded()
        else:
            scheduler.spawn_urgent(self._process_event_guarded)

    def _run_edge_handler(self):
        try:
            self.on_edge_triggered_events(self)
        finally:
            self._inuse_release()

    def _process_event_guarded(self):
        try:
            self._process_event()
        finally:
            self._inuse_release()

    def _process_event(self):
        while True:
            with self._read_lock:
                self._read_events = 0
            if self.messenger is not None:
                self.messenger.on_new_messages(self)
            with self._read_lock:
                if self._read_events == 0 or self.failed:
                    self._read_active = False
                    return

    def _on_epoll_err(self):
        self.set_failed(errors.EFAILEDSOCKET, "epoll error event")

    # ---- failure & lifecycle (SetFailed socket.h:352-364) ------------------
    # graceful close gives the peer this long to drain the response
    # before the close turns abortive — a Connection:-close client that
    # never reads must not pin the fd + a polling KeepWrite forever
    CLOSE_DRAIN_TIMEOUT_S = 15.0

    def close_after_flush(
        self, error_code: int = errors.ECLOSE, error_text: str = ""
    ) -> None:
        """Graceful close: fail the socket only once the write queue
        has fully drained.  ``set_failed`` DROPS queued writes — correct
        for errors, but a protocol-level "respond then close"
        (HTTP ``Connection: close``) must not truncate the response it
        just queued when the write went partial (kernel backpressure or
        an injected short write — caught by driving the HTTP surface
        under a `socket.write_io` chaos plan).  Bounded: a peer that
        stops reading gets CLOSE_DRAIN_TIMEOUT_S, then the close turns
        abortive (KeepWrite enforces the deadline)."""
        deadline_ns = _time.monotonic_ns() + int(
            self.CLOSE_DRAIN_TIMEOUT_S * 1e9
        )
        with self._write_lock:
            if self.failed:
                return
            if self._write_q or self._writing:
                # the active writer (inline or KeepWrite) closes at the
                # drain point in _do_write_once, or at the deadline
                self._close_after_flush = (error_code, error_text, deadline_ns)
                return
        self.set_failed(error_code, error_text)

    def set_failed(self, error_code: int, error_text: str = "") -> bool:
        with self._write_lock:
            if self.failed:
                return False
            self.failed = True
            self.error_code = error_code
            self.error_text = error_text
            pending = list(self._write_q)
            self._write_q.clear()
            self._unwritten = 0
        log_verbose("socket %x set_failed: %s %s", self.sid, error_code, error_text)
        # wake any parked KeepWrite
        self._epollout.fetch_add(1)
        self._epollout.wake_all()
        # fail every pending write's RPC and every in-flight waiter
        pool = _id_pool()
        for _, cid, span in pending:
            if cid:
                pool.error(cid, errors.EFAILEDSOCKET, error_text)
            if span is not None:
                span.write_done(errors.EFAILEDSOCKET)
        with self._write_lock:
            waiters = list(self.waiting_cids)
            self.waiting_cids.clear()
        for cid in waiters:
            pool.error(cid, errors.EFAILEDSOCKET, error_text)
        for cid, _ in list(self.pipelined_info):
            if cid:
                pool.error(cid, errors.EFAILEDSOCKET, error_text)
        self.pipelined_info.clear()
        # fail attached streams
        for stream in list(self.stream_map.values()):
            try:
                stream.on_socket_failed(error_code, error_text)
            except Exception:
                pass
        self._close_fd()
        g_connections << -1
        if self.user is not None:
            try:
                self.user.on_socket_failed(self)
            except Exception as e:  # noqa: BLE001
                log_error("socket user on_failed raised: %r", e)
        return True

    def _close_fd(self):
        if self.fd is not None and not self._closed:
            self._closed = True
            from incubator_brpc_tpu.transport.event_dispatcher import get_dispatcher

            try:
                fd_no = self.fd.fileno()
                get_dispatcher(fd_no).remove_consumer(fd_no)
            except Exception:
                pass
            try:
                self.fd.close()
            except OSError:
                pass

    def _inuse_acquire(self) -> bool:
        """Take a hold on this object; False once recycle was chosen
        (no new tasks may start on a dying socket)."""
        with self._life_lock:
            if self._dying:
                return False
            self._inuse += 1
            return True

    def _inuse_release(self):
        finish = False
        with self._life_lock:
            self._inuse -= 1
            if self._inuse == 0 and self._recycle_pending:
                self._recycle_pending = False
                finish = True
        if finish:
            self._do_recycle()

    def recycle(self):
        """Return to the pool (bumps SocketId version: stale ids die).
        Deferred while any task still holds this object; _dying closes
        the acquire window so the check-then-recycle is race-free."""
        with self._life_lock:
            if self._dying:
                return  # second recycle of the same life: ignore
            self._dying = True
            if self._inuse > 0:
                self._recycle_pending = True
                return
        self._do_recycle()

    def _do_recycle(self):
        self._close_fd()
        Socket._pool.return_resource(self.sid)

    def add_response_waiter(self, cid: int) -> None:
        with self._write_lock:
            if not self.failed:
                self.waiting_cids.add(cid)
                return
        # socket already failed: fail the waiter immediately
        _id_pool().error(cid, errors.EFAILEDSOCKET, self.error_text)

    def remove_response_waiter(self, cid: int) -> bool:
        """Returns whether the waiter was still registered — True means
        no response for `cid` ever arrived on this socket (the
        finalize sweep uses it to spot abandoned hedge/retry attempts
        worth a cancel frame)."""
        with self._write_lock:
            if cid in self.waiting_cids:
                self.waiting_cids.discard(cid)
                return True
        return False

    # ---- client connect ----------------------------------------------------
    @classmethod
    def connect(
        cls,
        remote: EndPoint,
        messenger,
        timeout_s: float = 3.0,
        user=None,
        connection_type: str = "single",
        ssl_params=None,  # (ssl.SSLContext, server_hostname) for TLS
    ) -> tuple[int, int]:
        """Blocking connect (runs on a worker task). Returns (error, sid).
        With ssl_params the TLS handshake also runs here, blocking with
        the same timeout (reference: SSLHandshake inside Socket
        connect/first-write; details/ssl_helper.cpp) — afterwards the
        SSLSocket goes non-blocking like any other fd."""
        try:
            if remote.scheme == "uds":
                fd = _pysocket.socket(_pysocket.AF_UNIX, _pysocket.SOCK_STREAM)
            else:
                fd = _pysocket.socket(_pysocket.AF_INET, _pysocket.SOCK_STREAM)
                fd.setsockopt(_pysocket.IPPROTO_TCP, _pysocket.TCP_NODELAY, 1)
            fd.settimeout(timeout_s)
            fd.connect(remote.sockaddr())
            if ssl_params is not None:
                ctx, hostname = ssl_params
                fd = ctx.wrap_socket(
                    fd, server_hostname=hostname or None,
                    do_handshake_on_connect=True,
                )
            fd.setblocking(False)
        except OSError as e:
            # error level: a failed connect is the start of most
            # "server unreachable" investigations (reference logs it in
            # Socket::Connect too)
            log_error("connect to %s failed: %r", remote, e)
            return (errors.EFAILEDSOCKET, 0)
        sid = cls.create(
            SocketOptions(
                fd=fd, remote=remote, messenger=messenger, user=user,
                connection_type=connection_type,
            )
        )
        return (0, sid)


Socket._pool = ResourcePool(Socket)
