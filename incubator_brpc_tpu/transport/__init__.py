"""Core transport (analog of reference src/brpc/ core files): Socket,
EventDispatcher, InputMessenger, Acceptor, SocketMap (SURVEY.md §2.4)."""

from incubator_brpc_tpu.transport.socket import Socket, SocketOptions  # noqa: F401
from incubator_brpc_tpu.transport.event_dispatcher import get_dispatcher  # noqa: F401
from incubator_brpc_tpu.transport.input_messenger import InputMessenger  # noqa: F401
from incubator_brpc_tpu.transport.socket_map import SocketMap, get_socket_map  # noqa: F401
