"""SSL/TLS helpers — context construction from option structs.

Analog of reference details/ssl_helper.{h,cpp} (CreateClientSSLContext /
CreateServerSSLContext) and the SSL option structs of channel.h /
server.h (ChannelSSLOptions, ServerSSLOptions, CertInfo).  The state
machine the reference hand-rolls over OpenSSL BIOs (SSLState on Socket,
socket.h:205 region) maps onto Python's ``ssl.SSLSocket`` here: the
handshake runs blocking-with-timeout on the connecting/accepting task
(the Python transport already does blocking connects on worker tasks),
after which the socket returns to non-blocking mode and the epoll loops
treat ``SSLWantReadError``/``SSLWantWriteError`` as EAGAIN
(utils/iobuf.py translates them).

TLS 1.3 never renegotiates, and for 1.2 we disable renegotiation where
OpenSSL allows, so the want-read-on-write cross-signal case the
reference's state machine handles cannot occur post-handshake.
"""

from __future__ import annotations

import ssl
from dataclasses import dataclass
from typing import Optional


@dataclass
class CertInfo:
    """A certificate + private key pair (reference CertInfo,
    server.h: certificate/private_key support PEM paths)."""

    certificate: str = ""  # PEM file path
    private_key: str = ""  # PEM file path


@dataclass
class ChannelSSLOptions:
    """Mirrors reference ChannelSSLOptions (ssl_options.h): client-side
    TLS knobs.  Default: TLS on, peer verification OFF (the reference
    default — verify.ca_file_path empty skips verification)."""

    sni_name: str = ""  # server_hostname for SNI + hostname check
    ca_file: str = ""   # non-empty → verify the server cert against it
    verify_hostname: bool = False  # also match sni_name against the cert
    client_cert: Optional[CertInfo] = None  # mutual-TLS client identity
    ciphers: str = ""
    protocols: str = ""  # reserved (ALPN), parity with reference field


@dataclass
class ServerSSLOptions:
    """Mirrors reference ServerSSLOptions (ssl_options.h): the default
    cert served on TLS connections + optional client-cert verification.
    ``alpns`` mirrors the reference's alpns field — a sequence of
    tokens, or the reference's comma-separated string form; gRPC
    clients require the "h2" token during the handshake."""

    default_cert: CertInfo = None
    verify_client_ca_file: str = ""  # non-empty → require client certs
    ciphers: str = ""
    alpns: tuple = ("h2", "http/1.1")


def _no_renegotiation(ctx: ssl.SSLContext) -> None:
    # TLS 1.2 renegotiation would surface want-read-on-write mid-stream,
    # which the epoll write path maps to "wait for EPOLLOUT" — a stall.
    # Disabling it makes the module invariant (no cross-signals after
    # the handshake) actually true.
    ctx.options |= ssl.OP_NO_RENEGOTIATION


def make_client_context(opts: ChannelSSLOptions) -> ssl.SSLContext:
    """Build the client SSLContext (CreateClientSSLContext analog)."""
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    _no_renegotiation(ctx)
    if opts.verify_hostname and not opts.sni_name:
        # silently skipping the check the caller asked for would let any
        # same-CA cert impersonate the server
        raise ValueError("verify_hostname=True requires sni_name")
    if opts.ca_file:
        ctx.load_verify_locations(cafile=opts.ca_file)
        ctx.verify_mode = ssl.CERT_REQUIRED
        ctx.check_hostname = bool(opts.verify_hostname and opts.sni_name)
    else:
        # reference default: no CA configured → no verification
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
    if opts.client_cert is not None and opts.client_cert.certificate:
        ctx.load_cert_chain(
            opts.client_cert.certificate,
            opts.client_cert.private_key or None,
        )
    if opts.ciphers:
        ctx.set_ciphers(opts.ciphers)
    return ctx


def wrap_server_side(conn, ctx: ssl.SSLContext, timeout_s: float, peer,
                     log_error):
    """Shared server-side handshake: blocking with timeout, returns the
    wrapped socket (timeout cleared) or None after logging + closing.
    Used by the RPC acceptor and the DCN bridge so the two can't drift."""
    try:
        conn.settimeout(timeout_s)
        wrapped = ctx.wrap_socket(conn, server_side=True)
        wrapped.settimeout(None)
        return wrapped
    except (OSError, ssl.SSLError) as e:
        log_error("TLS accept from %s failed: %r", peer, e)
        try:
            conn.close()
        except OSError:
            pass
        return None


def make_server_context(opts: ServerSSLOptions) -> ssl.SSLContext:
    """Build the server SSLContext (CreateServerSSLContext analog)."""
    if opts.default_cert is None or not opts.default_cert.certificate:
        raise ValueError("ServerSSLOptions.default_cert.certificate required")
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    _no_renegotiation(ctx)
    ctx.load_cert_chain(
        opts.default_cert.certificate, opts.default_cert.private_key or None
    )
    if opts.verify_client_ca_file:
        ctx.load_verify_locations(cafile=opts.verify_client_ca_file)
        ctx.verify_mode = ssl.CERT_REQUIRED
    if opts.ciphers:
        ctx.set_ciphers(opts.ciphers)
    if opts.alpns:
        # the multi-protocol port negotiates whatever it actually
        # speaks; gRPC clients refuse to proceed without "h2".
        # Accept the reference's comma-list string form too — list()
        # on a string would advertise bogus one-byte protocols.
        alpns = opts.alpns
        if isinstance(alpns, str):
            alpns = [t.strip() for t in alpns.split(",") if t.strip()]
        try:
            ctx.set_alpn_protocols(list(alpns))
        except NotImplementedError:  # openssl built without ALPN
            pass
    return ctx
