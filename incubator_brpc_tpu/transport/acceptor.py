"""Acceptor — accept loop on the listening socket.

Analog of reference Acceptor (acceptor.h:34-89, acceptor.cpp:84,130):
an InputMessenger subclass whose listening socket's edge-triggered IN
handler runs an accept loop creating connection Sockets owned by the
server's messenger; tracks the connection set for /connections and
closes them on server stop.
"""

from __future__ import annotations

import socket as _pysocket
import threading
import time
from typing import Dict, Set

from incubator_brpc_tpu.transport.input_messenger import InputMessenger
from incubator_brpc_tpu.transport.socket import Socket, SocketOptions
from incubator_brpc_tpu.utils.endpoint import EndPoint
from incubator_brpc_tpu.utils.logging import log_error


class Acceptor(InputMessenger):
    def __init__(self, server):
        super().__init__(None)
        self._server = server
        self._listen_sid = 0
        self._connections: Set[int] = set()
        self._lock = threading.Lock()
        self._reaper_stop = threading.Event()
        self._reaper = None

    def start_accept(self, listen_fd: _pysocket.socket) -> int:
        self._listen_sid = Socket.create(
            SocketOptions(
                fd=listen_fd,
                on_edge_triggered_events=self._on_new_connections,
                server=self._server,
            )
        )
        idle = getattr(
            getattr(self._server, "options", None), "idle_timeout_sec", -1
        )
        if idle and idle > 0:
            self._reaper = threading.Thread(
                target=self._reap_idle, args=(float(idle),), daemon=True
            )
            self._reaper.start()
        return 0

    def _reap_idle(self, idle_s: float):
        """Close connections with no read/write activity for idle_s
        (reference idle-connection reaper, acceptor.cpp:130)."""
        tick = max(0.05, min(idle_s / 4.0, 1.0))
        while not self._reaper_stop.wait(tick):
            now = time.monotonic()
            with self._lock:
                conns = list(self._connections)
            for sid in conns:
                s = Socket.address(sid)
                if s is None or s.failed:
                    continue
                if now - s.last_active_s > idle_s:
                    s.set_failed(0, f"idle > {idle_s:.0f}s, closed by reaper")
            # recycle what we (or anything else) killed — without this,
            # reaped sockets sit in _connections/the pool until someone
            # happens to poll connection_count()
            self._gc()

    def _on_new_connections(self, listen_sock):
        """accept4 loop until EAGAIN (OnNewConnections, acceptor.cpp:84)."""
        ssl_ctx = getattr(self._server, "_ssl_server_ctx", None)
        while True:
            try:
                conn, addr = listen_sock.fd.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError as e:
                if not listen_sock.failed:
                    log_error("accept failed: %r", e)
                return
            try:
                conn.setsockopt(_pysocket.IPPROTO_TCP, _pysocket.TCP_NODELAY, 1)
            except OSError:
                pass
            remote = (
                EndPoint.tcp(addr[0], addr[1])
                if isinstance(addr, tuple)
                else EndPoint.uds(str(addr))
            )
            if ssl_ctx is not None:
                # handshake on its own task so a slow/hostile peer can't
                # stall the accept loop (reference runs the SSL state
                # machine non-blocking per socket; blocking-with-timeout
                # on a worker task is this transport's equivalent)
                from incubator_brpc_tpu.runtime import scheduler

                scheduler.spawn(self._tls_accept, conn, remote, ssl_ctx)
                continue
            self._register_conn(conn, remote)

    def _tls_accept(self, conn, remote, ssl_ctx):
        from incubator_brpc_tpu.transport.ssl_helper import wrap_server_side

        conn = wrap_server_side(conn, ssl_ctx, 3.0, remote, log_error)
        if conn is not None:
            self._register_conn(conn, remote)

    def _register_conn(self, conn, remote):
        sid = Socket.create(
            SocketOptions(
                fd=conn,
                remote=remote,
                messenger=self,
                server=self._server,
            )
        )
        with self._lock:
            self._connections.add(sid)

    def connection_count(self) -> int:
        self._gc()
        return len(self._connections)

    def connections(self):
        self._gc()
        with self._lock:
            return [Socket.address(sid) for sid in self._connections]

    def _gc(self):
        with self._lock:
            dead = [
                sid
                for sid in self._connections
                if (s := Socket.address(sid)) is None or s.failed
            ]
            for sid in dead:
                s = Socket.address(sid)
                self._connections.discard(sid)
                if s is not None:
                    s.recycle()

    def stop_listening(self):
        """Phase one of graceful stop (Server::Stop closewait semantics):
        refuse NEW connections while existing ones keep serving, so
        in-flight requests can drain before stop_accept tears down."""
        listen = Socket.address(self._listen_sid)
        self._listen_sid = 0
        if listen is not None:
            listen.set_failed(0, "server stopping")
            listen.recycle()

    def stop_accept(self):
        self._reaper_stop.set()
        self.stop_listening()
        with self._lock:
            conns = list(self._connections)
            self._connections.clear()
        sockets = [s for sid in conns if (s := Socket.address(sid)) is not None]
        h2_socks = [s for s in sockets if s.h2_ctx is not None and not s.failed]
        if h2_socks:
            # graceful GOAWAY, then a short drain window so in-flight
            # handlers get their responses out — killing the fd right
            # after a GOAWAY that covers those sids would tell the peer
            # "possibly processed" and lose the answers
            from incubator_brpc_tpu.protocols.h2 import send_goaway

            for s in h2_socks:
                try:
                    send_goaway(s)
                except Exception:  # noqa: BLE001
                    pass
            deadline = time.monotonic() + 1.0
            while time.monotonic() < deadline:
                # drained = no open streams AND their queued response
                # bytes flushed (streams pop when bytes enter _write_q;
                # set_failed clears that queue, so wait it out too)
                if all(
                    s.failed
                    or s.h2_ctx is None
                    or (not s.h2_ctx.streams and s._unwritten == 0)
                    for s in h2_socks
                ):
                    break
                time.sleep(0.02)
        for s in sockets:
            s.set_failed(0, "server stopping")
            s.recycle()
