"""ReplicatedShardChannel: the client face of the replication tier.

Wraps the ShardRoutedChannel contract so existing stubs keep working
(``ps_stub(replicated_ps_channel(...))`` is a drop-in for
``ps_stub(sharded_ps_channel(...))``):

* **writes** (Put/Delete) route by key to the owning shard GROUP and
  run the quorum protocol (replication/group.py): through the leader,
  epoch-stamped, acked only after quorum — failures surface as ERPC
  codes (ESTALEEPOCH / ETOOMANYFAILS / EINTERNAL), never hangs;
* **reads** (everything else routed) fan to the nearest serving
  replica: each group's read plane is a
  :class:`~incubator_brpc_tpu.client.combo.ManualClusterChannel` under
  the ``mesh_locality`` LB with PR 8 backup-request hedging
  (``hedge_ms``) — a dead/slow replica costs one hedge, not a tail;
* **fan-out methods** (Forward) ride an inner ShardRoutedChannel whose
  partitions are the per-group LEADER channels — Forward mutates
  device state ordering, so it keeps the through-the-leader rule;
* **RF=1 is byte-for-byte the unreplicated path**: every group has one
  member, the channel delegates ALL calls to a plain
  ShardRoutedChannel built over those members, and no group/lease/
  quorum code runs on the call path (the OFF/ON/OFF bench triplet
  holds ≈0%).

Membership is refreshed off each group's ``members_version`` — one int
compare per call on the steady path; node lists rebuild only when a
replica dies, rejoins, or the leader moves.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

from incubator_brpc_tpu import errors
from incubator_brpc_tpu.replication import metrics as _m
from incubator_brpc_tpu.replication.group import (
    ReplicaGroup,
    ReplicaNode,
    ReplicationError,
    register_group,
)


def _server_node(endpoint: str):
    from incubator_brpc_tpu.client.naming_service import ServerNode
    from incubator_brpc_tpu.utils.endpoint import str2endpoint

    return ServerNode(str2endpoint(endpoint))


class ReplicatedShardChannel:
    """Channel duck-type over a list of :class:`ReplicaGroup`\\ s (one
    per shard, in shard order) plus their members' RPC endpoints."""

    WRITE_METHODS = frozenset({"Put", "Delete"})

    def __init__(
        self,
        groups: Sequence[ReplicaGroup],
        key_fn: Optional[Callable[[object], str]] = None,
        seed: int = 0,
        hedge_ms: int = 50,
        read_lb: str = "mesh_locality",
        timeout_ms: int = 20000,
        fail_limit: int = 0,
        channel_options=None,
        write_methods=None,
    ):
        from incubator_brpc_tpu.client.channel import ChannelOptions
        from incubator_brpc_tpu.client.combo import (
            ManualClusterChannel,
            ParallelChannelOptions,
            ShardRoutedChannel,
        )

        if not groups:
            raise ValueError("ReplicatedShardChannel needs >= 1 group")
        self.groups = list(groups)
        self._key_fn = key_fn or (
            lambda req: str(getattr(req, "message", "") or "")
        )
        self._seed = int(seed)
        self._write = (
            frozenset(write_methods)
            if write_methods is not None
            else self.WRITE_METHODS
        )
        self._lock = threading.Lock()
        self.rf1 = all(len(g.nodes) == 1 for g in self.groups)
        opts = ParallelChannelOptions(
            fail_limit=fail_limit, timeout_ms=timeout_ms
        )
        if self.rf1:
            # replication factor 1: the whole tier collapses to the
            # existing unreplicated ShardRoutedChannel — nothing
            # replication-shaped runs per call (the disabled path is
            # free by construction)
            from incubator_brpc_tpu.client.channel import Channel

            subs = []
            for g in self.groups:
                sub = Channel(channel_options)
                rc = sub.init(g.nodes[0].endpoint)
                if rc != 0:
                    raise ValueError(
                        f"cannot init shard channel to {g.nodes[0].endpoint}"
                    )
                subs.append(sub)
            self._direct = ShardRoutedChannel(
                options=opts, key_fn=self._key_fn, seed=self._seed
            )
            self._direct.set_partitions(subs)
            return
        self._direct = None
        from dataclasses import replace as _dc_replace

        base = channel_options if channel_options is not None else ChannelOptions()
        read_opts = _dc_replace(base, backup_request_ms=int(hedge_ms))
        # per-group read plane: serving replicas under the locality LB,
        # hedged; per-group write plane: the leader, re-fed on change
        self._read_chans = [
            ManualClusterChannel(read_lb, read_opts) for _ in self.groups
        ]
        self._leader_chans = [
            ManualClusterChannel("rr", channel_options) for _ in self.groups
        ]
        self._versions = [-1] * len(self.groups)
        self._reader = ShardRoutedChannel(
            options=opts, key_fn=self._key_fn, seed=self._seed
        )
        self._reader.set_partitions(self._read_chans)
        self._fan = ShardRoutedChannel(
            options=opts, key_fn=self._key_fn, seed=self._seed
        )
        self._fan.set_partitions(self._leader_chans)

    # -- ShardRoutedChannel surface ------------------------------------------
    def shard_of(self, key: str, n: Optional[int] = None) -> int:
        from incubator_brpc_tpu.utils.hashes import murmur3_32

        if n is None:
            n = len(self.groups)
        return murmur3_32(str(key).encode(), seed=self._seed) % n

    def partition_count(self) -> int:
        return len(self.groups)

    def set_fanout(self, method_name: str, prepare_leg=None, merge=None):
        if self._direct is not None:
            self._direct.set_fanout(method_name, prepare_leg, merge)
        else:
            self._fan.set_fanout(method_name, prepare_leg, merge)

    # -- membership refresh ---------------------------------------------------
    def _refresh(self, idx: int) -> None:
        """Re-feed group ``idx``'s read/leader channels iff its
        members_version moved — an int compare on the steady path."""
        g = self.groups[idx]
        v = g.members_version
        if v == self._versions[idx]:
            return
        with self._lock:
            if v == self._versions[idx]:
                return
            serving = g.serving_nodes()
            self._read_chans[idx].set_nodes(
                [_server_node(n.endpoint) for n in serving]
            )
            leader = g.ensure_leader()
            self._leader_chans[idx].set_nodes(
                [_server_node(leader.endpoint)] if leader is not None else []
            )
            # re-read: ensure_leader may itself bump the version (a
            # fresh election); cache the post-election value so the
            # next call doesn't rebuild again
            self._versions[idx] = g.members_version

    def _refresh_all(self) -> None:
        for i in range(len(self.groups)):
            self._refresh(i)

    # -- the call plane -------------------------------------------------------
    def call_method(self, method_spec, controller, request, response,
                    done=None):
        if self._direct is not None:  # RF=1: the unreplicated path
            return self._direct.call_method(
                method_spec, controller, request, response, done
            )
        m = method_spec.method_name
        if m in self._write:
            return self._call_write(
                m, method_spec, controller, request, response, done
            )
        if m in self._fan._fanout:
            self._refresh_all()
            return self._fan.call_method(
                method_spec, controller, request, response, done
            )
        return self._call_read(
            method_spec, controller, request, response, done
        )

    def _call_read(self, method_spec, controller, request, response, done):
        idx = self.shard_of(self._key_fn(request))
        self._refresh(idx)

        def account():
            if getattr(controller, "_used_backup", False):
                g = self.groups[idx]
                g.counters["hedged_reads"] += 1
                _m.replica_hedged_reads << 1

        if done is None:
            self._reader.call_method(method_spec, controller, request, response)
            account()
            return

        def wrapped_done():
            account()
            done()

        self._reader.call_method(
            method_spec, controller, request, response, wrapped_done
        )

    def _call_write(self, m, method_spec, controller, request, response,
                    done):
        key = self._key_fn(request)
        idx = self.shard_of(key)
        g = self.groups[idx]
        # the attachment is the value — snapshot before anything else
        # consumes it (the DynamicShardChannel discipline)
        value = (
            controller.request_attachment.to_bytes()
            if not controller.request_attachment.empty()
            else b""
        )

        def run_sync():
            start_ns = time.monotonic_ns()
            controller.shard_index = idx
            try:
                if m == "Delete":
                    existed = g.read_any(key) is not None
                    g.delete(key)
                    response.message = "1" if existed else "0"
                else:
                    g.put(key, value)
                    response.message = key
            except ReplicationError as e:
                controller.set_failed(e.code, f"{m}({key}): {e}")
            except Exception as e:  # noqa: BLE001
                controller.set_failed(
                    errors.EINTERNAL, f"replicated {m}({key}) raised: {e}"
                )
            controller.latency_us = (time.monotonic_ns() - start_ns) // 1000

        if done is None:
            run_sync()
        else:
            from incubator_brpc_tpu.runtime import scheduler

            def run_async():
                run_sync()
                done()

            scheduler.spawn(run_async)

    # -- introspection --------------------------------------------------------
    def describe(self) -> Dict[str, dict]:
        return {g.name: g.describe() for g in self.groups}


# ---------------------------------------------------------------------------
# builders
# ---------------------------------------------------------------------------

def replicated_ps_channel(
    group_endpoints: Sequence[Sequence[str]],
    board=None,
    quorum: Optional[int] = None,
    lease_ttl_s: float = 0.5,
    hedge_ms: int = 50,
    read_lb: str = "mesh_locality",
    timeout_ms: int = 20000,
    seed: int = 0,
    channel_options=None,
    store_timeout_ms: int = 10000,
    name_prefix: str = "ps",
    register: bool = True,
) -> ReplicatedShardChannel:
    """The replicated counterpart of ``sharded_ps_channel``:
    ``group_endpoints[i]`` lists shard i's replica endpoints (RF = its
    length; pass one endpoint per group for the unreplicated RF=1
    collapse).  Wires the PsService Forward fan-out contract and
    registers the groups for the ``/replication`` builtin."""
    from incubator_brpc_tpu.client.channel import Channel
    from incubator_brpc_tpu.models.parameter_server import (
        ps_forward_merge,
        ps_forward_prepare_leg,
    )
    from incubator_brpc_tpu.replication.lease import LeaseBoard
    from incubator_brpc_tpu.resharding.migration import PsShardStore

    if board is None:
        board = LeaseBoard(lease_ttl_s)
    groups: List[ReplicaGroup] = []
    for i, members in enumerate(group_endpoints):
        nodes = []
        for ep in members:
            sub = Channel(channel_options)
            rc = sub.init(str(ep))
            if rc != 0:
                raise ValueError(f"cannot init replica channel to {ep}")
            nodes.append(
                ReplicaNode(
                    name=f"{name_prefix}.g{i}.{ep}",
                    store=PsShardStore(sub, timeout_ms=store_timeout_ms),
                    endpoint=str(ep),
                )
            )
        g = ReplicaGroup(
            f"{name_prefix}.g{i}", nodes, board=board, quorum=quorum,
            lease_ttl_s=lease_ttl_s,
        )
        if register:
            register_group(g)
        groups.append(g)
    ch = ReplicatedShardChannel(
        groups, seed=seed, hedge_ms=hedge_ms, read_lb=read_lb,
        timeout_ms=timeout_ms, channel_options=channel_options,
    )
    ch.set_fanout("Forward", ps_forward_prepare_leg, ps_forward_merge)
    return ch


def replicated_cache_group(
    name: str,
    cache_channels: Sequence,
    endpoints: Optional[Sequence[str]] = None,
    board=None,
    quorum: Optional[int] = None,
    lease_ttl_s: float = 0.5,
    register: bool = True,
) -> ReplicaGroup:
    """A replica group over HBM cache members (CacheChannel each) —
    the cache tier's replication adapter.  Repair rides the bulk
    DMGET/DMSET surface automatically (CacheShardStore carries
    read_many/write_many), so catching a replica up moves key ranges
    in collective steps, not key-by-key."""
    from incubator_brpc_tpu.replication.lease import LeaseBoard
    from incubator_brpc_tpu.resharding.migration import CacheShardStore

    if board is None:
        board = LeaseBoard(lease_ttl_s)
    eps = list(endpoints) if endpoints is not None else [""] * len(
        list(cache_channels)
    )
    nodes = [
        ReplicaNode(
            name=f"{name}.{i}",
            store=CacheShardStore(cc),
            endpoint=eps[i] or f"{name}.{i}",
        )
        for i, cc in enumerate(cache_channels)
    ]
    g = ReplicaGroup(
        name, nodes, board=board, quorum=quorum, lease_ttl_s=lease_ttl_s
    )
    if register:
        register_group(g)
    return g
