"""Replicated HA tier: per-shard replica groups for the sharded PS and
the HBM cache — leader leases with epoch fencing, quorum writes,
hedged locality reads, and repair through the resharding verified-move
engine (docs/replication.md, ROADMAP item 3).

Layering (all composition, no forked services):

* ``lease``   — epoch-numbered leader leases + the naming-tag grammar
* ``group``   — ReplicaGroup/ReplicaNode: quorum writes, fencing,
  election, repair (= resharding ``verified_write``/``_many``)
* ``channel`` — ReplicatedShardChannel wrapping ShardRoutedChannel so
  existing stubs keep working; ``replicated_ps_channel`` /
  ``replicated_cache_group`` builders
* ``metrics`` — the ``rpc_replica_*`` adders (METRIC_MODULES)
"""

from incubator_brpc_tpu.replication.channel import (  # noqa: F401
    ReplicatedShardChannel,
    replicated_cache_group,
    replicated_ps_channel,
)
from incubator_brpc_tpu.replication.group import (  # noqa: F401
    LeaderLost,
    NoLeader,
    QuorumLost,
    ReplicaGroup,
    ReplicaNode,
    ReplicationError,
    StaleEpoch,
    groups_snapshot,
    register_group,
    unregister_group,
)
from incubator_brpc_tpu.replication.lease import (  # noqa: F401
    Lease,
    LeaseBoard,
    format_lease_tag,
    max_lease_epoch,
    parse_lease_tag,
)
