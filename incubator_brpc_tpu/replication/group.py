"""Per-shard replica groups: quorum writes under a leader lease,
epoch-fenced, with repair riding the resharding verified-move engine.

One :class:`ReplicaGroup` owns the replicas of ONE shard (PS shard i,
or one cache ring position).  The protocol is deliberately small
(docs/replication.md):

* the leader is whoever holds the group's lease on the
  :class:`~incubator_brpc_tpu.replication.lease.LeaseBoard` — elected
  by ``ensure_leader()`` (most-caught-up live replica wins ties), kept
  by renewal at half-TTL;
* a write fans from the leader to every serving replica carrying the
  lease epoch; each replica FENCES epochs older than the newest lease
  it has seen (``StaleEpoch`` → ESTALEEPOCH on the wire) — a deposed
  leader can never get a write acknowledged;
* the write acks to the caller only after ``quorum`` replicas applied
  it AND the lease is still valid at ack time — an acked write
  therefore lives on a majority and survives any single failure;
* reads may land on ANY serving replica (the channel fans them with
  hedging); a rejoining replica is NOT serving until ``repair()``
  copies it up to date through the resharding
  ``verified_write``/``verified_write_many`` path — migration and
  repair are one engine.

Chaos site ``replica.ack`` (docs/chaos.md) fires on each FOLLOWER
apply: ``drop`` loses the ack AFTER the apply (the write is durable on
that replica but uncounted — quorum degrades, data does not), and
``delay_us`` stretches the ack.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional

from incubator_brpc_tpu import errors
from incubator_brpc_tpu.chaos import injector as _chaos
from incubator_brpc_tpu.replication import metrics as _m
from incubator_brpc_tpu.replication.lease import Lease, LeaseBoard
from incubator_brpc_tpu.resharding.migration import (
    ShardUnavailable,
    verified_write,
    verified_write_many,
)


class ReplicationError(RuntimeError):
    """Base of the replication failures a channel maps onto ERPC
    codes (``.code``)."""

    code = errors.EINTERNAL


class StaleEpoch(ReplicationError):
    """The write's lease epoch is older than the group's newest lease —
    the fencing invariant fired.  The writer must re-elect and reissue
    under the new epoch; NEVER retriable under the same lease."""

    code = errors.ESTALEEPOCH


class QuorumLost(ReplicationError):
    """Fewer than ``quorum`` replicas acknowledged the write — too many
    dead/unreachable members.  Same family as a ParallelChannel with
    too many failed legs."""

    code = errors.ETOOMANYFAILS


class NoLeader(ReplicationError):
    """No candidate could take the lease within the write budget
    (board partitioned / chaos dropping every grant)."""

    code = errors.EINTERNAL


class LeaderLost(ReplicationError):
    """The leader's own store died mid-write — the group must step the
    lease down and re-elect before retrying."""

    code = errors.EINTERNAL


class ReplicaNode:
    """One replica: a shard store (PsShardStore / CacheShardStore /
    anything with read/write/delete/list_keys) plus the replication
    bookkeeping the group fences and repairs with."""

    def __init__(self, name: str, store, endpoint: str = ""):
        self.name = name
        self.store = store
        self.endpoint = endpoint or name
        self.alive = True
        #: a repairing replica applies nothing and serves nothing until
        #: repair() finishes copying it up to date
        self.repairing = False
        #: newest lease epoch this replica has SEEN — writes below
        #: max(floor, board epoch) are fenced even if the board is
        #: unreachable (the replica remembers)
        self.epoch_floor = 0
        #: highest write sequence applied — the election tiebreak
        #: (most-caught-up candidate wins) and the repair target
        self.applied_seq = 0

    def apply(self, group: "ReplicaGroup", epoch: int, seq: int,
              op: str, key: str, value: Optional[bytes],
              is_leader: bool) -> bool:
        """Apply one replicated write; True iff the leader may COUNT
        this replica's ack.  Raises StaleEpoch on a fenced epoch and
        ShardUnavailable when the replica is dead."""
        if not self.alive or self.repairing:
            raise ShardUnavailable(f"replica {self.name} not serving")
        floor = max(group.board.epoch_of(group.name), self.epoch_floor)
        if epoch < floor:
            raise StaleEpoch(
                f"epoch {epoch} < {floor} on {self.name} (fenced)"
            )
        self.epoch_floor = max(self.epoch_floor, epoch)
        acked = True
        if not is_leader and _chaos.armed:
            spec = _chaos.check(
                "replica.ack", peer=self.name, method=group.name
            )
            if spec is not None:
                if spec.action == "delay_us":
                    _chaos.sleep_us(spec.arg)
                elif spec.action == "drop":
                    # the ack is lost AFTER the apply below: the write
                    # is durable here, just uncounted — quorum
                    # degrades, readable data does not
                    acked = False
        if op == "put":
            self.store.write(key, value)
        elif op == "delete":
            self.store.delete(key)
        else:
            raise ValueError(f"unknown replicated op {op!r}")
        self.applied_seq = max(self.applied_seq, seq)
        return acked


# ---------------------------------------------------------------------------
# registry (the /replication builtin reads this)
# ---------------------------------------------------------------------------

_REGISTRY_LOCK = threading.Lock()
_GROUPS: Dict[str, "ReplicaGroup"] = {}


def register_group(group: "ReplicaGroup") -> None:
    with _REGISTRY_LOCK:
        _GROUPS[group.name] = group


def unregister_group(name: str) -> None:
    with _REGISTRY_LOCK:
        _GROUPS.pop(name, None)


def groups_snapshot() -> Dict[str, dict]:
    with _REGISTRY_LOCK:
        groups = list(_GROUPS.values())
    return {g.name: g.describe() for g in groups}


class ReplicaGroup:
    """The replicas of one shard plus the write/election/repair logic.

    ``quorum`` defaults to a majority of the group; RF=1 degenerates to
    quorum 1 with the sole member a permanent leader — the unreplicated
    semantics exactly (the channel additionally bypasses groups
    entirely at RF=1, so this is belt and braces)."""

    COUNTER_KEYS = (
        "leader_changes", "quorum_writes", "quorum_failures",
        "fenced_writes", "repair_keys", "hedged_reads",
    )

    def __init__(self, name: str, nodes: List[ReplicaNode],
                 board: Optional[LeaseBoard] = None,
                 quorum: Optional[int] = None,
                 lease_ttl_s: float = 0.5,
                 write_timeout_s: float = 5.0):
        if not nodes:
            raise ValueError("a replica group needs at least one node")
        self.name = name
        self.nodes = list(nodes)
        self.board = board if board is not None else LeaseBoard(lease_ttl_s)
        self.quorum = int(quorum) if quorum else len(nodes) // 2 + 1
        if not 1 <= self.quorum <= len(nodes):
            raise ValueError(
                f"quorum {self.quorum} out of range for {len(nodes)} nodes"
            )
        self.lease_ttl_s = float(lease_ttl_s)
        self.write_timeout_s = float(write_timeout_s)
        self._lock = threading.Lock()
        self._seq = 0
        self._lease: Optional[Lease] = None
        self._leader: Optional[ReplicaNode] = None
        # last DISTINCT leader name ever elected — leader_changes counts
        # transitions between different names, surviving the step_down
        # gap in between (initial elections from no-leader don't count)
        self._last_leader: Optional[str] = None
        #: bumped whenever the serving set or the leader changes — the
        #: channel compares this int per call to refresh its node lists
        #: cheaply (no allocation on the steady path)
        self.members_version = 0
        self.counters: Dict[str, int] = {k: 0 for k in self.COUNTER_KEYS}

    # -- membership --------------------------------------------------------
    def node(self, name: str) -> ReplicaNode:
        for n in self.nodes:
            if n.name == name:
                return n
        raise KeyError(name)

    def serving_nodes(self) -> List[ReplicaNode]:
        return [n for n in self.nodes if n.alive and not n.repairing]

    def mark_dead(self, name: str) -> None:
        node = self.node(name)
        if node.alive:
            node.alive = False
            with self._lock:
                self.members_version += 1
            # a dead leader steps its lease down so failover does not
            # have to wait out the TTL (the TTL still bounds the case
            # where nobody NOTICES the death)
            if self._leader is node and self._lease is not None:
                self.board.release(
                    self.name, self._lease.holder, self._lease.epoch
                )

    def mark_alive(self, name: str) -> None:
        """A rejoined replica is alive but NOT serving until repair()
        completes — lease-edge rule 3 (docs/replication.md)."""
        node = self.node(name)
        node.alive = True
        node.repairing = True
        with self._lock:
            self.members_version += 1

    # -- leadership --------------------------------------------------------
    def leader(self) -> Optional[ReplicaNode]:
        return self._leader

    def lease(self) -> Optional[Lease]:
        return self._lease

    def epoch(self) -> int:
        return self._lease.epoch if self._lease is not None else 0

    def ensure_leader(self) -> Optional[ReplicaNode]:
        """Renew the current lease (at < half TTL remaining) or elect:
        the most-caught-up serving replica acquires the next epoch.
        None when no lease could be taken (board dark / chaos) — the
        write loop retries until its budget runs out."""
        lease, leader = self._lease, self._leader
        if (
            lease is not None and leader is not None
            and leader.alive and not leader.repairing
            and self.board.validate(self.name, lease.holder, lease.epoch)
        ):
            if lease.remaining() < self.lease_ttl_s / 2.0:
                renewed = self.board.renew(
                    self.name, lease.holder, lease.epoch, self.lease_ttl_s
                )
                if renewed is not None:
                    self._lease = renewed
            return leader
        candidates = sorted(
            self.serving_nodes(), key=lambda n: -n.applied_seq
        )
        for cand in candidates:
            got = self.board.acquire(self.name, cand.name, self.lease_ttl_s)
            if got is None:
                continue
            self._lease, self._leader = got, cand
            with self._lock:
                self.members_version += 1
            if (
                self._last_leader is not None
                and self._last_leader != cand.name
            ):
                self.counters["leader_changes"] += 1
                _m.replica_leader_changes << 1
            self._last_leader = cand.name
            return cand
        return None

    def step_down(self) -> None:
        """Drop the local notion of leadership (and release the lease
        if still held) — the StaleEpoch/LeaderLost recovery edge."""
        lease = self._lease
        if lease is not None:
            self.board.release(self.name, lease.holder, lease.epoch)
        self._lease, self._leader = None, None
        with self._lock:
            self.members_version += 1

    # -- writes ------------------------------------------------------------
    def write_as(self, leader: ReplicaNode, epoch: int, op: str,
                 key: str, value: Optional[bytes] = None) -> int:
        """ONE write attempt as ``leader`` under ``epoch`` — the
        low-level step the lease-edge tests drive directly (an old
        leader calling this after losing its lease must see every
        attempt raise StaleEpoch and ack NOTHING).  Returns the
        sequence number on success."""
        with self._lock:
            self._seq += 1
            seq = self._seq
        acks = 0
        fenced: Optional[StaleEpoch] = None
        for node in self.nodes:
            if not node.alive or node.repairing:
                continue
            try:
                ok = node.apply(
                    self, epoch, seq, op, key, value,
                    is_leader=node is leader,
                )
            except StaleEpoch as e:
                fenced = e
            except ShardUnavailable:
                if node is leader:
                    raise LeaderLost(
                        f"leader {leader.name} died mid-write"
                    ) from None
                # a dead follower just fails to ack; health marking is
                # the caller's business (mark_dead)
            else:
                if ok:
                    acks += 1
        # never ack under a fenced or lapsed lease — even if a quorum
        # applied, the caller must re-elect and reissue so the ack is
        # attributable to a live epoch (the zero-acked-write-loss proof
        # leans on this ordering)
        if fenced is not None or not self.board.validate(
            self.name, leader.name, epoch
        ):
            self.counters["fenced_writes"] += 1
            _m.replica_fenced_writes << 1
            raise fenced if fenced is not None else StaleEpoch(
                f"lease for epoch {epoch} lapsed before ack"
            )
        if acks < self.quorum:
            self.counters["quorum_failures"] += 1
            _m.replica_quorum_failures << 1
            raise QuorumLost(
                f"{acks}/{self.quorum} acks for {op}({key})"
            )
        self.counters["quorum_writes"] += 1
        _m.replica_quorum_writes << 1
        return seq

    def _replicated(self, op: str, key: str,
                    value: Optional[bytes]) -> int:
        import time as _time

        deadline = _time.monotonic() + self.write_timeout_s
        last: ReplicationError = NoLeader(
            f"no leader for {self.name} within write budget"
        )
        while _time.monotonic() < deadline:
            leader = self.ensure_leader()
            if leader is None:
                _time.sleep(min(0.01, self.lease_ttl_s / 10.0))
                continue
            epoch = self.epoch()
            try:
                return self.write_as(leader, epoch, op, key, value)
            except LeaderLost as e:
                last = e
                self.mark_dead(leader.name)
                self.step_down()
            except StaleEpoch as e:
                # our lease moved on under us: drop it and re-elect
                last = e
                self._lease, self._leader = None, None
                with self._lock:
                    self.members_version += 1
            except QuorumLost as e:
                last = e
                _time.sleep(min(0.01, self.lease_ttl_s / 10.0))
        raise last

    def put(self, key: str, value: bytes) -> int:
        """Quorum write; returns the applied sequence.  Raises a
        ReplicationError (→ ERPC code) when the group cannot take the
        write within ``write_timeout_s``."""
        return self._replicated("put", key, bytes(value))

    def delete(self, key: str) -> int:
        return self._replicated("delete", key, None)

    # -- reads -------------------------------------------------------------
    def read_any(self, key: str) -> Optional[bytes]:
        """Read from the first serving replica that answers — the
        in-process fallback path; the channel's hedged fan-out is the
        production read plane."""
        err: Optional[Exception] = None
        for node in self.serving_nodes():
            try:
                return node.store.read(key)
            except ShardUnavailable as e:
                err = e
        if err is not None:
            raise err
        raise ShardUnavailable(f"no serving replica in {self.name}")

    # -- repair ------------------------------------------------------------
    def repair(self, name: str,
               on_copy: Optional[Callable[[str], None]] = None) -> int:
        """Catch replica ``name`` up from the leader through the
        resharding verified-move path (bulk when both stores carry the
        DMGET/DMSET surface and no chaos wants per-key semantics), then
        admit it to the serving set.  Returns keys copied (its
        behind-ness) — counted into ``repair_keys``."""
        node = self.node(name)
        leader = self.ensure_leader()
        if leader is None:
            raise NoLeader(f"cannot repair {name}: no leader")
        if node is leader:
            raise ValueError("cannot repair the leader from itself")
        node.alive = True
        node.repairing = True
        with self._lock:
            self.members_version += 1
        src, dst = leader.store, node.store
        want = set(src.list_keys())
        have = set(dst.list_keys())
        # extraneous keys (deleted while the replica was away) go first
        # so a read after repair can never resurrect a deleted value
        for key in sorted(have - want):
            dst.delete(key)
        missing = sorted(want - have)
        stale: List[str] = []
        copied = 0
        from incubator_brpc_tpu.resharding.migration import range_checksum

        for key in sorted(want & have):
            a, b = src.read(key), dst.read(key)
            if a is None:
                continue
            if b is None or range_checksum(a) != range_checksum(b):
                stale.append(key)
        todo = missing + stale
        bulk_ok = (
            not _chaos.armed
            and on_copy is None
            and callable(getattr(src, "read_many", None))
            and callable(getattr(dst, "write_many", None))
            and callable(getattr(dst, "read_many", None))
        )
        while todo:
            if bulk_ok and len(todo) >= 2:
                values = src.read_many(todo)
                present = [
                    (k, v) for k, v in zip(todo, values) if v is not None
                ]
                ok_keys, failed_keys, _ = (
                    verified_write_many(dst, present) if present
                    else ([], [], {})
                )
                copied += len(ok_keys)
                todo = list(failed_keys)
            else:
                remaining: List[str] = []
                for key in todo:
                    if on_copy is not None:
                        on_copy(key)
                    value = src.read(key)
                    if value is None:
                        continue  # deleted under us — nothing to copy
                    ok, _ = verified_write(dst, key, value)
                    if ok:
                        copied += 1
                    else:
                        remaining.append(key)  # re-copy next round
                todo = remaining
        node.applied_seq = leader.applied_seq
        node.epoch_floor = max(node.epoch_floor, self.epoch())
        node.repairing = False
        with self._lock:
            self.members_version += 1
        self.counters["repair_keys"] += copied
        _m.replica_repair_keys << copied
        return copied

    # -- introspection ------------------------------------------------------
    def describe(self) -> dict:
        lease = self._lease
        return {
            "leader": self._leader.name if self._leader else None,
            "epoch": lease.epoch if lease else 0,
            "lease_remaining_s": (
                round(max(0.0, lease.remaining()), 3) if lease else 0.0
            ),
            "quorum": self.quorum,
            "replicas": [
                {
                    "name": n.name,
                    "endpoint": n.endpoint,
                    "alive": n.alive,
                    "repairing": n.repairing,
                    "applied_seq": n.applied_seq,
                    "epoch_floor": n.epoch_floor,
                }
                for n in self.nodes
            ],
            "counters": dict(self.counters),
        }
