"""Leader leases with epoch fencing — the replication tier's whole
consensus budget (docs/replication.md).

No Raft.  One :class:`LeaseBoard` per coordination domain hands out
**epoch-numbered leases**, one per replica group:

* a candidate may acquire a group's lease only while no live lease is
  held by someone else; every successful acquisition bumps the group's
  epoch by one — epochs are totally ordered and never reused;
* the holder renews before the TTL runs out; a lost renewal (network,
  chaos) lets the lease expire, after which any candidate may take the
  next epoch — failover is bounded by the lease TTL;
* every replicated write carries its lease epoch, and replicas reject
  writes whose epoch is older than the newest lease they have seen —
  the **fencing invariant**: a deposed leader can keep writing forever
  and never get a single write acknowledged (ESTALEEPOCH).

Leases are *published* the same way the re-sharding epoch is: through
naming tags.  The tag grammar parallels PR 14's ``"i/N@E"``:

    ``"<group>@<epoch>:<holder>"``        e.g. ``"g0@3:ici://slice0/chip1"``

so a naming watcher (or the ``/replication`` builtin) learns the
leader and epoch of every group from the server list alone, and old
clients that only understand ``"i/N"`` partition tags ignore lease
tags entirely (``parse_epoch_tag`` returns None for them — mixed
fleets degrade safely).

Chaos site ``replica.lease`` (docs/chaos.md) fires on every grant and
renewal decision: ``drop`` refuses the grant / loses the renewal — the
seeded forced-failover knob — and ``delay_us`` stretches the decision.
"""

from __future__ import annotations

import threading
import time as _time
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

from incubator_brpc_tpu.chaos import injector as _chaos


# ---------------------------------------------------------------------------
# lease-in-tag naming grammar:  "<group>@<epoch>:<holder>"
# ---------------------------------------------------------------------------

def format_lease_tag(group: str, epoch: int, holder: str) -> str:
    """The naming-tag publication of a granted lease — the lease-plane
    parallel of resharding's ``format_epoch_tag`` (``"i/N@E"``)."""
    return f"{group}@{int(epoch)}:{holder}"


def parse_lease_tag(tag: str) -> Optional[Tuple[str, int, str]]:
    """``"g0@3:ici://slice0/chip1"`` → ``("g0", 3, "ici://slice0/chip1")``;
    None when the tag is not a lease tag (partition ``"i/N[@E]"`` tags
    and free-form tags both return None — the grammars coexist on one
    naming plane)."""
    base, at, rest = tag.partition("@")
    if not at or not base or "/" in base:
        return None
    epoch_s, colon, holder = rest.partition(":")
    if not colon or not holder:
        return None
    try:
        epoch = int(epoch_s)
    except ValueError:
        return None
    return base, epoch, holder


def max_lease_epoch(nodes, group: str) -> int:
    """The highest epoch any node's tag advertises for ``group`` — what
    a watcher adopts (the failover bump is exactly this going up)."""
    best = 0
    for node in nodes:
        parsed = parse_lease_tag(getattr(node, "tag", "") or "")
        if parsed is not None and parsed[0] == group:
            best = max(best, parsed[1])
    return best


# ---------------------------------------------------------------------------
# the board
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class Lease:
    """One granted lease: immutable; renewal returns a NEW Lease with a
    later deadline at the same epoch."""

    group: str
    holder: str
    epoch: int
    deadline: float  # time.monotonic() when the lease lapses
    ttl_s: float

    def remaining(self, now: Optional[float] = None) -> float:
        return self.deadline - (now if now is not None else _time.monotonic())

    def valid(self, now: Optional[float] = None) -> bool:
        return self.remaining(now) > 0.0

    def tag(self) -> str:
        return format_lease_tag(self.group, self.epoch, self.holder)


class LeaseBoard:
    """The serialized grant/renew authority — per-group epoch-numbered
    leases under one lock (the two-candidate race resolves HERE: grants
    are atomic, so exactly one candidate wins each epoch).

    In-process deployments (every test and the single-pod default)
    share one board object; renewals then cost a lock acquisition.  A
    remote board sits behind the same surface over the RPC plane — the
    group only ever calls acquire/renew/release/current, all of which
    are one round trip."""

    def __init__(self, default_ttl_s: float = 0.5, publish=None):
        self._lock = threading.Lock()
        self._leases: Dict[str, Lease] = {}
        # highest epoch ever granted per group — epochs survive expiry
        # so a re-grant after a lapse still moves FORWARD (fencing
        # depends on it)
        self._epochs: Dict[str, int] = {}
        self.default_ttl_s = float(default_ttl_s)
        # publish(lease_or_None, group) — push the lease tag into the
        # naming plane (e.g. retag the holder's ServerNode); optional
        self._publish = publish

    # -- chaos -------------------------------------------------------------
    @staticmethod
    def _chaos_gate(group: str) -> bool:
        """True when the grant/renewal message is LOST (chaos drop)."""
        if not _chaos.armed:
            return False
        spec = _chaos.check("replica.lease", method=group)
        if spec is None:
            return False
        if spec.action == "delay_us":
            _chaos.sleep_us(spec.arg)
            return False
        return spec.action == "drop"

    # -- grant / renew / release -------------------------------------------
    def acquire(self, group: str, candidate: str,
                ttl_s: Optional[float] = None) -> Optional[Lease]:
        """Grant ``candidate`` the next epoch's lease on ``group`` —
        None while a live lease is held by someone else (wait for it to
        lapse), or when chaos drops the grant.  Re-acquiring a lease
        the candidate already holds renews it instead (same epoch)."""
        if self._chaos_gate(group):
            return None
        ttl = float(ttl_s) if ttl_s is not None else self.default_ttl_s
        with self._lock:
            now = _time.monotonic()
            cur = self._leases.get(group)
            if cur is not None and cur.valid(now):
                if cur.holder != candidate:
                    return None  # live lease elsewhere: fencing says wait
                lease = Lease(group, candidate, cur.epoch, now + ttl, ttl)
            else:
                epoch = self._epochs.get(group, 0) + 1
                self._epochs[group] = epoch
                lease = Lease(group, candidate, epoch, now + ttl, ttl)
            self._leases[group] = lease
        if self._publish is not None:
            self._publish(lease, group)
        return lease

    def renew(self, group: str, holder: str, epoch: int,
              ttl_s: Optional[float] = None) -> Optional[Lease]:
        """Extend the lease — only for the CURRENT holder at the
        CURRENT epoch.  None when the renewal is lost (chaos) or the
        lease moved on (another candidate holds a newer epoch): the
        caller must step down and re-elect."""
        if self._chaos_gate(group):
            return None
        ttl = float(ttl_s) if ttl_s is not None else self.default_ttl_s
        with self._lock:
            cur = self._leases.get(group)
            if cur is None or cur.holder != holder or cur.epoch != int(epoch):
                return None
            now = _time.monotonic()
            lease = Lease(group, holder, cur.epoch, now + ttl, ttl)
            self._leases[group] = lease
        return lease

    def release(self, group: str, holder: str, epoch: int) -> bool:
        """Voluntary step-down by the holder's coordinator (e.g. the
        leader's server died under it) — lets the group fail over
        without waiting out the TTL.  Only the matching holder+epoch
        may release; the epoch counter is NOT rolled back."""
        with self._lock:
            cur = self._leases.get(group)
            if cur is None or cur.holder != holder or cur.epoch != int(epoch):
                return False
            del self._leases[group]
        if self._publish is not None:
            self._publish(None, group)
        return True

    # -- reads -------------------------------------------------------------
    def current(self, group: str) -> Optional[Lease]:
        with self._lock:
            return self._leases.get(group)

    def epoch_of(self, group: str) -> int:
        """The newest epoch ever granted for ``group`` (0 = never) —
        what replicas fence stale writes against.  Monotonic even
        across lapses and releases."""
        with self._lock:
            return self._epochs.get(group, 0)

    def validate(self, group: str, holder: str, epoch: int) -> bool:
        """Is (holder, epoch) the LIVE lease right now?  The leader's
        last check before acknowledging a quorum write — never ack
        under a lease the board no longer holds."""
        with self._lock:
            cur = self._leases.get(group)
            return (
                cur is not None
                and cur.holder == holder
                and cur.epoch == int(epoch)
                and cur.valid()
            )

    # -- test / operator instruments ---------------------------------------
    def expire(self, group: str) -> None:
        """Force the group's lease past its deadline (as if the TTL
        elapsed with every renewal lost) — the deterministic partition
        instrument the lease-edge tests use.  The epoch counter keeps
        its value, so the next acquire still moves forward."""
        with self._lock:
            cur = self._leases.get(group)
            if cur is not None:
                self._leases[group] = Lease(
                    cur.group, cur.holder, cur.epoch,
                    _time.monotonic() - 1.0, cur.ttl_s,
                )

    def snapshot(self) -> Dict[str, dict]:
        """Per-group lease state (the ``/replication`` builtin)."""
        with self._lock:
            now = _time.monotonic()
            return {
                g: {
                    "holder": lease.holder,
                    "epoch": lease.epoch,
                    "lease_remaining_s": round(max(0.0, lease.remaining(now)), 3),
                    "tag": lease.tag(),
                }
                for g, lease in self._leases.items()
            }
