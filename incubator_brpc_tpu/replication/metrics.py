"""Replication-tier metrics (``rpc_replica_*``; registered at import —
METRIC_MODULES lint).  This module is jax-free at import by contract:
the metrics lint imports it anywhere, including hosts with no
accelerator runtime.

The per-group step-log counters live on each :class:`ReplicaGroup`
(``group.counters``) — these process-wide adders mirror them so
``/metrics`` and dashboards see the pod totals.
"""

from __future__ import annotations

from incubator_brpc_tpu.metrics.reducer import Adder

#: a shard group's leader moved to a DIFFERENT node (initial elections
#: from no-leader do not count — the bench's steady-segment guard pins
#: this to 0 under healthy traffic)
replica_leader_changes = Adder(0).expose("rpc_replica_leader_changes")
#: writes acknowledged to the caller after a quorum of replicas
#: confirmed (the acked-write durability proof counts these)
replica_quorum_writes = Adder(0).expose("rpc_replica_quorum_writes")
#: write attempts that could NOT gather a quorum (too many dead /
#: unacked replicas) — surfaced to the caller as ETOOMANYFAILS
replica_quorum_failures = Adder(0).expose("rpc_replica_quorum_failures")
#: write attempts rejected because their lease epoch was stale
#: (ESTALEEPOCH — the fencing invariant firing, docs/replication.md)
replica_fenced_writes = Adder(0).expose("rpc_replica_fenced_writes")
#: keys copied onto a rejoining/fresh replica by the repair engine
#: (the shared resharding verified-move path)
replica_repair_keys = Adder(0).expose("rpc_replica_repair_keys")
#: replicated reads whose first attempt was slow/dead enough that the
#: PR 8 backup-request machinery hedged to another replica
replica_hedged_reads = Adder(0).expose("rpc_replica_hedged_reads")
