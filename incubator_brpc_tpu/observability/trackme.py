"""trackme — version census / kill-switch pings.

Analog of reference trackme.{h,cpp} (trackme.cpp:36-39): when a
trackme server is configured (flag ``trackme_server``), the process
pings it in the background with its framework version; the response's
severity drives WARNING/FATAL logs (known-bug notices) and the server
may retune the ping interval. Disabled by default (opt-in phone-home,
same stance as the reference's -trackme_server flag).

Server side: TrackMeService answers the pings — register it on any
server to act as the census endpoint (the reference ships
tools/trackme_server; ours is a first-class service).
"""

from __future__ import annotations

import threading
from typing import Optional

from incubator_brpc_tpu import __version__ as _version
from incubator_brpc_tpu.protos.trackme_pb2 import (
    TrackMeRequest,
    TrackMeResponse,
    TrackMeFatal,
    TrackMeOK,
    TrackMeWarning,
)
from incubator_brpc_tpu.server.service import Service, ServiceStub, rpc_method
from incubator_brpc_tpu.utils.flags import define_flag, get_flag
from incubator_brpc_tpu.utils.logging import log_error, log_info

define_flag(
    "trackme_server",
    "",
    "address of a TrackMeService census server; empty disables pings",
    validator=lambda v: True,
)

_DEFAULT_INTERVAL_S = 300
_rpc_version = 1  # bumped when wire-visible behavior changes


def rpc_version() -> int:
    return _rpc_version


class TrackMeService(Service):
    """The census endpoint (reference tools/trackme_server analog).
    Subclass and override ``check`` to flag known-bad versions."""

    # pinned: subclasses must keep answering at the canonical name the
    # pinger's stub addresses
    SERVICE_NAME = "TrackMeService"

    @rpc_method(TrackMeRequest, TrackMeResponse)
    def TrackMe(self, controller, request, response, done):
        sev, text, interval = self.check(request.rpc_version, request.server_addr)
        response.severity = sev
        if text:
            response.error_text = text
        if interval:
            response.new_interval = interval
        done()

    def check(self, version: int, server_addr: str):
        """→ (severity, error_text, new_interval_s). Default: all OK."""
        return TrackMeOK, "", 0


class _TrackMePinger:
    def __init__(self):
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._interval = _DEFAULT_INTERVAL_S
        self._lock = threading.Lock()
        self.last_response: Optional[TrackMeResponse] = None
        self.pings = 0

    def start_once(self):
        with self._lock:
            if self._thread is not None or not get_flag("trackme_server", ""):
                return
            # fresh Event per generation: the previous thread keeps ITS
            # (set) event, so a restart can never resurrect it
            self._stop = threading.Event()
            stop = self._stop
            self._thread = threading.Thread(
                target=self._run, args=(stop,), daemon=True,
                name="tpubrpc-trackme",
            )
            self._thread.start()

    def stop(self):
        with self._lock:
            self._stop.set()
            t, self._thread = self._thread, None
        if t is not None and t is not threading.current_thread():
            t.join(timeout=2)

    def ping_now(self, server_addr: str = "") -> Optional[TrackMeResponse]:
        """One synchronous ping (also the body of the background loop)."""
        target = get_flag("trackme_server", "")
        if not target:
            return None
        from incubator_brpc_tpu.client.channel import Channel, ChannelOptions
        from incubator_brpc_tpu.client.controller import Controller

        ch = Channel(ChannelOptions(timeout_ms=3000, max_retry=0))
        try:
            if ch.init(target) != 0:
                return None
            stub = ServiceStub(ch, TrackMeService)
            c = Controller()
            req = TrackMeRequest(rpc_version=_rpc_version)
            if server_addr:
                req.server_addr = server_addr
            resp = stub.TrackMe(c, req)
            if c.failed():
                return None
            self.pings += 1
            self.last_response = resp
            if resp.severity == TrackMeFatal:
                log_error("[TrackMe] FATAL notice: %s", resp.error_text)
            elif resp.severity == TrackMeWarning:
                log_error("[TrackMe] warning: %s", resp.error_text)
            if resp.new_interval > 0:
                self._interval = resp.new_interval
            return resp
        finally:
            ch.close()

    def _run(self, stop):
        log_info("trackme pinger started (version %s)", _version)
        while not stop.wait(1.0 if self.pings == 0 else self._interval):
            try:
                self.ping_now()
            except Exception as e:  # noqa: BLE001 — census must never hurt
                log_error("trackme ping failed: %r", e)


_pinger = _TrackMePinger()


def pinger() -> _TrackMePinger:
    return _pinger


def start_trackme():
    """Called on server start (reference triggers on first RPC)."""
    _pinger.start_once()
