"""Trace assembler — joins SpanDB rows into a hierarchical timeline.

One RPC crossing the pod leaves many spans sharing a trace_id: the
client call, per-chip collective legs, the server span, nested client
calls the handler made. This module reassembles them into the parent/
child tree (span_id ↔ parent_span_id) and renders the indented,
phase-annotated view /rpcz?trace=<id> serves — the reference's span
browsing (span.cpp SpanDB + rpcz_service) with the hierarchy made
explicit.
"""

from __future__ import annotations

from typing import List, Optional

from incubator_brpc_tpu.observability.span import (
    Span,
    format_trace_id,
    span_db,
)

# render order inside one parent: spans sort by start time, with kind
# breaking exact-us ties so client legs precede the server work they
# caused on fast loopback clocks
_KIND_RANK = {"client": 0, "collective": 1, "server": 2}


class TraceNode:
    __slots__ = ("span", "children")

    def __init__(self, span: Span):
        self.span = span
        self.children: List["TraceNode"] = []


def assemble(trace_id: int, db=None) -> List[TraceNode]:
    """Build the span tree for one trace from the in-memory ring.
    Returns the roots (spans whose parent is not in the trace —
    normally one: the originating client call)."""
    db = db or span_db()
    spans = db.by_trace(trace_id)
    nodes = {}
    for s in spans:
        # ring may hold duplicate ids after retries resubmit; last wins
        nodes[s.span_id] = TraceNode(s)
    roots: List[TraceNode] = []
    for node in nodes.values():
        parent = nodes.get(node.span.parent_span_id)
        if parent is not None and parent is not node:
            parent.children.append(node)
        else:
            roots.append(node)
    key = lambda n: (  # noqa: E731
        n.span.start_us, _KIND_RANK.get(n.span.kind, 3)
    )
    for node in nodes.values():
        node.children.sort(key=key)
    roots.sort(key=key)
    return roots


def _render_node(node: TraceNode, t0: int, depth: int, out: List[str]):
    s = node.span
    pad = "  " * depth
    deltas = s.phase_deltas()
    phases = (
        " [" + " ".join(f"{n}={d}us" for n, d in deltas) + "]"
        if deltas
        else ""
    )
    out.append(
        f"{pad}+{s.start_us - t0}us {s.kind} {s.service}.{s.method} "
        f"span={format_trace_id(s.span_id)} latency={s.latency_us}us "
        f"error={s.error_code} req={s.request_size}B "
        f"resp={s.response_size}B remote={s.remote_side}{phases}"
    )
    for t, a in s.annotations or ():
        out.append(f"{pad}    @{t - t0}us {a}")
    for child in node.children:
        _render_node(child, t0, depth + 1, out)


def render(trace_id: int, db=None) -> Optional[str]:
    """Indented timeline for one trace; None when the ring has no spans
    for it (the caller may still consult the sqlite backend)."""
    roots = assemble(trace_id, db)
    if not roots:
        return None
    t0 = min(n.span.start_us for n in roots)
    out = [
        f"trace {format_trace_id(trace_id)} (times relative to first span)"
    ]
    for root in roots:
        _render_node(root, t0, 0, out)
    return "\n".join(out)
