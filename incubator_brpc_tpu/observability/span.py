"""rpcz tracing — per-RPC spans through the bvar Collector.

Analog of reference Span (span.h:47, span.cpp 801 LoC): created per
client call (channel.cpp:478-485) and per server request
(baidu_rpc_protocol.cpp:382-394); trace_id/span_id/parent_span_id
propagate inside the request meta; annotations and phase timestamps
ride along; submission goes through the bvar Collector sampling
pipeline (bounded overhead) into an in-memory SpanDB (the reference
persists to leveldb; /rpcz browses it either way). The parent span for
nested client calls lives in task-local storage (reference
bthread::tls_bls, span.h:75-78).
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from typing import List, Optional

from incubator_brpc_tpu.metrics.collector import Collected
from incubator_brpc_tpu.runtime import local as task_local
from incubator_brpc_tpu.utils import flags as _flags_mod
from incubator_brpc_tpu.utils.flags import get_flag
from incubator_brpc_tpu.utils.hashes import fast_rand

_TLS_KEY = "rpcz_parent_span"


def format_trace_id(trace_id: int) -> str:
    """The ONE printable form of a trace/span id: lowercase hex, no
    prefix. Every surface that renders or transports an id as text
    (/rpcz pages, x-trace-id/x-span-id HTTP headers, /rpcz/export
    JSON) goes through this pair so ids copy-paste across them."""
    return f"{trace_id:x}"


def parse_trace_id(text: str) -> int:
    """Inverse of format_trace_id; raises ValueError on junk."""
    return int(text, 16)

# the rpcz_enabled Flag OBJECT, bound once: span creation runs per RPC
# and get_flag's dict lookup is measurable there (flag objects are
# permanent — /flags?setvalue mutates .value in place)
_RPCZ_FLAG = _flags_mod._flags["rpcz_enabled"]

_SPAN_RATE_FLAG = _flags_mod.define_flag(
    "rpcz_max_spans_per_second",
    500,
    "rpcz trace-creation budget per second; traffic beyond it is not "
    "traced (sampling, like the reference Collector speed limit — "
    "moved to creation so untraced requests pay nothing). 500 new "
    "traces/s saturates the /rpcz ring in ~4s; raise it for "
    "higher-fidelity capture at a hot-path cost",
    validator=lambda v: v > 0,
)

# Creation-side sampling window. The Collector always enforced a
# 1000/s admission at SUBMIT time; under load that meant most spans
# were created, stamped through every layer, then dropped. Applying
# the same budget at creation bounds rpcz's hot-path overhead by
# construction: over-budget RPCs skip span work entirely. Dirty
# (unlocked) counters — sampling is approximate by design, and the
# GIL keeps the list ops safe.
#
# Joined (trace-id-propagated) spans get their own counter with a 4x
# ceiling: sampled traces should stay complete across the pod, but the
# trace id is WIRE-CONTROLLED — without a bound, an upstream (or a
# hostile caller) stamping ids on every request would re-open the
# unbounded create-stamp-drop path the budget exists to close.
_JOIN_MULTIPLIER = 4
_window = [0.0, 0, 0]  # [window_start, roots_created, joined_created]


def _admit(joined: bool) -> bool:
    now = time.monotonic()
    w = _window
    if now - w[0] >= 1.0:
        w[0] = now
        w[1] = 0
        w[2] = 0
    if joined:
        if w[2] >= _SPAN_RATE_FLAG.value * _JOIN_MULTIPLIER:
            return False
        w[2] += 1
        return True
    if w[1] >= _SPAN_RATE_FLAG.value:
        return False
    w[1] += 1
    return True

# Phase timestamps an RPC picks up as it crosses the stack (the
# reference Span's received/start-parse/start-callback/sent stamps,
# span.h:47): every field is a wall-clock us, 0 = never reached.
#   received_us        bytes hit the event dispatcher / fabric CQ
#   enqueued_us        parsed message handed to a worker queue
#   parse_done_us      protocol parse produced the message
#   callback_start_us  user method entered
#   callback_done_us   user method ran its done()
#   response_write_us  serialized response queued on the socket
#   sent_us            response bytes flushed to the kernel/fabric
PHASE_FIELDS = (
    "received_us",
    "enqueued_us",
    "parse_done_us",
    "callback_start_us",
    "callback_done_us",
    "response_write_us",
    "sent_us",
    # device window inside the callback: stamped around kernel dispatch
    # + the sanctioned completion pull (models/parameter_server.py
    # Forward), so /latency_breakdown shows host-vs-device per method
    "device_start_us",
    "device_done_us",
)

# Named deltas derived from the stamps (what /latency_breakdown
# aggregates): (phase, from_field, to_field).
PHASE_DELTAS = (
    ("parse", "received_us", "parse_done_us"),
    ("queue", "enqueued_us", "callback_start_us"),
    ("callback", "callback_start_us", "callback_done_us"),
    ("device", "device_start_us", "device_done_us"),
    ("write", "callback_done_us", "response_write_us"),
    ("send", "response_write_us", "sent_us"),
)


class Span(Collected):
    __slots__ = (
        "trace_id",
        "span_id",
        "parent_span_id",
        "kind",
        "service",
        "method",
        "start_us",
        "end_us",
        "error_code",
        "remote_side",
        "annotations",
        "request_size",
        "response_size",
        "_open",  # one-shot close guard (see _try_close)
    ) + PHASE_FIELDS

    def __init__(self, kind: str, service: str = "", method: str = ""):
        self.kind = kind  # "client" | "server" | "collective"
        self.service = service
        self.method = method
        self.trace_id = 0
        self.span_id = fast_rand() & 0x7FFFFFFFFFFF
        self.parent_span_id = 0
        self.start_us = time.time_ns() // 1000
        self.end_us = 0
        self.error_code = 0
        self.remote_side = ""
        self.annotations: Optional[List] = None  # lazy: most spans have none
        self.request_size = 0
        self.response_size = 0
        self._open = True
        # phase fields are intentionally NOT initialised: spans are
        # created per RPC and 7 slot stores per span are measurable on
        # the hot path. Readers go through phase() / phase_deltas(),
        # which default unset slots to 0.

    def phase(self, field: str) -> int:
        """Phase stamp value; 0 when never reached (unset slot)."""
        return getattr(self, field, 0)

    def _try_close(self) -> bool:
        """GIL-atomic one-shot close: slot deletion is a single
        bytecode, so exactly one of two racing closers (write
        completion vs set_failed sweep) wins — no double submit."""
        try:
            del self._open
            return True
        except AttributeError:
            return False

    @classmethod
    def create_client(cls, service: str, method: str) -> Optional["Span"]:
        if not _RPCZ_FLAG.value:
            return None
        parent: Optional[Span] = task_local.get_local(_TLS_KEY)
        if not _admit(joined=parent is not None):
            return None  # over the creation budget: not traced
        span = cls("client", service, method)
        if parent is not None:
            span.trace_id = parent.trace_id
            span.parent_span_id = parent.span_id
        else:
            span.trace_id = fast_rand() & 0x7FFFFFFFFFFF
        return span

    @classmethod
    def create_server(cls, service: str, method: str, trace_id: int, parent_span_id: int):
        """Server span with a propagated trace. The caller scopes it as
        the task-local parent (swap_current_span) around the handler
        invocation and restores after — leaving it installed would
        misparent later unrelated spans from the same task/thread into
        this finished trace."""
        if not _RPCZ_FLAG.value:
            return None
        if not _admit(joined=bool(trace_id)):
            return None  # over the creation budget: not traced
        # propagated trace ids use the (bounded) joined budget so
        # sampled traces stay complete across the pod
        span = cls("server", service, method)
        span.trace_id = trace_id or (fast_rand() & 0x7FFFFFFFFFFF)
        span.parent_span_id = parent_span_id
        return span

    @classmethod
    def create_collective(
        cls, service: str, method: str, require_parent: bool = True
    ) -> Optional["Span"]:
        """Sub-span for one collective/fabric leg (kind "collective"),
        parented to the active task-local span so fan-out calls show
        per-chip legs under their RPC. With require_parent (the
        transport paths) a legless context creates nothing — transport
        frames outside any traced RPC would only be ring noise."""
        if not _RPCZ_FLAG.value:
            return None
        parent: Optional[Span] = task_local.get_local(_TLS_KEY)
        if parent is None and require_parent:
            return None
        span = cls("collective", service, method)
        if parent is not None:
            span.trace_id = parent.trace_id
            span.parent_span_id = parent.span_id
        else:
            span.trace_id = fast_rand() & 0x7FFFFFFFFFFF
        return span

    def annotate(self, text: str):
        if self.annotations is None:
            self.annotations = []
        self.annotations.append((time.time_ns() // 1000, text))

    def stamp(self, phase: str):
        """Record a phase timestamp (one of PHASE_FIELDS) as now."""
        setattr(self, phase, time.time_ns() // 1000)

    # per-leg chunk annotations are capped so a pathological
    # thousand-chunk frame can't balloon one span's memory; the cap
    # comfortably covers a 64MB frame at the default 8MB chunks
    MAX_CHUNK_MARKS = 64

    def chunk_mark(self, what: str, idx: int, total: int, nbytes: int):
        """Timestamped per-chunk stamp on a collective leg (chunked
        ICI/DCN transfers): /rpcz?trace= then shows each chunk's launch
        offset inside the leg, i.e. the pipeline's actual overlap."""
        anns = self.annotations
        if anns is not None and len(anns) >= self.MAX_CHUNK_MARKS:
            return
        of = f"/{total}" if total > 0 else ""  # 0 = streaming, count unknown
        self.annotate(f"{what} chunk {idx + 1}{of} {nbytes}B")

    def adopt_message_stamps(self, msg):
        """Copy receive/parse/queue stamps the transport left on the
        parsed message (input_messenger stamps them on objects with the
        matching slots) onto this span. Unrolled: runs once per RPC
        per side."""
        v = getattr(msg, "received_us", 0)
        if v:
            self.received_us = v
        v = getattr(msg, "parse_done_us", 0)
        if v:
            self.parse_done_us = v
        v = getattr(msg, "enqueued_us", 0)
        if v:
            self.enqueued_us = v

    def write_done(self, error_code: int = 0):
        """Socket write-completion hook: the bytes this span queued
        (server response / client request) hit the kernel or fabric.
        Server spans close HERE, so server latency includes
        serialization and send (reference: response_sent stamp)."""
        now = time.time_ns() // 1000
        if error_code == 0:
            self.sent_us = now
        if self.kind == "server" and self._try_close():
            self.end_us = now
            self.error_code = self.error_code or error_code
            self.submit()

    def end(self, error_code: int = 0):
        if not self._try_close():
            return  # already closed (write-completion vs failure race)
        self.end_us = time.time_ns() // 1000
        self.error_code = error_code
        self.submit()  # through the Collector sampling pipeline

    def speed_limit(self) -> int:
        """Submit-side cap for spans. Creation-side admission already
        bounds span WORK; this backstop only has to be generous enough
        that every admitted trace's spans (root + joined + per-chip
        legs) pass, or sampled traces would come back incomplete at
        the Collector — the default 1000/s base limit is far below
        what admission can legitimately produce."""
        return _SPAN_RATE_FLAG.value * 32

    def dump_and_destroy(self):
        _span_db.add(self)
        try:
            from incubator_brpc_tpu.observability import latency_breakdown

            latency_breakdown.record_span(self)
        except Exception:  # noqa: BLE001 — aggregation is best-effort
            pass

    @property
    def latency_us(self) -> int:
        return (self.end_us or self.start_us) - self.start_us

    def phase_deltas(self) -> List:
        """Computable (phase, delta_us) pairs in pipeline order."""
        out = []
        for name, frm, to in PHASE_DELTAS:
            a = getattr(self, frm, 0)
            b = getattr(self, to, 0)
            if a and b and b >= a:
                out.append((name, b - a))
        return out

    def describe(self) -> str:
        anns = "".join(
            f"\n    @{t - self.start_us}us {a}"
            for t, a in (self.annotations or ())
        )
        deltas = self.phase_deltas()
        phases = (
            " phases[" + " ".join(f"{n}={d}us" for n, d in deltas) + "]"
            if deltas
            else ""
        )
        return (
            f"{self.kind} {self.service}.{self.method} "
            f"trace={format_trace_id(self.trace_id)} "
            f"span={format_trace_id(self.span_id)} "
            f"parent={format_trace_id(self.parent_span_id)} "
            f"latency={self.latency_us}us error={self.error_code} "
            f"remote={self.remote_side} req={self.request_size}B "
            f"resp={self.response_size}B{phases}{anns}"
        )


class SpanDB:
    """Recent-span store browsed by /rpcz: an in-memory ring always,
    plus durable sqlite persistence when the reloadable flag
    ``rpcz_db_path`` names a file (the reference persists via leveldb,
    span.cpp SpanDB; sqlite is the stdlib equivalent). Persistence
    survives restarts and lets /rpcz answer trace queries older than
    the ring."""

    def __init__(self, capacity: int = 2048):
        self._spans: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._db = None
        self._db_path = None

    def _sqlite(self):
        """(Re)open the sqlite backend when the flag changes. Called
        with self._lock held, only from the Collector drain thread."""
        path = get_flag("rpcz_db_path", "") or None
        if path == self._db_path:
            return self._db
        if self._db is not None:
            try:
                self._db.close()
            except Exception:  # noqa: BLE001
                pass
            self._db = None
        self._db_path = path
        if path:
            import sqlite3

            db = sqlite3.connect(path, check_same_thread=False)
            db.execute(
                "CREATE TABLE IF NOT EXISTS spans ("
                "trace_id INTEGER, span_id INTEGER, parent_span_id INTEGER,"
                "kind TEXT, service TEXT, method TEXT, start_us INTEGER,"
                "latency_us INTEGER, error_code INTEGER, remote TEXT,"
                "description TEXT)"
            )
            db.execute(
                "CREATE INDEX IF NOT EXISTS spans_trace ON spans(trace_id)"
            )
            db.commit()
            self._db = db
        return self._db

    def add(self, span: Span):
        """Called from the Collector drain thread (never the RPC path),
        so the sqlite insert costs nothing on the hot path."""
        with self._lock:
            self._spans.append(span)
            db = self._sqlite()
            if db is not None:
                try:
                    db.execute(
                        "INSERT INTO spans VALUES (?,?,?,?,?,?,?,?,?,?,?)",
                        (
                            span.trace_id,
                            span.span_id,
                            span.parent_span_id,
                            span.kind,
                            span.service,
                            span.method,
                            span.start_us,
                            span.latency_us,
                            span.error_code,
                            str(span.remote_side),
                            span.describe(),
                        ),
                    )
                    db.commit()
                except Exception:  # noqa: BLE001 — persistence is best-effort
                    pass

    def recent(self, n: int = 100) -> List[Span]:
        with self._lock:
            return list(self._spans)[-n:]

    def by_trace(self, trace_id: int) -> List[Span]:
        with self._lock:
            mem = [s for s in self._spans if s.trace_id == trace_id]
        return mem

    def persisted_by_trace(self, trace_id: int) -> List[str]:
        """Descriptions from the sqlite backend (covers spans already
        evicted from the memory ring — and prior process runs)."""
        with self._lock:
            db = self._sqlite()
            if db is None:
                return []
            try:
                rows = db.execute(
                    "SELECT description FROM spans WHERE trace_id=? "
                    "ORDER BY start_us",
                    (trace_id,),
                ).fetchall()
            except Exception:  # noqa: BLE001
                return []
        return [r[0] for r in rows]

    def __len__(self):
        return len(self._spans)


_span_db = SpanDB()


def span_db() -> SpanDB:
    return _span_db


def current_span() -> Optional[Span]:
    """The active task-local span (parent for nested client calls and
    collective sub-spans; reference bthread::tls_bls, span.h:75-78)."""
    return task_local.get_local(_TLS_KEY)


def swap_current_span(span: Optional[Span]) -> Optional[Span]:
    """Install `span` as the task-local parent; returns the previous
    one so the caller can restore it (scoped parenting for fan-out).
    One storage lookup for the get+set pair — this runs per RPC."""
    d = task_local._storage()
    prev = d.get(_TLS_KEY)
    d[_TLS_KEY] = span
    return prev
