"""rpcz tracing — per-RPC spans through the bvar Collector.

Analog of reference Span (span.h:47, span.cpp 801 LoC): created per
client call (channel.cpp:478-485) and per server request
(baidu_rpc_protocol.cpp:382-394); trace_id/span_id/parent_span_id
propagate inside the request meta; annotations and phase timestamps
ride along; submission goes through the bvar Collector sampling
pipeline (bounded overhead) into an in-memory SpanDB (the reference
persists to leveldb; /rpcz browses it either way). The parent span for
nested client calls lives in task-local storage (reference
bthread::tls_bls, span.h:75-78).
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from typing import List, Optional

from incubator_brpc_tpu.metrics.collector import Collected
from incubator_brpc_tpu.runtime import local as task_local
from incubator_brpc_tpu.utils.flags import get_flag
from incubator_brpc_tpu.utils.hashes import fast_rand

_TLS_KEY = "rpcz_parent_span"


class Span(Collected):
    __slots__ = (
        "trace_id",
        "span_id",
        "parent_span_id",
        "kind",
        "service",
        "method",
        "start_us",
        "end_us",
        "error_code",
        "remote_side",
        "annotations",
        "request_size",
        "response_size",
    )

    def __init__(self, kind: str, service: str = "", method: str = ""):
        self.kind = kind  # "client" | "server"
        self.service = service
        self.method = method
        self.trace_id = 0
        self.span_id = fast_rand() & 0x7FFFFFFFFFFF
        self.parent_span_id = 0
        self.start_us = time.time_ns() // 1000
        self.end_us = 0
        self.error_code = 0
        self.remote_side = ""
        self.annotations: List = []
        self.request_size = 0
        self.response_size = 0

    @classmethod
    def create_client(cls, service: str, method: str) -> Optional["Span"]:
        if not get_flag("rpcz_enabled", True):
            return None
        span = cls("client", service, method)
        parent: Optional[Span] = task_local.get_local(_TLS_KEY)
        if parent is not None:
            span.trace_id = parent.trace_id
            span.parent_span_id = parent.span_id
        else:
            span.trace_id = fast_rand() & 0x7FFFFFFFFFFF
        return span

    @classmethod
    def create_server(cls, service: str, method: str, trace_id: int, parent_span_id: int):
        if not get_flag("rpcz_enabled", True):
            return None
        span = cls("server", service, method)
        span.trace_id = trace_id or (fast_rand() & 0x7FFFFFFFFFFF)
        span.parent_span_id = parent_span_id
        task_local.set_local(_TLS_KEY, span)
        return span

    def annotate(self, text: str):
        self.annotations.append((time.time_ns() // 1000, text))

    def end(self, error_code: int = 0):
        self.end_us = time.time_ns() // 1000
        self.error_code = error_code
        self.submit()  # through the Collector sampling pipeline

    def dump_and_destroy(self):
        _span_db.add(self)

    @property
    def latency_us(self) -> int:
        return (self.end_us or self.start_us) - self.start_us

    def describe(self) -> str:
        anns = "".join(
            f"\n    @{t - self.start_us}us {a}" for t, a in self.annotations
        )
        return (
            f"{self.kind} {self.service}.{self.method} trace={self.trace_id:x} "
            f"span={self.span_id:x} parent={self.parent_span_id:x} "
            f"latency={self.latency_us}us error={self.error_code} "
            f"remote={self.remote_side}{anns}"
        )


class SpanDB:
    """Recent-span store browsed by /rpcz: an in-memory ring always,
    plus durable sqlite persistence when the reloadable flag
    ``rpcz_db_path`` names a file (the reference persists via leveldb,
    span.cpp SpanDB; sqlite is the stdlib equivalent). Persistence
    survives restarts and lets /rpcz answer trace queries older than
    the ring."""

    def __init__(self, capacity: int = 2048):
        self._spans: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._db = None
        self._db_path = None

    def _sqlite(self):
        """(Re)open the sqlite backend when the flag changes. Called
        with self._lock held, only from the Collector drain thread."""
        path = get_flag("rpcz_db_path", "") or None
        if path == self._db_path:
            return self._db
        if self._db is not None:
            try:
                self._db.close()
            except Exception:  # noqa: BLE001
                pass
            self._db = None
        self._db_path = path
        if path:
            import sqlite3

            db = sqlite3.connect(path, check_same_thread=False)
            db.execute(
                "CREATE TABLE IF NOT EXISTS spans ("
                "trace_id INTEGER, span_id INTEGER, parent_span_id INTEGER,"
                "kind TEXT, service TEXT, method TEXT, start_us INTEGER,"
                "latency_us INTEGER, error_code INTEGER, remote TEXT,"
                "description TEXT)"
            )
            db.execute(
                "CREATE INDEX IF NOT EXISTS spans_trace ON spans(trace_id)"
            )
            db.commit()
            self._db = db
        return self._db

    def add(self, span: Span):
        """Called from the Collector drain thread (never the RPC path),
        so the sqlite insert costs nothing on the hot path."""
        with self._lock:
            self._spans.append(span)
            db = self._sqlite()
            if db is not None:
                try:
                    db.execute(
                        "INSERT INTO spans VALUES (?,?,?,?,?,?,?,?,?,?,?)",
                        (
                            span.trace_id,
                            span.span_id,
                            span.parent_span_id,
                            span.kind,
                            span.service,
                            span.method,
                            span.start_us,
                            span.latency_us,
                            span.error_code,
                            str(span.remote_side),
                            span.describe(),
                        ),
                    )
                    db.commit()
                except Exception:  # noqa: BLE001 — persistence is best-effort
                    pass

    def recent(self, n: int = 100) -> List[Span]:
        with self._lock:
            return list(self._spans)[-n:]

    def by_trace(self, trace_id: int) -> List[Span]:
        with self._lock:
            mem = [s for s in self._spans if s.trace_id == trace_id]
        return mem

    def persisted_by_trace(self, trace_id: int) -> List[str]:
        """Descriptions from the sqlite backend (covers spans already
        evicted from the memory ring — and prior process runs)."""
        with self._lock:
            db = self._sqlite()
            if db is None:
                return []
            try:
                rows = db.execute(
                    "SELECT description FROM spans WHERE trace_id=? "
                    "ORDER BY start_us",
                    (trace_id,),
                ).fetchall()
            except Exception:  # noqa: BLE001
                return []
        return [r[0] for r in rows]

    def __len__(self):
        return len(self._spans)


_span_db = SpanDB()


def span_db() -> SpanDB:
    return _span_db
