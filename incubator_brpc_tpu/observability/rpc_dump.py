"""rpc_dump — sampled request capture for replay.

Analog of reference rpc_dump.{h,cpp}: a fast sampling gate
(AskToBeSampled, rpc_dump.h:67) captures requests into round-robin
files under a directory (rpc_dump.cpp:48-58); the rpc_replay tool
re-issues them at controlled qps.

File format (one sample): b"TDMP" + meta_size(u32) + body_size(u32) +
meta(json: service/method/log_id) + body bytes.
"""

from __future__ import annotations

import json
import os
import struct
import threading
import time
from typing import Iterator, List, Optional, Tuple

from incubator_brpc_tpu.utils.iobuf import IOBuf

MAGIC = b"TDMP"


class RpcDumpContext:
    def __init__(
        self,
        dump_dir: str,
        sample_ratio: float = 0.01,
        max_files: int = 4,
        max_file_bytes: int = 8 << 20,
    ):
        self.dump_dir = dump_dir
        self.sample_ratio = sample_ratio
        self.max_files = max_files
        self.max_file_bytes = max_file_bytes
        os.makedirs(dump_dir, exist_ok=True)
        self._lock = threading.Lock()
        self._file_idx = 0
        self._cur = None
        self._cur_bytes = 0
        self._counter = 0
        self.sampled = 0

    def _should_sample(self) -> bool:
        self._counter += 1
        period = max(1, int(1 / self.sample_ratio))
        return self._counter % period == 1 or period == 1

    def sample_request(self, req_meta, payload: IOBuf):
        """Called on the server request path (the AskToBeSampled gate)."""
        if not self._should_sample():
            return
        meta = json.dumps(
            {
                "service": req_meta.service_name,
                "method": req_meta.method_name,
                "log_id": req_meta.log_id,
                "ts": time.time(),
            }
        ).encode()
        body = payload.to_bytes()
        record = MAGIC + struct.pack(">II", len(meta), len(body)) + meta + body
        with self._lock:
            f = self._file()
            f.write(record)
            f.flush()
            self._cur_bytes += len(record)
            self.sampled += 1

    def _file(self):
        if self._cur is None or self._cur_bytes >= self.max_file_bytes:
            if self._cur is not None:
                self._cur.close()
            path = os.path.join(
                self.dump_dir, f"requests.{self._file_idx % self.max_files:04d}"
            )
            self._file_idx += 1
            self._cur = open(path, "wb")  # round-robin: truncate old
            self._cur_bytes = 0
        return self._cur


def read_samples(path: str) -> Iterator[Tuple[dict, bytes]]:
    """Iterate (meta, body) samples from one dump file (rpc_replay input)."""
    with open(path, "rb") as f:
        data = f.read()
    pos = 0
    while pos + 12 <= len(data):
        if data[pos : pos + 4] != MAGIC:
            break
        meta_size, body_size = struct.unpack_from(">II", data, pos + 4)
        pos += 12
        meta = json.loads(data[pos : pos + meta_size])
        body = data[pos + meta_size : pos + meta_size + body_size]
        pos += meta_size + body_size
        yield meta, body


def list_dump_files(dump_dir: str) -> List[str]:
    try:
        return sorted(
            os.path.join(dump_dir, f)
            for f in os.listdir(dump_dir)
            if f.startswith("requests.")
        )
    except OSError:
        return []
