"""Observability: rpcz tracing spans, rpc_dump sampling (reference
span.{h,cpp}, rpc_dump.{h,cpp})."""
