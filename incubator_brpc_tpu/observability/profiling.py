"""Device-plane continuous profiling — HBM heap, device time, occupancy.

The host half of the reference's /hotspots suite (cpu/contention/heap/
growth) says nothing about the device plane: which subsystem pins how
much HBM, where device time goes, and whether the runtime's workers
are actually busy.  This module holds the three profilers that answer
those questions, each in the house shape — cheap always-on counters, an
on-demand deep capture, and a loud cross-check instead of a trusted
registry:

1. **HBM heap profiler** — ``hbm_account(tag)`` hands out a per-tag
   accounting handle every HBM-pinning subsystem adopts: the cache
   store's values and gather pads, StagingRing slots, sharded PS
   params, decode row state, in-flight ICI DeviceRefs.  Adopted bytes
   aggregate into ``rpc_hbm_bytes{component}``; /hotspots/hbm renders
   the per-tag profile and cross-checks the ledger against the
   device's own census (``device.memory_stats()`` where the backend
   provides it, a ``jax.live_arrays()`` walk otherwise) so bytes the
   registry does not know about surface as an explicit ``<dark>``
   bucket — a ledger drifting from reality fails loudly, it never lies.

2. **Device-time attribution** — kernel dispatch sites (FusedKernel,
   the sharded collective, decode step, ICI chunk pipeline, PS
   forward) wrap their dispatch in :class:`kernel_section`, feeding
   per-family execution counts and device-time EMAs.  Timing is taken
   at already-sanctioned completion points (the manifested host pulls
   that already follow a dispatch) — never by adding a ``block_until_ready``
   to a hot path, so the PR 10 transfer witness stays green.
   ``/hotspots/device?seconds=N`` arms an on-demand
   ``jax.profiler.trace`` window and summarizes the always-on counters
   over it per kernel family.

3. **Runtime occupancy sampler** — per-worker run-queue depth, steals,
   runs, parks and task queue-wait from runtime/scheduler's plain
   counters, exported as ``rpc_worker_*`` gauges and /hotspots/runtime
   (the occupancy evidence the M:N-scheduler roadmap item cites).

This module must import WITHOUT jax (it is render-checked by the
``metrics-unrenderable`` lint): every jax touch goes through
``sys.modules.get("jax")`` — if jax was never imported, no HBM exists
to account for.
"""

from __future__ import annotations

import sys
import tempfile
import threading
import time
from typing import Dict, Optional

from incubator_brpc_tpu.metrics.multi_dimension import MultiDimension
from incubator_brpc_tpu.metrics.passive_status import PassiveStatus, Status
from incubator_brpc_tpu.metrics.reducer import Adder
from incubator_brpc_tpu.runtime import scheduler as _sched
from incubator_brpc_tpu.utils.flags import define_flag

# ---------------------------------------------------------------------------
# gates — the always-on halves are flag-gated so the OFF/ON/OFF overhead
# bench (and an operator chasing a regression) can kill them at runtime
# ---------------------------------------------------------------------------

_HBM_FLAG = define_flag(
    "profiler_hbm_enabled",
    True,
    "always-on HBM accounting (rpc_hbm_bytes / /hotspots/hbm)",
    validator=lambda v: isinstance(v, bool),
)
_DEVICE_FLAG = define_flag(
    "profiler_device_enabled",
    True,
    "always-on per-kernel-family device-time attribution",
    validator=lambda v: isinstance(v, bool),
)
_OCC_FLAG = define_flag(
    "profiler_occupancy_enabled",
    True,
    "runtime occupancy sampling (rpc_worker_* / /hotspots/runtime)",
    validator=lambda v: isinstance(v, bool),
)

# ---------------------------------------------------------------------------
# (1) HBM heap profiler
# ---------------------------------------------------------------------------

#: live device bytes / allocation counts per accounting tag
rpc_hbm_bytes = MultiDimension(Adder, ["component"]).expose("rpc_hbm_bytes")
rpc_hbm_allocs = MultiDimension(Adder, ["component"]).expose("rpc_hbm_allocs")


class HbmAccount:
    """Per-tag accounting handle.  The contract every adopter follows:

    - ``n = acct.adopt(arr_or_nbytes)`` when a device buffer becomes
      this subsystem's responsibility (returns the bytes charged —
      store it);
    - ``acct.release(n)`` with exactly that stored value when the
      buffer is freed, donated away, or handed to another account.

    Storing adopt's return (instead of re-reading ``.nbytes`` at
    release) keeps the ledger balanced even across runtime gate flips.
    Reading ``.nbytes`` off a jax array is metadata only — no device
    transfer, so adoption is witness-safe on any path.
    """

    __slots__ = ("tag", "_bytes", "_allocs")

    def __init__(self, tag: str):
        self.tag = tag
        self._bytes = rpc_hbm_bytes.get_stats([tag])
        self._allocs = rpc_hbm_allocs.get_stats([tag])

    def adopt(self, obj) -> int:
        if not _HBM_FLAG.value:
            return 0
        n = obj if isinstance(obj, int) else int(getattr(obj, "nbytes", 0) or 0)
        if n > 0:
            self._bytes << n
            self._allocs << 1
        return n

    def release(self, nbytes: int, allocs: int = 1) -> None:
        if nbytes > 0:
            self._bytes << -int(nbytes)
            self._allocs << -int(allocs)

    def live_bytes(self) -> int:
        return int(self._bytes.get_value())

    def live_allocs(self) -> int:
        return int(self._allocs.get_value())


_accounts: Dict[str, HbmAccount] = {}
_accounts_lock = threading.Lock()


def hbm_account(tag: str) -> HbmAccount:
    """The one entry point: register (first call) or look up the
    accounting handle for ``tag``."""
    acct = _accounts.get(tag)
    if acct is None:
        with _accounts_lock:
            acct = _accounts.get(tag)
            if acct is None:
                acct = HbmAccount(tag)
                _accounts[tag] = acct
    return acct


def device_census() -> dict:
    """The device's own notion of live bytes, for the ``<dark>``
    cross-check.  Prefers ``device.memory_stats()`` (real allocator
    numbers on TPU/GPU); falls back to summing ``.nbytes`` over
    ``jax.live_arrays()`` (CPU backend has no allocator stats).  Both
    reads are metadata-only — no device→host transfer."""
    jax = sys.modules.get("jax")
    if jax is None:
        return {
            "available": False,
            "source": None,
            "bytes": 0,
            "reason": "jax not loaded (nothing on the device)",
        }
    try:
        total, got = 0, False
        for d in jax.local_devices():
            ms = getattr(d, "memory_stats", None)
            if ms is None:
                continue
            try:
                stats = ms()
            except Exception:  # noqa: BLE001 — backend without stats
                stats = None
            if stats and "bytes_in_use" in stats:
                total += int(stats["bytes_in_use"])
                got = True
        if got:
            return {"available": True, "source": "memory_stats", "bytes": total}
    except Exception:  # noqa: BLE001 — fall through to the array walk
        pass
    try:
        total = sum(int(a.nbytes) for a in jax.live_arrays())
        return {"available": True, "source": "live_arrays", "bytes": total}
    except Exception as e:  # noqa: BLE001
        return {
            "available": False,
            "source": None,
            "bytes": 0,
            "reason": repr(e),
        }


# census baseline: device bytes that predate the accounting horizon
# (compiled executables' constants, weights loaded before adoption
# began).  dark = census - baseline - accounted; rebase_census() snaps
# the horizon "everything currently resident is explained".
_census_baseline = [0]


def rebase_census() -> dict:
    cen = device_census()
    _census_baseline[0] = cen["bytes"] if cen["available"] else 0
    return cen


def hbm_profile() -> dict:
    """Ledger snapshot + census cross-check (the /hotspots/hbm data)."""
    tags: Dict[str, dict] = {}
    with _accounts_lock:
        accounts = list(_accounts.values())
    for acct in accounts:
        b, a = acct.live_bytes(), acct.live_allocs()
        if b or a:
            tags[acct.tag] = {"bytes": b, "allocs": a}
    accounted = sum(v["bytes"] for v in tags.values())
    cen = device_census()
    dark: Optional[int] = None
    if cen["available"]:
        dark = max(0, cen["bytes"] - _census_baseline[0] - accounted)
    return {
        "tags": tags,
        "accounted_bytes": accounted,
        "census": cen,
        "census_baseline": _census_baseline[0],
        "dark_bytes": dark,
    }


def render_hbm(profile: Optional[dict] = None, top: int = 40) -> str:
    """pprof-style text profile: hottest tag first, then the census
    cross-check with the explicit ``<dark>`` bucket."""
    p = profile if profile is not None else hbm_profile()
    cen = p["census"]
    out = [
        "--- hbm",
        f"accounted_bytes: {p['accounted_bytes']}  tags: {len(p['tags'])}",
    ]
    if cen["available"]:
        out.append(
            f"census: source={cen['source']} bytes={cen['bytes']} "
            f"baseline={p['census_baseline']}"
        )
        dark = p["dark_bytes"]
        span = max(1, cen["bytes"] - p["census_baseline"])
        out.append(f"<dark>: {dark} bytes ({100.0 * dark / span:.1f}%)")
    else:
        out.append(f"census: unavailable ({cen.get('reason')}) — <dark> unknown")
    out.append("")
    rows = sorted(
        p["tags"].items(), key=lambda kv: kv[1]["bytes"], reverse=True
    )[:top]
    for tag, row in rows:
        out.append(f"{row['bytes']:>14} {row['allocs']:>8} @ {tag}")
    return "\n".join(out)


# growth baseline slot (same idiom as /hotspots/growth's tracemalloc
# slot): each fetch diffs against the previous one
_hbm_growth_baseline: list = [None]


def render_hbm_growth(top: int = 40) -> str:
    p = hbm_profile()
    base = _hbm_growth_baseline[0]
    _hbm_growth_baseline[0] = p
    if base is None:
        return "hbm baseline captured; re-fetch for growth"
    out = ["--- hbm growth since last fetch", ""]
    deltas = []
    for tag in sorted(set(p["tags"]) | set(base["tags"])):
        nb = p["tags"].get(tag, {}).get("bytes", 0)
        ob = base["tags"].get(tag, {}).get("bytes", 0)
        na = p["tags"].get(tag, {}).get("allocs", 0)
        oa = base["tags"].get(tag, {}).get("allocs", 0)
        if nb != ob or na != oa:
            deltas.append((nb - ob, na - oa, tag))
    deltas.sort(key=lambda t: abs(t[0]), reverse=True)
    for db, da, tag in deltas[:top]:
        out.append(f"{db:>+14} {da:>+8} @ {tag}")
    if len(out) == 2:
        out.append("(no per-tag change)")
    out.append("")
    out.append(
        f"accounted: {base['accounted_bytes']} -> {p['accounted_bytes']} "
        f"({p['accounted_bytes'] - base['accounted_bytes']:+d})"
    )
    if p["census"]["available"] and base["census"]["available"]:
        out.append(
            f"census:    {base['census']['bytes']} -> {p['census']['bytes']} "
            f"({p['census']['bytes'] - base['census']['bytes']:+d})"
        )
    return "\n".join(out)


# ---------------------------------------------------------------------------
# (2) device-time attribution
# ---------------------------------------------------------------------------

rpc_kernel_executions = MultiDimension(Adder, ["family"]).expose(
    "rpc_kernel_executions"
)
rpc_kernel_device_us_total = MultiDimension(Adder, ["family"]).expose(
    "rpc_kernel_device_us_total"
)
rpc_kernel_device_us_ema = MultiDimension(
    lambda: Status(0.0), ["family"]
).expose("rpc_kernel_device_us_ema")

_EMA_ALPHA = 0.2


class _KernelStat:
    __slots__ = ("family", "_exec", "_total", "_ema_var", "ema_us", "last_us")

    def __init__(self, family: str):
        self.family = family
        self._exec = rpc_kernel_executions.get_stats([family])
        self._total = rpc_kernel_device_us_total.get_stats([family])
        self._ema_var = rpc_kernel_device_us_ema.get_stats([family])
        self.ema_us: Optional[float] = None
        self.last_us = 0.0

    def note(self, us: float) -> None:
        self._exec << 1
        self._total << us
        self.last_us = us
        ema = self.ema_us
        self.ema_us = us if ema is None else ema + _EMA_ALPHA * (us - ema)
        self._ema_var.set_value(round(self.ema_us, 2))


_kernels: Dict[str, _KernelStat] = {}
_kernels_lock = threading.Lock()


def _kernel_stat(family: str) -> _KernelStat:
    st = _kernels.get(family)
    if st is None:
        # construct OUTSIDE the lock (variable registration walks the
        # metrics registry); setdefault keeps first-registration unique
        fresh = _KernelStat(family)
        with _kernels_lock:
            st = _kernels.setdefault(family, fresh)
    return st


class kernel_section:
    """Times one kernel-family dispatch window.  Disarmed cost is one
    flag load; armed cost is two perf_counter reads plus the counter
    folds.  The window must close at an already-sanctioned completion
    point (a manifested host pull, or the dispatch return on paths
    with no pull) — this class never syncs the device itself."""

    __slots__ = ("family", "_t0")

    def __init__(self, family: str):
        self.family = family
        self._t0 = 0

    def __enter__(self) -> "kernel_section":
        if _DEVICE_FLAG.value:
            self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self._t0 and exc_type is None:
            _kernel_stat(self.family).note(
                (time.perf_counter_ns() - self._t0) / 1000.0
            )
        return False


def kernel_snapshot() -> Dict[str, dict]:
    """family → {executions, total_us, ema_us, last_us} (capture diffs
    and the /hotspots/device table read this)."""
    with _kernels_lock:
        stats = list(_kernels.values())
    out: Dict[str, dict] = {}
    for st in stats:
        out[st.family] = {
            "executions": int(st._exec.get_value()),
            "total_us": float(st._total.get_value()),
            "ema_us": round(st.ema_us, 2) if st.ema_us is not None else 0.0,
            "last_us": round(st.last_us, 2),
        }
    return out


def render_device(snapshot: Optional[Dict[str, dict]] = None) -> str:
    snap = snapshot if snapshot is not None else kernel_snapshot()
    out = [
        "--- device",
        f"kernel_families: {len(snap)}",
        "",
        f"{'executions':>12} {'total_us':>14} {'ema_us':>10} "
        f"{'last_us':>10}  family",
    ]
    for family, row in sorted(
        snap.items(), key=lambda kv: kv[1]["total_us"], reverse=True
    ):
        out.append(
            f"{row['executions']:>12} {row['total_us']:>14.1f} "
            f"{row['ema_us']:>10.1f} {row['last_us']:>10.1f}  {family}"
        )
    return "\n".join(out)


# ---- on-demand deep capture ------------------------------------------------

rpc_profiler_captures_total = Adder(0).expose("rpc_profiler_captures_total")
rpc_profiler_capture_failures_total = Adder(0).expose(
    "rpc_profiler_capture_failures_total"
)

_capture_lock = threading.Lock()
_trace_active = [False]
MAX_CAPTURE_SECONDS = 10.0


class CaptureError(RuntimeError):
    """A deep capture that could not run (chaos drop, concurrent
    capture, profiler failure).  The page maps it to an error response;
    serving continues and no armed trace session survives it."""


def capture_active() -> bool:
    return _trace_active[0]


def device_capture(seconds: float) -> dict:
    """Arm a ``jax.profiler.trace`` window for ``seconds`` and return a
    per-kernel-family summary of what executed inside it.  The chaos
    site ``profile.capture`` sits on this path: ``drop`` fails the
    capture (CaptureError → error page), ``delay_us`` stretches its
    start.  The trace session is disarmed in a ``finally`` — a failed
    or chaos-faulted capture can never leak an armed profiler."""
    from incubator_brpc_tpu.chaos import injector as _chaos

    seconds = min(max(float(seconds), 0.0), MAX_CAPTURE_SECONDS)
    if _chaos.armed:
        spec = _chaos.check("profile.capture")
        if spec is not None:
            if spec.action == "delay_us":
                _chaos.sleep_us(spec.arg)
            elif spec.action == "drop":
                rpc_profiler_capture_failures_total << 1
                raise CaptureError(
                    "deep capture dropped (chaos site profile.capture)"
                )
    if not _capture_lock.acquire(blocking=False):
        raise CaptureError("a device capture is already in progress")
    try:
        before = kernel_snapshot()
        t0 = time.perf_counter()
        jax = sys.modules.get("jax")
        trace_dir: Optional[str] = None
        trace_error: Optional[str] = None
        started = False
        if jax is not None:
            try:
                trace_dir = tempfile.mkdtemp(prefix="device-trace-")
                jax.profiler.start_trace(trace_dir)
                started = True
                _trace_active[0] = True
            except Exception as e:  # noqa: BLE001 — degrade to counters-only
                trace_error = repr(e)
                trace_dir = None
        else:
            trace_error = "jax not loaded"
        try:
            time.sleep(seconds)
        finally:
            if started:
                try:
                    jax.profiler.stop_trace()
                except Exception as e:  # noqa: BLE001
                    trace_error = trace_error or repr(e)
                _trace_active[0] = False
        after = kernel_snapshot()
        rpc_profiler_captures_total << 1
        families: Dict[str, dict] = {}
        for family, row in after.items():
            prev = before.get(family, {"executions": 0, "total_us": 0.0})
            d_exec = row["executions"] - prev["executions"]
            if d_exec <= 0:
                continue
            families[family] = {
                "executions": d_exec,
                "device_us": round(row["total_us"] - prev["total_us"], 1),
                "ema_us": row["ema_us"],
            }
        return {
            "seconds": round(time.perf_counter() - t0, 3),
            "families": families,
            "trace_dir": trace_dir,
            "trace_error": trace_error,
        }
    finally:
        _capture_lock.release()


def render_capture(result: dict) -> str:
    out = [
        "--- device capture",
        f"window_s: {result['seconds']}",
        f"trace_dir: {result['trace_dir'] or '(none)'}",
    ]
    if result["trace_error"]:
        out.append(f"trace: unavailable ({result['trace_error']}) — "
                   f"summary is counter-based")
    out.append("")
    out.append(f"{'executions':>12} {'device_us':>14} {'ema_us':>10}  family")
    for family, row in sorted(
        result["families"].items(),
        key=lambda kv: kv[1]["device_us"],
        reverse=True,
    ):
        out.append(
            f"{row['executions']:>12} {row['device_us']:>14.1f} "
            f"{row['ema_us']:>10.1f}  {family}"
        )
    if not result["families"]:
        out.append("(no kernel dispatches inside the window)")
    return "\n".join(out)


# ---------------------------------------------------------------------------
# (3) runtime occupancy sampler
# ---------------------------------------------------------------------------

# queue-wait aggregate fed by the scheduler's occupancy observer slot.
# Plain dict slots mutated under the GIL — a lost update under extreme
# contention costs one sample, never correctness.
_queue_wait = {"count": 0, "total_us": 0, "ema_us": 0.0}


def _occupancy_cb(wait_us: int) -> None:
    _queue_wait["count"] += 1
    _queue_wait["total_us"] += wait_us
    ema = _queue_wait["ema_us"]
    _queue_wait["ema_us"] = (
        float(wait_us) if not ema else ema + _EMA_ALPHA * (wait_us - ema)
    )


def _ctl():
    # never get_task_control(): a metrics render must not be what spawns
    # the worker pool
    return _sched._default_control


def occupancy_snapshot() -> dict:
    ctl = _ctl()
    base = (
        ctl.occupancy_snapshot()
        if ctl is not None
        else {
            "workers": 0,
            "blocked": 0,
            "parked": 0,
            "parks_total": 0,
            "steals_total": 0,
            "remote_q": 0,
            "per_worker": [],
        }
    )
    base["queue_wait"] = {
        "count": _queue_wait["count"],
        "total_us": _queue_wait["total_us"],
        "ema_us": round(_queue_wait["ema_us"], 1),
    }
    return base


def render_runtime(snapshot: Optional[dict] = None) -> str:
    s = snapshot if snapshot is not None else occupancy_snapshot()
    qw = s["queue_wait"]
    out = [
        "--- runtime occupancy",
        f"workers: {s['workers']}  blocked: {s['blocked']}  "
        f"parked: {s['parked']}",
        f"steals_total: {s['steals_total']}  parks_total: {s['parks_total']}  "
        f"remote_q: {s['remote_q']}",
        f"queue_wait: count={qw['count']} total_us={qw['total_us']} "
        f"ema_us={qw['ema_us']}",
        "",
        f"{'worker':>8} {'rq_depth':>10} {'steals':>8} {'runs':>10}",
    ]
    for w in s["per_worker"]:
        out.append(
            f"{w['worker_id']:>8} {w['rq_depth']:>10} {w['steals']:>8} "
            f"{w['runs']:>10}"
        )
    if not s["per_worker"]:
        out.append("(runtime not started)")
    return "\n".join(out)


# worker gauges: PassiveStatus over the (maybe not yet created) default
# control — 0 before the runtime starts, live numbers after
rpc_worker_count = PassiveStatus(
    lambda: _ctl().worker_count() if _ctl() else 0
).expose("rpc_worker_count")
rpc_worker_blocked = PassiveStatus(
    lambda: _ctl().blocked_count() if _ctl() else 0
).expose("rpc_worker_blocked")
rpc_worker_parked = PassiveStatus(
    lambda: _ctl().parked_count() if _ctl() else 0
).expose("rpc_worker_parked")
rpc_worker_parks_total = PassiveStatus(
    lambda: _ctl().parks_total() if _ctl() else 0
).expose("rpc_worker_parks_total")
rpc_worker_steals_total = PassiveStatus(
    lambda: _ctl().steals_total() if _ctl() else 0
).expose("rpc_worker_steals_total")
rpc_worker_runqueue_depth = PassiveStatus(
    lambda: _ctl().runqueue_depth() if _ctl() else 0
).expose("rpc_worker_runqueue_depth")
rpc_worker_queue_waits_total = PassiveStatus(
    lambda: _queue_wait["count"]
).expose("rpc_worker_queue_waits_total")
rpc_worker_queue_wait_us_ema = PassiveStatus(
    lambda: round(_queue_wait["ema_us"], 1)
).expose("rpc_worker_queue_wait_us_ema")

# arm the sampler: the scheduler stamps queue-in times only while an
# observer's gate is open, so flipping profiler_occupancy_enabled off
# removes even the per-spawn clock read (unless rpcz wants it too)
_sched.set_occupancy_observer(_occupancy_cb, gate=_OCC_FLAG)
