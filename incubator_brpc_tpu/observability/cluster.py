"""Cluster observability plane — pod-scope trace stitching, exact
metric merging, and shard straggler attribution.

PR 1 made every process rich locally (rpcz span trees,
/latency_breakdown); trace ids already propagate over tpu_std and
HTTP — but each SpanDB is an island.  This module is the cross-process
half, served by the /cluster builtin family (builtin/__init__.py):

* **Trace stitching** — every process exports its SpanDB's spans for
  one trace as JSON (/rpcz/export?trace=); the stitcher follows the
  peer endpoints recorded on the local trace's client sub-spans
  (Controller._finalize_locked stamps remote_side), pulls each peer's
  spans for the same trace over the builtin HTTP surface (the same
  port that served the RPC — the InputMessenger protocol coexistence),
  and renders ONE tree where every fan-out/hedge/shard leg nests the
  remote server's phase stamps under the client leg, with the
  client-minus-server residual attributed as wire+queue per leg.
* **Mergeable metric aggregation** — replicas export aggregation STATE
  (counts + histogram buckets, metrics.latency_recorder
  mergeable_snapshot), never computed percentiles; merging sums the
  state elementwise so /cluster/metrics and /cluster/latency_breakdown
  serve exactly the percentiles of the pooled samples.
* **Straggler attribution** — fan-out completion (client/combo.py)
  records every leg's (peer, total_us, server_time_us); over a sliding
  window /cluster/stragglers ranks peers by their drag on fan-out tail
  latency, split into server time vs wire+queue residual, so one slow
  shard in an 8-way Forward is named, not inferred.

The wire+queue residual needs the server's own elapsed time:
RpcResponseMeta.server_time_us (protos/rpc_meta.proto), stamped by
tpu_std send_response, read back into Controller.server_time_us.
"""

from __future__ import annotations

import json
import re
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from incubator_brpc_tpu.metrics.latency_recorder import (
    merge_latency_snapshots,
    snapshot_stats,
)
from incubator_brpc_tpu.metrics.multi_dimension import MultiDimension
from incubator_brpc_tpu.metrics.reducer import Adder
from incubator_brpc_tpu.observability import trace as trace_mod
from incubator_brpc_tpu.observability.span import (
    PHASE_FIELDS,
    Span,
    format_trace_id,
    parse_trace_id,
    span_db,
)

# ---------------------------------------------------------------------------
# span JSON export / import (the /rpcz/export wire format)
# ---------------------------------------------------------------------------

# non-phase span state that crosses the export boundary
_SPAN_FIELDS = (
    "kind", "service", "method", "start_us", "end_us", "error_code",
    "remote_side", "request_size", "response_size",
)


def span_to_dict(span: Span) -> dict:
    """One span as a JSON-safe dict.  Ids travel in the canonical
    printable form (span.format_trace_id) so the export endpoint,
    /rpcz pages and x-trace-id headers all show the same string."""
    d = {
        "trace_id": format_trace_id(span.trace_id),
        "span_id": format_trace_id(span.span_id),
        "parent_span_id": format_trace_id(span.parent_span_id),
    }
    for f in _SPAN_FIELDS:
        d[f] = getattr(span, f)
    phases = {}
    for f in PHASE_FIELDS:
        v = span.phase(f)
        if v:
            phases[f] = v
    if phases:
        d["phases"] = phases
    if span.annotations:
        d["annotations"] = [[t, a] for t, a in span.annotations]
    return d


class RemoteSpan(Span):
    """A span reconstructed from another process's export.  Carries the
    peer endpoint it came from (`origin`) for the stitched render, and
    is never ended/submitted — it exists only to be assembled."""

    __slots__ = ("origin",)


def span_from_dict(d: dict, origin: str = "") -> RemoteSpan:
    span = RemoteSpan(
        str(d.get("kind", "server")),
        str(d.get("service", "")),
        str(d.get("method", "")),
    )
    span.trace_id = parse_trace_id(d["trace_id"])
    span.span_id = parse_trace_id(d["span_id"])
    span.parent_span_id = parse_trace_id(d.get("parent_span_id", "0"))
    for f in ("start_us", "end_us", "error_code",
              "request_size", "response_size"):
        setattr(span, f, int(d.get(f, 0)))
    span.remote_side = str(d.get("remote_side", ""))
    for f, v in (d.get("phases") or {}).items():
        if f in PHASE_FIELDS:
            setattr(span, f, int(v))
    anns = d.get("annotations")
    if anns:
        span.annotations = [(int(t), str(a)) for t, a in anns]
    span.origin = origin
    return span


def export_trace(trace_id: int, endpoint: str = "") -> dict:
    """The /rpcz/export?trace= payload: this process's SpanDB spans for
    one trace."""
    spans = span_db().by_trace(trace_id)
    return {
        "endpoint": endpoint,
        "trace": format_trace_id(trace_id),
        "spans": [span_to_dict(s) for s in spans],
    }


# ---------------------------------------------------------------------------
# trace stitching
# ---------------------------------------------------------------------------

# peers worth following are host:port builtin-HTTP surfaces; ICI
# coordinates ("ici://0/1") and empty remotes are skipped gracefully
_HOSTPORT_RE = re.compile(r"^[\w\.\-]+:\d{1,5}$")


def _peer_endpoints(spans) -> List[str]:
    """Peer endpoints recorded on client/collective spans, in first-seen
    order: the remote processes that hold this trace's server spans."""
    out: List[str] = []
    seen = set()
    for s in spans:
        if s.kind == "server":
            continue
        ep = str(s.remote_side or "")
        if ep and ep not in seen and _HOSTPORT_RE.match(ep):
            seen.add(ep)
            out.append(ep)
    return out


def _fetch_remote_spans(
    endpoint: str, trace_id: int, timeout: float, retries: int,
    retry_delay_s: float,
) -> List[RemoteSpan]:
    """Pull one peer's spans for the trace over its builtin surface.
    Remote spans reach the peer's SpanDB through its Collector drain
    (~100ms rounds), so an empty answer right after the RPC retries
    briefly before concluding the peer has nothing."""
    from incubator_brpc_tpu.tools.rpc_view import fetch_page_full

    page = f"rpcz/export?trace={format_trace_id(trace_id)}"
    for attempt in range(retries + 1):
        status, _ctype, body = fetch_page_full(
            endpoint, page, timeout=timeout, retries=1
        )
        if status != 200:
            raise OSError(f"/rpcz/export answered {status}")
        payload = json.loads(body.decode("utf-8"))
        dicts = payload.get("spans") or []
        if dicts or attempt == retries:
            # tag with the endpoint we actually reached, not the peer's
            # self-reported listen address (often a 0.0.0.0 wildcard)
            return [span_from_dict(d, endpoint) for d in dicts]
        time.sleep(retry_delay_s)
    return []


class _StitchDB:
    """by_trace facade over an already-collected span list, so
    trace.assemble works unchanged on the stitched set."""

    def __init__(self, spans):
        self._spans = list(spans)

    def by_trace(self, trace_id: int):
        return [s for s in self._spans if s.trace_id == trace_id]


def collect_stitched(
    trace_id: int,
    db=None,
    max_peers: int = 16,
    timeout: float = 2.0,
    retries: int = 3,
    retry_delay_s: float = 0.15,
    fetch=None,
) -> Tuple[List[Span], Dict[str, int], List[str]]:
    """BFS from the local trace across peer builtin surfaces.

    Returns (spans, origins, errors): the combined span set, per-peer
    fetched-span counts, and one message per peer that could not be
    reached (stitching is best-effort — a dead peer leaves its legs
    rendered from the client side only)."""
    db = db or span_db()
    fetch = fetch or _fetch_remote_spans
    spans: List[Span] = list(db.by_trace(trace_id))
    frontier = deque(_peer_endpoints(spans))
    visited = set()
    origins: Dict[str, int] = {}
    errors: List[str] = []
    while frontier and len(visited) < max_peers:
        ep = frontier.popleft()
        if ep in visited:
            continue
        visited.add(ep)
        try:
            remote = fetch(ep, trace_id, timeout, retries, retry_delay_s)
        except Exception as e:  # noqa: BLE001 — a dead peer degrades, not fails
            errors.append(f"{ep}: {e}")
            continue
        known = {(s.span_id, s.kind) for s in spans}
        added = 0
        for s in remote:
            if (s.span_id, s.kind) not in known:
                spans.append(s)
                added += 1
        origins[ep] = added
        # multi-hop: the peer's own client sub-spans name the next tier
        for nxt in _peer_endpoints(remote):
            if nxt not in visited:
                frontier.append(nxt)
    return spans, origins, errors


def _render_stitched_node(
    node, t0: int, depth: int, out: List[str], parent: Optional[Span]
):
    s = node.span
    pad = "  " * depth
    deltas = s.phase_deltas()
    phases = (
        " [" + " ".join(f"{n}={d}us" for n, d in deltas) + "]"
        if deltas
        else ""
    )
    origin = getattr(s, "origin", "")
    at = f" @{origin}" if origin else ""
    out.append(
        f"{pad}+{s.start_us - t0}us {s.kind} {s.service}.{s.method} "
        f"span={format_trace_id(s.span_id)} latency={s.latency_us}us "
        f"error={s.error_code} req={s.request_size}B "
        f"resp={s.response_size}B remote={s.remote_side}{at}{phases}"
    )
    if parent is not None and s.kind == "server" and parent.kind == "client":
        # the leg's client-observed latency minus the server's own
        # elapsed time: everything the server never saw — wire both
        # ways plus client-side queueing.  Clock-skew safe: both terms
        # are single-process durations, never cross-host differences.
        residual = parent.latency_us - s.latency_us
        if residual >= 0:
            out.append(
                f"{pad}    wire+queue residual={residual}us "
                f"(client {parent.latency_us}us - server {s.latency_us}us)"
            )
    for t, a in s.annotations or ():
        out.append(f"{pad}    @{t - t0}us {a}")
    for child in node.children:
        _render_stitched_node(child, t0, depth + 1, out, s)


def render_stitched(trace_id: int, db=None, **kw) -> Optional[str]:
    """The /rpcz?trace=N&stitch=1 view: one tree for the whole pod.
    None when even the local ring has no spans for the trace."""
    spans, origins, errors = collect_stitched(trace_id, db=db, **kw)
    if not spans:
        return None
    roots = trace_mod.assemble(trace_id, _StitchDB(spans))
    if not roots:
        return None
    t0 = min(n.span.start_us for n in roots)
    remote_total = sum(origins.values())
    head = (
        f"stitched trace {format_trace_id(trace_id)}: "
        f"{len(spans)} spans ({remote_total} remote from "
        f"{len(origins)} peers; times relative to first span)"
    )
    out = [head]
    for ep in sorted(origins):
        out.append(f"  peer {ep}: {origins[ep]} spans")
    for err in errors:
        out.append(f"  [unreachable] {err}")
    for root in roots:
        _render_stitched_node(root, t0, 0, out, None)
    return "\n".join(out)


# ---------------------------------------------------------------------------
# replica scraping + exact merging (/cluster/metrics, /cluster/latency_breakdown)
# ---------------------------------------------------------------------------

def resolve_replicas(spec: str) -> List[str]:
    """A replica list from either an explicit "host:port,host:port"
    string or a naming-service url (list://, file://, tpu://) — the
    same resolvers channels use (client/naming_service.py)."""
    spec = (spec or "").strip()
    if not spec:
        return []
    if "://" not in spec:
        return [s.strip() for s in spec.split(",") if s.strip()]
    from incubator_brpc_tpu.client.naming_service import (
        PeriodicNamingService,
        find_naming_service,
    )

    ns = find_naming_service(spec)
    if ns is None:
        raise ValueError(f"unknown naming scheme in {spec!r}")
    if isinstance(ns, PeriodicNamingService):
        path = spec.split("://", 1)[1]
        nodes = ns.get_servers(path)
    else:
        # one-shot resolution of a push-style service (list://): run
        # with a pre-set stop event — it publishes once and returns
        class _Once:
            nodes: list = []

            def on_servers_changed(self, nodes):
                _Once.nodes = nodes

        ev = threading.Event()
        ev.set()
        ns.run(spec, _Once(), ev)
        nodes = _Once.nodes
    return [str(n.endpoint) for n in nodes]


def scrape_exports(
    replicas: List[str], timeout: float = 3.0
) -> Tuple[List[dict], List[str]]:
    """Fetch /cluster/export from each replica; (payloads, errors)."""
    from incubator_brpc_tpu.tools.rpc_view import fetch_page_full

    payloads: List[dict] = []
    errors: List[str] = []
    for ep in replicas:
        try:
            status, _ctype, body = fetch_page_full(
                ep, "cluster/export", timeout=timeout, retries=1
            )
            if status != 200:
                raise OSError(f"/cluster/export answered {status}")
            payloads.append(json.loads(body.decode("utf-8")))
        except Exception as e:  # noqa: BLE001 — degrade per replica
            errors.append(f"{ep}: {e}")
        cluster_scrapes_total << 1
    return payloads, errors


def _is_latency_state(v) -> bool:
    return isinstance(v, dict) and "buckets" in v


def merge_dim_snapshots(snaps: List[dict]) -> dict:
    """Merge MultiDimension.mergeable_snapshot dicts from N replicas:
    numeric states add, {"sum","num"} recorder states add fieldwise,
    latency states merge through merge_latency_snapshots."""
    labels: List[str] = []
    merged: dict = {}
    for snap in snaps:
        if not snap:
            continue
        labels = labels or list(snap.get("labels") or [])
        for key, state in (snap.get("stats") or {}).items():
            cur = merged.get(key)
            if cur is None:
                if _is_latency_state(state):
                    state = merge_latency_snapshots([state])  # deep copy
                elif isinstance(state, dict):
                    state = dict(state)
                merged[key] = state
            elif _is_latency_state(state):
                merged[key] = merge_latency_snapshots([cur, state])
            elif isinstance(state, dict):
                for k, v in state.items():
                    if isinstance(v, (int, float)):
                        cur[k] = cur.get(k, 0) + v
            elif isinstance(state, (int, float)):
                merged[key] = cur + state
    return {"labels": labels, "stats": merged}


def merge_exports(payloads: List[dict]) -> dict:
    """Fold N /cluster/export payloads into one merged view:
    {"replicas": [...], "methods": {...}, "dims": {...}}."""
    methods: Dict[str, dict] = {}
    dims: Dict[str, List[dict]] = {}
    replicas: List[str] = []
    for p in payloads:
        replicas.append(p.get("endpoint", "?"))
        for name, m in (p.get("methods") or {}).items():
            cur = methods.setdefault(name, {"latency": None, "errors": 0})
            cur["latency"] = merge_latency_snapshots(
                [cur["latency"], m.get("latency")]
                if cur["latency"]
                else [m.get("latency")]
            )
            cur["errors"] += int(m.get("errors", 0))
        for name, snap in (p.get("dims") or {}).items():
            dims.setdefault(name, []).append(snap)
    return {
        "replicas": replicas,
        "methods": methods,
        "dims": {
            name: merge_dim_snapshots(snaps)
            for name, snaps in dims.items()
        },
    }


def merged_breakdown(merged: dict) -> Dict[str, Dict[str, dict]]:
    """The rpc_phase_latency_us family of a merged export, reshaped to
    the {method: {phase: stats}} table latency_breakdown renders."""
    fam = (merged.get("dims") or {}).get("rpc_phase_latency_us") or {}
    out: Dict[str, Dict[str, dict]] = {}
    for key, state in (fam.get("stats") or {}).items():
        if not _is_latency_state(state):
            continue
        method, _, phase = key.partition(MultiDimension._KEY_SEP)
        out.setdefault(method, {})[phase] = snapshot_stats(state)
    return out


def render_merged_metrics(merged: dict, errors: List[str]) -> str:
    """Prometheus-style text over a merged export: counter families
    summed, latency families re-read from merged buckets (exact)."""
    lines = [
        f"# cluster aggregation over {len(merged['replicas'])} replicas: "
        + ",".join(merged["replicas"])
    ]
    for err in errors:
        lines.append(f"# unreachable: {err}")
    for name in sorted(merged.get("methods") or ()):
        m = merged["methods"][name]
        stats = snapshot_stats(m["latency"] or {})
        label = f'method="{name}"'
        for stat in ("count", "avg_us", "p50_us", "p90_us", "p99_us", "max_us"):
            v = stats[stat]
            lines.append(
                f"rpc_method_latency_us{{{label},stat=\"{stat}\"}} {v:g}"
            )
        lines.append(f"rpc_method_errors_total{{{label}}} {m['errors']}")
        qps = (m["latency"] or {}).get("qps", 0.0)
        lines.append(f"rpc_method_qps{{{label}}} {qps:g}")
    for name in sorted(merged.get("dims") or ()):
        fam = merged["dims"][name]
        labels = fam.get("labels") or []
        for key in sorted(fam.get("stats") or ()):
            state = fam["stats"][key]
            parts = key.split(MultiDimension._KEY_SEP)
            label = ",".join(
                f'{k}="{v}"' for k, v in zip(labels, parts)
            )
            if _is_latency_state(state):
                stats = snapshot_stats(state)
                for stat in ("count", "avg_us", "p50_us", "p99_us"):
                    lines.append(
                        f"{name}{{{label},stat=\"{stat}\"}} {stats[stat]:g}"
                    )
            elif isinstance(state, dict):
                num = state.get("num", 0)
                avg = state.get("sum", 0) / num if num else 0.0
                lines.append(f"{name}{{{label},stat=\"num\"}} {num:g}")
                lines.append(f"{name}{{{label},stat=\"avg\"}} {avg:g}")
            else:
                lines.append(f"{name}{{{label}}} {state:g}")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# straggler attribution (/cluster/stragglers)
# ---------------------------------------------------------------------------

# peer-labeled fan-out counters for /metrics (bounded label set: a pod
# has a fixed shard count; hostile/unbounded peers collapse to _other)
_MAX_PEERS = 64
cluster_fanout_legs_total = MultiDimension(
    lambda: Adder(0), ["peer"]
).expose("cluster_fanout_legs_total")
cluster_fanout_slowest_total = MultiDimension(
    lambda: Adder(0), ["peer"]
).expose("cluster_fanout_slowest_total")
cluster_scrapes_total = Adder(0).expose("cluster_scrapes_total")


class StragglerTracker:
    """Sliding window of fan-out completions, attributed per peer.

    Each fan-out contributes its slowest leg's DRAG — how much longer
    the fan-out took than it would have at the median leg latency —
    to that leg's peer, split into server time vs wire+queue residual
    by the leg's own server_time_us share.  Ranking by accumulated
    drag names the shard actually stretching the tail, not merely the
    one with the worst mean.
    """

    def __init__(self, window_s: float = 300.0, max_fanouts: int = 2048):
        self.window_s = window_s
        self._lock = threading.Lock()
        # (ts_s, method, legs) where legs = [(peer, total_us, server_us,
        # failed), ...] — only live (non-skipped) legs
        self._fanouts: deque = deque(maxlen=max_fanouts)
        self._peers: set = set()

    def _peer_label(self, peer: str) -> str:
        if peer in self._peers:
            return peer
        if len(self._peers) >= _MAX_PEERS:
            return "_other"
        self._peers.add(peer)
        return peer

    def note_fanout(self, method: str, legs) -> None:
        """Record one completed fan-out (called from the combo-channel
        finish closures).  legs: [(peer, total_us, server_us, failed)].
        Cheap by design — one deque append + two counter bumps."""
        if len(legs) < 2:
            return  # no siblings: straggling is relative
        now = time.time()
        with self._lock:
            legs = [
                (self._peer_label(str(p)), int(t), int(s), bool(f))
                for p, t, s, f in legs
            ]
            self._fanouts.append((now, method, legs))
        slowest = max(legs, key=lambda leg: leg[1])
        for peer, _t, _s, _f in legs:
            cluster_fanout_legs_total.get_stats([peer]) << 1
        cluster_fanout_slowest_total.get_stats([slowest[0]]) << 1

    def report(self, window_s: Optional[float] = None) -> dict:
        """Ranked per-peer attribution over the window."""
        window = window_s if window_s is not None else self.window_s
        cutoff = time.time() - window
        with self._lock:
            fanouts = [f for f in self._fanouts if f[0] >= cutoff]
        peers: Dict[str, dict] = {}

        def agg(peer):
            return peers.setdefault(peer, {
                "peer": peer, "legs": 0, "failed": 0, "slowest": 0,
                "drag_us": 0, "drag_server_us": 0, "drag_wire_us": 0,
                "total_us": 0, "server_us": 0, "wire_us": 0,
                "max_total_us": 0,
            })

        for _ts, _method, legs in fanouts:
            totals = sorted(t for _p, t, _s, _f in legs)
            median = totals[len(totals) // 2]
            slowest = max(legs, key=lambda leg: leg[1])
            for peer, total, server, failed in legs:
                a = agg(peer)
                a["legs"] += 1
                a["failed"] += int(failed)
                a["total_us"] += total
                server = min(server, total)
                wire = total - server if server > 0 else 0
                a["server_us"] += server
                a["wire_us"] += wire
                if total > a["max_total_us"]:
                    a["max_total_us"] = total
            peer, total, server, _failed = slowest
            a = agg(peer)
            a["slowest"] += 1
            drag = max(0, total - median)
            a["drag_us"] += drag
            # split the drag by the slowest leg's own composition:
            # server share = stamped server time, remainder = wire+queue
            if total > 0 and server > 0:
                ds = drag * min(server, total) // total
            else:
                ds = 0
            a["drag_server_us"] += ds
            a["drag_wire_us"] += drag - ds
        ranked = sorted(
            peers.values(),
            key=lambda a: (a["drag_us"], a["slowest"]),
            reverse=True,
        )
        for a in ranked:
            n = a["legs"] or 1
            a["mean_total_us"] = a["total_us"] // n
            a["mean_server_us"] = a["server_us"] // n
            a["mean_wire_us"] = a["wire_us"] // n
        return {
            "window_s": window,
            "fanouts": len(fanouts),
            "peers": ranked,
        }


_tracker = StragglerTracker()


def fanout_tracker() -> StragglerTracker:
    return _tracker


def note_fanout(method: str, legs) -> None:
    """Module-level hook the combo channels call (lazy-imported there:
    a fan-out completion pays one sys.modules lookup)."""
    _tracker.note_fanout(method, legs)
