"""Contention profiler — lock-wait sampling through the Collector.

Analog of the reference's in-house contention profiler
(bthread/mutex.cpp:106-180): contended TaskMutex acquisitions submit a
(duration, stack) sample through the bvar Collector pipeline (bounded
overhead — the Collector's speed limit plus a 1-in-N sampling gate on
the stack capture itself); /hotspots/contention renders the aggregate
as a pprof-style text profile (count + wait time per unique stack).
"""

from __future__ import annotations

import threading
import traceback
from collections import defaultdict
from typing import Dict, List, Tuple

from incubator_brpc_tpu.metrics.collector import Collected
from incubator_brpc_tpu.utils.hashes import fast_rand

# capture a stack only for ~1 in N contended waits: stack extraction is
# the expensive part (reference samples at COLLECTOR_SAMPLING_BASE too)
SAMPLING_BASE = 16
_MAX_FRAMES = 12


class ContentionSample(Collected):
    __slots__ = ("duration_ns", "stack")

    def __init__(self, duration_ns: int, stack: Tuple[str, ...]):
        self.duration_ns = duration_ns
        self.stack = stack

    def dump_and_destroy(self):
        _profiler.add(self)

    def speed_limit(self) -> int:
        return 200  # samples/s ceiling through the Collector


class ContentionProfiler:
    """Aggregates samples by stack; rendered by /hotspots/contention."""

    def __init__(self):
        self._lock = threading.Lock()
        # stack -> [count, total_ns]
        self._agg: Dict[Tuple[str, ...], List[int]] = defaultdict(lambda: [0, 0])
        self.total_samples = 0
        self.total_wait_ns = 0

    def add(self, sample: ContentionSample):
        with self._lock:
            slot = self._agg[sample.stack]
            slot[0] += 1
            slot[1] += sample.duration_ns
            self.total_samples += 1
            self.total_wait_ns += sample.duration_ns

    def snapshot(self) -> Dict[Tuple[str, ...], List[int]]:
        """stack → [count, total_ns] copy (flamegraph rendering)."""
        with self._lock:
            return {k: list(v) for k, v in self._agg.items()}

    def reset(self):
        with self._lock:
            self._agg.clear()
            self.total_samples = 0
            self.total_wait_ns = 0

    def render(self, top: int = 40) -> str:
        """pprof-style text: '--- contention' header then per-stack
        'count  wait_us @ frame; frame; ...' hottest first."""
        with self._lock:
            rows = sorted(
                self._agg.items(), key=lambda kv: kv[1][1], reverse=True
            )[:top]
            total_s, total_ns = self.total_samples, self.total_wait_ns
        out = [
            "--- contention",
            f"sampling_base: {SAMPLING_BASE}",
            f"samples: {total_s}  total_wait_us: {total_ns // 1000}",
            "",
        ]
        for stack, (count, ns) in rows:
            out.append(f"{count:>8} {ns // 1000:>12}us @ " + "; ".join(stack))
        return "\n".join(out)


_profiler = ContentionProfiler()


def profiler() -> ContentionProfiler:
    return _profiler


def record_contention(duration_ns: int):
    """Called from TaskMutex on a contended acquire. The stack-capture
    gate keeps the fast path cheap; accepted samples flow through the
    Collector so aggregate work happens off the caller's thread."""
    if fast_rand() % SAMPLING_BASE:
        return
    frames = traceback.extract_stack(limit=_MAX_FRAMES + 2)[:-2]
    stack = tuple(
        f"{f.name}({f.filename.rsplit('/', 1)[-1]}:{f.lineno})" for f in frames
    )
    ContentionSample(duration_ns * SAMPLING_BASE, stack).submit()
