"""Per-method per-phase latency aggregation behind /latency_breakdown.

Spans carry phase timestamps (observability/span.py PHASE_FIELDS); when
the Collector drain thread persists a span, its phase deltas fold into
one LatencyRecorder per (method, phase) — the same log-bucketed
percentile machinery /status uses, windowed by the 1 Hz bvar sampler.
Aggregation runs entirely off the RPC hot path (the drain thread), so
enabling rpcz costs the stamps, not the statistics.

Also hosts the runtime queue-wait recorders: the scheduler and
ExecutionQueues report time-in-queue here under the ``_runtime``
pseudo-method, closing the queue-in/queue-out leg spans can't see.

The whole family is exported to Prometheus as labeled series
``rpc_phase_latency_us{method=...,phase=...,stat=...}`` through a
MultiDimension façade the /metrics exposition walks.
"""

from __future__ import annotations

import threading
from typing import Dict, Tuple

from incubator_brpc_tpu.metrics.latency_recorder import LatencyRecorder
from incubator_brpc_tpu.metrics.multi_dimension import MultiDimension

# distinct methods tracked before new ones collapse into "_other"
# (unbounded method names would leak recorders under hostile traffic)
_MAX_METHODS = 128

_lock = threading.Lock()
_recorders: Dict[Tuple[str, str], LatencyRecorder] = {}
_methods: set = set()


def recorder(method: str, phase: str) -> LatencyRecorder:
    key = (method, phase)
    rec = _recorders.get(key)
    if rec is not None:
        return rec
    with _lock:
        if method not in _methods and len(_methods) >= _MAX_METHODS:
            method = "_other"  # over the cap: collapse, don't grow
            key = (method, phase)
        rec = _recorders.get(key)
        if rec is None:
            _methods.add(method)
            rec = _recorders[key] = LatencyRecorder()
        return rec


def _method_key(span) -> str:
    """Aggregation key for one span. Collective legs carry per-pair
    method names ('slice0/chip1->slice0/chip2') — unbounded label
    cardinality on a pod — so they aggregate under their service
    ('ici'/'dcn'/'collective'); the pair stays visible on the span
    itself in /rpcz."""
    if span.kind == "collective":
        return span.service or "collective"
    method = f"{span.service}.{span.method}" if span.service else span.method
    return method or "_unknown"


def record_span(span) -> None:
    """Fold one finished span's phase deltas (called from the Collector
    drain thread via Span.dump_and_destroy — never the RPC path).
    update_batched keeps even the drain thread's cost at an append per
    observation — on a single shared core, drain-thread work still
    competes with serving."""
    method = _method_key(span)
    for phase, delta in span.phase_deltas():
        recorder(method, phase).update_batched(delta)
    recorder(method, f"total_{span.kind}").update_batched(span.latency_us)


def queue_wait_recorder(name: str):
    """Callable(wait_us) for ExecutionQueue/scheduler queue-out hooks;
    records under the _runtime pseudo-method with phase `name`.
    Flag-gated: with rpcz disabled the callable is a cheap no-op, and
    its ``gate`` attribute lets the queue skip even the enqueue-side
    clock read — runtime queues pay nothing when observability is
    off."""
    from incubator_brpc_tpu.observability.span import _RPCZ_FLAG

    update = recorder("_runtime", name).update_batched

    def record(wait_us: int) -> None:
        if _RPCZ_FLAG.value:
            update(wait_us)

    record.gate = _RPCZ_FLAG
    return record


def snapshot() -> Dict[str, Dict[str, dict]]:
    """{method: {phase: {count, avg, p50, p90, p99, max}}}."""
    with _lock:
        items = list(_recorders.items())
    out: Dict[str, Dict[str, dict]] = {}
    for (method, phase), rec in items:
        n = rec.count()
        if not n:
            continue
        out.setdefault(method, {})[phase] = {
            "count": n,
            "avg_us": rec.latency(),
            "p50_us": rec.latency_percentile(0.5),
            "p90_us": rec.latency_percentile(0.9),
            "p99_us": rec.latency_percentile(0.99),
        }
    return out


def mergeable_snapshot() -> Dict[str, Dict[str, dict]]:
    """{method: {phase: LatencyRecorder.mergeable_snapshot()}} — the
    aggregation STATE of the whole family, for /cluster/export.  Merged
    across replicas (metrics.latency_recorder.merge_latency_snapshots)
    it yields exactly the pooled-sample percentiles; the pre-computed
    stats snapshot() returns can never be merged that way."""
    with _lock:
        items = list(_recorders.items())
    out: Dict[str, Dict[str, dict]] = {}
    for (method, phase), rec in items:
        snap = rec.mergeable_snapshot()
        if not snap["count"] and not snap["latency_num"]:
            continue
        out.setdefault(method, {})[phase] = snap
    return out


_PHASE_ORDER = {
    p: i
    for i, p in enumerate(
        ("parse", "queue", "callback", "device", "write", "send")
    )
}


def render() -> str:
    """Plain-text table for the /latency_breakdown builtin page."""
    snap = snapshot()
    if not snap:
        return (
            "no phase data collected yet "
            "(rpcz_enabled must be true; make some calls)"
        )
    return render_table(snap)


def render_table(snap: Dict[str, Dict[str, dict]]) -> str:
    """Table body over a snapshot()-shaped stats dict — shared by the
    local page and /cluster/latency_breakdown's merged view."""
    out = []
    for method in sorted(snap):
        out.append(f"{method}:")
        phases = snap[method]
        for phase in sorted(
            phases, key=lambda p: (_PHASE_ORDER.get(p, 99), p)
        ):
            s = phases[phase]
            out.append(
                f"  {phase:<16} count={s['count']:<8} "
                f"avg={s['avg_us']:.0f}us p50={s['p50_us']:.0f} "
                f"p90={s['p90_us']:.0f} p99={s['p99_us']:.0f}"
            )
        out.append("")
    return "\n".join(out)


class _Value:
    """Minimal get_value carrier for the MultiDimension walk."""

    __slots__ = ("_v",)

    def __init__(self, v):
        self._v = v

    def get_value(self):
        return self._v

    def describe(self):
        v = self._v
        return f"{v:.6g}" if isinstance(v, float) else str(v)


class _PhaseDimension(MultiDimension):
    """Read-only MultiDimension over the recorder family: the /metrics
    exposition iterates items() and emits one labeled gauge per
    (method, phase, stat)."""

    _STATS = (
        ("count", lambda r: r.count()),
        ("avg", lambda r: r.latency()),
        ("p50", lambda r: r.latency_percentile(0.5)),
        ("p99", lambda r: r.latency_percentile(0.99)),
    )

    def __init__(self):
        super().__init__(lambda: None, ["method", "phase", "stat"])

    def items(self):
        with _lock:
            recs = list(_recorders.items())
        out = []
        for (method, phase), rec in recs:
            if not rec.count():
                continue
            for stat, fn in self._STATS:
                out.append(((method, phase, stat), _Value(fn(rec))))
        return out

    def mergeable_snapshot(self) -> dict:
        """Override the generic walk: items() yields COMPUTED stats
        (avg/p50/p99) whose cross-replica sum would be nonsense.  Export
        the underlying recorder state per (method, phase) instead."""
        stats = {
            self._KEY_SEP.join((method, phase)): snap
            for method, phases in mergeable_snapshot().items()
            for phase, snap in phases.items()
        }
        return {"labels": ["method", "phase"], "stats": stats}


phase_dimension = _PhaseDimension().expose("rpc_phase_latency_us")

# scheduler queue-out hook: every task's spawn→run delay lands under
# _runtime/task_queue (the queue-wait leg spans can't see directly);
# the rpcz flag gates even the per-task clock reads
from incubator_brpc_tpu.observability.span import _RPCZ_FLAG  # noqa: E402
from incubator_brpc_tpu.runtime import scheduler as _scheduler  # noqa: E402

_scheduler.set_task_queue_observer(
    queue_wait_recorder("task_queue"), gate=_RPCZ_FLAG
)
