"""Service definition model.

The reference uses protobuf generated services
(google::protobuf::Service; registration at server.cpp:1470 builds
fullname→method maps). Python protobuf dropped generic services, so the
TPU build declares services as classes with @rpc_method-decorated
handlers over protobuf message classes — same shape, same registry:
``Server.add_service`` builds the (service_name, method_name) →
MethodSpec map, and client stubs are generated from the same specs.

Handler signature (identical contract to the reference's CallMethod):
    def Echo(self, controller, request, response, done):
        ...fill response...
        done()       # MUST run exactly once, may be called later (async)
"""

from __future__ import annotations

import inspect
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional, Type


@dataclass
class MethodSpec:
    service_name: str
    method_name: str
    request_class: type
    response_class: type
    fn: Optional[Callable] = None  # bound at add_service time
    # batched-method registration (see batched_method below): the raw
    # batch-signature handler + its default BatchPolicy.  The server
    # builds a Batcher from these when batching is enabled; otherwise
    # `fn` (the synthesized single-request adapter) serves the method
    # on the existing dispatch path unchanged.
    batch_fn: Optional[Callable] = None
    batch_policy: Optional[object] = None

    @property
    def full_name(self) -> str:
        return f"{self.service_name}.{self.method_name}"


def rpc_method(request_class: type, response_class: type):
    """Mark a Service method as an RPC method with its message types."""

    def deco(fn):
        fn.__rpc_spec__ = (request_class, response_class)
        return fn

    return deco


def batched_method(request_class: type, response_class: type, policy=None):
    """Mark a BATCH-signature handler as an RPC method eligible for
    server-side micro-batching (docs/batching.md).  The decorated
    function takes parallel LISTS — one entry per coalesced request —
    and ONE done that completes them all:

        @batched_method(EchoRequest, EchoResponse,
                        policy=BatchPolicy(max_batch_size=32))
        def Get(self, controllers, requests, responses, done):
            ...fill responses[i] / controllers[i].set_failed(...)...
            done()      # exactly once; scatters per-row responses

    The decorator synthesizes a single-request adapter with the normal
    handler signature, so the method ALSO serves the existing dispatch
    path — unbatched servers, the batching-off config, and stubs see no
    difference (the adapter's cost is three list wraps).  Per-row
    failure = set_failed on that row's controller; batch-mates are
    unaffected.
    """
    from incubator_brpc_tpu.batching.policy import BatchPolicy

    batch_policy = policy if policy is not None else BatchPolicy()

    def deco(fn):
        def single(self, controller, request, response, done):
            fn(self, [controller], [request], [response], done)

        single.__name__ = fn.__name__
        single.__qualname__ = getattr(fn, "__qualname__", fn.__name__)
        single.__doc__ = fn.__doc__
        single.__rpc_spec__ = (request_class, response_class)
        single.__batch_fn__ = fn
        single.__batch_policy__ = batch_policy
        return single

    return deco


class Service:
    """Base class for RPC services."""

    @classmethod
    def service_name(cls) -> str:
        return getattr(cls, "SERVICE_NAME", cls.__name__)

    @classmethod
    def method_specs(cls) -> Dict[str, MethodSpec]:
        """Walk the MRO so a subclass overriding a decorated method (a
        common test pattern: fault-injecting Echo) keeps the spec."""
        specs: Dict[str, MethodSpec] = {}
        for klass in cls.__mro__:
            for name, member in vars(klass).items():
                if name in specs:
                    continue
                spec = getattr(member, "__rpc_spec__", None)
                if spec is not None:
                    req_cls, res_cls = spec
                    specs[name] = MethodSpec(
                        cls.service_name(), name, req_cls, res_cls,
                        batch_fn=getattr(member, "__batch_fn__", None),
                        batch_policy=getattr(member, "__batch_policy__", None),
                    )
        return specs


# Sentinel response: skip response-object parsing entirely — the raw
# response payload lands on controller.response_bytes (native fast path;
# see docs/fastpath.md).  Pairs with passing an already-serialized
# `bytes` request: zero protobuf object work per call.
RAW_RESPONSE = object()


class ServiceStub:
    """Client-side stub generated from a Service class (analog of the
    pb-generated EchoService_Stub).

    stub = ServiceStub(channel, EchoService)
    stub.Echo(cntl, request)               -> response (sync)
    stub.Echo(cntl, request, done=fn)      -> response obj (async; done()
                                              runs when the RPC ends)
    stub.Echo(cntl, payload_bytes, response=RAW_RESPONSE)
                                           -> bytes mode: request is the
                                              serialized pb, reply bytes
                                              on cntl.response_bytes
    """

    def __init__(self, channel, service_cls: Type[Service]):
        self._channel = channel
        specs = service_cls.method_specs()
        self._method_specs = specs
        idx = {n: i for i, n in enumerate(sorted(specs))}
        for name, spec in specs.items():
            # index-addressed legacy protocols (hulu/nova/public) use
            # the method's position in sorted name order as its id
            spec._public_method_id = spec._nova_index = idx[name]
            setattr(self, name, self._make_method(spec))

    def method_spec(self, name: str) -> MethodSpec:
        """The MethodSpec behind a stub method — what Channel.call_many
        and SubmissionRing.submit take as their method argument."""
        return self._method_specs[name]

    def call_many(self, name: str, requests, timeout_ms=None,
                  controllers=None):
        """Vectorized convenience: Channel.call_many over this stub's
        method `name` (see client/channel.py for the full contract)."""
        return self._channel.call_many(
            self._method_specs[name], requests, timeout_ms, controllers
        )

    def _make_method(self, spec: MethodSpec):
        def call(controller, request, response=None, done=None):
            if response is None:
                response = spec.response_class()
            elif response is RAW_RESPONSE:
                response = None
            self._channel.call_method(spec, controller, request, response, done)
            return response

        call.__name__ = spec.method_name
        return call
