"""MethodStatus + ConcurrencyLimiter.

Analog of reference details/method_status.{h,cpp} and
concurrency_limiter.h: per-method concurrency gate + qps/latency stats
(LatencyRecorder gives qps, p50/p90/p99/p99.9 per method exactly as the
reference's /status page shows). The "auto" limiter implements the
reference's gradient algorithm (policy/auto_concurrency_limiter.{h,cpp},
doc docs/cn/auto_concurrency_limiter.md): track min latency and
windowed qps, derive max_concurrency ≈ peak_qps × min_latency with a
periodic exploration phase that lowers the limit to re-sample the
no-load latency.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from incubator_brpc_tpu.metrics.latency_recorder import LatencyRecorder
from incubator_brpc_tpu.metrics.reducer import Adder


class ConcurrencyLimiter:
    """Interface (concurrency_limiter.h)."""

    def on_request(self, current: int) -> bool:
        raise NotImplementedError

    def on_response(self, latency_us: int) -> None:
        pass

    def on_response_bulk(self, latency_us: int, n: int) -> None:
        """Fold `n` responses averaging `latency_us` in O(1).  Used by
        the native fast-path harvest; limiters that estimate qps from
        call counts must override (one plain on_response per harvest
        would collapse the estimate)."""
        self.on_response(latency_us)

    def max_concurrency(self) -> int:
        return 0


class ConstantConcurrencyLimiter(ConcurrencyLimiter):
    def __init__(self, limit: int):
        self._limit = limit

    def on_request(self, current: int) -> bool:
        return self._limit <= 0 or current <= self._limit

    def max_concurrency(self) -> int:
        return self._limit


class AutoConcurrencyLimiter(ConcurrencyLimiter):
    """Gradient/EMA limiter (auto_concurrency_limiter.h:29-80)."""

    def __init__(
        self,
        alpha: float = 0.3,
        min_limit: int = 8,
        sample_window_s: float = 1.0,
        explore_interval_s: float = 15.0,
        explore_ratio: float = 0.7,
    ):
        self._alpha = alpha
        self._min_limit = min_limit
        self._limit = 64
        self._min_latency_us: Optional[float] = None
        self._win_start = time.monotonic()
        self._win_count = 0
        self._win_lat_sum = 0.0
        self._last_explore = time.monotonic()
        self._explore_interval = explore_interval_s
        self._explore_ratio = explore_ratio
        self._sample_window = sample_window_s
        self._lock = threading.Lock()
        # observed-latency feedback (server/admission.py
        # feed_limiter_from_tier_latency): when set, each window update
        # also reads this live signal — e.g. the interactive tier's p99
        # — and shrinks the limit proportionally whenever it exceeds
        # the target, instead of trusting the static no-load estimate
        self._observed_us_fn = None
        self._target_us = 0

    def set_latency_target(self, observed_us_fn, target_us: int) -> None:
        """Feed an observed-latency source (callable returning the
        current latency in us, e.g. a tier p99) and the acceptable
        target.  observed > target ⇒ the next window update scales the
        limit by target/observed (floored at min_limit)."""
        self._observed_us_fn = observed_us_fn
        self._target_us = int(target_us)

    def on_request(self, current: int) -> bool:
        return current <= self._limit

    def on_response(self, latency_us: int) -> None:
        self.on_response_bulk(latency_us, 1)

    def on_response_bulk(self, latency_us: int, n: int) -> None:
        now = time.monotonic()
        with self._lock:
            self._win_count += n
            self._win_lat_sum += latency_us * n
            span = now - self._win_start
            if span < self._sample_window or self._win_count < 10:
                return
            avg_lat = self._win_lat_sum / self._win_count
            qps = self._win_count / span
            self._win_start = now
            self._win_count = 0
            self._win_lat_sum = 0.0
            if self._min_latency_us is None:
                self._min_latency_us = avg_lat
            else:
                # EMA toward observed minimum (reference smoothing)
                self._min_latency_us = min(
                    self._min_latency_us * (1 - self._alpha) + avg_lat * self._alpha,
                    max(self._min_latency_us, 1.0),
                )
            # little's law: concurrency that keeps latency near no-load
            target = qps * (self._min_latency_us / 1e6) * 1.2 + self._min_limit
            self._limit = max(self._min_limit, int(target))
            if self._observed_us_fn is not None and self._target_us > 0:
                try:
                    observed = float(self._observed_us_fn() or 0.0)
                except Exception:  # noqa: BLE001 — a failing signal
                    # source must never take the method down with it
                    observed = 0.0
                if observed > self._target_us:
                    self._limit = max(
                        self._min_limit,
                        min(
                            self._limit,
                            int(self._limit * self._target_us / observed),
                        ),
                    )
            if now - self._last_explore > self._explore_interval:
                # exploration: drop the limit briefly to re-measure
                self._last_explore = now
                self._limit = max(self._min_limit, int(self._limit * self._explore_ratio))
                self._min_latency_us = avg_lat

    def max_concurrency(self) -> int:
        return self._limit


def make_limiter(spec) -> Optional[ConcurrencyLimiter]:
    """Parse an adaptive max-concurrency spec: 0/None=unlimited, int=N,
    "auto"=gradient, "constant=N" (reference AdaptiveMaxConcurrency's
    string forms, adaptive_max_concurrency.cpp)."""
    if spec in (None, 0, "", "unlimited"):
        return None
    if spec == "auto":
        return AutoConcurrencyLimiter()
    if isinstance(spec, str) and spec.startswith("constant="):
        return ConstantConcurrencyLimiter(int(spec.partition("=")[2]))
    if isinstance(spec, ConcurrencyLimiter):
        return spec
    return ConstantConcurrencyLimiter(int(spec))


class MethodStatus:
    """Per-method stats + concurrency gate (details/method_status.h)."""

    def __init__(self, full_name: str, limiter: Optional[ConcurrencyLimiter] = None):
        self.full_name = full_name
        self.latency_rec = LatencyRecorder()
        self.errors = Adder(0)
        self._concurrency = 0
        self._lock = threading.Lock()
        self.limiter = limiter

    def expose(self):
        safe = self.full_name.replace(".", "_").lower()
        self.latency_rec.expose(f"rpc_server_{safe}")
        self.errors.expose(f"rpc_server_{safe}_error")

    def on_requested(self) -> bool:
        with self._lock:
            self._concurrency += 1
            current = self._concurrency
        if self.limiter is not None and not self.limiter.on_request(current):
            with self._lock:
                self._concurrency -= 1
            return False
        return True

    def on_response(self, latency_us: int, error: bool = False) -> None:
        with self._lock:
            self._concurrency -= 1
        if error:
            self.errors << 1
        elif latency_us > 0:
            self.latency_rec.update(latency_us)
        if self.limiter is not None:
            self.limiter.on_response(latency_us)

    @property
    def concurrency(self) -> int:
        return self._concurrency
