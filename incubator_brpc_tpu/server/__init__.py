"""Server stack (analog of reference src/brpc/server.{h,cpp} + builtin/)."""

from incubator_brpc_tpu.server.service import Service, rpc_method, MethodSpec  # noqa: F401
from incubator_brpc_tpu.server.server import Server, ServerOptions  # noqa: F401
