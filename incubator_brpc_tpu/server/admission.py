"""Admission control — ONE decision point for every server shed path.

The survey's overload story (adaptive_max_concurrency.cpp + backup
requests) previously lived in three unconnected places here: the
per-method concurrency limiter rejected in each protocol's dispatch,
the micro-batcher shed expired rows at flush and overflowing rows at
its queue cap — each with its own error code.  This module unifies
them (docs/overload.md):

* **code mapping** — one table says what each shed means to the
  caller.  ``EOVERCROWDED`` = *this server* is overloaded; the same
  request is fine on a different replica (the client retry policy
  reissues it only against another server).  ``ELIMIT`` = the
  *request* is no longer worth serving (its deadline expired while
  queued); retrying anywhere is wasted work — drop.  ``ECANCELED`` =
  the caller gave up (hedge loser): shed silently, no response.

* **priority tiers + quotas** — tenant identity rides
  ``RpcRequestMeta.tenant``; the policy maps tenants (and methods) to
  tiers.  Each tier has a ``weight`` — its claim on method capacity
  under contention — and lower-priority tiers stop admitting at
  ``limit × share`` while higher tiers run to the full limit, so
  weighted shedding drains the bulk tier before the interactive tier.
  Per-tenant quotas bound one tenant's concurrent rows outright.

* **enforcement at dispatch, before user code** — the protocols call
  :meth:`AdmissionController.admit` where they used to call
  ``status.on_requested()`` directly; the batcher reads the row's tier
  (stamped on the controller) for its tier-aware queue cap and routes
  its shed codes through :func:`shed_code`.

Every shed lands in ``rpc_shed_total{method,tier,reason}``; per-tier
inflight and batch-queue depth are exposed on /metrics; the
``/admission`` builtin live-tunes weights and quotas.  The chaos site
``admission.decide`` (docs/chaos.md) injects forced rejections and
decision delays for the storm suite.

The inactive policy (no tenant/method mappings, no quotas) keeps the
pre-admission fast path: one gate call per request, no ticket object,
no gauge writes — the ``admission_disabled_overhead`` bench pins it
under 1%.
"""

from __future__ import annotations

import threading
import weakref
from typing import Dict, Optional

from incubator_brpc_tpu import errors
from incubator_brpc_tpu.chaos import injector as _chaos
from incubator_brpc_tpu.metrics.multi_dimension import MultiDimension
from incubator_brpc_tpu.metrics.passive_status import PassiveStatus
from incubator_brpc_tpu.metrics.reducer import Adder

#: canonical tier names (policies may define more)
TIER_INTERACTIVE = "interactive"
TIER_BULK = "bulk"

# ---------------------------------------------------------------------------
# shed-code mapping — THE table (satellite: consistent shed codes)
# ---------------------------------------------------------------------------

#: reason key -> wire error code.  "retry elsewhere" reasons map to
#: EOVERCROWDED, "drop" reasons to ELIMIT, hedge-loser cancellation to
#: ECANCELED (no response at all).  errors.py documents the same split.
SHED_CODES: Dict[str, int] = {
    "overload": errors.EOVERCROWDED,      # concurrency limiter said no
    "tier_share": errors.EOVERCROWDED,    # tier past its capacity share
    "tier_quota": errors.EOVERCROWDED,    # tier past its absolute quota
    "tenant_quota": errors.EOVERCROWDED,  # tenant past its quota
    "queue_full": errors.EOVERCROWDED,    # batch queue cap (max_queue_rows)
    "stopping": errors.EOVERCROWDED,      # batcher draining at stop()
    "chaos": errors.EOVERCROWDED,         # injected admission.decide reject
    "session_cap": errors.EOVERCROWDED,   # decode replica at max_sessions:
    #                                       the session router retries the
    #                                       admission on another replica
    #                                       (serving/decode.py)
    "deadline": errors.ELIMIT,            # expired while queued: drop
    "cancelled": errors.ECANCELED,        # hedge loser: silent shed
}


def shed_code(reason: str) -> int:
    """Wire code for one shed reason — every shed path maps through
    here so a given code always means the same thing to clients."""
    return SHED_CODES.get(reason, errors.EOVERCROWDED)


# ---------------------------------------------------------------------------
# metrics (module-level: names are process-global like every exposed var)
# ---------------------------------------------------------------------------

rpc_shed_total = MultiDimension(Adder, ["method", "tier", "reason"]).expose(
    "rpc_shed_total"
)
rpc_tier_inflight = MultiDimension(Adder, ["tier"]).expose("rpc_tier_inflight")

# live controllers, for the per-tier queue-depth gauges (batch rows
# queued per tier across every server in the process)
_controllers: "weakref.WeakSet[AdmissionController]" = weakref.WeakSet()
_exposed_depth_tiers = set()
_expose_lock = threading.Lock()


def note_shed(method: str, tier: Optional[str], reason: str) -> None:
    rpc_shed_total.get_stats([method, tier or TIER_INTERACTIVE, reason]) << 1


# ---------------------------------------------------------------------------
# per-tier observed latency (PR 8's named follow-on): the protocols feed
# each completed request's latency here when a tier was stamped, and the
# auto concurrency limiter can derive its pressure signal from a tier's
# OBSERVED p99 instead of a static target (feed_limiter_from_tier_latency)
# ---------------------------------------------------------------------------

_tier_latency: Dict[str, "object"] = {}


def tier_latency_recorder(tier: str):
    """The tier's LatencyRecorder (lazily created + exposed as
    ``rpc_tier_latency_<tier>``) — per-tier qps/p50/p99 on /metrics,
    and the signal source for latency-fed auto limiters."""
    rec = _tier_latency.get(tier)
    if rec is None:
        from incubator_brpc_tpu.metrics.latency_recorder import LatencyRecorder

        with _expose_lock:
            rec = _tier_latency.get(tier)
            if rec is None:
                rec = LatencyRecorder().expose(f"rpc_tier_latency_{tier}")
                _tier_latency[tier] = rec
    return rec


def note_latency(tier: str, latency_us: int) -> None:
    """One completed (non-shed) request's latency for `tier`.  Called
    from the protocol response paths only when a tier was stamped at
    admission, so inactive-policy traffic pays nothing."""
    if latency_us > 0:
        tier_latency_recorder(tier).update(latency_us)


def note_controller_latency(ctrl, latency_us: int) -> None:
    """The one feed point every protocol response path calls (tpu_std,
    HTTP, h2): records `latency_us` for the tier stamped on `ctrl` at
    admission.  Untier-ed (inactive-policy) traffic is one dict miss;
    failed requests (sheds included) stay out of the tail signal —
    fast-fails would deflate the p99 the limiter steers by."""
    tier = ctrl.__dict__.get("_admission_tier")
    if tier is not None and not ctrl.failed():
        note_latency(tier, latency_us)


def _queue_depth(tier: str) -> int:
    total = 0
    for ac in list(_controllers):
        total += ac.queue_depth(tier)
    return total


def _ensure_depth_gauge(tier: str) -> None:
    with _expose_lock:
        if tier in _exposed_depth_tiers:
            return
        PassiveStatus(lambda t=tier: _queue_depth(t)).expose(
            f"rpc_tier_queue_depth_{tier}"
        )
        _exposed_depth_tiers.add(tier)


# default tiers render on /metrics from import time (the PR 7
# metrics-unrenderable lint imports this module)
_ensure_depth_gauge(TIER_INTERACTIVE)
_ensure_depth_gauge(TIER_BULK)


# ---------------------------------------------------------------------------
# policy
# ---------------------------------------------------------------------------


class TierSpec:
    """One priority tier.  ``priority`` 0 is highest (shed last);
    ``weight`` is the tier's claim on method capacity under contention
    — a tier's admission share is (its weight + every lower tier's)
    over the total, so the top tier always sees share 1.0 and lower
    tiers stop admitting earlier.  ``quota`` (0 = unlimited) bounds
    the tier's concurrent rows absolutely, limiter or not."""

    __slots__ = ("name", "priority", "weight", "quota")

    def __init__(self, name: str, priority: int = 0, weight: float = 1.0,
                 quota: int = 0):
        if weight <= 0:
            raise ValueError(f"tier {name!r} weight must be > 0")
        self.name = name
        self.priority = int(priority)
        self.weight = float(weight)
        self.quota = int(quota)

    def to_dict(self) -> dict:
        return {
            "priority": self.priority,
            "weight": self.weight,
            "quota": self.quota,
        }


def _default_tiers() -> Dict[str, TierSpec]:
    # bulk claims 3/4 of capacity under contention; the remaining 1/4
    # is reserved headroom only interactive may use — under overload
    # bulk stops admitting at 75% of the limit while interactive runs
    # to 100%, which is what drains bulk first
    return {
        TIER_INTERACTIVE: TierSpec(TIER_INTERACTIVE, priority=0, weight=1.0),
        TIER_BULK: TierSpec(TIER_BULK, priority=1, weight=3.0),
    }


class AdmissionPolicy:
    """Tier/quota configuration.  Mutable at runtime (the /admission
    builtin live-tunes it); share recomputation happens under the
    policy lock and readers see a consistent snapshot dict."""

    def __init__(
        self,
        tiers: Optional[Dict[str, object]] = None,
        tenant_tiers: Optional[Dict[str, str]] = None,
        method_tiers: Optional[Dict[str, str]] = None,
        tenant_quotas: Optional[Dict[str, int]] = None,
        default_tier: str = TIER_INTERACTIVE,
    ):
        self._lock = threading.Lock()
        self.tiers: Dict[str, TierSpec] = _default_tiers()
        for name, spec in (tiers or {}).items():
            if isinstance(spec, TierSpec):
                self.tiers[name] = spec
            else:
                self.tiers[name] = TierSpec(name, **dict(spec))
        self.tenant_tiers = dict(tenant_tiers or {})
        self.method_tiers = dict(method_tiers or {})
        self.tenant_quotas = {k: int(v) for k, v in (tenant_quotas or {}).items()}
        if default_tier not in self.tiers:
            raise ValueError(f"default_tier {default_tier!r} is not a tier")
        self.default_tier = default_tier
        for t in list(self.tenant_tiers.values()) + list(
            self.method_tiers.values()
        ):
            if t not in self.tiers:
                raise ValueError(f"mapping names unknown tier {t!r}")
        self._shares: Dict[str, float] = {}
        self._recompute_shares()

    @classmethod
    def from_dict(cls, d: dict) -> "AdmissionPolicy":
        known = {"tiers", "tenant_tiers", "method_tiers", "tenant_quotas",
                 "default_tier"}
        unknown = set(d) - known
        if unknown:
            raise ValueError(
                f"unknown admission policy keys {sorted(unknown)} "
                f"(known: {sorted(known)})"
            )
        return cls(**d)

    def _recompute_shares(self) -> None:
        total = sum(t.weight for t in self.tiers.values())
        shares = {}
        for t in self.tiers.values():
            covered = sum(
                u.weight for u in self.tiers.values()
                if u.priority >= t.priority
            )
            shares[t.name] = covered / total if total else 1.0
        self._shares = shares

    def share(self, tier: str) -> float:
        """Fraction of the method limit this tier may fill; 1.0 for
        the highest-priority tier."""
        return self._shares.get(tier, 1.0)

    def tier_of(self, tenant: str, method: str) -> str:
        """Tenant mapping wins, then method mapping, then the default."""
        if tenant:
            t = self.tenant_tiers.get(tenant)
            if t is not None:
                return t
        t = self.method_tiers.get(method)
        return t if t is not None else self.default_tier

    @property
    def active(self) -> bool:
        """False = nothing configured beyond the defaults: every
        request resolves to the default (top) tier with share 1.0 and
        no quota, so admit() may skip tier bookkeeping entirely."""
        return bool(
            self.tenant_tiers
            or self.method_tiers
            or self.tenant_quotas
            or any(t.quota for t in self.tiers.values())
        )

    # ---- live tuning (the /admission builtin posts through these) ----------
    def set_tier(self, name: str, weight: Optional[float] = None,
                 quota: Optional[int] = None,
                 priority: Optional[int] = None) -> TierSpec:
        # validate EVERYTHING before touching state: a failed live-tune
        # must not leave a phantom tier or a half-applied spec behind
        # its 400 response
        if weight is not None:
            weight = float(weight)
            if weight <= 0:
                raise ValueError("weight must be > 0")
        if quota is not None:
            quota = int(quota)
        if priority is not None:
            priority = int(priority)
        created = False
        with self._lock:
            spec = self.tiers.get(name)
            if spec is None:
                created = True
                spec = self.tiers[name] = TierSpec(
                    name, priority=max(
                        (t.priority for t in self.tiers.values()), default=0
                    ) + 1,
                )
            if weight is not None:
                spec.weight = weight
            if quota is not None:
                spec.quota = quota
            if priority is not None:
                spec.priority = priority
            self._recompute_shares()
        if created:
            # a live-created tier gets its queue-depth gauge like tiers
            # declared at construction — otherwise its batch backlog is
            # invisible on /metrics.  Registered OUTSIDE the policy
            # lock: the expose path takes the module gauge lock and
            # nesting it under ours would mint a lock-order edge.
            _ensure_depth_gauge(name)
        return spec

    def set_tenant(self, tenant: str, tier: Optional[str] = None,
                   quota: Optional[int] = None) -> None:
        with self._lock:
            if tier is not None:
                if tier not in self.tiers:
                    raise ValueError(f"unknown tier {tier!r}")
                self.tenant_tiers[tenant] = tier
            if quota is not None:
                if int(quota) <= 0:
                    self.tenant_quotas.pop(tenant, None)
                else:
                    self.tenant_quotas[tenant] = int(quota)

    def set_method_tier(self, method: str, tier: str) -> None:
        with self._lock:
            if tier not in self.tiers:
                raise ValueError(f"unknown tier {tier!r}")
            self.method_tiers[method] = tier

    def snapshot(self):
        """Consistent copies of the mutable maps, under the policy
        lock — renders iterate these while POST /admission mutates the
        originals (an unlocked sorted(...items()) can raise
        'dictionary changed size during iteration')."""
        with self._lock:
            return (
                dict(self.tiers),
                dict(self.tenant_tiers),
                dict(self.method_tiers),
                dict(self.tenant_quotas),
            )

    def to_dict(self) -> dict:
        tiers, tenant_tiers, method_tiers, tenant_quotas = self.snapshot()
        return {
            "tiers": {
                n: dict(t.to_dict(), share=round(self.share(n), 4))
                for n, t in sorted(tiers.items())
            },
            "tenant_tiers": tenant_tiers,
            "method_tiers": method_tiers,
            "tenant_quotas": tenant_quotas,
            "default_tier": self.default_tier,
        }


# ---------------------------------------------------------------------------
# the decision point
# ---------------------------------------------------------------------------


class Admission:
    """One admit() outcome.  ``admitted`` False carries the shed code
    + reason; True may carry a ticket (active policies) that MUST be
    released exactly once when the request completes — the protocols
    release it in their response path."""

    __slots__ = ("admitted", "code", "reason", "tier", "_controller",
                 "_tenant", "_released")

    def __init__(self, admitted: bool, code: int = 0, reason: str = "",
                 tier: Optional[str] = None, controller=None,
                 tenant: str = ""):
        self.admitted = admitted
        self.code = code
        self.reason = reason
        self.tier = tier
        self._controller = controller
        self._tenant = tenant
        self._released = False

    @property
    def ticket(self) -> Optional["Admission"]:
        return self if self._controller is not None else None

    def release(self) -> None:
        """Idempotent: response paths funnel through more than one
        cleanup point and double-decrementing a gauge would corrupt
        the inflight accounting."""
        ac = self._controller
        if ac is None or self._released:
            return
        self._released = True
        ac._on_release(self.tier, self._tenant)


#: shared fast-path outcome for inactive policies — no per-request
#: allocation on the hot path
_ADMIT_PLAIN = Admission(True)


class AdmissionController:
    """Per-Server admission state: the policy plus live per-tier /
    per-tenant inflight counts.  The server owns one; protocols reach
    it via ``server.admission``."""

    def __init__(self, server=None, policy: Optional[AdmissionPolicy] = None):
        # weakref: the module-level gauge registry must not keep dead
        # servers (and their batchers) alive
        self._server_ref = weakref.ref(server) if server is not None else None
        if isinstance(policy, dict):
            policy = AdmissionPolicy.from_dict(policy)
        self.policy = policy or AdmissionPolicy()
        self._lock = threading.Lock()
        self._tier_inflight: Dict[str, int] = {}
        self._tenant_inflight: Dict[str, int] = {}
        # shared outcome for the top-tier short-circuit: carries the
        # policy's default tier (so batcher metrics attribute the rows
        # correctly) but no ticket — default_tier is fixed at policy
        # construction, so one object serves every such request
        self._admit_default = Admission(True, tier=self.policy.default_tier)
        for name in self.policy.tiers:
            _ensure_depth_gauge(name)
        _controllers.add(self)

    # ---- the per-request decision ------------------------------------------
    def admit(self, full_name: str, status, tenant: str = "") -> Admission:
        """Decide one request, before user code.  ``status`` is the
        method's MethodStatus (or None); on admit its concurrency is
        already counted (on_requested ran) — the caller's normal
        on_response accounting is unchanged.  Shed outcomes carry the
        mapped code; the caller answers and returns."""
        policy = self.policy
        if not policy.active:
            # fast path: concurrency gate + code mapping only
            if _chaos.armed:
                denied = self._chaos_check(full_name, policy.default_tier)
                if denied is not None:
                    return denied
            if status is not None and not status.on_requested():
                note_shed(full_name, policy.default_tier, "overload")
                return Admission(
                    False, shed_code("overload"),
                    "method concurrency limit reached (retry elsewhere)",
                    tier=policy.default_tier,
                )
            return _ADMIT_PLAIN
        tier = policy.tier_of(tenant, full_name)
        if _chaos.armed:
            denied = self._chaos_check(full_name, tier)
            if denied is not None:
                return denied
        tspec = policy.tiers.get(tier)
        share = policy.share(tier)
        if (
            share >= 1.0
            and (tspec is None or not tspec.quota)
            and not (tenant and policy.tenant_quotas.get(tenant))
        ):
            # top-tier, quota-free traffic: no tiered rule can shed it,
            # so skip the bookkeeping — an ACTIVE policy costs the
            # unmapped hot path the same as a disabled one (the
            # admission_disabled_overhead bench pins this).  The shared
            # outcome still names the tier so downstream metrics
            # (batch queue depth, shed labels) attribute the rows to
            # the policy's actual default tier, not a hardcoded one.
            if status is not None and not status.on_requested():
                note_shed(full_name, tier, "overload")
                return Admission(
                    False, shed_code("overload"),
                    "method concurrency limit reached (retry elsewhere)",
                    tier=tier,
                )
            return self._admit_default if tier == policy.default_tier else (
                Admission(True, tier=tier)
            )
        limit = 0
        if status is not None and status.limiter is not None:
            limit = status.limiter.max_concurrency()
        # tier share gate: a sub-1.0 tier stops admitting once the
        # method's concurrency would pass limit*share — the reserved
        # headroom above that belongs to higher-priority tiers.  Read
        # before on_requested: approximate under races, exact enough
        # (the hard cap below still holds).
        if limit > 0 and share < 1.0 and status is not None:
            if status.concurrency + 1 > max(1, int(limit * share)):
                note_shed(full_name, tier, "tier_share")
                return Admission(
                    False, shed_code("tier_share"),
                    f"tier {tier} past its {share:.0%} capacity share "
                    f"(retry elsewhere)", tier=tier,
                )
        with self._lock:
            if tspec is not None and tspec.quota:
                if self._tier_inflight.get(tier, 0) + 1 > tspec.quota:
                    deny = ("tier_quota", f"tier {tier} quota "
                            f"{tspec.quota} reached (retry elsewhere)")
                else:
                    deny = None
            else:
                deny = None
            if deny is None and tenant:
                q = policy.tenant_quotas.get(tenant, 0)
                if q and self._tenant_inflight.get(tenant, 0) + 1 > q:
                    deny = ("tenant_quota", f"tenant {tenant!r} quota {q} "
                            f"reached (retry elsewhere)")
            if deny is None:
                self._tier_inflight[tier] = self._tier_inflight.get(tier, 0) + 1
                if tenant:
                    self._tenant_inflight[tenant] = (
                        self._tenant_inflight.get(tenant, 0) + 1
                    )
        if deny is not None:
            reason_key, text = deny
            note_shed(full_name, tier, reason_key)
            return Admission(False, shed_code(reason_key), text, tier=tier)
        rpc_tier_inflight.get_stats([tier]) << 1
        if status is not None and not status.on_requested():
            # the hard concurrency gate; undo the tier bookkeeping the
            # lines above already counted for this request
            self._on_release(tier, tenant)
            note_shed(full_name, tier, "overload")
            return Admission(
                False, shed_code("overload"),
                "method concurrency limit reached (retry elsewhere)",
                tier=tier,
            )
        return Admission(True, tier=tier, controller=self, tenant=tenant)

    def _chaos_check(self, full_name: str, tier: str) -> Optional[Admission]:
        spec = _chaos.check("admission.decide", method=full_name, tier=tier)
        if spec is None:
            return None
        if spec.action == "delay_us":
            _chaos.sleep_us(spec.arg)
            return None
        # action == "reject": a forced shed, the storm suite's
        # deterministic admission-pressure knob
        note_shed(full_name, tier, "chaos")
        return Admission(
            False, shed_code("chaos"),
            "chaos: admission rejected (retry elsewhere)", tier=tier,
        )

    def _on_release(self, tier: Optional[str], tenant: str) -> None:
        tier = tier or self.policy.default_tier
        with self._lock:
            n = self._tier_inflight.get(tier, 0)
            if n > 0:
                self._tier_inflight[tier] = n - 1
            if tenant:
                n = self._tenant_inflight.get(tenant, 0)
                if n > 0:
                    self._tenant_inflight[tenant] = n - 1
        rpc_tier_inflight.get_stats([tier]) << -1

    def feed_limiter_from_tier_latency(
        self, status, tier: str = TIER_INTERACTIVE,
        target_us: int = 100_000, ratio: float = 0.99,
    ):
        """Wire a method's AUTO concurrency limiter to the observed
        per-tier latency (docs/overload.md): the limiter's window
        update reads the tier's live p99 (``ratio``) from the latency
        recorder and, whenever it exceeds ``target_us``, shrinks the
        concurrency limit proportionally — overload pressure measured
        where it hurts (the protected tier's tail) instead of a static
        no-load estimate.  ``status`` is the method's MethodStatus;
        its limiter must support ``set_latency_target`` (the "auto"
        limiter does).  Returns the recorder feeding the signal."""
        limiter = getattr(status, "limiter", None)
        if limiter is None or not hasattr(limiter, "set_latency_target"):
            raise ValueError(
                "feed_limiter_from_tier_latency needs a method whose "
                "limiter supports set_latency_target (max_concurrency="
                '"auto")'
            )
        rec = tier_latency_recorder(tier)
        limiter.set_latency_target(
            lambda: rec.latency_percentile(ratio), target_us
        )
        return rec

    def retire(self) -> None:
        """Detach from the gauge registry and the server (called when a
        replacement controller takes over): in-flight tickets still
        release against this object, but it must stop contributing to
        the per-tier queue-depth gauges — a retired controller summing
        the SAME server's batchers would double-count every queued
        row."""
        _controllers.discard(self)
        self._server_ref = None

    # ---- introspection -----------------------------------------------------
    def tier_inflight(self, tier: str) -> int:
        return self._tier_inflight.get(tier, 0)

    def queue_depths(self) -> Dict[str, int]:
        """Batch rows queued in this server's batchers, grouped by
        tier — ONE pending_by_tier() pass per batcher (renders that
        need several tiers must not re-walk every queue per tier)."""
        server = self._server_ref() if self._server_ref is not None else None
        if server is None:
            return {}
        out: Dict[str, int] = {}
        for batcher in list(getattr(server, "_batchers", {}).values()):
            by_tier = getattr(batcher, "pending_by_tier", None)
            if by_tier is not None:
                for tier, n in by_tier().items():
                    out[tier] = out.get(tier, 0) + n
        return out

    def queue_depth(self, tier: str) -> int:
        """Batch rows queued in this server's batchers for `tier`."""
        return self.queue_depths().get(tier, 0)

    def describe(self) -> dict:
        policy = self.policy
        # snapshot the policy maps under ITS lock (a racing POST
        # /admission mutates them), and take queue depths OUTSIDE the
        # admission lock: they take the batchers' locks, and nesting
        # those under ours would mint a cross-module lock edge for a
        # render
        tier_specs, tenant_tiers, method_tiers, tenant_quotas = (
            policy.snapshot()
        )
        depths = self.queue_depths()
        with self._lock:
            tiers = {}
            for name, spec in sorted(tier_specs.items()):
                tiers[name] = dict(
                    spec.to_dict(),
                    share=round(policy.share(name), 4),
                    inflight=self._tier_inflight.get(name, 0),
                    queue_depth=depths.get(name, 0),
                )
            tenants = {
                t: {
                    "tier": tenant_tiers.get(t, policy.default_tier),
                    "quota": tenant_quotas.get(t, 0),
                    "inflight": self._tenant_inflight.get(t, 0),
                }
                for t in sorted(
                    set(tenant_tiers)
                    | set(tenant_quotas)
                    | set(self._tenant_inflight)
                )
            }
        shed = {}
        for (method, tier, reason), var in rpc_shed_total.items():
            v = var.get_value()
            if v:
                shed[f"{method}|{tier}|{reason}"] = v
        return {
            "active": policy.active,
            "default_tier": policy.default_tier,
            "tiers": tiers,
            "tenants": tenants,
            "method_tiers": method_tiers,
            "shed_total": shed,
            "codes": {k: v for k, v in sorted(SHED_CODES.items())},
        }
