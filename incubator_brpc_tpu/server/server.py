"""Server — service hosting over the shared transport.

Analog of reference brpc::Server (server.{h,cpp}; StartInternal at
server.cpp:734-1121): validates options, warms the runtime, registers
builtin observability services, builds per-method status/limiters,
listens and starts the Acceptor. One port speaks every registered
protocol (the InputMessenger inversion, SURVEY.md §1).
"""

from __future__ import annotations

import socket as _pysocket
import threading
from dataclasses import dataclass
from typing import Dict, Optional

from incubator_brpc_tpu import errors
from incubator_brpc_tpu.global_init import global_init
from incubator_brpc_tpu.runtime.scheduler import get_task_control
from incubator_brpc_tpu.server.method_status import MethodStatus, make_limiter
from incubator_brpc_tpu.server.service import MethodSpec, Service
from incubator_brpc_tpu.transport.acceptor import Acceptor
from incubator_brpc_tpu.utils.endpoint import EndPoint
from incubator_brpc_tpu.utils.logging import log_error, log_info


@dataclass
class ServerOptions:
    """Mirrors reference ServerOptions (server.h)."""

    num_threads: int = 0  # 0 = runtime default
    max_concurrency: object = 0  # 0 | int | "auto" (server-level)
    method_max_concurrency: object = 0  # default per-method limiter spec
    idle_timeout_sec: int = -1
    auth: object = None
    has_builtin_services: bool = True
    internal_port: int = -1
    server_info_name: str = "tpubrpc"
    rpc_dump_dir: str = ""  # non-empty enables request sampling
    # a protocols.redis.RedisService instance makes this server speak
    # redis on the same port (reference ServerOptions.redis_service)
    redis_service: object = None
    # Run request parse + user handlers inline in the event-dispatcher
    # thread (two fewer scheduler handoffs per request). Only safe when
    # every handler is non-blocking — the latency-tuned threading model
    # (reference docs/cn/benchmark.md; inverse of -usercode_in_pthread).
    usercode_in_dispatcher: bool = False


class _InternalPortView:
    """Server facade for the internal_port acceptor: serves ONLY the
    builtin observability pages, never user pb services (reference
    internal_port acceptor, server.cpp:1042-1080)."""

    def __init__(self, server: "Server"):
        self._server = server

    def __getattr__(self, name):
        return getattr(self._server, name)

    def builtin_allowed(self) -> bool:
        return True

    def find_method(self, service_name: str, method_name: str):
        return None  # pb services stay on the public port


class Server:
    def __init__(self, options: Optional[ServerOptions] = None):
        self.options = options or ServerOptions()
        self._services: Dict[str, Service] = {}
        self._methods: Dict[str, MethodSpec] = {}  # "Svc.Method" -> spec
        self._method_status: Dict[str, MethodStatus] = {}
        self._acceptor: Optional[Acceptor] = None
        self._listen_fd: Optional[_pysocket.socket] = None
        self._listen_ep: Optional[EndPoint] = None
        self._running = False
        self._lock = threading.Lock()
        self._rpc_dump_ctx = None
        self._session_local_factory = None
        self._ici_port = None
        self._builtin_handlers = {}
        self._internal_acceptor: Optional[Acceptor] = None
        self._internal_ep: Optional[EndPoint] = None

    def builtin_allowed(self) -> bool:
        """When internal_port is set, builtin pages are denied on the
        public port (they move behind the firewall-able internal one)."""
        return self.options.internal_port is None or self.options.internal_port < 0

    # ---- registration (AddService, server.cpp:1230,1470) -------------------
    def add_service(self, service: Service) -> int:
        name = service.service_name()
        if name in self._services:
            log_error("service %s already added", name)
            return -1
        specs = service.method_specs()
        if not specs:
            log_error("service %s has no rpc methods", name)
            return -1
        self._services[name] = service
        for mname, spec in specs.items():
            bound = MethodSpec(
                spec.service_name,
                spec.method_name,
                spec.request_class,
                spec.response_class,
                fn=getattr(service, mname),
            )
            self._methods[bound.full_name] = bound
            self._method_status[bound.full_name] = MethodStatus(
                bound.full_name, make_limiter(self.options.method_max_concurrency)
            )
        return 0

    def remove_service(self, service: Service) -> int:
        name = service.service_name()
        if name not in self._services:
            return -1
        del self._services[name]
        for full in [f for f in self._methods if f.startswith(name + ".")]:
            del self._methods[full]
            self._method_status.pop(full, None)
        return 0

    def has_service(self, name: str) -> bool:
        return name in self._services

    def find_method(self, service_name: str, method_name: str) -> Optional[MethodSpec]:
        return self._methods.get(f"{service_name}.{method_name}")

    def method_status(self, full_name: str) -> Optional[MethodStatus]:
        return self._method_status.get(full_name)

    def services(self) -> Dict[str, Service]:
        return dict(self._services)

    def methods(self) -> Dict[str, MethodSpec]:
        return dict(self._methods)

    # ---- lifecycle (Start → StartInternal, server.cpp:734-1121) ------------
    def start(self, addr=8000) -> int:
        global_init()
        if self._running:
            return -1
        if isinstance(addr, int):
            ep = EndPoint.tcp("0.0.0.0", addr)
        elif isinstance(addr, EndPoint):
            ep = addr
        else:
            from incubator_brpc_tpu.utils.endpoint import str2endpoint

            ep = str2endpoint(str(addr))
        # warm the runtime (bthread_setconcurrency, server.cpp:953-961)
        if self.options.num_threads:
            get_task_control()
        if self.options.has_builtin_services:
            self._add_builtin_services()
        if self.options.rpc_dump_dir:
            from incubator_brpc_tpu.observability.rpc_dump import RpcDumpContext

            self._rpc_dump_ctx = RpcDumpContext(self.options.rpc_dump_dir)
        for status in self._method_status.values():
            status.expose()
        try:
            if ep.scheme == "uds":
                fd = _pysocket.socket(_pysocket.AF_UNIX, _pysocket.SOCK_STREAM)
                fd.bind(ep.host)
            else:
                fd = _pysocket.socket(_pysocket.AF_INET, _pysocket.SOCK_STREAM)
                fd.setsockopt(_pysocket.SOL_SOCKET, _pysocket.SO_REUSEADDR, 1)
                fd.bind((ep.host, ep.port))
            fd.listen(1024)
            fd.setblocking(False)
        except OSError as e:
            log_error("listen on %s failed: %r", ep, e)
            return -1
        if ep.scheme == "tcp" and ep.port == 0:
            ep = EndPoint.tcp(ep.host, fd.getsockname()[1])
        self._listen_fd = fd
        self._listen_ep = ep
        self._running = True
        self._acceptor = Acceptor(self)
        self._acceptor.start_accept(fd)
        if self.options.internal_port is not None and self.options.internal_port >= 0:
            # UDS main listener: the internal port is TCP, serve loopback
            host = ep.host if ep.scheme == "tcp" else "127.0.0.1"
            rc = self._start_internal_port(host)
            if rc != 0:
                self.stop()
                return rc
        log_info("Server started on %s", ep)
        return 0

    def _start_internal_port(self, host: str) -> int:
        """Second acceptor for builtin services only (server.cpp:1042)."""
        try:
            fd = _pysocket.socket(_pysocket.AF_INET, _pysocket.SOCK_STREAM)
            fd.setsockopt(_pysocket.SOL_SOCKET, _pysocket.SO_REUSEADDR, 1)
            fd.bind((host, self.options.internal_port))
            fd.listen(128)
            fd.setblocking(False)
        except OSError as e:
            log_error("listen on internal_port %s failed: %r",
                      self.options.internal_port, e)
            return -1
        self._internal_ep = EndPoint.tcp(host, fd.getsockname()[1])
        self._internal_acceptor = Acceptor(_InternalPortView(self))
        self._internal_acceptor.start_accept(fd)
        log_info("builtin services on internal port %s", self._internal_ep)
        return 0

    def _add_builtin_services(self):
        try:
            from incubator_brpc_tpu.builtin import register_builtin_services

            register_builtin_services(self)
        except ImportError:
            pass

    def add_builtin_handler(self, path: str, fn):
        self._builtin_handlers[path.rstrip("/") or "/"] = fn

    def find_builtin_handler(self, path: str):
        h = self._builtin_handlers.get(path)
        if h is not None:
            return h
        # prefix match for parameterized pages (/pprof/...)
        for p, fn in self._builtin_handlers.items():
            if p != "/" and path.startswith(p + "/"):
                return fn
        return None

    def start_ici(self, slice_id: int = 0, chip_id: int = 0, device=None) -> int:
        """Expose this server on the ICI fabric at ici://slice/chip —
        the TPU-transport analog of listening on a port (reference:
        ServerOptions.use_rdma + rdma init, server.cpp:772-782).
        Can serve ICI alongside (or instead of) TCP."""
        global_init()
        from incubator_brpc_tpu.parallel.ici import get_fabric

        if device is None:
            try:
                import jax

                device = jax.devices()[chip_id % len(jax.devices())]
            except Exception:
                device = None
        try:
            self._ici_port = get_fabric().register(
                (slice_id, chip_id), server=self, device=device
            )
        except ValueError as e:
            log_error("start_ici failed: %r", e)
            return -1
        self._running = True
        if self._listen_ep is None:
            self._listen_ep = EndPoint.ici(slice_id, chip_id)
        for status in self._method_status.values():
            status.expose()
        log_info("Server exposed on ici://slice%d/chip%d", slice_id, chip_id)
        return 0

    def stop(self) -> int:
        with self._lock:
            if not self._running:
                return 0
            self._running = False
        if self._ici_port is not None:
            from incubator_brpc_tpu.parallel.ici import get_fabric

            get_fabric().unregister(self._ici_port.coords)
            self._ici_port = None
        if self._acceptor is not None:
            self._acceptor.stop_accept()
            self._acceptor = None
        if self._internal_acceptor is not None:
            self._internal_acceptor.stop_accept()
            self._internal_acceptor = None
        self._listen_fd = None
        return 0

    def join(self) -> int:
        return 0

    def is_running(self) -> bool:
        return self._running

    @property
    def listen_endpoint(self) -> Optional[EndPoint]:
        return self._listen_ep

    @property
    def port(self) -> int:
        return self._listen_ep.port if self._listen_ep else 0

    @property
    def internal_port(self) -> int:
        return self._internal_ep.port if self._internal_ep else -1

    def connection_count(self) -> int:
        return self._acceptor.connection_count() if self._acceptor else 0
