"""Server — service hosting over the shared transport.

Analog of reference brpc::Server (server.{h,cpp}; StartInternal at
server.cpp:734-1121): validates options, warms the runtime, registers
builtin observability services, builds per-method status/limiters,
listens and starts the Acceptor. One port speaks every registered
protocol (the InputMessenger inversion, SURVEY.md §1).
"""

from __future__ import annotations

import socket as _pysocket
import threading
import time as _time
from dataclasses import dataclass
from typing import Dict, Optional

from incubator_brpc_tpu import errors
from incubator_brpc_tpu.global_init import global_init
from incubator_brpc_tpu.runtime.scheduler import get_task_control
from incubator_brpc_tpu.server.method_status import MethodStatus, make_limiter
from incubator_brpc_tpu.server.service import MethodSpec, Service
from incubator_brpc_tpu.transport.acceptor import Acceptor
from incubator_brpc_tpu.utils.endpoint import EndPoint
from incubator_brpc_tpu.utils.logging import log_error, log_info, log_warning


@dataclass
class ServerOptions:
    """Mirrors reference ServerOptions (server.h)."""

    num_threads: int = 0  # 0 = runtime default
    max_concurrency: object = 0  # 0 | int | "auto" (server-level)
    method_max_concurrency: object = 0  # default per-method limiter spec
    idle_timeout_sec: int = -1
    auth: object = None
    has_builtin_services: bool = True
    internal_port: int = -1
    server_info_name: str = "tpubrpc"
    rpc_dump_dir: str = ""  # non-empty enables request sampling
    # a protocols.redis.RedisService instance makes this server speak
    # redis on the same port (reference ServerOptions.redis_service)
    redis_service: object = None
    # a protocols.memcache.MemcacheService makes this server answer the
    # memcached binary protocol on the same port (TPU extension — the
    # reference client is client-only)
    memcache_service: object = None
    # a protocols.thrift.ThriftService makes this server speak framed
    # thrift on the same port (reference ServerOptions.thrift_service)
    thrift_service: object = None
    # a protocols.mongo.MongoServiceAdaptor makes this server answer
    # mongo wire protocol (reference ServerOptions.mongo_service_adaptor)
    mongo_service_adaptor: object = None
    # a protocols.legacy.NsheadService answers raw nshead requests
    # (reference ServerOptions.nshead_service)
    nshead_service: object = None
    # a Service whose methods answer nova_pbrpc (nshead + pb body,
    # method index in head.reserved; reference nova server adaptor)
    nova_service: object = None
    # a protocols.rtmp.RtmpService gates/observes RTMP streams; media
    # relay publisher→players is built in (reference RtmpService)
    rtmp_service: object = None
    # Per-RPC reusable user data, pooled across requests (reference
    # ServerOptions.session_local_data_factory, server.cpp:811-851):
    # handlers call controller.session_local_data(); the object returns
    # to the pool when the response is sent.
    session_local_data_factory: object = None
    # Per worker thread user data (thread_local_data_factory):
    # controller.thread_local_data() creates once per thread.
    thread_local_data_factory: object = None
    # Run request parse + user handlers inline in the event-dispatcher
    # thread (two fewer scheduler handoffs per request). Only safe when
    # every handler is non-blocking — the latency-tuned threading model
    # (reference docs/cn/benchmark.md; inverse of -usercode_in_pthread).
    usercode_in_dispatcher: bool = False
    # Serve tpu_std over the C++ engine (native/engine.cpp): epoll +
    # framing + native-fastpath methods entirely off the GIL; other
    # methods fall back to the Python stack via the dispatch callback.
    # The reference is C++ end to end — this restores that property for
    # the hot loops (input_messenger.cpp:317-382, socket.cpp:1584-1790).
    # Requires auth=None (first-message verify stays on the Python
    # transport) and speaks only tpu_std framing on the port.
    native_engine: bool = False
    # TLS: a transport/ssl_helper.ServerSSLOptions serves every accepted
    # connection over SSL (reference ServerOptions.mutable_ssl_options;
    # handshake per-connection in transport/acceptor.py). Incompatible
    # with native_engine (the C++ engine is plaintext) — ssl wins and
    # the server falls back to the Python transport.
    ssl_options: object = None
    # SIGTERM/SIGINT → stop(closewait_ms=graceful_quit_closewait_ms)
    # (reference -graceful_quit_on_sigterm, server.cpp signal hook).
    # Best-effort: signal handlers install only from the main thread.
    graceful_quit_on_sigterm: bool = False
    graceful_quit_closewait_ms: int = 5000
    # Adaptive micro-batching (docs/batching.md): True builds a Batcher
    # for every @batched_method whose policy is enabled, so concurrent
    # same-method requests coalesce into one fused handler execution.
    # False (default): every method takes the existing dispatch path —
    # the disabled-path cost is one empty-dict check per request.
    enable_batching: bool = False
    # Per-method policy overrides, full_name -> BatchPolicy | dict |
    # None (None/0 force-disables that method while enable_batching
    # covers the rest).
    batch_policies: object = None
    # Multi-tenant admission control (docs/overload.md): an
    # AdmissionPolicy (or its dict form) with priority tiers, tenant →
    # tier mappings and quotas.  None = the default inactive policy:
    # requests still route through server.admission (one decision
    # point for every shed path, with the unified code mapping) but
    # pay only the concurrency-gate check.
    admission_policy: object = None


# ---- server response ring (docs/fastpath.md "server ring") ----
# Per-thread staging of native-connection response frames: while a
# harvested window is being answered (a native read-burst loop, or a
# micro-batcher scatter fan-out), _NativeConnSocket.write stages frames
# here instead of crossing into C per call, and resp_ring_flush ships
# each connection's frames as ONE ns_send_burst (one writev burst per
# harvested window — the server half of nc_mux_submit_many).  tpu_std
# frames carry correlation ids, so batching replies is order-safe; the
# HTTP/RESP paths never reach this collector.
_resp_ring_tls = threading.local()


def resp_ring_begin():
    """Open a response-ring staging scope on this thread.  Returns a
    truthy token when THIS call opened the scope (the caller must pass
    it to resp_ring_flush), falsy when an enclosing scope is already
    staging (the outer scope flushes — nesting is safe)."""
    if getattr(_resp_ring_tls, "frames", None) is not None:
        return False
    _resp_ring_tls.frames = []
    return True


def resp_ring_flush(token) -> None:
    """Close a staging scope: group the staged frames by connection and
    flush each group through ONE engine send_burst.  Staged writes
    already returned 0 to their callers (buffered-write semantics, same
    contract as the engine's internal outq); a failed burst marks every
    staged socket failed so subsequent writes surface the error."""
    if not token:
        return
    frames = _resp_ring_tls.frames
    _resp_ring_tls.frames = None
    if not frames:
        return
    # the ring.submit chaos site covers BOTH ring halves: here it hits
    # the server response ring's flush (drop = the whole window's
    # replies never reach the engine — clients recover via their
    # timeout/retry budget; delay_us = a slow flush).  Short/partial
    # writev mid-burst is the native srv_write fault inside
    # conn_write_parts, which ns_send_burst inherits.
    from incubator_brpc_tpu.chaos import injector as _chaos

    if _chaos.armed:
        spec = _chaos.check("ring.submit", direction="flush")
        if spec is not None:
            if spec.action == "delay_us":
                _chaos.sleep_us(spec.arg)
            elif spec.action == "drop":
                for sock, _ in frames:
                    sock.failed = True
                return
    groups: Dict[tuple, list] = {}
    order = []
    for sock, data in frames:
        key = (id(sock.server), sock._conn_id)
        group = groups.get(key)
        if group is None:
            group = (sock.server, sock._conn_id, [], [])
            groups[key] = group
            order.append(group)
        group[2].append(data)
        group[3].append(sock)
    for server, conn_id, datas, socks in order:
        rc = server._engine_op(
            lambda eng, c=conn_id, d=datas: eng.send_burst(c, d)
        )
        if rc is None or rc != 0:
            for sock in socks:
                sock.failed = True
    try:
        from incubator_brpc_tpu.metrics import ring_metrics

        ring_metrics.rpc_ring_flush_bursts << len(order)
    except Exception:  # noqa: BLE001 — metrics never fail a flush
        pass


class _NativeConnSocket:
    """Socket facade over one native-engine connection: gives the
    Python fallback path (tpu_std.process_request/send_response) the
    surface it needs while IO stays in the C++ engine."""

    is_server_side = True

    def __init__(self, server: "Server", conn_id: int):
        self.server = server
        self._conn_id = conn_id
        self.remote = None
        self.failed = False

    def write(self, buf, ignore_eovercrowded=False, span=None) -> int:
        data = buf.to_bytes()
        frames = getattr(_resp_ring_tls, "frames", None)
        if frames is not None:
            # response ring open on this thread: stage instead of
            # crossing into C — resp_ring_flush ships the window as one
            # writev burst.  0 here means "handed to the ring", the
            # same buffered contract as the engine's outq below.
            frames.append((self, data))
            if span is not None:
                span.write_done(0)
            return 0
        rc = self.server._engine_op(
            lambda eng: eng.send(self._conn_id, data)
        )
        if rc is None or rc != 0:
            self.failed = True
            if span is not None:
                span.write_done(errors.EFAILEDSOCKET)
            return errors.EFAILEDSOCKET
        if span is not None:
            span.write_done(0)  # handed to the engine's writer
        return 0

    def set_failed(self, code=0, reason=""):
        self.failed = True
        self.server._engine_op(lambda eng: eng.close_conn(self._conn_id))


class _InternalPortView:
    """Server facade for the internal_port acceptor: serves ONLY the
    builtin observability pages, never user pb services (reference
    internal_port acceptor, server.cpp:1042-1080)."""

    def __init__(self, server: "Server"):
        self._server = server

    def __getattr__(self, name):
        return getattr(self._server, name)

    def builtin_allowed(self) -> bool:
        return True

    def find_method(self, service_name: str, method_name: str):
        return None  # pb services stay on the public port


class Server:
    def __init__(self, options: Optional[ServerOptions] = None):
        self.options = options or ServerOptions()
        self._services: Dict[str, Service] = {}
        self._methods: Dict[str, MethodSpec] = {}  # "Svc.Method" -> spec
        self._method_status: Dict[str, MethodStatus] = {}
        self._acceptor: Optional[Acceptor] = None
        self._listen_fd: Optional[_pysocket.socket] = None
        self._listen_ep: Optional[EndPoint] = None
        self._running = False
        self._lock = threading.Lock()
        self._rpc_dump_ctx = None
        self._session_local_pool = []  # reusable session-local objects
        self._session_local_lock = threading.Lock()
        self._thread_local_store = threading.local()
        self._ici_port = None
        self._batchers: Dict[str, object] = {}  # full_name -> Batcher
        # per-thread burst collector: while a multi-frame native read
        # burst (a client submission-ring window) is being processed,
        # batched-method rows defer here and land in each Batcher as
        # ONE submit_many accumulation (see _process_native_frame)
        self._burst_tls = threading.local()
        self._builtin_handlers = {}
        self._internal_acceptor: Optional[Acceptor] = None
        self._internal_ep: Optional[EndPoint] = None
        self._native_engine = None
        self._native_fast_methods = []
        from incubator_brpc_tpu.server.admission import AdmissionController

        # every dispatch path sheds through this one decision point
        self.admission = AdmissionController(
            self, self.options.admission_policy
        )
        self._harvest_lock = threading.Lock()
        # engine-lifetime readers/writer state: _engine_op holds a ref
        # while calling into C; stop() drains refs before destroy()
        self._engine_cv = threading.Condition(self._harvest_lock)
        self._engine_refs = 0
        self._ssl_server_ctx = None

    def builtin_allowed(self) -> bool:
        """When internal_port is set, builtin pages are denied on the
        public port (they move behind the firewall-able internal one)."""
        return self.options.internal_port is None or self.options.internal_port < 0

    # ---- registration (AddService, server.cpp:1230,1470) -------------------
    def add_service(self, service: Service) -> int:
        name = service.service_name()
        if name in self._services:
            log_error("service %s already added", name)
            return -1
        specs = service.method_specs()
        if not specs:
            log_error("service %s has no rpc methods", name)
            return -1
        self._services[name] = service
        for mname, spec in specs.items():
            bound = MethodSpec(
                spec.service_name,
                spec.method_name,
                spec.request_class,
                spec.response_class,
                fn=getattr(service, mname),
                batch_fn=(
                    spec.batch_fn.__get__(service)
                    if spec.batch_fn is not None
                    else None
                ),
                batch_policy=spec.batch_policy,
            )
            self._methods[bound.full_name] = bound
            self._method_status[bound.full_name] = MethodStatus(
                bound.full_name, make_limiter(self.options.method_max_concurrency)
            )
        return 0

    def remove_service(self, service: Service) -> int:
        name = service.service_name()
        if name not in self._services:
            return -1
        del self._services[name]
        for full in [f for f in self._methods if f.startswith(name + ".")]:
            del self._methods[full]
            self._method_status.pop(full, None)
        return 0

    def has_service(self, name: str) -> bool:
        return name in self._services

    def find_method(self, service_name: str, method_name: str) -> Optional[MethodSpec]:
        return self._methods.get(f"{service_name}.{method_name}")

    def method_status(self, full_name: str) -> Optional[MethodStatus]:
        return self._method_status.get(full_name)

    def run_user_method(self, method, ctrl, request, response, done):
        """Invoke the user callback with rpcz callback-entry stamping
        (callback-exit is stamped by the protocol's done wrapper just
        before the response is built). Returns the exception the method
        raised, or None — the caller decides how to answer it, so
        protocol-specific failure shapes stay in the protocols."""
        span = getattr(ctrl, "_span", None)
        if span is not None:
            span.callback_start_us = _time.time_ns() // 1000
        try:
            method.fn(ctrl, request, response, done)  # ← USER CODE
            return None
        except Exception as e:  # noqa: BLE001
            log_error("service method %s raised: %r", method.full_name, e)
            return e

    # ---- micro-batching (batching/, docs/batching.md) ----------------------
    def _init_batchers(self):
        """Build Batchers for every @batched_method with an enabled
        policy (ServerOptions.batch_policies overrides the decorator's
        default; None/0 there force-disables one method)."""
        if not self.options.enable_batching:
            return
        overrides = self.options.batch_policies or {}
        batchable = {n for n, s in self._methods.items()
                     if s.batch_fn is not None}
        for unknown in sorted(set(overrides) - batchable):
            # a typo'd key would otherwise silently leave the intended
            # method on its decorator default
            log_warning(
                "batch_policies[%r] matches no registered "
                "@batched_method (batchable: %s)",
                unknown, sorted(batchable),
            )
        for full_name, spec in self._methods.items():
            if spec.batch_fn is None:
                continue
            if full_name in self._batchers:
                # already live (start_ici alongside start, or a restart):
                # rebuilding would stop+drain a serving batcher and zero
                # its counters for nothing
                continue
            policy = overrides.get(full_name, spec.batch_policy)
            if policy in (None, 0):
                continue  # explicit per-method off
            self.enable_method_batching(full_name, policy)

    def enable_method_batching(self, full_name: str, policy=None):
        """(Re)build the Batcher for one @batched_method; returns it,
        or None when the method is unknown/unbatchable or the policy is
        off (max_batch_size <= 1).  Runtime-callable: the /batching
        builtin tunes live policies through here."""
        from incubator_brpc_tpu.batching.batcher import Batcher
        from incubator_brpc_tpu.batching.policy import BatchPolicy

        spec = self._methods.get(full_name)
        if spec is None or spec.batch_fn is None:
            return None
        # validate the replacement policy FIRST: a bad one must fail
        # cleanly, not tear down the live batcher on its way to raising
        # (which would leave the method silently unbatched).  The
        # Batcher itself is built only after the old one stops — its
        # exposed metric variables share the per-method names the old
        # stop() hides.
        if policy is not None and not isinstance(policy, (BatchPolicy, dict)):
            # an explicit falsy value (0, False) = force-off, same
            # convention as ServerOptions.batch_policies; only None
            # means "use the decorator default".  Truthy garbage (a
            # bare int batch size, a string) must raise, not silently
            # tear the live batcher down as "off".
            if policy:
                raise TypeError(
                    f"policy must be a BatchPolicy, a policy dict, None "
                    f"(decorator default) or falsy (force-off); got "
                    f"{policy!r}"
                )
            policy = False
        else:
            if isinstance(policy, dict):
                policy = BatchPolicy.from_dict(policy)
            policy = policy or spec.batch_policy or BatchPolicy()
            # private copy: the Batcher's policy is runtime-tunable
            # (POST /batching) and must never write through to a
            # decorator-level object shared across methods and future
            # servers
            policy = BatchPolicy.from_dict(policy.to_dict())
        old = self._batchers.pop(full_name, None)
        if old is not None:
            old.stop()
        if policy is False or not policy.enabled:
            return None  # the off config: existing dispatch path
        batcher = Batcher(
            full_name,
            spec.batch_fn,
            policy,
            inline=self.options.usercode_in_dispatcher,
        )
        self._batchers[full_name] = batcher
        return batcher

    # ---- admission control (server/admission.py, docs/overload.md) ---------
    def set_admission_policy(self, policy) -> None:
        """Swap the admission policy live (the /admission builtin and
        the overhead bench toggle through here).  None = the inactive
        default.  In-flight tickets release against the controller
        that issued them, so a mid-flight swap never corrupts the
        inflight gauges."""
        from incubator_brpc_tpu.server.admission import AdmissionController

        old, self.admission = self.admission, AdmissionController(self, policy)
        # stop the replaced controller's queue-depth contribution: both
        # resolve the same batchers, and two live controllers would
        # double-count every queued row on /metrics
        old.retire()

    def disable_method_batching(self, full_name: str) -> None:
        old = self._batchers.pop(full_name, None)
        if old is not None:
            old.stop()

    def batcher(self, full_name: str):
        return self._batchers.get(full_name)

    def submit_batched(self, method, ctrl, request, response, done) -> bool:
        """Hand one parsed request to the method's Batcher.  False =
        not batched (no batcher, or it stopped) — the caller runs the
        existing dispatch path.  Inside a native read-burst window the
        row defers to the per-thread collector instead, so the whole
        window reaches the Batcher as one submit_many accumulation."""
        batcher = self._batchers.get(method.full_name)
        if batcher is None:
            return False
        rows = getattr(self._burst_tls, "rows", None)
        if rows is not None:
            rows.append((batcher, method, ctrl, request, response, done))
            return True
        return batcher.submit(ctrl, request, response, done)

    def _burst_begin(self) -> None:
        self._burst_tls.rows = []

    def _burst_end(self) -> None:
        """Flush the burst collector: group deferred rows by Batcher and
        hand each group over in ONE submit_many (one lock, one flush
        decision).  A batcher that stopped mid-burst degrades to the
        direct dispatch path per row — the same fallback submit's False
        return would have triggered inline."""
        rows = self._burst_tls.rows
        self._burst_tls.rows = None
        if not rows:
            return
        groups = {}
        for batcher, method, ctrl, request, response, done in rows:
            groups.setdefault(id(batcher), (batcher, []))[1].append(
                (method, ctrl, request, response, done)
            )
        for batcher, group in groups.values():
            if batcher.submit_many(
                [(c, req, res, d) for _, c, req, res, d in group]
            ):
                continue
            from incubator_brpc_tpu.observability.span import (
                swap_current_span,
            )

            for method, ctrl, request, response, done in group:
                prev = (
                    swap_current_span(ctrl._span)
                    if ctrl._span is not None
                    else None
                )
                try:
                    exc = self.run_user_method(
                        method, ctrl, request, response, done
                    )
                    if exc is not None:
                        ctrl.set_failed(
                            errors.EINTERNAL, f"method raised: {exc}"
                        )
                        done()
                finally:
                    if ctrl._span is not None:
                        swap_current_span(prev)

    def _engine_op(self, fn):
        """Run fn(engine), or return None if the engine is gone.

        Reader/writer discipline instead of a global mutex on the send
        hot path (the engine is internally thread-safe): ops take a
        refcount under the lifetime lock and run CONCURRENTLY outside
        it; stop() swaps the field to None under the lock and waits for
        the refcount to drain before destroy().  An op that entered
        before the swap finishes on a live engine; one after sees None.
        (ADVICE r4 use-after-free, without serializing responses.)"""
        cv = self._engine_cv
        with cv:
            eng = self._native_engine
            if eng is None:
                return None
            self._engine_refs += 1
        try:
            return fn(eng)
        finally:
            with cv:
                self._engine_refs -= 1
                if self._engine_refs == 0:
                    cv.notify_all()

    def harvest_native_stats(self) -> None:
        """Fold native fast-path completions into MethodStatus.

        The C++ engine answers fast-path frames without touching Python,
        so their counts/latencies accumulate in per-method atomics
        (engine.cpp NativeMethod).  This pulls the deltas into the same
        MethodStatus the Python transport feeds — /status, /vars and the
        auto limiter then see ALL traffic.  Called lazily by the /status
        builtin and at stop(); cheap enough for every render (a couple
        of atomic loads per method)."""
        # single-flight: concurrent /status renders would diff the same
        # snapshot and double-count deltas.  The engine read must ALSO
        # happen under the lock: stop() swaps the field to None and
        # destroys the engine under this same lock, so a render racing
        # stop() either sees None or finishes before the free.
        with self._harvest_lock:
            eng = self._native_engine
            if eng is None:
                return
            for entry in self._native_fast_methods:
                name, mname, last = entry
                cur = eng.method_stats(name, mname)
                if cur is None:
                    continue
                dn = cur["count"] - last["count"]
                status = self._method_status.get(f"{name}.{mname}")
                if status is not None and dn > 0:
                    avg_us = (
                        cur["latency_ns_sum"] - last["latency_ns_sum"]
                    ) / (dn * 1000.0)
                    status.latency_rec.update_bulk(avg_us, dn)
                    if status.limiter is not None:
                        status.limiter.on_response_bulk(int(avg_us), dn)
                derr = (cur["errors"] - last["errors"]) + (
                    cur["rejected"] - last["rejected"]
                )
                if status is not None and derr > 0:
                    status.errors << derr
                if status is not None and status.limiter is not None:
                    # re-push the (possibly moving) limit into the C++ gate
                    eng.set_method_max_concurrency(
                        name, mname, status.limiter.max_concurrency()
                    )
                entry[2] = cur

    def services(self) -> Dict[str, Service]:
        return dict(self._services)

    def methods(self) -> Dict[str, MethodSpec]:
        return dict(self._methods)

    # ---- lifecycle (Start → StartInternal, server.cpp:734-1121) ------------
    def start(self, addr=8000) -> int:
        global_init()
        if self._running:
            return -1
        if isinstance(addr, int):
            ep = EndPoint.tcp("0.0.0.0", addr)
        elif isinstance(addr, EndPoint):
            ep = addr
        else:
            from incubator_brpc_tpu.utils.endpoint import str2endpoint

            ep = str2endpoint(str(addr))
        # warm the runtime (bthread_setconcurrency, server.cpp:953-961)
        if self.options.num_threads:
            get_task_control()
        if self.options.has_builtin_services:
            self._add_builtin_services()
        if self.options.rpc_dump_dir:
            from incubator_brpc_tpu.observability.rpc_dump import RpcDumpContext

            self._rpc_dump_ctx = RpcDumpContext(self.options.rpc_dump_dir)
        for status in self._method_status.values():
            status.expose()
        self._init_batchers()
        self._ssl_server_ctx = None
        if self.options.ssl_options is not None:
            from incubator_brpc_tpu.transport.ssl_helper import (
                make_server_context,
            )

            try:
                self._ssl_server_ctx = make_server_context(
                    self.options.ssl_options
                )
            except (OSError, ValueError) as e:
                log_error("server SSL context failed: %r", e)
                return -1
        if self.options.native_engine and self._ssl_server_ctx is None:
            rc = self._start_native(ep)
            if rc <= 0:
                return rc
            # rc > 0: engine unavailable → plain Python transport
        elif self.options.native_engine:
            log_error("native_engine is plaintext-only; ssl_options set → "
                      "serving on the Python transport")
        try:
            if ep.scheme == "uds":
                fd = _pysocket.socket(_pysocket.AF_UNIX, _pysocket.SOCK_STREAM)
                fd.bind(ep.host)
            else:
                fd = _pysocket.socket(_pysocket.AF_INET, _pysocket.SOCK_STREAM)
                fd.setsockopt(_pysocket.SOL_SOCKET, _pysocket.SO_REUSEADDR, 1)
                fd.bind((ep.host, ep.port))
            fd.listen(1024)
            fd.setblocking(False)
        except OSError as e:
            log_error("listen on %s failed: %r", ep, e)
            return -1
        if ep.scheme == "tcp" and ep.port == 0:
            ep = EndPoint.tcp(ep.host, fd.getsockname()[1])
        self._listen_fd = fd
        self._listen_ep = ep
        self._running = True
        self._acceptor = Acceptor(self)
        self._acceptor.start_accept(fd)
        if self.options.internal_port is not None and self.options.internal_port >= 0:
            # UDS main listener: the internal port is TCP, serve loopback
            host = ep.host if ep.scheme == "tcp" else "127.0.0.1"
            rc = self._start_internal_port(host)
            if rc != 0:
                self.stop()
                return rc
        log_info("Server started on %s", ep)
        # trackme census pings (opt-in via -trackme_server flag;
        # reference triggers on first RPC, trackme.cpp:36-39)
        try:
            from incubator_brpc_tpu.observability.trackme import start_trackme

            start_trackme()
        except ImportError:
            pass
        # SIGUSR1 → stack dump to stderr (tools/task_stacks CLI target;
        # best-effort: only works from the main thread)
        try:
            from incubator_brpc_tpu.tools.task_stacks import (
                install_sigusr1_handler,
            )

            install_sigusr1_handler()
        except ImportError:
            pass
        self._maybe_install_graceful_quit()
        return 0

    def _maybe_install_graceful_quit(self):
        """SIGTERM/SIGINT → graceful stop (reference
        -graceful_quit_on_sigterm).  Chains any previous handler so the
        process's own shutdown logic still runs after the drain."""
        if not self.options.graceful_quit_on_sigterm:
            return
        import signal

        prev_handlers = {}

        def handler(signum, frame):
            self.stop(closewait_ms=self.options.graceful_quit_closewait_ms)
            prev = prev_handlers.get(signum)
            if callable(prev):
                prev(signum, frame)

        try:
            for sig in (signal.SIGTERM, signal.SIGINT):
                prev = signal.signal(sig, handler)
                prev_handlers[sig] = (
                    prev if prev not in (signal.SIG_DFL, signal.SIG_IGN) else None
                )
        except ValueError:
            # not the main thread: the reference's hook has the same
            # constraint; callers stop() explicitly instead
            pass

    def _start_native(self, ep: EndPoint) -> int:
        """Bring the C++ engine up on `ep`. Returns 0 = serving natively,
        <0 = hard error, >0 = engine unusable here (caller falls back)."""
        if ep.scheme not in ("tcp", "uds"):
            log_error("native_engine serves TCP/UDS only; falling back")
            return 1
        if self.options.auth is not None:
            log_error("native_engine does not do first-message auth; "
                      "falling back to the Python transport")
            return 1
        from incubator_brpc_tpu import native

        if not native.available():
            log_error("native engine unavailable (%s); falling back",
                      native.unavailable_reason())
            return 1
        import os as _os

        # default scales with the machine: extra epoll workers on a
        # single shared core only add context switches
        nworkers = self.options.num_threads or min(4, _os.cpu_count() or 4)
        eng = native.NativeServerEngine(nworkers=nworkers)
        eng.set_dispatch(self._native_fallback_frame)
        # one port speaks every protocol (the InputMessenger inversion):
        # the engine sniffs http/redis per connection, answers native
        # fast paths in C, and hands everything else to the Python
        # stack above (builtin pages, restful routing, RedisService)
        eng.enable_protocols(
            http=True, redis=self.options.redis_service is not None
        )
        if self.options.redis_service is not None and getattr(
            self.options.redis_service, "native_kv", False
        ):
            eng.redis_enable_native_kv()
        self._native_fast_methods = []  # (service, method, harvested snapshot)
        for name, svc in self._services.items():
            for path in getattr(svc, "native_http_fastpaths", list)():
                # raw-body echo endpoints answered entirely in C (the
                # reference http_server example's trivial handler shape)
                eng.register_native_http_echo(path)
            for mname, fast in getattr(svc, "native_fastpaths", dict)().items():
                kind, attach = fast
                if kind == "echo":
                    eng.register_native_echo(name, mname, attach)
                elif kind == "method":
                    eng.register_native_method(name, mname, attach)
                else:
                    continue
                self._native_fast_methods.append(
                    [name, mname, {"count": 0, "latency_ns_sum": 0,
                                   "rejected": 0, "errors": 0}]
                )
                # mirror the method's concurrency limit into the C++
                # gate (fast-path rejections return EOVERCROWDED like
                # the Python admission path; the auto limiter's moving
                # limit is re-pushed on every stats harvest)
                status = self._method_status.get(f"{name}.{mname}")
                if status is not None and status.limiter is not None:
                    eng.set_method_max_concurrency(
                        name, mname, status.limiter.max_concurrency()
                    )
        try:
            port = eng.listen(0 if ep.scheme == "uds" else ep.port, ep.host)
        except OSError as e:
            log_error("native listen on %s failed: %r", ep, e)
            eng.destroy()
            return -1
        self._native_engine = eng
        self._listen_ep = ep if ep.scheme == "uds" else EndPoint.tcp(ep.host, port)
        self._running = True
        if self.options.internal_port is not None and self.options.internal_port >= 0:
            # the internal port is always TCP; a UDS main listener
            # serves builtins on loopback (matches the non-native path)
            rc = self._start_internal_port(
                ep.host if ep.scheme == "tcp" else "127.0.0.1"
            )
            if rc != 0:
                self.stop()
                return rc
        log_info("Server started on %s (native engine, %d workers)",
                 self._listen_ep, nworkers)
        self._maybe_install_graceful_quit()
        return 0

    def _native_fallback_frame(self, conn_id: int, proto: int, frame: bytes):
        """Frames the C++ fast path didn't answer: full Python-stack
        semantics. Runs on an engine worker thread — hand off to the
        scheduler so slow handlers never stall the event loop.  proto
        says which wire protocol the engine sniffed on the connection
        (tpu_std / http / redis).

        With usercode_in_dispatcher the handler runs INLINE on the
        engine worker, inside the dispatch callback (same trade as the
        Python transport's flag: no handoff latency, but a slow handler
        stalls that worker's event loop).  Inline mode also makes the
        fallback reply synchronous with the engine's cut — the reply
        leaves before the dispatch returns — which is what the
        reply-ordering tests rely on to be deterministic."""
        from incubator_brpc_tpu import native
        from incubator_brpc_tpu.runtime import scheduler

        if proto == native.PROTO_HTTP:
            fn = self._process_native_http
        elif proto == native.PROTO_REDIS:
            fn = self._process_native_redis
        else:
            fn = self._process_native_frame
        if self.options.usercode_in_dispatcher:
            try:
                fn(conn_id, frame)
            except Exception as e:  # noqa: BLE001 — never unwind into C
                log_error("inline native fallback raised: %r", e)
            return
        scheduler.spawn(fn, conn_id, frame)

    def _process_native_http(self, conn_id: int, frame: bytes):
        """One complete HTTP request the engine's framer cut but no
        native handler answered: run it through the full Python http
        stack (restful routing, builtins, pb services) and write the
        response back through the engine."""
        from incubator_brpc_tpu.protocols import ParseError
        from incubator_brpc_tpu.protocols import http as http_mod
        from incubator_brpc_tpu.utils.iobuf import IOBuf

        if self._native_engine is None:
            return
        sock = _NativeConnSocket(self, conn_id)
        buf = IOBuf(frame)
        try:
            res = http_mod.parse(buf, sock, False)
        except Exception:  # noqa: BLE001
            res = None
        if res is None or res.error != ParseError.OK or res.message is None:
            self._engine_op(lambda eng: eng.close_conn(conn_id))
            self._engine_op(lambda eng: eng.py_done(conn_id))
            return
        try:
            http_mod.process_request(res.message, sock)
        except Exception as e:  # noqa: BLE001
            log_error("native http fallback handler raised: %r", e)
        finally:
            # resume the paused connection (replies stay in order: the
            # engine cut nothing since dispatching this frame)
            self._engine_op(lambda eng: eng.py_done(conn_id))

    def _process_native_redis(self, conn_id: int, frame: bytes):
        """One complete RESP command the engine's native KV didn't
        recognize: hand it to the Python RedisService."""
        from incubator_brpc_tpu.protocols import ParseError
        from incubator_brpc_tpu.protocols import redis as redis_mod
        from incubator_brpc_tpu.utils.iobuf import IOBuf

        if self._native_engine is None:
            return
        sock = _NativeConnSocket(self, conn_id)
        buf = IOBuf(frame)
        try:
            res = redis_mod.parse(buf, sock, False)
        except Exception:  # noqa: BLE001
            res = None
        if res is None or res.error != ParseError.OK or res.message is None:
            self._engine_op(lambda eng: eng.close_conn(conn_id))
            self._engine_op(lambda eng: eng.py_done(conn_id))
            return
        try:
            redis_mod.process_request(res.message, sock)
        except Exception as e:  # noqa: BLE001
            log_error("native redis fallback handler raised: %r", e)
        finally:
            self._engine_op(lambda eng: eng.py_done(conn_id))

    def _process_native_frame(self, conn_id: int, frame: bytes):
        import struct as _struct

        from incubator_brpc_tpu.protocols import tpu_std
        from incubator_brpc_tpu.protos import rpc_meta_pb2 as _pb
        from incubator_brpc_tpu.utils.iobuf import IOBuf

        if self._native_engine is None:  # racing stop(): engine is gone
            return

        def _kill():  # garbage framing kills the conn, same as
            # ParseResult.bad() on the Python transport; routed through
            # _engine_op so a racing stop() can't hand us a freed engine
            self._engine_op(lambda eng: eng.close_conn(conn_id))

        # The engine coalesces every Python-fallback tpu_std frame it
        # cut from ONE read burst into a single dispatch (engine.cpp
        # cut_frames), so `frame` may hold N concatenated TRPC frames —
        # a client submission-ring window arrives here whole, as one
        # scheduler task.  Validate the framing of the whole burst
        # first (any garbage kills the conn, exactly like the
        # single-frame path did), then process in arrival order.
        bounds = []
        off = 0
        total = len(frame)
        while off < total:
            if total - off < 12 or frame[off : off + 4] != b"TRPC":
                _kill()
                return
            meta_size, body_size = _struct.unpack_from(">II", frame, off + 4)
            end = off + 12 + meta_size + body_size
            if end > total:
                _kill()
                return
            bounds.append((off, meta_size, end))
            off = end
        if not bounds:
            _kill()
            return
        burst = len(bounds) > 1
        # server response ring: replies to a multi-frame window stage on
        # this thread and flush as one writev burst after the window is
        # fully dispatched (including inline-executed batch fan-outs)
        ring_token = resp_ring_begin() if burst else False
        if burst:
            # batched-method rows in this burst defer into the
            # collector and reach each Batcher as ONE accumulation
            self._burst_begin()
        try:
            sock = _NativeConnSocket(self, conn_id)
            for off, meta_size, end in bounds:
                meta = _pb.RpcMeta()
                try:
                    meta.ParseFromString(frame[off + 12 : off + 12 + meta_size])
                except Exception:  # noqa: BLE001
                    _kill()
                    return
                body_size = end - off - 12 - meta_size
                if meta.attachment_size < 0 or meta.attachment_size > body_size:
                    _kill()
                    return
                payload = IOBuf(frame[off + 12 + meta_size : end])
                msg = tpu_std.TpuStdMessage(meta, payload)
                # rpcz stamps for the native fallback: the engine cut the
                # frame off-GIL, so received≈parse_done≈enqueued at entry
                now_us = _time.time_ns() // 1000
                msg.received_us = msg.parse_done_us = msg.enqueued_us = now_us
                tpu_std.process_request(msg, sock)
        finally:
            if burst:
                try:
                    self._burst_end()
                finally:
                    # flush AFTER _burst_end: inline-executed batch
                    # handlers' responses also ride this window's burst
                    resp_ring_flush(ring_token)

    def _start_internal_port(self, host: str) -> int:
        """Second acceptor for builtin services only (server.cpp:1042)."""
        try:
            fd = _pysocket.socket(_pysocket.AF_INET, _pysocket.SOCK_STREAM)
            fd.setsockopt(_pysocket.SOL_SOCKET, _pysocket.SO_REUSEADDR, 1)
            fd.bind((host, self.options.internal_port))
            fd.listen(128)
            fd.setblocking(False)
        except OSError as e:
            log_error("listen on internal_port %s failed: %r",
                      self.options.internal_port, e)
            return -1
        self._internal_ep = EndPoint.tcp(host, fd.getsockname()[1])
        self._internal_acceptor = Acceptor(_InternalPortView(self))
        self._internal_acceptor.start_accept(fd)
        log_info("builtin services on internal port %s", self._internal_ep)
        return 0

    def _add_builtin_services(self):
        try:
            from incubator_brpc_tpu.builtin import register_builtin_services

            register_builtin_services(self)
        except ImportError:
            pass

    def add_builtin_handler(self, path: str, fn):
        self._builtin_handlers[path.rstrip("/") or "/"] = fn

    def find_builtin_handler(self, path: str):
        h = self._builtin_handlers.get(path)
        if h is not None:
            return h
        # prefix match for parameterized pages (/pprof/...)
        for p, fn in self._builtin_handlers.items():
            if p != "/" and path.startswith(p + "/"):
                return fn
        return None

    def start_ici(self, slice_id: int = 0, chip_id: int = 0, device=None) -> int:
        """Expose this server on the ICI fabric at ici://slice/chip —
        the TPU-transport analog of listening on a port (reference:
        ServerOptions.use_rdma + rdma init, server.cpp:772-782).
        Can serve ICI alongside (or instead of) TCP."""
        global_init()
        from incubator_brpc_tpu.parallel.ici import get_fabric

        if device is None:
            try:
                import jax

                device = jax.devices()[chip_id % len(jax.devices())]
            except Exception:
                device = None
        try:
            self._ici_port = get_fabric().register(
                (slice_id, chip_id), server=self, device=device
            )
        except ValueError as e:
            log_error("start_ici failed: %r", e)
            return -1
        self._running = True
        if self._listen_ep is None:
            self._listen_ep = EndPoint.ici(slice_id, chip_id)
        for status in self._method_status.values():
            status.expose()
        self._init_batchers()
        log_info("Server exposed on ici://slice%d/chip%d", slice_id, chip_id)
        return 0

    def stop(self, closewait_ms: int = 0) -> int:
        """Stop serving.  ``closewait_ms`` > 0 gives in-flight requests
        that long to finish before connections close (reference
        Server::Stop(closewait_ms), server.cpp: stop listening first,
        drain, then tear down): the listener refuses new connections
        immediately while existing ones flush their responses."""
        with self._lock:
            if not self._running:
                return 0
            self._running = False
        # stop batchers first: each flushes its queued rows so admitted
        # requests finish inside the closewait drain below; late
        # arrivals fall back to direct dispatch (and then ELOGOFF)
        for batcher in list(self._batchers.values()):
            batcher.stop()
        self._batchers.clear()
        if self._ici_port is not None:
            from incubator_brpc_tpu.parallel.ici import get_fabric

            get_fabric().unregister(self._ici_port.coords)
            self._ici_port = None
        if closewait_ms > 0:
            # refuse NEW connections on every listener right away (the
            # docstring's contract), then drain
            if self._acceptor is not None:
                self._acceptor.stop_listening()
            if self._internal_acceptor is not None:
                self._internal_acceptor.stop_listening()
            deadline = _time.monotonic() + closewait_ms / 1000.0
            clean_streak = 0
            while _time.monotonic() < deadline:
                if self._drained():
                    # require the quiet state to HOLD: a request parsed
                    # but not yet counted in concurrency shows as a
                    # momentary zero on a single sample
                    clean_streak += 1
                    if clean_streak >= 3:
                        break
                else:
                    clean_streak = 0
                _time.sleep(0.01)
        if self._acceptor is not None:
            self._acceptor.stop_accept()
            self._acceptor = None
        if self._native_engine is not None:
            self.harvest_native_stats()  # final fold before teardown
            # swap under the lifetime lock, then wait for in-flight
            # _engine_op refs to drain before freeing the C++ object.
            # New ops see None; old ops finish on the live engine.
            with self._engine_cv:
                eng, self._native_engine = self._native_engine, None
                drained = self._engine_cv.wait_for(
                    lambda: self._engine_refs == 0, timeout=5.0
                )
            if drained:
                eng.destroy()
            else:
                # a ref-holder is wedged inside the C engine: freeing it
                # now would be the exact use-after-free this guards
                # against.  Stop the engine's threads but leak the
                # object — bounded, and strictly safer.
                log_error(
                    "native engine refs not drained after 5s; stopping "
                    "without destroy (leaking engine object)"
                )
                eng.stop()
            # remove the UDS socket file we bound, or a later
            # Python-transport restart on the path hits EADDRINUSE
            if self._listen_ep is not None and self._listen_ep.scheme == "uds":
                import os as _os

                try:
                    _os.unlink(self._listen_ep.host)
                except OSError:
                    pass
        if self._internal_acceptor is not None:
            self._internal_acceptor.stop_accept()
            self._internal_acceptor = None
        self._listen_fd = None
        return 0

    def _drained(self) -> bool:
        """No handler running, no queued response bytes, no unparsed
        request bytes on any live connection."""
        if any(st.concurrency > 0 for st in self._method_status.values()):
            return False
        acceptor = self._acceptor
        if acceptor is not None:
            for sock in acceptor.connections():
                if sock is None or sock.failed:
                    continue
                if sock._unwritten > 0 or not sock.read_buf.empty():
                    return False
        return True

    def join(self, timeout_s: Optional[float] = None) -> int:
        """Block until the server is STOPPED and every in-flight handler
        finished (reference Server::Join: returns only after Stop).
        Returns 0 when stopped+drained, -1 on timeout."""
        deadline = (
            _time.monotonic() + timeout_s if timeout_s is not None else None
        )
        while self._running or any(
            st.concurrency > 0 for st in self._method_status.values()
        ):
            if deadline is not None and _time.monotonic() > deadline:
                return -1
            _time.sleep(0.01)
        return 0

    def is_running(self) -> bool:
        return self._running

    @property
    def listen_endpoint(self) -> Optional[EndPoint]:
        return self._listen_ep

    @property
    def port(self) -> int:
        return self._listen_ep.port if self._listen_ep else 0

    @property
    def internal_port(self) -> int:
        return self._internal_ep.port if self._internal_ep else -1

    def connection_count(self) -> int:
        return self._acceptor.connection_count() if self._acceptor else 0

    # ---- session/thread-local data pools (server.cpp:811-851) --------------
    def acquire_session_local(self):
        """Pop a pooled object (or build one via the factory)."""
        factory = self.options.session_local_data_factory
        if factory is None:
            return None
        with self._session_local_lock:
            if self._session_local_pool:
                return self._session_local_pool.pop()
        return factory()

    def return_session_local(self, data):
        if data is None:
            return
        with self._session_local_lock:
            if len(self._session_local_pool) < 1024:
                self._session_local_pool.append(data)

    def thread_local_data(self):
        """Per worker-thread user data (thread_local_data_factory)."""
        factory = self.options.thread_local_data_factory
        if factory is None:
            return None
        store = self._thread_local_store
        data = getattr(store, "data", None)
        if data is None:
            data = store.data = factory()
        return data
