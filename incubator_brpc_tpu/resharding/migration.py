"""Live re-sharding — zero-downtime scheme migration for sharded stores.

The sharded PS (docs/sharded_ps.md) and the HBM cache tier
(docs/cache.md) pin a shard count at process start; this module
migrates either store from an N-shard to an M-shard murmur3 scheme
WHILE serving traffic (docs/resharding.md), the sharded-store analog
of the reference DynamicPartitionChannel's scheme coexistence:

  PREPARE     census every old shard's keys; plan the moved set
              (``moved_keys`` — exactly the scheme delta, nothing else)
  DUAL_WRITE  clients (DynamicShardChannel) apply writes to BOTH
              schemes, so keys written mid-migration are already in
              place on their new owner
  COPY        moved keys stream shard→shard in (src, dst) ranges with
              per-key read-back checksums (murmur3 over value bytes);
              a source shard dying mid-COPY completes from the
              dual-written copy on the destination, or the migration
              rolls back — never a stale half-state
  CUTOVER     ONE epoch bump published through naming ("i/N@E" tags);
              in-flight fan-outs finish on the scheme they started on
              (the client snapshots its scheme per call)
  DRAIN       moved keys delete from their source shards (idempotent)
              — post-DRAIN the sources hold zero live migrated keys
  DONE        (or ROLLED_BACK: old scheme stays authoritative, copied
              keys best-effort deleted from the new-only shards)

Chaos sites (docs/chaos.md): ``reshard.copy`` faults individual key
copies (drop = retry next round, corrupt = checksum mismatch →
re-copy, delay_us = wider kill window), ``reshard.cutover`` faults the
epoch-bump publication (drop = rollback).  The acceptance suite
(tests/test_resharding.py) runs ``chaos.storm.reshard_storm_plan``
under RecoveryHarness and kills a source shard mid-COPY.

This module is jax-free at import (METRIC_MODULES contract): metrics
register here, device work stays in the stores.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from incubator_brpc_tpu.metrics.reducer import Adder
from incubator_brpc_tpu.utils.hashes import murmur3_32
from incubator_brpc_tpu.utils.logging import log_error

# ---------------------------------------------------------------------------
# phases
# ---------------------------------------------------------------------------

IDLE = "IDLE"
PREPARE = "PREPARE"
DUAL_WRITE = "DUAL_WRITE"
COPY = "COPY"
CUTOVER = "CUTOVER"
DRAIN = "DRAIN"
DONE = "DONE"
ROLLED_BACK = "ROLLED_BACK"

PHASES = (IDLE, PREPARE, DUAL_WRITE, COPY, CUTOVER, DRAIN, DONE,
          ROLLED_BACK)

# phases during which the client channel treats the migration as live
_MIGRATING = frozenset({PREPARE, DUAL_WRITE, COPY, CUTOVER, DRAIN})
# phases during which writes dual-apply to both schemes
_DUAL = frozenset({DUAL_WRITE, COPY, CUTOVER})

# ---------------------------------------------------------------------------
# metrics (rpc_reshard_*; registered at import — METRIC_MODULES lint)
# ---------------------------------------------------------------------------

reshard_keys_moved = Adder(0).expose("rpc_reshard_keys_moved")
reshard_ranges_copied = Adder(0).expose("rpc_reshard_ranges_copied")
reshard_checksum_failures = Adder(0).expose(
    "rpc_reshard_checksum_failures"
)
reshard_copy_retries = Adder(0).expose("rpc_reshard_copy_retries")
reshard_survivor_completions = Adder(0).expose(
    "rpc_reshard_survivor_completions"
)
reshard_cutovers = Adder(0).expose("rpc_reshard_cutovers")
reshard_rollbacks = Adder(0).expose("rpc_reshard_rollbacks")
reshard_keys_drained = Adder(0).expose("rpc_reshard_keys_drained")
# collective bulk-move (one stacked read + write + verify per
# (src, dst) range instead of per-key RPCs): the step-log proof that
# an N→M COPY moves shards in collective steps is
# collective_steps ≪ keys_moved
reshard_collective_steps = Adder(0).expose(
    "rpc_reshard_collective_steps"
)
reshard_bulk_ranges = Adder(0).expose("rpc_reshard_bulk_ranges")
reshard_bulk_fallbacks = Adder(0).expose("rpc_reshard_bulk_fallbacks")


# ---------------------------------------------------------------------------
# the pure scheme planner
# ---------------------------------------------------------------------------

def shard_of(key, n: int, seed: int = 0) -> int:
    """The ShardRoutedChannel's owner function, importable without a
    channel: murmur3(key) % n.  Golden-pinned in tests — changing this
    silently strands every stored key."""
    return murmur3_32(str(key).encode(), seed=seed) % n


def moved_keys(
    keys: Sequence, old_n: int, new_n: int, seed: int = 0
) -> Dict[str, Tuple[int, int]]:
    """{key: (src_shard, dst_shard)} for exactly the keys whose owner
    CHANGES between the N- and M-shard schemes (shards 0..N-1 keep
    their identity in the new scheme, so same-index keys never move).
    This is the migration's whole work list — and the golden test's
    assertion that no key remaps gratuitously."""
    out: Dict[str, Tuple[int, int]] = {}
    for key in keys:
        k = key.decode("utf-8", "surrogateescape") if isinstance(
            key, (bytes, bytearray)
        ) else str(key)
        src = shard_of(k, old_n, seed)
        dst = shard_of(k, new_n, seed)
        if src != dst:
            out[k] = (src, dst)
    return out


def range_checksum(value: bytes) -> int:
    """Per-range copy checksum: murmur3 over the value bytes (the same
    hash family as the chunk pipeline's chained checksums)."""
    return murmur3_32(bytes(value))


# ---------------------------------------------------------------------------
# epoch-in-tag naming grammar:  "i/N@E"
# ---------------------------------------------------------------------------

def parse_epoch_tag(tag: str) -> Optional[Tuple[int, int, int]]:
    """"i/N@E" → (index, count, epoch); "i/N" → (index, count, 0);
    None when the tag is not a partition tag.  The plain-"i/N" parser
    in client/combo.py returns None for epoch-extended tags, so mixed
    fleets degrade safely (old clients ignore epoch-tagged nodes
    rather than misrouting)."""
    base, _, ep = tag.partition("@")
    try:
        idx_s, _, cnt_s = base.partition("/")
        idx, cnt = int(idx_s), int(cnt_s)
        epoch = int(ep) if ep else 0
    except ValueError:
        return None
    return idx, cnt, epoch


def format_epoch_tag(index: int, count: int, epoch: int) -> str:
    return f"{index}/{count}@{epoch}"


def max_epoch(nodes) -> int:
    """The highest epoch any node's tag advertises — what a naming
    watcher adopts (the CUTOVER bump is exactly this going up by 1)."""
    best = 0
    for node in nodes:
        parsed = parse_epoch_tag(getattr(node, "tag", "") or "")
        if parsed is not None:
            best = max(best, parsed[2])
    return best


# ---------------------------------------------------------------------------
# the client's view of the migration
# ---------------------------------------------------------------------------

class MigrationView:
    """What a DynamicShardChannel reads per call: the migration phase
    and the routing epoch.  The epoch is AUTHORITATIVE for scheme
    choice — phase only widens behavior (dual writes, read fallback).
    Feed it as a naming watcher (``on_servers_changed``) so the
    CUTOVER bump propagates to every client through the naming plane,
    or drive it directly from a co-located coordinator."""

    def __init__(self, epoch: int = 0):
        self._lock = threading.Lock()
        self.phase = IDLE
        self.epoch = int(epoch)
        self._base_epoch = int(epoch)

    # -- predicates the channel calls (one lock-free read each; phase
    # and epoch are single attributes, torn reads impossible) --------------
    def cut_over(self) -> bool:
        return self.epoch > self._base_epoch

    def dual_writing(self) -> bool:
        return self.phase in _DUAL

    def migrating(self) -> bool:
        return self.phase in _MIGRATING

    # -- transitions ---------------------------------------------------------
    def set_phase(self, phase: str) -> None:
        if phase not in PHASES:
            raise ValueError(f"unknown migration phase {phase!r}")
        self.phase = phase

    def bump_epoch(self, epoch: Optional[int] = None) -> int:
        with self._lock:
            self.epoch = int(epoch) if epoch is not None else self.epoch + 1
            return self.epoch

    def rearm(self) -> None:
        """Adopt the current epoch as the new baseline (after DONE /
        ROLLED_BACK, so the next migration starts un-cut-over)."""
        with self._lock:
            self._base_epoch = self.epoch

    # -- naming watcher ------------------------------------------------------
    def on_servers_changed(self, nodes) -> None:
        e = max_epoch(nodes)
        with self._lock:
            if e > self.epoch:
                self.epoch = e


# ---------------------------------------------------------------------------
# per-replica persisted state + the /resharding registry
# ---------------------------------------------------------------------------

_registry_lock = threading.Lock()
_registry: Dict[str, "ReshardingState"] = {}


def register_state(state: "ReshardingState") -> None:
    with _registry_lock:
        _registry[state.name] = state


def states_snapshot() -> Dict[str, dict]:
    """All registered migrations' states (the /resharding builtin)."""
    with _registry_lock:
        return {name: st.to_dict() for name, st in _registry.items()}


class ReshardingState:
    """One migration's durable state on one replica: phase, epoch,
    scheme pair, and the step-log counters the zero-downtime proof
    reads.  ``path`` persists every transition as JSON so a restarted
    replica resumes (``ReshardingState.load``) instead of forgetting a
    half-done migration."""

    def __init__(self, name: str, old_n: int, new_n: int, seed: int = 0,
                 path: Optional[str] = None, epoch: int = 0):
        self.name = name
        self.old_n = int(old_n)
        self.new_n = int(new_n)
        self.seed = int(seed)
        self.path = path
        self.phase = IDLE
        self.epoch = int(epoch)
        self.counters: Dict[str, int] = {
            "keys_total": 0,
            "keys_moved": 0,
            "keys_copied": 0,
            "keys_drained": 0,
            "ranges_copied": 0,
            "checksum_failures": 0,
            "copy_retries": 0,
            "survivor_completions": 0,
            "rollbacks": 0,
            "collective_steps": 0,
            "bulk_ranges": 0,
        }
        self._lock = threading.Lock()
        register_state(self)

    def enter(self, phase: str, epoch: Optional[int] = None) -> None:
        if phase not in PHASES:
            raise ValueError(f"unknown migration phase {phase!r}")
        with self._lock:
            self.phase = phase
            if epoch is not None:
                self.epoch = int(epoch)
        self.save()

    def bump(self, counter: str, delta: int = 1) -> None:
        with self._lock:
            self.counters[counter] = self.counters.get(counter, 0) + delta

    def to_dict(self) -> dict:
        with self._lock:
            return {
                "name": self.name,
                "phase": self.phase,
                "epoch": self.epoch,
                "old_n": self.old_n,
                "new_n": self.new_n,
                "seed": self.seed,
                "counters": dict(self.counters),
            }

    # -- persistence ---------------------------------------------------------
    def save(self) -> None:
        if not self.path:
            return
        try:
            tmp = f"{self.path}.tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(self.to_dict(), f)
            os.replace(tmp, self.path)
        except OSError as e:
            log_error("resharding state save failed: %r", e)

    @classmethod
    def load(cls, path: str) -> Optional["ReshardingState"]:
        try:
            with open(path, "r", encoding="utf-8") as f:
                d = json.load(f)
        except (OSError, ValueError):
            return None
        st = cls(d["name"], d["old_n"], d["new_n"], seed=d.get("seed", 0),
                 path=path, epoch=d.get("epoch", 0))
        st.phase = d.get("phase", IDLE)
        st.counters.update(d.get("counters", {}))
        return st


# ---------------------------------------------------------------------------
# per-shard store adapters (what the coordinator copies through)
# ---------------------------------------------------------------------------

class ShardUnavailable(RuntimeError):
    """A shard did not answer (dead / unreachable) — distinct from a
    clean miss, which reads as None."""


class PsShardStore:
    """One PS shard behind its sub-channel: the coordinator's
    read/write/delete/census surface over the Keys/Get/Put/Delete
    RPCs.  Values move as bytes (device payloads materialize through
    the manifested iobuf spill on read and re-ingest on write — the
    migration is a control-plane copy, not a hot path)."""

    def __init__(self, channel, timeout_ms: int = 10000):
        from incubator_brpc_tpu.models.parameter_server import ps_stub

        self._stub = ps_stub(channel)
        self._timeout_ms = timeout_ms

    def _controller(self):
        from incubator_brpc_tpu.client.controller import Controller

        c = Controller()
        c.timeout_ms = self._timeout_ms
        return c

    def _request(self, key: str = ""):
        from incubator_brpc_tpu.protos.echo_pb2 import EchoRequest

        return EchoRequest(message=key)

    def list_keys(self) -> List[str]:
        c = self._controller()
        self._stub.Keys(c, self._request())
        if c.failed():
            raise ShardUnavailable(f"Keys failed: {c.error_text()}")
        raw = c.response_attachment.to_bytes()
        return raw.decode("utf-8").split("\n") if raw else []

    def read(self, key: str) -> Optional[bytes]:
        from incubator_brpc_tpu import errors

        c = self._controller()
        self._stub.Get(c, self._request(key))
        if c.failed():
            if c.error_code == errors.EREQUEST:
                return None  # clean miss
            raise ShardUnavailable(f"Get({key}) failed: {c.error_text()}")
        return c.response_attachment.to_bytes()

    def write(self, key: str, value: bytes) -> None:
        c = self._controller()
        c.request_attachment.append(bytes(value))
        self._stub.Put(c, self._request(key))
        if c.failed():
            raise ShardUnavailable(f"Put({key}) failed: {c.error_text()}")

    def delete(self, key: str) -> bool:
        c = self._controller()
        resp = self._stub.Delete(c, self._request(key))
        if c.failed():
            raise ShardUnavailable(
                f"Delete({key}) failed: {c.error_text()}"
            )
        return resp.message == "1"


class CacheShardStore:
    """One cache shard behind a (typically single-member) CacheChannel
    — same surface as PsShardStore over GET/SET/DEL/KEYS, plus the
    bulk surface (``read_many``/``write_many`` over DMGET/DMSET) the
    coordinator's collective COPY path probes for: one round trip moves
    a whole (src, dst) key range instead of one RPC per key.
    (PsShardStore stays per-key — its Get/Put protobuf surface has no
    bulk verb — so PS migrations ride the per-key engine unchanged.)"""

    def __init__(self, cache_channel):
        self._cc = cache_channel

    def list_keys(self) -> List[str]:
        from incubator_brpc_tpu.cache.channel import CacheError

        try:
            return [
                k.decode("utf-8", "surrogateescape")
                for k in self._cc.keys()
            ]
        except CacheError as e:
            raise ShardUnavailable(f"KEYS failed: {e}") from e

    def read(self, key: str) -> Optional[bytes]:
        from incubator_brpc_tpu.cache.channel import CacheError

        try:
            return self._cc.get_host(key)
        except CacheError as e:
            raise ShardUnavailable(f"GET({key}) failed: {e}") from e

    def write(self, key: str, value: bytes) -> None:
        from incubator_brpc_tpu.cache.channel import CacheError

        try:
            self._cc.set(key, bytes(value))
        except CacheError as e:
            raise ShardUnavailable(f"SET({key}) failed: {e}") from e

    def delete(self, key: str) -> bool:
        from incubator_brpc_tpu.cache.channel import CacheError

        try:
            return self._cc.delete(key)
        except CacheError as e:
            raise ShardUnavailable(f"DEL({key}) failed: {e}") from e

    # -- bulk surface (collective COPY) --------------------------------------
    def read_many(self, keys: Sequence[str]) -> List[Optional[bytes]]:
        """One DMGET for the whole key list; misses read as None."""
        from incubator_brpc_tpu.cache.channel import CacheError

        keys = list(keys)
        try:
            res = self._cc.get_many(keys)
            return [res.host_bytes(i) for i in range(len(keys))]
        except CacheError as e:
            raise ShardUnavailable(f"DMGET({len(keys)}) failed: {e}") from e

    def write_many(self, items: Sequence[Tuple[str, bytes]]) -> None:
        """One DMSET for the whole (key, value) list."""
        from incubator_brpc_tpu.cache.channel import CacheError

        items = [(k, bytes(v)) for k, v in items]
        try:
            self._cc.set_many(items)
        except CacheError as e:
            raise ShardUnavailable(
                f"DMSET({len(items)}) failed: {e}"
            ) from e


# ---------------------------------------------------------------------------
# the verified move step — shared by resharding COPY and replica repair
# ---------------------------------------------------------------------------

def verified_write(dst_store, key: str, value: bytes) -> Tuple[bool, int]:
    """The one checksum-verified move step: write + read-back + murmur3
    verify against the source bytes.  Returns ``(ok, checksum)`` where
    ``checksum`` is the SOURCE checksum (what a ledger records on
    success).  ShardUnavailable propagates — the caller owns retry
    semantics.  This is the single primitive the resharding COPY engine
    (``_copy_one``) and replication repair (replication/group.py) share:
    one path, one verification discipline."""
    checksum = range_checksum(value)
    dst_store.write(key, value)
    back = dst_store.read(key)
    verify = range_checksum(back) if back is not None else ~checksum
    return verify == checksum, checksum


def verified_write_many(
    dst_store, items: Sequence[Tuple[str, bytes]],
) -> Tuple[List[str], List[str], Dict[str, int]]:
    """Bulk flavor of :func:`verified_write` riding the stacked
    DMSET/DMGET surface (the PR 17 bulk-move lowering) when the store
    has one: ONE stacked write + ONE stacked read-back verifies the
    whole batch in two collective steps.  Returns ``(ok_keys,
    failed_keys, checksums)``; ``failed_keys`` must be re-moved (the
    per-key engine or the next round).  Callers probe
    ``write_many``/``read_many`` before calling; ShardUnavailable
    propagates."""
    items = [(k, bytes(v)) for k, v in items]
    checksums = {k: range_checksum(v) for k, v in items}
    dst_store.write_many(items)
    back = dst_store.read_many([k for k, _ in items])
    ok_keys: List[str] = []
    failed_keys: List[str] = []
    for (k, _v), b in zip(items, back):
        want = checksums[k]
        verify = range_checksum(b) if b is not None else ~want
        (ok_keys if verify == want else failed_keys).append(k)
    return ok_keys, failed_keys, checksums


# ---------------------------------------------------------------------------
# the coordinator
# ---------------------------------------------------------------------------

class MigrationFailed(RuntimeError):
    """The migration could neither complete nor roll back cleanly."""


class ReshardCoordinator:
    """Drives one N→M migration over per-shard store adapters.

    ``old_parts``/``new_parts`` are the per-shard stores of each
    scheme (shards 0..N-1 of the new scheme are normally the SAME
    stores as the old scheme's — only indices N..M-1 are new
    capacity).  ``view`` is the MigrationView the co-located client
    channel reads; remote clients get the epoch through ``publish``
    (republish naming with ``format_epoch_tag`` tags) and the phase
    through their own naming-fed views.

    ``run()`` executes the whole state machine synchronously and
    returns the step-log report; it either reaches DONE or ROLLED_BACK
    (raising MigrationFailed only when rollback itself cannot restore
    the old scheme's invariants)."""

    def __init__(
        self,
        name: str,
        old_parts: Sequence,
        new_parts: Sequence,
        seed: int = 0,
        view: Optional[MigrationView] = None,
        state: Optional[ReshardingState] = None,
        publish: Optional[Callable[[int, str], None]] = None,
        copy_rounds: int = 8,
        on_copy: Optional[Callable[[str, int, int], None]] = None,
        key_filter: Optional[Callable[[str], bool]] = None,
    ):
        self.name = name
        self.old_parts = list(old_parts)
        self.new_parts = list(new_parts)
        self.seed = int(seed)
        self.view = view if view is not None else MigrationView()
        self.state = state if state is not None else ReshardingState(
            name, len(self.old_parts), len(self.new_parts), seed=seed,
            epoch=self.view.epoch,
        )
        self._publish = publish
        self.copy_rounds = int(copy_rounds)
        # test hook: called before each key's copy attempt with
        # (key, src, dst) — the kill-mid-COPY suite stops a source
        # shard from inside this
        self._on_copy = on_copy
        # census filter: keys it rejects stay OUT of the migration —
        # per-scheme layout keys (scattered parameter slices, which
        # hold DIFFERENT bytes on every shard) must re-scatter through
        # the remesh path, never copy by owner
        self._key_filter = key_filter
        self.moved: Dict[str, Tuple[int, int]] = {}
        self._copied: Dict[str, int] = {}  # key -> checksum

    # -- phase helpers -------------------------------------------------------
    def _span(self, phase: str):
        from incubator_brpc_tpu.observability.span import Span

        span = Span.create_client("resharding", phase)
        if span is not None:
            span.annotate(
                f"migration {self.name}: {len(self.old_parts)}→"
                f"{len(self.new_parts)} shards"
            )
        return span

    def _enter(self, phase: str) -> None:
        self.state.enter(phase, epoch=self.view.epoch)
        self.view.set_phase(phase)

    def _chaos_copy(self, key: str) -> Optional[str]:
        """→ None (proceed), "drop" (skip this attempt), "corrupt"
        (force a checksum mismatch on this attempt)."""
        from incubator_brpc_tpu.chaos import injector as _chaos

        if not _chaos.armed:
            return None
        spec = _chaos.check("reshard.copy", method=key)
        if spec is None:
            return None
        if spec.action == "delay_us":
            _chaos.sleep_us(spec.arg)
            return None
        return spec.action  # "drop" | "corrupt"

    def _chaos_cutover(self) -> bool:
        """True = the cutover publication is dropped (→ rollback)."""
        from incubator_brpc_tpu.chaos import injector as _chaos

        if not _chaos.armed:
            return False
        spec = _chaos.check("reshard.cutover", method=self.name)
        if spec is None:
            return False
        if spec.action == "delay_us":
            _chaos.sleep_us(spec.arg)
            return False
        return spec.action == "drop"

    # -- the state machine ---------------------------------------------------
    def run(self) -> dict:
        span = self._span("migration")
        try:
            result = self._run_inner()
            if span is not None:
                span.annotate(f"finished {self.state.phase}")
                span.end(0 if self.state.phase == DONE else 1)
            return result
        except Exception:
            if span is not None:
                span.end(1)
            raise

    def _run_inner(self) -> dict:
        self._prepare()
        self._enter(DUAL_WRITE)
        self._enter(COPY)
        copied_all = self._copy()
        if not copied_all:
            return self._rollback("COPY could not complete")
        if not self._cutover():
            return self._rollback("CUTOVER publication dropped")
        self._drain()
        self._enter(DONE)
        # NO rearm here: the new scheme stays authoritative
        # (cut_over() True) for the life of this view — a follow-on
        # migration builds a fresh view/channel pair and rearms THAT
        return self.report()

    def _prepare(self) -> None:
        self._enter(PREPARE)
        span = self._span(PREPARE)
        keys: set = set()
        for i, part in enumerate(self.old_parts):
            try:
                shard_keys = part.list_keys()
            except ShardUnavailable as e:
                # a shard we cannot census is a shard we cannot migrate
                if span is not None:
                    span.end(1)
                raise MigrationFailed(
                    f"PREPARE: shard {i} census failed: {e}"
                ) from e
            # census trusts each shard's OWN key list; keys the scheme
            # wouldn't route there (e.g. mid-crash leftovers) still
            # migrate by their canonical owner mapping
            keys.update(shard_keys)
        if self._key_filter is not None:
            keys = {k for k in keys if self._key_filter(k)}
        self.moved = moved_keys(
            sorted(keys), len(self.old_parts), len(self.new_parts),
            self.seed,
        )
        self.state.bump("keys_total", len(keys))
        self.state.bump("keys_moved", len(self.moved))
        if span is not None:
            span.annotate(
                f"census {len(keys)} keys, {len(self.moved)} move"
            )
            span.end(0)

    def _copy(self) -> bool:
        """Copy every moved key src→dst with read-back checksums.
        Ranges whose stores expose the bulk surface move collectively
        (``_copy_range_bulk``: 3 stacked steps per (src, dst) pair);
        the rest — and every chaos/hook run — ride the per-key engine.
        Returns True when every key is in place on its destination."""
        span = self._span(COPY)
        pending = dict(self.moved)
        rounds = 0
        while pending and rounds < self.copy_rounds:
            rounds += 1
            if rounds > 1:
                self.state.bump("copy_retries")
                reshard_copy_retries << 1
            # group into (src, dst) ranges: one range = one src shard
            # streaming its slice of the moved set to one dst shard
            ranges: Dict[Tuple[int, int], List[str]] = {}
            for key, (src, dst) in pending.items():
                ranges.setdefault((src, dst), []).append(key)
            for (src, dst), range_keys in sorted(ranges.items()):
                done_all = self._copy_range_bulk(
                    range_keys, src, dst, pending
                )
                if done_all is None:  # per-key engine (fallback)
                    done_all = True
                    for key in sorted(range_keys):
                        if self._copy_one(key, src, dst):
                            del pending[key]
                        else:
                            done_all = False
                if done_all:
                    self.state.bump("ranges_copied")
                    reshard_ranges_copied << 1
        if span is not None:
            span.annotate(
                f"{len(self.moved) - len(pending)}/{len(self.moved)} "
                f"keys copied in {rounds} rounds"
            )
            span.end(0 if not pending else 1)
        return not pending

    def _copy_range_bulk(
        self, range_keys: List[str], src: int, dst: int,
        pending: Dict[str, Tuple[int, int]],
    ) -> Optional[bool]:
        """Collective move of one (src, dst) range: ONE stacked read,
        ONE stacked write, ONE stacked read-back verify — three
        collective steps for the whole range instead of three RPCs per
        key, the bulk path the Pallas stacked transmit carries at the
        fabric layer.  Completed keys are pruned from ``pending``
        directly.  Returns None to defer the range to the per-key
        engine: stores without a bulk surface (PsShardStore), an armed
        chaos injector or a registered ``_on_copy`` hook (both target
        per-key fault semantics — seeded plans must replay exactly), or
        a shard failure mid-bulk (the per-key engine owns survivor
        completion)."""
        from incubator_brpc_tpu.chaos import injector as _chaos

        src_store = self.old_parts[src]
        dst_store = self.new_parts[dst]
        if (
            len(range_keys) < 2
            or _chaos.armed
            or self._on_copy is not None
            or not callable(getattr(src_store, "read_many", None))
            or not callable(getattr(dst_store, "write_many", None))
            or not callable(getattr(dst_store, "read_many", None))
        ):
            if len(range_keys) >= 2:
                reshard_bulk_fallbacks << 1
            return None
        keys = sorted(range_keys)
        try:
            values = src_store.read_many(keys)
        except ShardUnavailable:
            reshard_bulk_fallbacks << 1
            return None
        present = [(k, v) for k, v in zip(keys, values) if v is not None]
        misses = [k for k, v in zip(keys, values) if v is None]
        steps = 1
        done_all = True
        if present:
            try:
                ok_keys, failed_keys, checksums = verified_write_many(
                    dst_store, present
                )
            except ShardUnavailable:
                reshard_bulk_fallbacks << 1
                return None
            steps = 3
            for _k in failed_keys:  # re-copy next round
                self.state.bump("checksum_failures")
                reshard_checksum_failures << 1
                done_all = False
            for k in ok_keys:
                if k not in self._copied:
                    self._copied[k] = checksums[k]
                    self.state.bump("keys_copied")
                    reshard_keys_moved << 1
                del pending[k]
        self.state.bump("collective_steps", steps)
        reshard_collective_steps << steps
        self.state.bump("bulk_ranges")
        reshard_bulk_ranges << 1
        # source misses (deleted under us / survivor-held) are the rare
        # leg — the per-key engine's survivor-completion logic handles
        # each one
        for k in misses:
            if self._copy_one(k, src, dst):
                pending.pop(k, None)
            else:
                done_all = False
        return done_all

    def _copy_one(self, key: str, src: int, dst: int) -> bool:
        if self._on_copy is not None:
            self._on_copy(key, src, dst)
        chaos = self._chaos_copy(key)
        if chaos == "drop":
            return False  # this attempt lost; the key stays pending
        try:
            value = self.old_parts[src].read(key)
        except ShardUnavailable:
            value = None
            src_dead = True
        else:
            src_dead = False
        if value is None:
            # source miss/dead: the dual-written (or previously copied)
            # destination copy completes this key from the survivor —
            # the ISSUE's "completes from surviving replicas" leg
            try:
                existing = self.new_parts[dst].read(key)
            except ShardUnavailable:
                return False
            if existing is not None:
                if key not in self._copied:
                    self._copied[key] = range_checksum(existing)
                    self.state.bump("keys_copied")
                    self.state.bump("survivor_completions")
                    reshard_keys_moved << 1
                    reshard_survivor_completions << 1
                return True
            if src_dead:
                return False  # unrecoverable this round; retry/rollback
            # clean miss on BOTH sides: the key was deleted under us —
            # nothing to move
            self.moved.pop(key, None)
            self._copied.pop(key, None)
            return True
        try:
            ok, checksum = verified_write(self.new_parts[dst], key, value)
        except ShardUnavailable:
            return False
        if chaos == "corrupt":
            ok = False  # injected wire corruption: checksum trips
        if not ok:
            self.state.bump("checksum_failures")
            reshard_checksum_failures << 1
            return False  # re-copy next round
        if key not in self._copied:
            self._copied[key] = checksum
            self.state.bump("keys_copied")
            reshard_keys_moved << 1
        return True

    def _cutover(self) -> bool:
        span = self._span(CUTOVER)
        if self._chaos_cutover():
            if span is not None:
                span.annotate("publication dropped (chaos)")
                span.end(1)
            return False
        new_epoch = self.view.epoch + 1
        if self._publish is not None:
            try:
                self._publish(new_epoch, CUTOVER)
            except Exception as e:  # noqa: BLE001
                log_error("cutover publish raised: %r", e)
                if span is not None:
                    span.end(1)
                return False
        self.view.bump_epoch(new_epoch)
        self._enter(CUTOVER)
        reshard_cutovers << 1
        if span is not None:
            span.annotate(f"epoch → {new_epoch}")
            span.end(0)
        return True

    def _drain(self) -> None:
        self._enter(DRAIN)
        span = self._span(DRAIN)
        drained = 0
        for key, (src, dst) in sorted(self.moved.items()):
            try:
                if self.old_parts[src].delete(key):
                    drained += 1
            except ShardUnavailable:
                # a source that died mid-COPY holds no LIVE copy (its
                # store died with it); nothing to drain
                continue
        self.state.bump("keys_drained", drained)
        reshard_keys_drained << drained
        if span is not None:
            span.annotate(f"{drained} source copies deleted")
            span.end(0)

    def _rollback(self, reason: str) -> dict:
        span = self._span(ROLLED_BACK)
        # the old scheme never stopped being authoritative (no epoch
        # bump happened / is reverted by republishing the old tags)
        if self._publish is not None:
            try:
                self._publish(self.view.epoch, ROLLED_BACK)
            except Exception as e:  # noqa: BLE001
                log_error("rollback publish raised: %r", e)
        # best-effort: clear copies from NEW-ONLY shards so a later
        # retry starts clean (shards shared with the old scheme keep
        # their store untouched — they ARE the old scheme)
        old_n = len(self.old_parts)
        for key in list(self._copied):
            dst = self.moved.get(key, (0, -1))[1]
            if dst >= old_n:
                try:
                    self.new_parts[dst].delete(key)
                except ShardUnavailable:
                    pass
        self.state.bump("rollbacks")
        reshard_rollbacks << 1
        self._enter(ROLLED_BACK)
        # no epoch was bumped (or the old tags were republished at the
        # same epoch), so cut_over() stays False: old stays authoritative
        if span is not None:
            span.annotate(reason)
            span.end(0)
        return self.report()

    def report(self) -> dict:
        """The step-log report the acceptance suite asserts on —
        counts, never timing."""
        d = self.state.to_dict()
        d["completed"] = self.state.phase == DONE
        d["rolled_back"] = self.state.phase == ROLLED_BACK
        return d
