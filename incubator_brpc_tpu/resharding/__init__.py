"""resharding/ — live N→M scheme migration for sharded stores.

See :mod:`resharding.migration` for the state machine and
docs/resharding.md for the design.  Jax-free at import (the migration
control plane never touches device state directly — values move
through the store adapters' RPC surfaces).
"""

from incubator_brpc_tpu.resharding.migration import (
    COPY,
    CUTOVER,
    DONE,
    DRAIN,
    DUAL_WRITE,
    IDLE,
    PHASES,
    PREPARE,
    ROLLED_BACK,
    CacheShardStore,
    MigrationFailed,
    MigrationView,
    PsShardStore,
    ReshardCoordinator,
    ReshardingState,
    ShardUnavailable,
    format_epoch_tag,
    max_epoch,
    moved_keys,
    parse_epoch_tag,
    range_checksum,
    shard_of,
    states_snapshot,
)

__all__ = [
    "IDLE",
    "PREPARE",
    "DUAL_WRITE",
    "COPY",
    "CUTOVER",
    "DRAIN",
    "DONE",
    "ROLLED_BACK",
    "PHASES",
    "CacheShardStore",
    "MigrationFailed",
    "MigrationView",
    "PsShardStore",
    "ReshardCoordinator",
    "ReshardingState",
    "ShardUnavailable",
    "format_epoch_tag",
    "max_epoch",
    "moved_keys",
    "parse_epoch_tag",
    "range_checksum",
    "shard_of",
    "states_snapshot",
]
