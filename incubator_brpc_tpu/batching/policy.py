"""BatchPolicy — the per-method coalescing contract.

Dependency-free on purpose: ``server/service.py`` imports it at class
definition time (the ``@batched_method`` decorator carries a policy),
so it must not pull the runtime/transport stack in.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Tuple


@dataclass
class BatchPolicy:
    """Knobs of one method's micro-batcher.

    max_batch_size   rows per fused execution; <= 1 means batching OFF
                     for the method (the "zero-batch-size" config): the
                     server never builds a Batcher and requests take
                     the existing dispatch path unchanged.
    max_wait_us      longest a row may sit waiting for batch-mates.
                     The classic latency/throughput dial; tunable at
                     runtime via POST /batching.
    padding_buckets  ascending batch sizes the fused device execution
                     pads up to.  jit specializes per leading-dim, so
                     without buckets every distinct batch size retraces;
                     with them the trace-cache size is bounded by the
                     bucket count (asserted in tests).  () = no padding.
    deadline_us      per-request time budget from enqueue.  0 disables
                     the deadline guard.  Two effects:
                       * flush is scheduled so a row never waits past
                         (deadline - expected batch service time) — its
                         remaining budget always covers the execution;
                       * a row already past its deadline at dequeue is
                         SHED with ELIMIT before user code runs (the
                         shed feeds the method's concurrency limiter
                         like any other errored response).
    expected_service_us  seed for the batch-service-time EMA the
                     deadline guard subtracts; the Batcher refines it
                     from measured flushes.  With deadline_us set and
                     no explicit seed, it floors at deadline_us / 10 —
                     a zero seed would let the very first window flush
                     exactly AT its rows' deadline, landing their
                     responses past it.
    max_queue_rows   overload bound: rows the batcher may hold queued
                     (batches execute one at a time per method, so the
                     queue is where sustained overload accumulates).  A
                     row arriving at a full queue is shed immediately
                     with EOVERCROWDED — bounded memory and bounded
                     queue wait instead of unbounded growth.  0 = auto
                     (16 x max_batch_size).
    """

    max_batch_size: int = 32
    max_wait_us: int = 1000
    padding_buckets: Tuple[int, ...] = field(default_factory=tuple)
    deadline_us: int = 0
    expected_service_us: int = 0
    max_queue_rows: int = 0

    def __post_init__(self):
        self.max_batch_size = int(self.max_batch_size)
        self.max_wait_us = int(self.max_wait_us)
        self.deadline_us = int(self.deadline_us)
        self.expected_service_us = int(self.expected_service_us)
        self.max_queue_rows = int(self.max_queue_rows)
        buckets = tuple(int(b) for b in self.padding_buckets)
        if self.max_wait_us < 0:
            raise ValueError("max_wait_us must be >= 0")
        if self.deadline_us < 0 or self.expected_service_us < 0:
            raise ValueError("deadline_us/expected_service_us must be >= 0")
        if self.max_queue_rows < 0:
            raise ValueError("max_queue_rows must be >= 0 (0 = auto)")
        if self.deadline_us and not self.expected_service_us:
            # conservative seed until the EMA has a real measurement
            self.expected_service_us = self.deadline_us // 10
        if any(b <= 0 for b in buckets):
            raise ValueError("padding buckets must be positive")
        if list(buckets) != sorted(set(buckets)):
            raise ValueError("padding buckets must be strictly ascending")
        if buckets and self.max_batch_size > 1 and buckets[-1] < self.max_batch_size:
            # a batch bigger than the last bucket would execute unpadded
            # at its exact size — an unbounded-retrace hole the bucket
            # contract exists to close
            raise ValueError(
                f"largest padding bucket {buckets[-1]} < max_batch_size "
                f"{self.max_batch_size}: oversize batches would bypass "
                f"the retrace bound"
            )
        self.padding_buckets = buckets

    @property
    def enabled(self) -> bool:
        return self.max_batch_size > 1

    @property
    def queue_cap(self) -> int:
        """Effective queued-row bound (max_queue_rows, auto-derived
        when 0)."""
        return self.max_queue_rows or 16 * max(1, self.max_batch_size)

    def bucket_for(self, n: int) -> int:
        """Smallest padding bucket >= n (n itself without buckets)."""
        for b in self.padding_buckets:
            if b >= n:
                return b
        return n

    def to_dict(self) -> dict:
        return {
            "max_batch_size": self.max_batch_size,
            "max_wait_us": self.max_wait_us,
            "padding_buckets": list(self.padding_buckets),
            "deadline_us": self.deadline_us,
            "expected_service_us": self.expected_service_us,
            "max_queue_rows": self.max_queue_rows,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "BatchPolicy":
        unknown = set(d) - {
            "max_batch_size", "max_wait_us", "padding_buckets",
            "deadline_us", "expected_service_us", "max_queue_rows",
        }
        if unknown:
            raise ValueError(f"unknown BatchPolicy keys {sorted(unknown)}")
        return cls(**d)
