"""Sharded FusedKernel — shard_map/pjit lowering of batched device ops.

The pod-scale half of the micro-batching story (docs/sharded_ps.md):
``FusedKernel`` fuses N coalesced requests into ONE device execution on
one chip; ``ShardedFusedKernel`` lowers the same padded batch onto a
mesh, so the parameter operand lives sharded across every chip's HBM
and the batch executes as ONE fused sharded computation whose
cross-shard partial results merge via a SINGLE collective (psum over
the "chip" axis — ICI, not DCN, per the mesh convention).

For the flagship ``Y = X @ W``:

  W  : (d_in, d_out)  sharded P(axis, None)   — each chip holds
                      d_in/n rows; per-chip HBM, not one chip's,
                      bounds the servable parameter size
  X  : (bucket, d_in) sharded P(None, axis)   — the contraction dim
                      splits so each chip contracts its own W rows
  Y  : partial (bucket, d_out) per chip → jax.lax.psum(axis) → full Y
                      replicated (ONE collective merge per batch)

Everything around the kernel is unchanged: the Batcher still pads to
policy buckets (bounding retraces through the shared
``batching.fused`` trace counter), still scatters per-row responses,
and the padded stack still ships host→device once per batch.

Proof hooks ("asserted via step-log count, not timing"):

* ``executions`` / ``collective_merges`` — host-side step log, one
  increment per fused call.  The bench-smoke guard pins
  ``executions == batches`` so a silently-unsharded fallback (N
  per-row executions) fails loudly.
* an rpcz sub-span (kind "collective", method ``psum_forward@<axis>``)
  per call, parented to the active request trace — a batched sharded
  Forward reads as one trace with exactly one collective leg.

Chaos: the merge dispatch is a registered injection site
(``collective.merge``: delay_us stretches the dispatch, reset fails
it).  A reset surfaces as ONE exception per batch which the caller
maps to per-row ERPC errors — batch-mates in other groups still
execute (regression-tested in tests/test_sharded_ps.py).
"""

from __future__ import annotations

import threading
from typing import Optional

from incubator_brpc_tpu.batching import fused as _fused
from incubator_brpc_tpu.chaos import injector as _chaos
from incubator_brpc_tpu.observability.profiling import hbm_account, kernel_section


class CollectiveMergeError(RuntimeError):
    """An injected (or real) failure of the cross-shard merge; the
    batch handler maps it to per-row ERPC errors."""


def shardable_rows(shape, mesh, axis: str = "chip") -> bool:
    """True when a parameter of `shape` can row-shard over `axis`:
    2D with the leading (contraction) dim divisible by the axis size.
    Indivisible shapes stay on the single-chip path rather than pay a
    ragged-shard layout."""
    if mesh is None or len(shape) != 2:
        return False
    n = int(mesh.shape.get(axis, 1))
    return n > 1 and int(shape[0]) % n == 0


class ShardedFusedKernel:
    """The sharded variant of ``FusedKernel`` for the batched GEMM.

        K = ShardedFusedKernel(mesh)          # axes ("slice","chip")
        W = K.shard_param(w)                  # rows spread over "chip"
        Y = K(W, X_padded)                    # ONE sharded execution,
                                              # ONE psum merge

    Shares the module trace counter with the unsharded kernels, so the
    padding buckets bound ITS retraces the same way
    (``fused.trace_count()`` diffs stay assertable).
    """

    def __init__(self, mesh, axis: str = "chip",
                 label: str = "PsService.Forward"):
        self.mesh = mesh
        self.axis = axis
        # chaos-match + rpcz label: the method whose batches run here
        self.label = label
        self._jit = None
        self._lock = threading.Lock()
        # step log (see module docstring): one fused device execution
        # and one collective merge per __call__, by construction —
        # tests and the bench-smoke guard count these, never timing
        self.executions = 0
        self.collective_merges = 0

    # ---- placement ---------------------------------------------------------
    def shard_param(self, w):
        """Place `w` row-sharded over the mesh axis (each chip holds
        shape[0]/n rows).  Raises ValueError for shapes that cannot
        shard — callers fall back to the single-chip store."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        if not shardable_rows(getattr(w, "shape", ()), self.mesh, self.axis):
            raise ValueError(
                f"shape {getattr(w, 'shape', None)} cannot row-shard over "
                f"{self.axis!r} (size {self.mesh.shape.get(self.axis)})"
            )
        return jax.device_put(w, NamedSharding(self.mesh, P(self.axis, None)))

    def n_shards(self) -> int:
        return int(self.mesh.shape[self.axis])

    def remesh(self, mesh, axis: Optional[str] = None) -> None:
        """Re-target the kernel at a new mesh live (the server half of
        a scheme migration, docs/resharding.md): swap the mesh/axis and
        drop the compiled lowering so the next batch traces against the
        new topology.  Callers must re-``shard_param`` stored
        parameters — an old placement fed to the new lowering would be
        a silent cross-mesh transfer.  Step-log counters survive (the
        migration proof reads executions across the cutover)."""
        with self._lock:
            self.mesh = mesh
            if axis is not None:
                self.axis = axis
            self._jit = None

    # ---- the fused sharded execution ---------------------------------------
    def _get_jit(self):
        if self._jit is None:
            with self._lock:
                if self._jit is None:
                    import jax

                    from incubator_brpc_tpu.parallel.collectives import (
                        shard_map_relaxed,
                    )
                    from jax.sharding import PartitionSpec as P

                    axis = self.axis

                    def _fwd(w_local, x_local):
                        # trace-time only: one increment per new padded
                        # shape, same bound as the unsharded kernels
                        _fused._trace_count[0] += 1
                        part = x_local @ w_local  # per-chip partial
                        # THE single cross-shard merge of the batch
                        return jax.lax.psum(part, axis)

                    fn = shard_map_relaxed(
                        _fwd,
                        self.mesh,
                        in_specs=(P(axis, None), P(None, axis)),
                        out_specs=P(),
                    )
                    self._jit = jax.jit(fn)
        return self._jit

    def __call__(self, w, x):
        """One padded batch: ``x`` (bucket, d_in) host or device array,
        ``w`` the shard_param()-placed parameter.  Returns the full
        (bucket, d_out) result (replicated)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from incubator_brpc_tpu.observability.span import Span

        if _chaos.armed:
            spec = _chaos.check("collective.merge", method=self.label)
            if spec is not None:
                if spec.action == "delay_us":
                    _chaos.sleep_us(spec.arg)
                elif spec.action == "reset":
                    raise CollectiveMergeError(
                        "chaos: cross-shard collective merge reset"
                    )
        # split the contraction dim so each chip contracts against its
        # own rows of W; the stacked batch ships host→device once
        x_dev = jax.device_put(x, NamedSharding(self.mesh, P(None, self.axis)))
        # HBM ledger: the staged batch pins device memory until the
        # execution's output replaces it — release rides GC
        import weakref

        acct = hbm_account("sharded.batch_stage")
        charged = acct.adopt(x_dev)
        if charged:
            try:
                weakref.finalize(x_dev, acct.release, charged)
            except TypeError:
                acct.release(charged)
        # rpcz: the merge leg under the active request trace (outside
        # any RPC no span is created — same rule as parallel/collectives)
        span = Span.create_collective(
            "collective", f"psum_forward@{self.axis}"
        )
        try:
            # device-time attribution: the sharded dispatch window (the
            # caller's manifested pull owns the wider family)
            with kernel_section(f"sharded.{self.label}"):
                out = self._get_jit()(w, x_dev)
        except Exception:
            if span is not None:
                span.end(1)
            raise
        self.executions += 1
        self.collective_merges += 1
        if span is not None:
            span.annotate(
                f"sharded batch {tuple(x.shape)} over {self.n_shards()} "
                f"shards, one psum merge"
            )
            span.end(0)
        return out
