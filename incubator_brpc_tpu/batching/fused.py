"""Padded-stack device fusion for batch handlers.

``fused_stack_rows`` turns N same-shape device rows into ONE stacked
device execution: stack along a new leading axis, pad the batch dim up
to the policy bucket, run a single jitted kernel over the stack, hand
each row its slice back.  Device payloads never detour through host
bytes — the inputs are the jax.Arrays the IOBuf ``DeviceRef`` segments
already hold, and the outputs go back out as DeviceRefs.

Padding rows are DONATED from the caller's freelist (the Batcher's
per-method StagingRing — PR 4's staging-slot shape): steady state pads
with recycled buffers instead of allocating, and every pad returns to
the ring right after the stack copies it.  Pad VALUES are never read
(their output rows are discarded), so recycled contents are fine.

Because jit specializes on the leading dim, padding to buckets bounds
the trace cache at the bucket count; ``trace_count()`` exposes the
running total so tests can assert the bound.
"""

from __future__ import annotations

import threading
from typing import Callable, List, Optional

from incubator_brpc_tpu.observability.profiling import kernel_section

_trace_count = [0]
_jit_stack = None
# guards lazy jit construction (module stack kernel + every FusedKernel):
# racing first calls would each build a wrapper, double-tracing one shape
# and breaking the retraces <= buckets bound trace_count() exists to assert
_init_lock = threading.Lock()


def trace_count() -> int:
    """Total traces of fused kernels so far (monotonic; tests diff it
    around a workload to assert padding bounds retraces).  Shared by the
    stack kernel below and every ``FusedKernel``."""
    return _trace_count[0]


class FusedKernel:
    """A user batch kernel jitted with the module's shared trace
    counter, so padding-bucket retrace bounds are assertable for custom
    fused ops exactly like for the built-in stack kernel.

        _FWD = FusedKernel(lambda w, x: x @ w)
        y = _FWD(W, X_padded)   # ONE device execution per call;
                                # retraces only per new padded shape

    ``label``/``batch_buckets`` opt the kernel into the retrace witness
    (analysis/device_witness.py): each retrace is attributed to a shape
    *family* — argument shapes/dtypes with the batch arg's (last
    positional, by fused convention) leading dim wildcarded — and a
    family retracing more than ``len(batch_buckets)`` times contradicts
    the padding bound and fails the witness lane.
    """

    __slots__ = ("_fn", "_jit", "label", "batch_buckets", "_traces",
                 "_families", "_section")

    def __init__(self, fn: Callable, label: Optional[str] = None,
                 batch_buckets=None):
        self._fn = fn
        self._jit = None
        self.label = label or getattr(fn, "__name__", "fused")
        self.batch_buckets = (
            tuple(batch_buckets) if batch_buckets is not None else None
        )
        self._traces = [0]
        self._families = {}
        # device-time attribution family (observability/profiling.py):
        # precomputed so the hot path never formats a string
        self._section = f"fused.{self.label}"

    def trace_count(self) -> int:
        """Traces of THIS kernel so far (the module-level
        ``trace_count()`` stays the shared total)."""
        return self._traces[0]

    def __call__(self, *args):
        if self._jit is None:
            with _init_lock:
                if self._jit is None:
                    import jax

                    fn = self._fn
                    mine = self._traces

                    def _traced(*a):
                        # runs at TRACE time only: one increment per
                        # distinct input-shape specialization
                        _trace_count[0] += 1
                        mine[0] += 1
                        return fn(*a)

                    self._jit = jax.jit(_traced)
        # the section times the DISPATCH window (async dispatch returns
        # immediately; paths with a manifested pull add their own wider
        # family, e.g. ps.forward) — it never syncs the device
        if self.batch_buckets is None:
            with kernel_section(self._section):
                return self._jit(*args)
        before = self._traces[0]
        with kernel_section(self._section):
            out = self._jit(*args)
        if self._traces[0] != before:
            self._note_retrace(args)
        return out

    def _note_retrace(self, args) -> None:
        fam = []
        for i, a in enumerate(args):
            shape = tuple(getattr(a, "shape", ()) or ())
            if i == len(args) - 1 and shape:
                shape = ("*",) + shape[1:]
            fam.append((shape, str(getattr(a, "dtype", ""))))
        fam = tuple(fam)
        with _init_lock:
            n = self._families.get(fam, 0) + 1
            self._families[fam] = n
        from incubator_brpc_tpu.analysis import device_witness

        device_witness.note_trace(
            self.label, fam, n, len(self.batch_buckets)
        )


def _get_jit():
    global _jit_stack
    if _jit_stack is None:
        with _init_lock:
            if _jit_stack is None:
                import jax
                import jax.numpy as jnp

                def _fused(xs):
                    # stack + copy fuse into ONE compiled kernel: a
                    # single device dispatch per batch instead of one
                    # eager stack plus one jitted pass (the eager stack
                    # alone costs more than the whole unbatched op at
                    # small shapes)
                    _trace_count[0] += 1
                    return jnp.stack(xs) + 0

                _jit_stack = jax.jit(_fused)
    return _jit_stack


def fused_stack_rows(arrays: List, pad_to: int, freelist=None) -> List:
    """One fused device execution over ``arrays`` (same shape/dtype),
    padded to ``pad_to`` rows.  Returns len(arrays) per-row outputs.

    ``freelist`` is a StagingRing-shaped pool (acquire(shape, dtype) /
    release(arr)); None pads with fresh zeros."""
    import jax.numpy as jnp

    n = len(arrays)
    if n == 0:
        return []
    proto = arrays[0]
    pad_to = max(pad_to, n)
    pads = []
    for _ in range(pad_to - n):
        slot = freelist.acquire(proto.shape, proto.dtype) if freelist is not None else None
        if slot is None:
            slot = jnp.zeros(proto.shape, proto.dtype)
        pads.append(slot)
    # jit specializes on the tuple length (= the padding bucket) and row
    # shape, so the trace cache stays bounded by the policy's buckets
    with kernel_section("fused.stack"):
        out = _get_jit()(tuple(arrays) + tuple(pads))
    # the stack copied every pad into the batch buffer (jax arrays are
    # immutable, so recycling the slot refs immediately is safe even
    # while the async dispatch still reads them)
    if freelist is not None:
        for s in pads:
            freelist.release(s)
    return [out[i] for i in range(n)]
