"""Adaptive micro-batching — deadline-aware request coalescing.

The server-side symmetric half of the collective *merge* lowerings in
``parallel/collectives.py``: where those fuse a fan-out's N partial
responses into one collective, this subsystem fuses N concurrent
same-method requests into ONE batched user-handler execution (the
continuous-batching shape of inference serving, grafted onto the brpc
server stack).  See docs/batching.md.

Layers:
  policy.py   BatchPolicy — per-method coalescing knobs + deadline guard
  batcher.py  Batcher — accumulate / flush / shed / scatter
  fused.py    padded-stack device fusion with bounded jit retraces
"""

from incubator_brpc_tpu.batching.policy import BatchPolicy

# batcher/fused re-exports are lazy (PEP 562): BatchPolicy is imported
# at service-class-definition time (the @batched_method decorator) and
# must stay dependency-free — eagerly pulling batcher.py here would
# drag the chaos/metrics/runtime stack into every service definition
_LAZY = {
    "Batcher": ("incubator_brpc_tpu.batching.batcher", "Batcher"),
    "BatchContext": ("incubator_brpc_tpu.batching.batcher", "BatchContext"),
    "current_batch": ("incubator_brpc_tpu.batching.batcher", "current_batch"),
    "FusedKernel": ("incubator_brpc_tpu.batching.fused", "FusedKernel"),
    "fused_stack_rows": ("incubator_brpc_tpu.batching.fused",
                         "fused_stack_rows"),
}


def __getattr__(name):
    try:
        mod_name, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}"
        ) from None
    import importlib

    return getattr(importlib.import_module(mod_name), attr)


__all__ = [
    "BatchPolicy",
    "Batcher",
    "BatchContext",
    "current_batch",
    "FusedKernel",
    "fused_stack_rows",
]
