"""Batcher — per-method accumulation of concurrent requests into one
fused handler execution.

Sits between protocol dispatch and user code: ``tpu_std`` hands a
parsed (controller, request, response, done) row to ``submit`` instead
of ``run_user_method``; the Batcher accumulates rows under one lock
(a burst delivered through ``IciFabric.delivery_burst`` →
``ExecutionQueue.execute_batch`` drains its frames on ONE consumer
task, so the whole burst lands here with zero extra wakes), then
flushes when any trigger fires:

  size       pending == policy.max_batch_size → flush now;
  wait       max_wait_us after the oldest row enqueued (timer);
  deadline   the guard keeps flush no later than any row's
             (deadline - expected batch service time), so a row's
             remaining budget always covers the batch execution.

At flush, rows already past their deadline are SHED — ELIMIT through
the normal per-row done(), before user code runs, feeding the method's
concurrency limiter (server/method_status.py) like any errored
response — and the survivors run through the user's batch handler
ONCE.  The handler's done() scatters: each row's protocol done() sends
its own response, so per-row failures (``controller.set_failed``) map
to per-controller ERPC errors without poisoning batch-mates.

Metrics count REQUESTS, not batches: every row's done() drives the
method's LatencyRecorder/qps/limiter individually; the per-batch shape
lands in ``rpc_batch_size_<method>`` (IntRecorder) and
``rpc_batch_occupancy_<method>`` (PassiveStatus), both on /metrics.
"""

from __future__ import annotations

import threading
import time as _time
from collections import deque
from typing import Callable, List, Optional

from incubator_brpc_tpu import errors
from incubator_brpc_tpu.batching.policy import BatchPolicy
from incubator_brpc_tpu.chaos import injector as _chaos
from incubator_brpc_tpu.server import admission as _admission
from incubator_brpc_tpu.metrics.passive_status import PassiveStatus
from incubator_brpc_tpu.metrics.recorder import IntRecorder
from incubator_brpc_tpu.metrics.reducer import Adder
from incubator_brpc_tpu.utils.logging import log_error

_tls = threading.local()


def current_batch() -> Optional["BatchContext"]:
    """The BatchContext of the batch currently executing on this
    thread, or None (single-request fallback / unbatched dispatch).
    Batch handlers read it for the pad target and the padding freelist."""
    return getattr(_tls, "ctx", None)


class BatchContext:
    """What a batch handler may want to know about its invocation."""

    __slots__ = ("full_name", "batch_size", "pad_to", "_batcher", "policy")

    def __init__(self, full_name, batch_size, pad_to, batcher, policy):
        self.full_name = full_name
        self.batch_size = batch_size
        self.pad_to = pad_to
        self._batcher = batcher
        self.policy = policy

    @property
    def freelist(self):
        """The method's padding freelist (lazily built: only handlers
        that actually fuse device payloads pay for the ring)."""
        return self._batcher.pad_freelist

    @property
    def pad_fraction(self) -> float:
        return (self.pad_to - self.batch_size) / self.pad_to if self.pad_to else 0.0


class _Row:
    __slots__ = ("controller", "request", "response", "done",
                 "enqueue_ns", "deadline_ns")

    def __init__(self, controller, request, response, done,
                 enqueue_ns, deadline_ns):
        self.controller = controller
        self.request = request
        self.response = response
        self.done = done
        self.enqueue_ns = enqueue_ns
        self.deadline_ns = deadline_ns


class _Scatter:
    """The single done() a batch handler receives: first call fans out
    to every row's protocol done() (each serializes + sends its own
    response); later calls are no-ops (same contract as a single
    method's done)."""

    __slots__ = ("_rows", "called", "_on_done", "_once")

    def __init__(self, rows: List[_Row], on_done: Callable[[], None]):
        self._rows = rows
        self.called = False
        self._on_done = on_done
        self._once = threading.Lock()

    def __call__(self):
        # atomic check-and-set: a handler's async completion racing its
        # own synchronous exception fence must not fan out twice (a
        # double _finish_window would chain two concurrent batches)
        with self._once:
            if self.called:
                return
            self.called = True
        # rows first: every response is on its way to the wire before
        # on_done may chain straight into the next fused execution.
        # Multi-row fan-outs on a native conn open a server response
        # ring scope so the whole window leaves as one writev burst
        # per connection (no-op off the native path, and deferred to
        # the enclosing scope when a read-burst window already staged).
        ring_flush = None
        if len(self._rows) > 1:
            try:
                from incubator_brpc_tpu.server.server import (
                    resp_ring_begin,
                    resp_ring_flush,
                )

                ring_token = resp_ring_begin()
                if ring_token:
                    ring_flush = lambda: resp_ring_flush(ring_token)  # noqa: E731
            except Exception:  # noqa: BLE001 — staging is optional
                ring_flush = None
        try:
            for r in self._rows:
                try:
                    r.done()
                except Exception as e:  # noqa: BLE001 — one row's send
                    # failure must not strand its batch-mates
                    log_error("batched done() for one row raised: %r", e)
        finally:
            if ring_flush is not None:
                ring_flush()
        self._on_done()


class Batcher:
    """One method's micro-batcher (see module docstring)."""

    def __init__(
        self,
        full_name: str,
        batch_fn: Callable,
        policy: BatchPolicy,
        inline: bool = False,
    ):
        if not policy.enabled:
            raise ValueError(
                f"Batcher({full_name}) needs max_batch_size >= 2 "
                f"(got {policy.max_batch_size}); the off config takes "
                f"the existing dispatch path"
            )
        self.full_name = full_name
        self._batch_fn = batch_fn
        self.policy = policy
        # inline: flush runs on the submitting thread when the size /
        # overdue trigger fires (the usercode_in_dispatcher threading
        # model — no handoff, but a slow batch stalls that loop).
        # Timer-fired flushes always hop to the scheduler: user code
        # must never run on the process-wide timer thread.
        self._inline = inline
        self._lock = threading.Lock()
        self._pending: List[_Row] = []
        self._due_ns = 0  # earliest flush-by time of the pending window
        # continuous-batching discipline: at most ONE batch executes per
        # method at a time.  Rows arriving during an execution
        # accumulate; the finishing flush chains straight into the next
        # window.  Without this, the wait timer fires mid-execution and
        # fragments a saturated stream into small concurrent batches —
        # heavy padding waste and overlapping device executions instead
        # of full back-to-back ones.
        self._in_flight = False
        self._timer_id = 0
        # ownership token of the live timer: unschedule is best-effort,
        # so a popped-but-not-yet-run timer can still fire — the token
        # lets _on_timer recognize itself as stale instead of touching
        # a newer window's timer state
        self._timer_token = None
        self._stopped = False
        # batch service time EMA (us) the deadline guard subtracts
        self._service_ema_us = float(policy.expected_service_us)
        # padding freelist: donated device rows for pad slots, the
        # StagingRing shape from PR 4's ICI pipeline reused verbatim
        # (keyed by (shape, dtype), LRU-bounded); built lazily via the
        # pad_freelist property — host-padding handlers never touch it
        self._pad_freelist = None
        # -- stats / exposed variables --
        safe = full_name.replace(".", "_").lower()
        self.batch_size_rec = IntRecorder().expose(f"rpc_batch_size_{safe}")
        self._occ_var = PassiveStatus(self.occupancy).expose(
            f"rpc_batch_occupancy_{safe}"
        )
        self.shed = Adder(0).expose(f"rpc_batch_shed_{safe}")
        self.batches = 0
        self.rows = 0
        self.max_batch_seen = 0
        self._recent: deque = deque(maxlen=64)

    # ---- admission ---------------------------------------------------------
    def _row_cap(self, controller) -> int:
        """Tier-aware queue cap (docs/overload.md): a sub-1.0 tier stops
        queueing at cap*share, so under sustained overload the bulk
        tier's rows shed here while interactive rows still queue into
        the reserved headroom — same weighted-shedding rule the
        admission gate applies to concurrency."""
        cap = self.policy.queue_cap
        tier = controller.__dict__.get("_admission_tier")
        if tier is not None:
            server = getattr(controller, "server", None)
            adm = getattr(server, "admission", None)
            if adm is not None:
                share = adm.policy.share(tier)
                if share < 1.0:
                    cap = max(1, int(cap * share))
        return cap

    def submit(self, controller, request, response, done) -> bool:
        """Queue one parsed request row.  False = batcher stopped (the
        caller falls back to direct dispatch)."""
        if self._stopped:
            return False
        now = _time.monotonic_ns()
        deadline_ns = getattr(controller, "_batch_deadline_ns", 0)
        if not deadline_ns and self.policy.deadline_us:
            deadline_ns = now + self.policy.deadline_us * 1000
        row = _Row(controller, request, response, done, now, deadline_ns)
        due = self._flush_by(row)
        flush_rows = None
        arm_due = 0
        overflow = False
        cap = self._row_cap(controller)
        with self._lock:
            if self._stopped:
                return False
            if len(self._pending) >= cap:
                overflow = True
            else:
                self._pending.append(row)
                due_moved = self._due_ns == 0 or due < self._due_ns
                if due_moved:
                    self._due_ns = due
                if self._in_flight:
                    # a batch is executing: accumulate — its completion
                    # chain-flushes this window with zero extra wakes
                    pass
                elif len(self._pending) >= self.policy.max_batch_size or self._due_ns <= now:
                    flush_rows = self._take_pending_locked()
                    self._in_flight = True
                elif due_moved or self._timer_id == 0:
                    # (re)aim the flush timer only when the window's
                    # flush-by time actually moved — later-due rows ride
                    # the already-armed timer for free
                    arm_due = self._due_ns
        if overflow:
            # batches execute one at a time per method, so sustained
            # overload accumulates HERE — bound it: shed at admission
            # instead of growing the queue (and queue wait) without limit
            self._shed([row], _admission.shed_code("queue_full"),
                       "batch queue full (max_queue_rows; retry elsewhere)",
                       reason_key="queue_full")
            return True
        if flush_rows is not None:
            self._dispatch(flush_rows, inline_ok=True)
        elif arm_due:
            self._arm_timer(arm_due)
        return True

    def submit_many(self, rows_in) -> bool:
        """Queue a whole client submission window as ONE accumulation:
        one lock pass, one flush decision — a `call_many` window of N
        batched calls arriving in one read burst becomes ~one fused
        execution instead of N lock round-trips racing the wait timer.
        rows_in is a list of (controller, request, response, done).
        False = batcher stopped (caller falls back to direct dispatch
        for every row); overflow rows shed internally, like submit."""
        if self._stopped:
            return False
        now = _time.monotonic_ns()
        rows: List[_Row] = []
        for controller, request, response, done in rows_in:
            deadline_ns = getattr(controller, "_batch_deadline_ns", 0)
            if not deadline_ns and self.policy.deadline_us:
                deadline_ns = now + self.policy.deadline_us * 1000
            rows.append(
                _Row(controller, request, response, done, now, deadline_ns)
            )
        overflow: List[_Row] = []
        flush_rows = None
        arm_due = 0
        with self._lock:
            if self._stopped:
                return False
            for row in rows:
                if len(self._pending) >= self._row_cap(row.controller):
                    overflow.append(row)
                    continue
                self._pending.append(row)
                due = self._flush_by(row)
                if self._due_ns == 0 or due < self._due_ns:
                    self._due_ns = due
            if self._pending and not self._in_flight:
                if (
                    len(self._pending) >= self.policy.max_batch_size
                    or self._due_ns <= now
                ):
                    # a window past max_batch_size dequeues one max-size
                    # batch; the completion chain flushes the remainder
                    # back-to-back (continuous-batching discipline)
                    flush_rows = self._take_pending_locked()
                    self._in_flight = True
                else:
                    arm_due = self._due_ns
        if overflow:
            self._shed(overflow, _admission.shed_code("queue_full"),
                       "batch queue full (max_queue_rows; retry elsewhere)",
                       reason_key="queue_full")
        if flush_rows is not None:
            self._dispatch(flush_rows, inline_ok=True)
        elif arm_due:
            self._arm_timer(arm_due)
        return True

    def _flush_by(self, row: _Row) -> int:
        """The latest acceptable flush time for one row: max_wait after
        enqueue, clamped so its remaining deadline budget still covers
        the expected batch execution."""
        due = row.enqueue_ns + self.policy.max_wait_us * 1000
        if row.deadline_ns:
            margin_ns = int(self._service_ema_us * 1000)
            if margin_ns == 0:
                # unseeded EMA (a per-request _batch_deadline_ns on a
                # deadline-less policy, before the first measured
                # flush): reserve 10% of the row's budget — a zero
                # margin would aim the flush exactly AT the deadline
                # and shed a perfectly viable row at dequeue.  Once
                # measured, the EMA alone governs.
                margin_ns = (row.deadline_ns - row.enqueue_ns) // 10
            due = min(due, row.deadline_ns - margin_ns)
        return due

    def _take_pending_locked(self) -> List[_Row]:
        limit = self.policy.max_batch_size
        if len(self._pending) <= limit:
            rows, self._pending = self._pending, []
        else:
            # rows kept accumulating during an execution: dequeue one
            # max-size window FIFO, leave the rest for the next chain
            rows = self._pending[:limit]
            self._pending = self._pending[limit:]
        self._due_ns = (
            0
            if not self._pending
            else min(self._flush_by(r) for r in self._pending)
        )
        if self._timer_id:
            # best-effort: a fired-but-superseded timer recognizes the
            # dropped token and no-ops
            from incubator_brpc_tpu.runtime.timer_thread import get_timer_thread

            get_timer_thread().unschedule(self._timer_id)
            self._timer_id = 0
            self._timer_token = None
        return rows

    def _arm_timer(self, due_ns: int) -> None:
        from incubator_brpc_tpu.runtime.timer_thread import get_timer_thread

        tt = get_timer_thread()
        with self._lock:
            if not self._pending or self._due_ns != due_ns:
                return  # flushed or re-aimed while we were outside
            if self._timer_id:
                tt.unschedule(self._timer_id)
            token = object()
            self._timer_token = token
            delay_s = max(0.0, (due_ns - _time.monotonic_ns()) / 1e9)
            self._timer_id = tt.schedule(self._on_timer, delay_s, token)

    def _on_timer(self, token) -> None:
        with self._lock:
            if token is not self._timer_token:
                return  # stale: a newer timer owns the window
            self._timer_id = 0
            self._timer_token = None
            if not self._pending or self._stopped:
                return
            if self._in_flight:
                # a batch is executing: its completion chain-flushes
                # (or re-arms) this window — nothing to do here
                return
            now = _time.monotonic_ns()
            if self._due_ns > now + 50_000:  # re-aimed later: rearm
                due = self._due_ns
                rows = None
            else:
                rows = self._take_pending_locked()
                self._in_flight = True
        if rows:
            # never run user code on the process-wide timer thread
            self._dispatch(rows, inline_ok=False)
        else:
            self._arm_timer(due)

    def _dispatch(self, rows: List[_Row], inline_ok: bool) -> None:
        if self._inline and inline_ok:
            self._flush(rows)
            return
        from incubator_brpc_tpu.runtime import scheduler

        scheduler.spawn(self._flush, rows)

    # ---- execution ---------------------------------------------------------
    def _flush(self, rows: List[_Row]) -> None:
        if _chaos.armed:
            spec = _chaos.check("batch.flush", method=self.full_name)
            if spec is not None:
                if spec.action == "delay_us":
                    _chaos.sleep_us(spec.arg)
                elif spec.action == "drop":
                    # the flush decision is lost: shed the whole window
                    # cleanly — every controller gets exactly one ERPC
                    # completion, nothing waits on a flush that will
                    # never come
                    self._shed(rows, _admission.shed_code("chaos"),
                               "chaos: batch flush dropped",
                               reason_key="chaos")
                    self._finish_window()
                    return
        now = _time.monotonic_ns()
        live: List[_Row] = []
        dead: List[_Row] = []
        cancelled: List[_Row] = []
        for r in rows:
            if r.controller.__dict__.get("_cancel_requested"):
                # hedge loser (cancel frame beat the flush): the row
                # never reaches device work; its done() completes the
                # server bookkeeping but the response is suppressed
                cancelled.append(r)
            elif r.deadline_ns and now > r.deadline_ns:
                dead.append(r)
            else:
                live.append(r)
        if cancelled:
            self._shed(cancelled, _admission.shed_code("cancelled"),
                       "cancelled by caller (hedge loser)",
                       reason_key="cancelled")
        if dead:
            # the request itself expired: the DROP code — retrying it
            # anywhere is wasted work (docs/overload.md code mapping)
            self._shed(dead, _admission.shed_code("deadline"),
                       "batch deadline exceeded while queued (drop)",
                       reason_key="deadline")
        if not live:
            self._finish_window()
            return
        n = len(live)
        pad_to = self.policy.bucket_for(n)
        self.batch_size_rec << n
        with self._lock:
            # occupancy() snapshots this deque from scrape threads;
            # unsynchronized append vs iteration raises RuntimeError
            self._recent.append(n)
        self.batches += 1
        self.rows += n
        if n > self.max_batch_seen:
            self.max_batch_seen = n
        ctx = BatchContext(self.full_name, n, pad_to, self, self.policy)
        wall_us = _time.time_ns() // 1000
        first_span = None
        for r in live:
            span = getattr(r.controller, "_span", None)
            if span is not None:
                # per-row rpcz: callback entry is the fused execution's
                # start; the batch shape rides as an annotation so
                # /rpcz shows size / padding waste / queue wait per row
                span.callback_start_us = wall_us
                span.annotate(
                    f"batch size={n} pad_fraction={ctx.pad_fraction:.2f} "
                    f"queue_wait={(now - r.enqueue_ns) // 1000}us"
                )
                if first_span is None:
                    first_span = span
        t0 = _time.monotonic_ns()
        scatter = _Scatter(live, on_done=lambda: self._on_batch_done(t0))
        from incubator_brpc_tpu.observability.span import swap_current_span

        # parent nested client calls / fabric legs made inside the
        # batch handler to the first row's trace (a batch has N traces;
        # one representative parent beats none)
        prev_parent = swap_current_span(first_span) if first_span else None
        # save/restore like _tls.draining: a nested inline flush into
        # another batcher must not strip the outer handler's context
        prev_ctx = getattr(_tls, "ctx", None)
        _tls.ctx = ctx
        exc = None
        try:
            self._batch_fn(
                [r.controller for r in live],
                [r.request for r in live],
                [r.response for r in live],
                scatter,
            )  # ← USER CODE, once per batch
        except Exception as e:  # noqa: BLE001
            exc = e
            log_error("batched method %s raised: %r", self.full_name, e)
        finally:
            _tls.ctx = prev_ctx
            if first_span is not None:
                swap_current_span(prev_parent)
        if exc is not None and not scatter.called:
            for r in live:
                if not r.controller.failed():
                    r.controller.set_failed(
                        errors.EINTERNAL, f"batched method raised: {exc}"
                    )
            scatter()
        # a handler that neither raised nor called done() is async: the
        # scatter fires (and the service EMA updates) whenever it does

    def _on_batch_done(self, t0_ns: int) -> None:
        self._note_service(t0_ns)
        self._finish_window()

    def _next_window_locked_step(self):
        """One completion step: either take the next ready window
        (chaining, _in_flight stays True) or release the method and
        report the timer deadline to re-arm.  Returns (rows, arm_due)."""
        with self._lock:
            if self._stopped:
                # stop() is the sole drainer of whatever remains; the
                # chain just releases the method so it can proceed
                self._in_flight = False
                return None, 0
            now = _time.monotonic_ns()
            if self._pending and (
                len(self._pending) >= self.policy.max_batch_size
                or self._due_ns <= now
            ):
                # _in_flight stays True: back-to-back fused executions
                return self._take_pending_locked(), 0
            self._in_flight = False
            return None, self._due_ns if self._pending else 0

    def _finish_window(self) -> None:
        """The in-flight execution (or a fully-shed window) finished:
        chain straight into the next window if its trigger already
        fired, otherwise hand the accumulated rows back to the wait
        timer.  This is what makes the one-batch-per-method discipline
        continuous instead of a one-shot.  Inline chaining drains in a
        loop — a saturated stream must not recurse one stack frame per
        back-to-back batch."""
        tok = getattr(_tls, "draining", None)
        if tok is not None and tok[0] is self:
            tok[1] = True  # tell the draining frame below to continue
            return
        if not self._inline:
            # non-inline chaining hops through scheduler.spawn: each
            # _flush runs as its own task, no recursion possible
            rows, arm_due = self._next_window_locked_step()
            if rows is not None:
                self._dispatch(rows, inline_ok=True)
            elif arm_due:
                self._arm_timer(arm_due)
            return
        prev = tok  # a DIFFERENT batcher's token (nested inline RPC
        # into this one): restore it on exit or the outer drain loop
        # loses its recursion guard
        tok = [self, False]
        _tls.draining = tok
        try:
            while True:
                rows, arm_due = self._next_window_locked_step()
                if rows is None:
                    if arm_due:
                        self._arm_timer(arm_due)
                    return
                tok[1] = False
                self._flush(rows)
                if not tok[1]:
                    # async handler: done() hasn't fired yet — its own
                    # completion (on another thread) continues the chain
                    return
        finally:
            _tls.draining = prev

    def _note_service(self, t0_ns: int) -> None:
        service_us = (_time.monotonic_ns() - t0_ns) / 1000.0
        # EMA, single-writer-ish: racing flushes may interleave but the
        # estimate only steers the deadline guard's flush-by time
        self._service_ema_us = (
            service_us
            if self._service_ema_us == 0.0
            else self._service_ema_us * 0.7 + service_us * 0.3
        )

    def _shed(self, rows: List[_Row], code: int, reason: str,
              reason_key: str = "queue_full") -> None:
        now = _time.monotonic_ns()
        for r in rows:
            self.shed << 1
            _admission.note_shed(
                self.full_name,
                r.controller.__dict__.get("_admission_tier"),
                reason_key,
            )
            span = getattr(r.controller, "_span", None)
            if span is not None:
                # the shed phase, stamped before the span closes via
                # the normal error-response path
                span.annotate(
                    f"batch_shed {reason} "
                    f"queued={(now - r.enqueue_ns) // 1000}us"
                )
            r.controller.set_failed(code, reason)
            try:
                r.done()
            except Exception as e:  # noqa: BLE001
                log_error("batched shed done() raised: %r", e)

    @property
    def pad_freelist(self):
        """Donated device rows for pad slots (see __init__)."""
        if self._pad_freelist is None:
            from incubator_brpc_tpu.parallel.ici import StagingRing

            self._pad_freelist = StagingRing(depth=4, max_keys=8)
        return self._pad_freelist

    # ---- runtime tuning ----------------------------------------------------
    def set_max_wait_us(self, us: int) -> None:
        """Live-tune the wait dial (POST /batching): updates the policy
        AND re-aims the window's flush-by time, so rows already queued
        under the old wait feel the new one immediately — not only the
        next arrival."""
        arm_due = 0
        with self._lock:
            self.policy.max_wait_us = int(us)
            if self._pending:
                self._due_ns = min(self._flush_by(r) for r in self._pending)
                if not self._in_flight:
                    # in-flight: the completion chain reads _due_ns
                    arm_due = self._due_ns
        if arm_due:
            self._arm_timer(arm_due)

    # ---- introspection / lifecycle -----------------------------------------
    def pending(self) -> int:
        return len(self._pending)

    def pending_by_tier(self) -> dict:
        """Queued rows grouped by admission tier (rows dispatched while
        no tiered policy was active count as the default tier) — feeds
        the per-tier queue-depth gauges on /metrics."""
        out: dict = {}
        with self._lock:
            rows = list(self._pending)
        for r in rows:
            tier = (
                r.controller.__dict__.get("_admission_tier")
                or _admission.TIER_INTERACTIVE
            )
            out[tier] = out.get(tier, 0) + 1
        return out

    def occupancy(self) -> float:
        """Recent mean batch size over max_batch_size, 0..1 — how full
        the fused executions actually run."""
        with self._lock:
            recent = list(self._recent)
        if not recent or not self.policy.max_batch_size:
            return 0.0
        return (sum(recent) / len(recent)) / self.policy.max_batch_size

    @property
    def service_ema_us(self) -> float:
        return self._service_ema_us

    def describe(self) -> dict:
        return {
            "policy": self.policy.to_dict(),
            "pending": self.pending(),
            "occupancy": round(self.occupancy(), 4),
            "batches": self.batches,
            "rows": self.rows,
            "shed": self.shed.get_value(),
            "max_batch_seen": self.max_batch_seen,
            "service_ema_us": round(self._service_ema_us, 1),
        }

    def stop(self) -> None:
        """Refuse new rows, then drain what is queued (requests already
        admitted deserve execution, not an error), release variables.
        stop() is the SOLE drainer: it waits out any in-flight batch
        first — flushing alongside one would run the user handler
        concurrently with itself, breaking the one-batch-per-method
        guarantee — then flushes the backlog window by window on this
        thread.  A handler stuck past the bounded wait forfeits the
        backlog: remaining rows are shed so no client waits forever on
        a flush that will never come."""
        with self._lock:
            self._stopped = True
        deadline_ns = _time.monotonic_ns() + 5_000_000_000
        while True:
            with self._lock:
                busy = self._in_flight
                rows = (None if busy or not self._pending
                        else self._take_pending_locked())
                if rows is not None:
                    # completion (sync or async) clears this through the
                    # stopped branch of _next_window_locked_step; an
                    # async handler keeps the loop waiting here instead
                    # of overlapping it with the next window
                    self._in_flight = True
            if busy:
                if _time.monotonic_ns() > deadline_ns:
                    with self._lock:
                        stale = []
                        while self._pending:
                            stale.extend(self._take_pending_locked())
                    if stale:
                        self._shed(stale, _admission.shed_code("stopping"),
                                   "batcher stopping (retry elsewhere)",
                                   reason_key="stopping")
                    break
                _time.sleep(0.001)
                continue
            if rows is None:
                break
            self._flush(rows)
        self.batch_size_rec.hide()
        self._occ_var.hide()
        self.shed.hide()
        if self._pad_freelist is not None:
            self._pad_freelist.clear()
