"""Protocol fronts for the HBM cache store: redis + memcache.

One `HBMCacheStore` can sit behind both protocols on the same server
(``ServerOptions.redis_service`` and ``.memcache_service``), so any
off-the-shelf redis or binary-memcached client reads the cluster cache.

Reply residency is decided PER CONNECTION: an ICI-peer socket
(``sock.ici_port``) gets the value as a DeviceRef segment — HBM to HBM
through the staging-ring pipeline, zero pulls — while a host transport
(TCP/DCN client) gets exact bytes through the store's manifested
``cache.host-spill`` choke point.

Redis command surface: GET/SET/DEL/EXISTS/MGET/STRLEN/FLUSHALL/DBSIZE
plus the device-batched DMGET (see `HBMCacheService.dmget`): same-length
hit groups coalesce through the store's fused gather into ONE stacked
bulk, with a lengths header the client unpacks rows from.  DMSET is the
write-side mirror — one round trip ingests a whole key range, so the
resharding coordinator's bulk COPY crosses the wire per DESTINATION,
not per key.
"""

from __future__ import annotations

import threading
from typing import List, Optional

from incubator_brpc_tpu.cache.store import HBMCacheStore
from incubator_brpc_tpu.protocols.memcache import (
    OP_GET,
    STATUS_KEY_NOT_FOUND,
    STATUS_OK,
    MemcacheService,
)
from incubator_brpc_tpu.protocols.redis import (
    REPLY_STRING,
    RedisReply,
    RedisService,
)
from incubator_brpc_tpu.utils.iobuf import DeviceRef


def _is_ici(sock) -> bool:
    return getattr(sock, "ici_port", None) is not None


class HBMCacheService(RedisService):
    """Redis front of the cache tier (connection-aware: the protocol
    routes through ``handle_conn`` so replies know their transport)."""

    def __init__(self, store: Optional[HBMCacheStore] = None, **store_kwargs):
        self.store = store if store is not None else HBMCacheStore(**store_kwargs)
        # the current connection, stashed per worker thread so command
        # methods (fixed handle() signature) can see their transport
        self._tls = threading.local()

    @property
    def _sock(self):
        return getattr(self._tls, "sock", None)

    # protocols.redis.process_request prefers this over handle()
    def handle_conn(self, command: str, args: List, sock) -> RedisReply:
        self._tls.sock = sock
        try:
            cmd = command.upper()
            if cmd == "DEL":  # python keyword, same aliasing as KVRedisService
                return RedisReply.integer(
                    sum(1 for k in args if self.store.delete(k))
                )
            return self.handle(command, args)
        finally:
            self._tls.sock = None

    def _value_reply(self, key: bytes) -> RedisReply:
        if _is_ici(self._sock):
            v = self.store.get(key)
            if v is None:
                return RedisReply.nil()
            return RedisReply(REPLY_STRING, v)  # device or host-mode bytes
        v = self.store.get_host(key)
        if v is None:
            return RedisReply.nil()
        return RedisReply.bulk(v)

    # ---- commands (lower-case name == wire name) ---------------------------
    def get(self, key):
        return self._value_reply(key)

    def set(self, key, value):
        if value is None:
            return RedisReply.error("ERR protocol error: SET value missing")
        if not self.store.set(key, value):
            return RedisReply.error("ERR value exceeds cache HBM budget")
        return RedisReply.status("OK")

    def exists(self, key):
        return 1 if key in self.store else 0

    def strlen(self, key):
        v = self.store.get(key)
        if v is None:
            return 0
        return len(v) if isinstance(v, bytes) else int(v.nbytes)

    def mget(self, *keys):
        # standard redis MGET: per-key bulks, no fusion (redis-cli
        # compatible); the fused device batch is DMGET
        return RedisReply.array([self._value_reply(k) for k in keys])

    def dmget(self, *keys):
        """Device multi-GET → [fused, lengths, payload]:

        fused=1: every hit shares one length; ``payload`` is ONE
        stacked (bucket, L) device bulk — hit i is row i in hit order
        (misses carry length -1 and consume no row).
        fused=0: ``payload`` is a per-key array of bulks like MGET."""
        if not keys:
            return RedisReply.error("ERR wrong number of arguments for 'dmget'")
        values, stacked = self.store.get_many(keys)
        lengths = RedisReply.array([
            RedisReply.integer(
                -1 if v is None
                else (len(v) if isinstance(v, bytes) else int(v.nbytes))
            )
            for v in values
        ])
        if stacked is not None and _is_ici(self._sock):
            return RedisReply.array([
                RedisReply.integer(1),
                lengths,
                RedisReply(REPLY_STRING, stacked),
            ])
        per_key = []
        for k, v in zip(keys, values):
            if v is None:
                per_key.append(RedisReply.nil())
            elif isinstance(v, bytes):
                per_key.append(RedisReply.bulk(v))
            elif _is_ici(self._sock):
                per_key.append(RedisReply(REPLY_STRING, v))
            else:
                per_key.append(RedisReply.bulk(self.store.get_host(k) or b""))
        return RedisReply.array([
            RedisReply.integer(0), lengths, RedisReply.array(per_key),
        ])

    def dmset(self, *kv):
        """Device multi-SET (``DMSET k1 v1 k2 v2 ...``) → integer count
        of values stored.  The ingest counterpart of DMGET: a resharding
        COPY range (or any batched writer) lands on a replica as ONE
        round trip instead of one SET per key — the collective bulk-move
        leg of the Pallas data plane.  Values over the HBM budget are
        skipped (count < pairs tells the client which path to retry)."""
        if not kv or len(kv) % 2:
            return RedisReply.error(
                "ERR wrong number of arguments for 'dmset'"
            )
        stored = 0
        for i in range(0, len(kv), 2):
            if self.store.set(kv[i], kv[i + 1]):
                stored += 1
        return RedisReply.integer(stored)

    def keys(self, *args):
        """Key census for the re-sharding coordinator (argument-free —
        no glob matching; migrations enumerate everything)."""
        return RedisReply.array(
            [RedisReply.bulk(k) for k in self.store.keys()]
        )

    def flushall(self, *args):
        self.store.flush()
        return RedisReply.status("OK")

    def dbsize(self):
        return len(self.store)


class HBMCacheMemcacheService(MemcacheService):
    """Memcache front over the SAME store: GET serves the device array
    to ICI peers (the binary framing ships it as the value region),
    spills to host bytes for everyone else; SET/DELETE/FLUSH hit the
    shared store so both protocols see one cache."""

    def __init__(self, store: Optional[HBMCacheStore] = None, **store_kwargs):
        super().__init__()
        self.store = store if store is not None else HBMCacheStore(**store_kwargs)

    def handle_op(self, op, sock):
        import struct

        code = op.opcode
        if code == OP_GET:
            if _is_ici(sock):
                v = self.store.get(op.key)
            else:
                v = self.store.get_host(op.key)
            if v is None:
                return STATUS_KEY_NOT_FOUND, b"", b"Not found", 0
            return STATUS_OK, struct.pack(">I", 0), v, 0
        if code == 0x01:  # OP_SET
            value = op.value
            if not isinstance(value, (bytes, DeviceRef)):
                value = bytes(value)
            if not self.store.set(op.key, value):
                return 0x0005, b"", b"", 0  # ITEM_NOT_STORED: over budget
            return STATUS_OK, b"", b"", 0
        if code == 0x04:  # OP_DELETE
            ok = self.store.delete(op.key)
            return (STATUS_OK if ok else STATUS_KEY_NOT_FOUND), b"", b"", 0
        if code == 0x08:  # OP_FLUSH
            self.store.flush()
            return STATUS_OK, b"", b"", 0
        return super().handle_op(op, sock)
