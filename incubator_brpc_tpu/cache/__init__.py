"""HBM-resident cluster cache tier (ROADMAP item 4, the flagship
memcached-shaped serving workload).

Values live in HBM as exact-length uint8 jax.Arrays; GETs on ICI peers
ship them as IOBuf DeviceRef segments with zero device->host pulls
(proven by the transfer-witness lane), host clients get bytes through
the manifested ``cache.host-spill`` scope only.  The redis and memcache
protocols front the same store; `CacheChannel` routes by consistent
hashing with mesh-coordinate locality.  See docs/cache.md.
"""

from incubator_brpc_tpu.cache.channel import CacheChannel, MGetResult
from incubator_brpc_tpu.cache.service import (
    HBMCacheMemcacheService,
    HBMCacheService,
)
from incubator_brpc_tpu.cache.store import HBMCacheStore

__all__ = [
    "CacheChannel",
    "HBMCacheMemcacheService",
    "HBMCacheService",
    "HBMCacheStore",
    "MGetResult",
]
