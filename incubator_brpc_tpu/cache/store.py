"""Device-resident KV store: the cache tier's HBM value plane.

Every value is ONE exact-length uint8 jax.Array (never a slab row: the
ICI placement path only ships whole arrays zero-copy, and RESP/memcache
framing needs nbytes == value length exactly).  SETs ingest host bytes
with a single host->device put — or adopt the array of an arriving
DeviceRef without any copy at all (the ICI SET path).  GETs return the
stored array untouched: the hot path does zero device ops and zero
device->host pulls.  Host-client reads funnel through ``get_host``,
the one sanctioned spill choke point (manifested ``cache.host-spill``).

Capacity is an HBM byte budget with LRU eviction.  Metrics:
``rpc_cache_{hits,misses,evictions,hbm_bytes}`` (registered in
METRIC_MODULES for the render lint).  The chaos site ``cache.lookup``
(docs/chaos.md) faults individual lookups: drop = forced miss for a
present key, delay_us = straggler replica.

Multi-GET fusion: same-length hit groups stack through ONE jitted
gather (`fused_stack` below, a batching.FusedKernel with padding
buckets), so a DMGET of N keys leaves as a single device execution and
one stacked wire segment instead of N.
"""

from __future__ import annotations

import threading
import weakref
from collections import OrderedDict
from typing import List, Optional, Sequence, Tuple

from incubator_brpc_tpu.analysis.device_witness import allowed_transfer
from incubator_brpc_tpu.batching.fused import FusedKernel
from incubator_brpc_tpu.chaos import injector as _chaos
from incubator_brpc_tpu.metrics.reducer import Adder
from incubator_brpc_tpu.observability.profiling import hbm_account
from incubator_brpc_tpu.utils.iobuf import DeviceRef

cache_hits = Adder(0).expose("rpc_cache_hits")
cache_misses = Adder(0).expose("rpc_cache_misses")
cache_evictions = Adder(0).expose("rpc_cache_evictions")
cache_hbm_bytes = Adder(0).expose("rpc_cache_hbm_bytes")

# HBM heap profiler tags (observability/profiling.py): stored values
# hold their adopt charge on the entry; fused-gather stacks are
# transient (bucket, L) buffers released when the array is collected
_VALUES_ACCT = hbm_account("cache.values")
_GATHER_ACCT = hbm_account("cache.gather")

DEFAULT_HBM_BUDGET = 64 << 20

# padding buckets for the fused multi-GET gather: jit specializes on
# the stacked leading dim, so padding the hit count up to a bucket
# bounds retraces at len(buckets) per value length
MGET_BUCKETS = (1, 2, 4, 8, 16, 32, 64)

def _stack_rows(*rows):
    import jax.numpy as jnp

    return jnp.stack(rows)


_mget_gather = FusedKernel(
    _stack_rows, label="cache.mget_gather", batch_buckets=MGET_BUCKETS
)


def _pad_bucket(n: int) -> int:
    for b in MGET_BUCKETS:
        if n <= b:
            return b
    return n


def fused_stack(rows: Sequence) -> object:
    """Stack same-shape device rows into one (bucket, L) array via a
    single fused execution; rows beyond ``len(rows)`` are padding
    (repeats of row 0 — their contents ride along but are never read)."""
    bucket = _pad_bucket(len(rows))
    padded = list(rows) + [rows[0]] * (bucket - len(rows))
    out = _mget_gather(*padded)
    charged = _GATHER_ACCT.adopt(out)
    if charged:
        try:  # release rides GC: the stack lives exactly as long as the
            # response holding it (pad rows included — they pin HBM too)
            weakref.finalize(out, _GATHER_ACCT.release, charged)
        except TypeError:  # array type not weakref-able: net out now
            _GATHER_ACCT.release(charged)
    return out


class _Entry:
    __slots__ = ("array", "length", "host", "charge")

    def __init__(self, array, length: int, host: Optional[bytes] = None,
                 charge: int = 0):
        self.array = array  # exact-length uint8 jax.Array (device mode)
        self.length = length
        self.host = host  # bytes (disabled mode only)
        self.charge = charge  # hbm_account adopt return (release this)


class HBMCacheStore:
    """LRU KV store of HBM-resident values, byte-budgeted.

    ``enabled=False`` degrades to a plain host-bytes dict with the same
    surface — the cache-disabled overhead baseline (bench's OFF/ON/OFF
    triplet), and the fallback when no accelerator is wanted."""

    def __init__(self, hbm_budget_bytes: int = DEFAULT_HBM_BUDGET,
                 device=None, enabled: bool = True):
        self.budget = int(hbm_budget_bytes)
        self.device = device
        self.enabled = enabled
        self._d: "OrderedDict[bytes, _Entry]" = OrderedDict()
        self._used = 0
        self._lock = threading.RLock()

    # ---- ingest -----------------------------------------------------------
    def _to_device(self, value):
        """→ (array, nbytes).  DeviceRef whole arrays ADOPT (zero-copy:
        the ICI transport already delivered the value into local HBM);
        host bytes take one h2d put (h2d is never witness-guarded)."""
        import jax

        if isinstance(value, DeviceRef):
            arr = value.whole_array()
            if arr is None:
                # windowed ref: no identity to adopt; materialize the
                # window (manifested iobuf.host-view) and re-ingest
                value = bytes(value.view())
            else:
                return arr, int(arr.nbytes)
        if isinstance(value, (bytes, bytearray, memoryview)):
            import numpy as np

            host = np.frombuffer(bytes(value), dtype=np.uint8)
            if self.device is not None:
                return jax.device_put(host, self.device), host.nbytes
            return jax.device_put(host), host.nbytes
        # raw jax.Array (in-process producer)
        return value, int(value.nbytes)

    def set(self, key: bytes, value) -> bool:
        """Insert/replace.  False = value alone exceeds the budget."""
        key = bytes(key)
        if not self.enabled:
            if isinstance(value, DeviceRef):
                value = bytes(value.view())
            elif not isinstance(value, (bytes, bytearray, memoryview)):
                value = bytes(DeviceRef(value).view())
            with self._lock:
                self._d[key] = _Entry(None, len(value), bytes(value))
                self._d.move_to_end(key)
            return True
        arr, nbytes = self._to_device(value)
        if nbytes > self.budget:
            return False
        with self._lock:
            old = self._d.pop(key, None)
            if old is not None:
                self._used -= old.length
                cache_hbm_bytes << -old.length
                _VALUES_ACCT.release(old.charge)
            while self._used + nbytes > self.budget and self._d:
                _, ev = self._d.popitem(last=False)
                self._used -= ev.length
                cache_evictions << 1
                cache_hbm_bytes << -ev.length
                _VALUES_ACCT.release(ev.charge)
            self._d[key] = _Entry(arr, nbytes, charge=_VALUES_ACCT.adopt(nbytes))
            self._used += nbytes
            cache_hbm_bytes << nbytes
        return True

    # ---- lookup -----------------------------------------------------------
    def _chaos_drop(self, key: bytes) -> bool:
        if not _chaos.armed:
            return False
        spec = _chaos.check("cache.lookup", method=key.decode("latin1"))
        if spec is None:
            return False
        if spec.action == "delay_us":
            _chaos.sleep_us(spec.arg)
            return False
        return spec.action == "drop"

    def get(self, key: bytes):
        """The hot path: the stored device array (or host bytes when
        disabled), None on miss.  NO device ops, NO pulls."""
        key = bytes(key)
        forced_miss = self._chaos_drop(key)
        with self._lock:
            ent = None if forced_miss else self._d.get(key)
            if ent is None:
                cache_misses << 1
                return None
            self._d.move_to_end(key)
            cache_hits << 1
            return ent.host if ent.array is None else ent.array

    def get_host(self, key: bytes) -> Optional[bytes]:
        """Host-client read: device values SPILL to bytes here, under
        the manifested ``cache.host-spill`` scope — the only sanctioned
        device->host exit of the cache tier."""
        v = self.get(key)
        if v is None or isinstance(v, bytes):
            return v
        import numpy as np

        with allowed_transfer("cache.host-spill"):
            return np.asarray(v).tobytes()

    def get_many(self, keys: Sequence[bytes]) -> Tuple[List, Optional[object]]:
        """Batched lookup → (values, stacked).  ``values`` has one
        entry per key (array/bytes or None).  When every hit is a
        device value of ONE common length and there are ≥2 hits, they
        additionally coalesce through the fused gather into ``stacked``
        ((bucket, L) uint8) — one device execution, one wire segment."""
        values = [self.get(k) for k in keys]
        hits = [v for v in values if v is not None]
        if (
            len(hits) >= 2
            and all(not isinstance(v, bytes) for v in hits)
            and len({int(v.nbytes) for v in hits}) == 1
        ):
            return values, fused_stack(hits)
        return values, None

    def keys(self) -> List[bytes]:
        """Snapshot of live keys (LRU order, oldest first) — the
        re-sharding coordinator's key census.  Does NOT touch recency:
        enumerating for a migration must not distort eviction order."""
        with self._lock:
            return list(self._d)

    # ---- maintenance ------------------------------------------------------
    def delete(self, key: bytes) -> bool:
        with self._lock:
            ent = self._d.pop(bytes(key), None)
            if ent is None:
                return False
            if ent.array is not None:
                self._used -= ent.length
                cache_hbm_bytes << -ent.length
                _VALUES_ACCT.release(ent.charge)
            return True

    def flush(self) -> int:
        with self._lock:
            n = len(self._d)
            if self._used:
                cache_hbm_bytes << -self._used
            charged = [e.charge for e in self._d.values() if e.charge]
            if charged:
                _VALUES_ACCT.release(sum(charged), allocs=len(charged))
            self._d.clear()
            self._used = 0
            return n

    def __len__(self) -> int:
        return len(self._d)

    def __contains__(self, key) -> bool:
        return bytes(key) in self._d

    @property
    def hbm_used(self) -> int:
        return self._used

    def stats(self) -> dict:
        """Snapshot for the /cache builtin."""
        with self._lock:
            return {
                "enabled": self.enabled,
                "entries": len(self._d),
                "hbm_used": self._used,
                "hbm_budget": self.budget,
                "hits": cache_hits.get_value(),
                "misses": cache_misses.get_value(),
                "evictions": cache_evictions.get_value(),
            }
