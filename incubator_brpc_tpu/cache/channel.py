"""CacheChannel — the cluster cache's client data plane.

A thin typed wrapper over a redis-protocol `Channel` with naming-fed
membership: every key routes by its murmur3 hash (``request_code`` =
``murmur3_32(key)``) through the channel's load balancer — by default
``mesh_locality``, the ConsistentHashingLB ring re-ranked by ICI
locality and shed pressure (client/load_balancer.py).  GETs from an
ICI-local replica come back as HBM-resident jax.Arrays (DeviceRef bulk
segments, zero pulls); the host-bytes accessors materialize through the
manifested scopes only.

``get_many`` issues one DMGET: the server coalesces same-length hits
through the store's fused gather into ONE stacked device bulk, which
`MGetResult` slices rows out of on the consumer device.  ``set_many``
mirrors it with DMSET — one round trip per routed replica — so bulk
movers (resharding COPY) cross the wire per destination, not per key.

Replication (docs/replication.md): a cache position gains HA by
listing its member CacheChannels in ``replication.
replicated_cache_group`` — the CacheShardStore adapter gives the
replica group quorum writes, fencing, and BULK repair (the DMGET/DMSET
surface above means catching a replica up moves key ranges in
collective steps).  The cache service itself is untouched.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from incubator_brpc_tpu.client.channel import Channel, ChannelOptions
from incubator_brpc_tpu.client.controller import Controller
from incubator_brpc_tpu.protocols import redis as _redis
from incubator_brpc_tpu.utils.hashes import murmur3_32
from incubator_brpc_tpu.utils.iobuf import DeviceRef


class CacheError(RuntimeError):
    def __init__(self, code: int, text: str):
        super().__init__(f"cache rpc failed ({code}): {text}")
        self.code = code


class MGetResult:
    """One DMGET's worth of values.

    ``lengths[i]`` is value i's byte length, -1 on miss.  When the
    server fused (``stacked`` is a (bucket, L) uint8 device array), hit
    i is row ``hit_index(i)`` — sliced lazily so consumers that feed
    rows straight into device compute never touch host memory."""

    def __init__(self, keys: Sequence[bytes], lengths: List[int],
                 stacked=None, per_key: Optional[List] = None):
        self.keys = list(keys)
        self.lengths = lengths
        self.stacked = stacked
        self._per_key = per_key

    def hit(self, i: int) -> bool:
        return self.lengths[i] >= 0

    def _hit_index(self, i: int) -> int:
        return sum(1 for l in self.lengths[:i] if l >= 0)

    def row(self, i: int):
        """Value i as a device array (or host bytes on the unfused host
        path); None on miss."""
        if not self.hit(i):
            return None
        if self.stacked is not None:
            return self.stacked[self._hit_index(i)]
        return self._per_key[i]

    def host_bytes(self, i: int) -> Optional[bytes]:
        """Value i as host bytes — device rows MATERIALIZE (manifested
        iobuf.host-view); keep off the hot path."""
        v = self.row(i)
        if v is None or isinstance(v, bytes):
            return v
        return bytes(DeviceRef(v).view())


class CacheChannel:
    """Client of the HBM cache tier.

    ``local_coords`` (the caller's (slice, chip) mesh position) arms the
    locality ranking; without it the ``mesh_locality`` balancer degrades
    to plain deterministic consistent hashing."""

    def __init__(self, naming_url: str = "tpu://fabric",
                 lb: str = "mesh_locality",
                 local_coords=None,
                 options: Optional[ChannelOptions] = None):
        options = options or ChannelOptions(timeout_ms=30000)
        options.protocol = "redis"  # the tier speaks RESP whatever the caller set
        self._channel = Channel(options)
        rc = self._channel.init(naming_url, lb)
        if rc != 0:
            raise ValueError(f"cache channel init failed ({rc}) for {naming_url!r}")
        if local_coords is not None:
            balancer = self.balancer()
            if hasattr(balancer, "set_local_coords"):
                balancer.set_local_coords(local_coords)

    def balancer(self):
        """The underlying LoadBalancer (e.g. MeshLocalityLB for
        locality stats)."""
        lbn = self._channel._lb
        return lbn._lb if lbn is not None else None

    def locality_fraction(self) -> float:
        b = self.balancer()
        return b.locality_fraction() if hasattr(b, "locality_fraction") else 0.0

    # ---- single-command plumbing ------------------------------------------
    def _call(self, key: bytes, *components) -> _redis.RedisReply:
        req = _redis.RedisRequest()
        req.add_command(*components)
        resp = _redis.RedisResponse()
        ctrl = Controller()
        ctrl.request_code = murmur3_32(bytes(key))
        self._channel.call_method(_redis.redis_method_spec(), ctrl, req, resp)
        if ctrl.failed():
            raise CacheError(ctrl.error_code, ctrl.error_text())
        return resp.reply(0)

    def _call_window(self, calls, total_keys: int) -> List[_redis.RedisReply]:
        """Issue one WINDOW of routed commands concurrently — one call
        per replica group, all in flight together — and wait for every
        completion.  ``calls`` is ``[(route_key, components), ...]``;
        replies return in call order.  Error semantics match the old
        sequential loop: the first failed group (in call order) raises
        CacheError.  The fan-out step log records the window: crossings
        == groups, never keys (client/ring.py fanout_log)."""
        import threading as _threading

        n = len(calls)
        spec = _redis.redis_method_spec()
        ctrls: List[Controller] = []
        resps: List[_redis.RedisResponse] = []
        event = _threading.Event()
        lock = _threading.Lock()
        remaining = [n]

        def _one_done():
            with lock:
                remaining[0] -= 1
                if remaining[0] == 0:
                    event.set()

        max_tmo_ms = 0
        for route_key, components in calls:
            req = _redis.RedisRequest()
            req.add_command(*components)
            resp = _redis.RedisResponse()
            ctrl = Controller()
            ctrl.request_code = murmur3_32(bytes(route_key))
            ctrls.append(ctrl)
            resps.append(resp)
            try:
                self._channel.call_method(spec, ctrl, req, resp,
                                          done=_one_done)
            except Exception as e:  # noqa: BLE001 — a raising leg must
                # not strand the window's shared completion
                if not ctrl.failed():
                    from incubator_brpc_tpu import errors as _errors

                    ctrl.set_failed(
                        _errors.EINTERNAL, f"cache window leg raised: {e}"
                    )
                _one_done()
            tmo = ctrl.timeout_ms or self._channel.options.timeout_ms or 0
            max_tmo_ms = max(max_tmo_ms, tmo)
        # the transport's own timeout sweep completes every leg; the
        # backstop only guards a wedged transport (legs it catches read
        # as failed controllers below)
        event.wait(max_tmo_ms / 1000.0 + 5.0 if max_tmo_ms > 0 else 65.0)
        from incubator_brpc_tpu.client.ring import fanout_log

        fanout_log.record(crossings=n, keys=total_keys)
        for ctrl in ctrls:
            if ctrl.failed():
                raise CacheError(ctrl.error_code, ctrl.error_text())
        return [resp.reply(0) for resp in resps]

    # ---- KV surface --------------------------------------------------------
    def get(self, key):
        """The stored value: an HBM-resident jax.Array when the replica
        answered over ICI, host bytes otherwise, None on miss."""
        key = key.encode() if isinstance(key, str) else bytes(key)
        r = self._call(key, "GET", key)
        if r.is_nil():
            return None
        if r.is_error():
            raise CacheError(0, str(r.value))
        arr = r.device_array()
        return arr if arr is not None else r.bytes_value()

    def get_host(self, key) -> Optional[bytes]:
        v = self.get(key)
        if v is None or isinstance(v, bytes):
            return v
        return bytes(DeviceRef(v).view())

    def set(self, key, value) -> None:
        """``value``: host bytes, a jax.Array, or a DeviceRef — device
        values ride the wire as DeviceRef segments (zero-copy over ICI)."""
        key = key.encode() if isinstance(key, str) else bytes(key)
        if isinstance(value, str):
            value = value.encode()
        r = self._call(key, "SET", key, value)
        if r.is_error():
            raise CacheError(0, str(r.value))

    def delete(self, key) -> bool:
        key = key.encode() if isinstance(key, str) else bytes(key)
        r = self._call(key, "DEL", key)
        return bool(r.value)

    def get_many(self, keys: Sequence) -> MGetResult:
        """Batched GET.  Keys are grouped by the replica the balancer
        routes each one to, and every group ships as ONE ``DMGET`` —
        the server coalesces each group's same-length hits through the
        store's fused gather.  A batch that lands on a single replica
        (co-located keys — the hot shape) keeps the one stacked device
        array end to end; a batch spanning replicas merges per key."""
        bkeys = [k.encode() if isinstance(k, str) else bytes(k) for k in keys]
        balancer = self.balancer()
        groups: dict = {}
        if balancer is None:
            groups[None] = list(range(len(bkeys)))
        else:
            from incubator_brpc_tpu.client.load_balancer import SelectIn

            for i, k in enumerate(bkeys):
                node = balancer.select_server(
                    SelectIn(request_code=murmur3_32(k))
                )
                groups.setdefault(node, []).append(i)
        if len(groups) == 1:
            lengths, vals, stacked = self._dmget(bkeys[0], bkeys)
            if stacked is not None:
                return MGetResult(bkeys, lengths, stacked=stacked)
            return MGetResult(bkeys, lengths, per_key=vals)
        # multi-replica batch: ONE window — every group's DMGET is in
        # flight concurrently (crossings == groups, not keys), replies
        # merge per key in group order
        lengths = [-1] * len(bkeys)
        per_key: List = [None] * len(bkeys)
        group_idxs = list(groups.values())
        calls = []
        for idxs in group_idxs:
            gkeys = [bkeys[i] for i in idxs]
            calls.append((gkeys[0], ("DMGET", *gkeys)))
        replies = self._call_window(calls, total_keys=len(bkeys))
        for idxs, r in zip(group_idxs, replies):
            glens, gvals, _ = self._parse_dmget(r)
            for i, L, v in zip(idxs, glens, gvals):
                lengths[i] = L
                per_key[i] = v
        return MGetResult(bkeys, lengths, per_key=per_key)

    def _dmget(self, route_key: bytes, bkeys: List[bytes]):
        """One DMGET round trip: (lengths, per-key values, stacked).
        Fused replies keep ``stacked`` whole and slice rows lazily —
        device rows never leave HBM here."""
        return self._parse_dmget(self._call(route_key, "DMGET", *bkeys))

    @staticmethod
    def _parse_dmget(r: _redis.RedisReply):
        if r.is_error():
            raise CacheError(0, str(r.value))
        fused, lengths_r, payload = r.value
        lengths = [x.value for x in lengths_r.value]
        if fused.value == 1:
            stacked = payload.device_array()
            vals: List = []
            hi = 0
            for L in lengths:
                if L < 0:
                    vals.append(None)
                else:
                    vals.append(stacked[hi])
                    hi += 1
            return lengths, vals, stacked
        vals = []
        for item in payload.value:
            if item.is_nil():
                vals.append(None)
            else:
                arr = item.device_array()
                vals.append(arr if arr is not None else item.bytes_value())
        return lengths, vals, None

    def set_many(self, items: Sequence) -> int:
        """Batched SET: ``items`` is (key, value) pairs.  Pairs are
        grouped by the replica the balancer routes each key to and every
        group ships as ONE ``DMSET`` — the resharding coordinator's
        bulk COPY moves a whole (src, dst) range in one round trip per
        destination instead of one SET per key.  Returns the stored
        count; raises CacheError when any value was refused (HBM
        budget), so callers fall back to the per-key engine."""
        pairs: List = []
        for k, v in items:
            k = k.encode() if isinstance(k, str) else bytes(k)
            if isinstance(v, str):
                v = v.encode()
            pairs.append((k, v))
        if not pairs:
            return 0
        balancer = self.balancer()
        groups: dict = {}
        if balancer is None:
            groups[None] = list(range(len(pairs)))
        else:
            from incubator_brpc_tpu.client.load_balancer import SelectIn

            for i, (k, _) in enumerate(pairs):
                node = balancer.select_server(
                    SelectIn(request_code=murmur3_32(k))
                )
                groups.setdefault(node, []).append(i)
        # one DMSET per destination replica, ALL in flight as one
        # window (crossings == groups); refusal semantics unchanged —
        # the first failed/refused group in group order raises
        group_idxs = list(groups.values())
        if len(group_idxs) == 1:
            idxs = group_idxs[0]
            flat: List = []
            for i in idxs:
                flat.extend(pairs[i])
            replies = [self._call(pairs[idxs[0]][0], "DMSET", *flat)]
        else:
            calls = []
            for idxs in group_idxs:
                flat = []
                for i in idxs:
                    flat.extend(pairs[i])
                calls.append((pairs[idxs[0]][0], ("DMSET", *flat)))
            replies = self._call_window(calls, total_keys=len(pairs))
        stored = 0
        for r in replies:
            if r.is_error():
                raise CacheError(0, str(r.value))
            stored += int(r.value)
        if stored != len(pairs):
            raise CacheError(
                0, f"DMSET stored {stored}/{len(pairs)} values"
            )
        return stored

    def keys(self) -> List[bytes]:
        """Key census of the replica this channel routes to.  The
        re-sharding coordinator holds one single-member channel per
        shard and reads each shard's census through this; on a
        multi-replica channel it censuses whichever replica the empty
        route key hashes to."""
        r = self._call(b"", "KEYS")
        if r.is_error():
            raise CacheError(0, str(r.value))
        return [item.bytes_value() for item in r.value]

    def flush_all(self) -> None:
        self._call(b"", "FLUSHALL")

    def close(self) -> None:
        self._channel.close()
